"""Wave-commit lattice: vectorized bulk pass + bounded conflict-resolution.

The first-cut kernel (ops/lattice.py) reproduced scheduleOne's serial
semantics as a P-step lax.scan — measured at ~3.5 ms/pod on hardware because
every step re-ran topology segment-sums and rewrote a multi-MB carry. This
kernel restructures the batch cycle so nothing scales with P serially:

  Stage A (fully vectorized, template granularity):
    * filter masks, score matrix, normalization per TEMPLATE [TPL, N] — a
      burst of Deployment pods is one template, not P pods;
    * topology-domain sums ONCE per (predicate, topology-key) pair [J, V]
      (the PairTable), not once per pod;
    * per-template top-M candidate nodes; per-pod candidate order =
      score-descending with per-pod random tie-noise (selectHost's uniform
      tie-break, generic_scheduler.go:235).

  Stage B (W waves, all-vectorized):
    every wave, each unplaced pod takes its best still-feasible candidate;
    conflicts are resolved batch-wide: per-node capacity by prefix-fit in
    pod order, per-(pair, domain) exclusivity by lowest pod index (one
    contributor per topology domain per wave keeps anti-affinity/spread
    sound). Losers retry next wave against updated deltas. The lowest
    active pod always wins all its groups, so every wave commits ≥1 pod —
    no livelock; leftovers defer to the next batch.

Serial-equivalence note (SURVEY §7 hard part (c)): within a batch, scores
are not recomputed after each commit (reference recomputes per pod), and
near-tie candidates may swap under the tie-noise epsilon. Placements remain
feasible-at-commit-time under full filter semantics; the divergence is
bounded to score staleness inside one batch window — the same staleness the
reference tolerates between its snapshot and async binds.

The snapshot's occupancy tensors are DONATED and returned updated with all
committed pods, so consecutive batches chain on-device with no host round
trip (SURVEY §7 hard part (d): persistent device state, delta-only uplink).
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .encoding import (
    DeviceSnapshot,
    ETERM_AFF_PREF,
    ETERM_AFF_REQ,
    ETERM_ANTI_PREF,
    ETERM_ANTI_REQ,
    PodBatch,
    RES_CPU,
    RES_MEM,
)
from .lattice import (
    DEFAULT_WEIGHTS,
    NUM_SCORE_COMPONENTS,
    SC_BALANCED,
    SC_IMAGE,
    SC_INTERPOD,
    SC_LEAST_ALLOC,
    SC_MOST_ALLOC,
    SC_NODE_AFFINITY,
    SC_PREFER_AVOID,
    SC_REQ_TO_CAP,
    SC_TAINT,
    SC_TOPO_SPREAD,
    _image_locality,
    _label_cols,
    _node_affinity_required,
    _node_affinity_score,
    _prefer_avoid,
    _taints,
)
from .templates import PairTable, TemplateBatch

TIE_EPS = 1e-3


class WaveResult(NamedTuple):
    chosen: Any  # [P] int32 node row, -1 = not placed
    placed: Any  # [P] bool
    deferred: Any  # [P] bool — feasible nodes existed but waves ran out
    feasible_count: Any  # [P] int32 base-feasible node count
    score: Any  # [P] float32
    resolvable_tpl: Any  # [TPL, N] bool — preemption candidates per template
    feasible_tpl: Any  # [TPL, N] bool — pre-commit filter verdicts (the
    # differential-fuzz oracle surface; never fetched by the scheduler)


def _group_prefix_sums(groups, sort_key, values):
    """Exclusive prefix sums of `values` within equal-`groups` runs after
    sorting by sort_key (sort_key must sort group-contiguously, e.g.
    group*(P+1)+idx). Returns (order, exclusive_prefix[sorted order])."""
    order = jnp.argsort(sort_key)
    g = groups[order]
    v = values[order]
    cum = jnp.cumsum(v, axis=0)
    excl_global = cum - v
    # group start position via running max over indices where a new group starts
    pos = jnp.arange(g.shape[0])
    is_start = jnp.concatenate([jnp.array([True]), g[1:] != g[:-1]])
    start_pos = jax.lax.associative_scan(
        jnp.maximum, jnp.where(is_start, pos, -1)
    )
    base = excl_global[start_pos]
    return order, excl_global - base


DEFAULT_RTC_SHAPE = ((0.0, 0.0), (100.0, 10.0))


@functools.lru_cache(maxsize=32)
def make_wave_kernel(
    v_cap: int,
    m_cand: int = 128,
    n_waves: int = 8,
    hard_pod_affinity_weight: float = 1.0,
    use_pallas_fit: bool = False,
    score_refresh: bool = True,
    rtc_shape: tuple = DEFAULT_RTC_SHAPE,
    has_pinned: bool = True,
):
    """Build the wave kernel (unjitted) for the given static capacities.

    has_pinned=False compiles OUT the per-wave pinned-row plan (the
    [J, P] pair gathers + [TPL, J, P] verdict vmap below) — for the
    common all-unpinned batch that work is the same order as the [TPL, N]
    recompute this kernel eliminated, and its results would be discarded
    by the pinned select. The host passes the batch's actual pinnedness
    (a numpy any() over pod_name_row) as part of the variant key.

    rtc_shape: the RequestedToCapacityRatio piecewise points
    ((utilization%, score 0..10), ...) — static per profile, part of the
    kernel-variant key, interpolated device-side with jnp.interp so an
    arbitrary shape matches the host plugin exactly
    (requested_to_capacity_ratio.go:33; r4 verdict #7 closed the
    default-shape hardcode).

    use_pallas_fit routes the resource-fit mask (Stage A's fits0 and each
    wave's fits_w — the kernel's hottest recomputation) through the fused
    Pallas kernel in ops/pallas_ops.py instead of the XLA [TPL, N, R]
    broadcast; interpret mode on non-TPU backends keeps it testable.

    score_refresh re-evaluates the RESOURCE score components at each pod's
    candidate nodes every wave (cheap [P, M] gathers) so later waves see
    in-batch commits in their packing decisions instead of the batch-start
    snapshot — the serial-fidelity improvement for SURVEY §7 hard part (c);
    non-resource components stay Stage-A static (their pair counts are the
    documented in-batch staleness)."""
    if use_pallas_fit:
        from .pallas_ops import fit_mask as _pallas_fit_mask

        _interpret = jax.devices()[0].platform != "tpu"

        def _fit(req, free):
            return _pallas_fit_mask(req, free, interpret=_interpret)

    else:

        def _fit(req, free):
            return jnp.all(
                (req[:, None, :] == 0) | (req[:, None, :] <= free[None]),
                axis=-1,
            )

    def kernel(snap: DeviceSnapshot, tb: TemplateBatch, pt: PairTable, weights, rng):
        tpl: PodBatch = tb.tpl
        n = snap.valid.shape[0]
        TPL = tpl.valid.shape[0]
        P = tb.pod_tpl.shape[0]
        J = pt.col.shape[0]
        m_c = min(m_cand, n)  # candidate list cannot exceed node capacity

        # ================= Stage A: per-template statics =================
        def statics_one(bp):
            ns_aff = _node_affinity_required(snap, bp)
            taint_ok, prefer_cnt = _taints(snap, bp)
            unsched_ok = ~snap.unschedulable | bp.tolerates_unschedulable
            static_ok = snap.valid & ns_aff & taint_ok & unsched_ok
            return (
                static_ok,
                ns_aff,
                _node_affinity_score(snap, bp),
                prefer_cnt,
                _image_locality(snap, bp),
                _prefer_avoid(snap, bp),
            )

        static_ok, ns_aff, aff_score, prefer_cnt, img, avoid = jax.vmap(
            statics_one
        )(tpl)  # each [TPL, N]

        free0 = snap.allocatable - snap.requested  # [N, R]
        fits0 = _fit(tpl.req, free0)  # [TPL, N]
        ports0 = jnp.any(
            tpl.port_mask[:, None, :] & (snap.port_counts[None] > 0), axis=-1
        )  # [TPL, N]

        # ---- pair domain structure ----
        def pair_cols(j):
            col = jnp.clip(pt.col[j], 0, None)
            sidv = snap.sel_counts[:, jnp.clip(col, 0, snap.sel_counts.shape[1] - 1)]
            etv = snap.eterm_w[:, jnp.clip(col, 0, snap.eterm_w.shape[1] - 1)]
            w = jnp.where(pt.is_eterm[j], etv, sidv.astype(jnp.float32))
            dom, _ = _label_cols(snap, pt.key[j])
            e = pt.elig_tpl[j]
            elig = jnp.where(
                e >= 0, ns_aff[jnp.clip(e, 0, TPL - 1)], jnp.ones_like(snap.valid)
            )
            elig = elig & snap.valid & (dom >= 0)
            return w, dom, elig

        w_j, dom_j, elig_j = jax.vmap(pair_cols)(jnp.arange(J))  # [J, N]

        def dom_sums(w, dom, elig, delta):
            seg = jnp.where(elig, dom, v_cap)
            sums = jax.ops.segment_sum(
                jnp.where(elig, w, 0.0), seg, num_segments=v_cap
            ) + delta  # [V]
            present = (
                jax.ops.segment_max(elig.astype(jnp.int32), seg, num_segments=v_cap)
                > 0
            )
            node_cnt = jnp.where(dom >= 0, sums[jnp.clip(dom, 0, v_cap - 1)], 0.0)
            min_dom = jnp.min(jnp.where(present, sums, jnp.inf))
            return node_cnt, min_dom, jnp.sum(sums), sums

        cnt0, min0, tot0, base_dom = jax.vmap(dom_sums)(
            w_j, dom_j, elig_j, jnp.zeros((J, v_cap))
        )  # cnt0 [J, N]; base_dom [J, V]
        present_dom = jax.vmap(
            lambda j: jax.ops.segment_max(
                elig_j[j].astype(jnp.int32),
                jnp.where(elig_j[j], dom_j[j], v_cap),
                num_segments=v_cap,
            )
            > 0
        )(jnp.arange(J))  # [J, V] — wave-invariant

        def tpl_pair_verdicts(t, cnt, min_d, tot, dom):
            """Carry-dependent filter verdicts for template t given pair
            counts (cnt [J, X], min_d [J], tot [J], dom [J, X]). X is the
            column axis: all N node rows in Stage A, the template's M
            candidate columns in the waves."""
            def spread_c(pair, skew, hard, selfm):
                ok_pair = pair >= 0
                p = jnp.clip(pair, 0, J - 1)
                haskey = dom[p] >= 0
                m = jnp.where(jnp.isfinite(min_d[p]), min_d[p], 0.0)
                skewed = cnt[p] + jnp.where(selfm, 1.0, 0.0) - m > skew
                bad = hard & (skewed | ~haskey)
                soft = jnp.where(~hard, cnt[p], 0.0)
                return jnp.where(ok_pair, bad, False), jnp.where(ok_pair, soft, 0.0)

            sbad, ssoft = jax.vmap(spread_c)(
                pt.spr_pair[t], pt.spr_skew[t], pt.spr_hard[t], pt.spr_self[t]
            )
            spread_bad = jnp.any(sbad, axis=0)
            spread_pen = jnp.sum(ssoft, axis=0)

            def aff_a(pair, selfm):
                ok_pair = pair >= 0
                p = jnp.clip(pair, 0, J - 1)
                haskey = dom[p] >= 0
                ok = (cnt[p] > 0) | ((tot[p] == 0) & selfm & haskey)
                return jnp.where(ok_pair, ok, True)

            aff_ok = jnp.all(jax.vmap(aff_a)(pt.aff_pair[t], pt.aff_self[t]), axis=0)

            def anti_b(pair):
                ok_pair = pair >= 0
                p = jnp.clip(pair, 0, J - 1)
                bad = (dom[p] >= 0) & (cnt[p] > 0)
                return jnp.where(ok_pair, bad, False)

            anti_bad = jnp.any(jax.vmap(anti_b)(pt.anti_pair[t]), axis=0)

            et_rel = pt.etm_match[t] & (pt.kind == ETERM_ANTI_REQ)  # [J]
            eterm_bad = jnp.any(
                et_rel[:, None] & (dom >= 0) & (cnt > 0), axis=0
            )
            return spread_bad, spread_pen, aff_ok, anti_bad, eterm_bad

        spread_bad0, spread_pen0, aff_ok0, anti_bad0, eterm_bad0 = jax.vmap(
            lambda t: tpl_pair_verdicts(t, cnt0, min0, tot0, dom_j)
        )(jnp.arange(TPL))

        feasible0 = (
            static_ok & fits0 & ~ports0 & ~spread_bad0 & aff_ok0 & ~anti_bad0
            & ~eterm_bad0
        )  # [TPL, N]
        resolvable_tpl = static_ok & ~feasible0
        feas_cnt_tpl = jnp.sum(feasible0.astype(jnp.int32), axis=1)  # [TPL]

        # ---- scores [TPL, N] ----
        # resource scores only read the cpu/mem columns: compute the two
        # [TPL, N] fraction planes directly instead of materializing the
        # [TPL, N, R] nz_used broadcast (R× less HBM traffic in Stage A)
        def _frac(col):
            a = jnp.maximum(
                snap.allocatable[:, col].astype(jnp.float32), 1.0
            )[None]
            u = (
                snap.nonzero_req[:, col][None]
                + tpl.nonzero_req[:, col][:, None]
            ).astype(jnp.float32)
            return jnp.clip(u / a, 0.0, 1.0)

        cpu_f, mem_f = _frac(RES_CPU), _frac(RES_MEM)
        least = ((1.0 - cpu_f) + (1.0 - mem_f)) * 50.0
        most = (cpu_f + mem_f) * 50.0
        balanced = (1.0 - jnp.abs(cpu_f - mem_f)) * 100.0
        # piecewise shape over mean utilization%, scaled 0..100 like the
        # host plugin (score 0..10 * 10)
        rtc_xs = jnp.asarray([p[0] for p in rtc_shape], jnp.float32)
        rtc_ys = jnp.asarray([p[1] for p in rtc_shape], jnp.float32)

        def _rtc(cf, mf):
            return jnp.interp((cf + mf) * 50.0, rtc_xs, rtc_ys) * 10.0

        rtc = _rtc(cpu_f, mem_f)

        # interpod score: existing pods' terms + incoming preferred terms
        sgn = jnp.select(
            [
                pt.kind == ETERM_ANTI_PREF,
                pt.kind == ETERM_AFF_PREF,
                pt.kind == ETERM_AFF_REQ,
            ],
            [-1.0, 1.0, hard_pod_affinity_weight],
            default=0.0,
        )  # [J]
        ip_et = jnp.einsum(
            "tj,jn->tn", pt.etm_match.astype(jnp.float32) * sgn[None, :], cnt0
        )

        def ppref_t(t):
            def one(pair, w):
                p = jnp.clip(pair, 0, J - 1)
                return jnp.where(pair >= 0, w * cnt0[p], 0.0)

            return jnp.sum(jax.vmap(one)(pt.pref_pair[t], pt.pref_w[t]), axis=0)

        ip = ip_et + jax.vmap(ppref_t)(jnp.arange(TPL))  # [TPL, N]

        def norm_max(x, feas):
            mx = jnp.max(jnp.where(feas, x, -jnp.inf), axis=1, keepdims=True)
            safe = jnp.where(jnp.isfinite(mx) & (mx > 0), mx, 1.0)
            return jnp.clip(x / safe * 100.0, 0.0, 100.0)

        def norm_invert(x, feas):
            mx = jnp.max(jnp.where(feas, x, -jnp.inf), axis=1, keepdims=True)
            ok = jnp.isfinite(mx) & (mx > 0)
            safe = jnp.where(ok, mx, 1.0)
            return jnp.where(ok, (safe - x) / safe * 100.0, 100.0)

        ip_mx = jnp.max(
            jnp.where(feasible0, jnp.abs(ip), 0.0), axis=1, keepdims=True
        )
        ip_norm = jnp.where(ip_mx > 0, ip / ip_mx * 100.0, 0.0)

        # DefaultPodTopologySpread: same-service pods per node through the
        # service-derived sel_counts columns (templates sharing a service
        # share the mask); MAX over matching services mirrors the host's
        # any()-dedup for non-overlapping services. Stage-A counts like the
        # other pair scores — staleness within the batch window is the
        # kernel's documented score model.
        svc_cnt = jnp.max(
            jnp.where(
                tpl.match_svc[:, None, :],
                snap.sel_counts[None].astype(jnp.float32),
                0.0,
            ),
            axis=-1,
        )  # [TPL, N]

        # heterogeneity/cost columns are per-node; broadcast over templates
        # so the same norm_invert (per-template over feasible) applies
        cost_col = jnp.broadcast_to(
            snap.cost_milli.astype(jnp.float32)[None, :], least.shape
        )
        energy_col = jnp.broadcast_to(
            snap.energy_milli.astype(jnp.float32)[None, :], least.shape
        )
        comps = jnp.stack(
            [
                least,
                most,
                balanced,
                rtc,
                norm_max(aff_score, feasible0),
                norm_invert(prefer_cnt, feasible0),
                img,
                avoid,
                norm_invert(spread_pen0, feasible0),
                ip_norm,
                norm_invert(svc_cnt, feasible0),
                norm_invert(cost_col, feasible0),
                norm_invert(energy_col, feasible0),
            ]
        )  # [K, TPL, N]
        total_score = jnp.einsum("k,ktn->tn", weights, comps)

        # ---- top-M candidates per template ----
        masked = jnp.where(feasible0, total_score, -jnp.inf)
        top_v, top_i = jax.lax.top_k(masked, m_c)  # [TPL, M]

        # ---- per-pod candidate ordering ----
        t_of = jnp.clip(tb.pod_tpl, 0, TPL - 1)  # [P]
        noise = jax.random.uniform(rng, (P, m_c), maxval=0.999)
        # top_v is sorted descending; equal-score runs form groups. Order
        # candidates by score-group, uniformly random within a group (the
        # float-safe form of selectHost's uniform tie-break — adding tiny
        # noise to raw scores underflows when weights reach 1e4×100).
        grp_id = jnp.cumsum(
            jnp.concatenate(
                [jnp.zeros((TPL, 1), jnp.float32),
                 (top_v[:, 1:] != top_v[:, :-1]).astype(jnp.float32)],
                axis=1,
            ),
            axis=1,
        )  # [TPL, M]
        pod_v = top_v[t_of]  # [P, M]
        order = jnp.argsort(grp_id[t_of] + noise, axis=1)  # [P, M]
        # order doubles as the SLOT index into the template's top-M column
        # list: per-wave feasibility is evaluated once per (template,
        # column) at [TPL, M] and pods read it through cand_slot — exact,
        # because every non-pinned candidate is one of its template's
        # top-M columns (r4 verdict #2: wave re-checks must not scale
        # with N)
        cand_slot = order
        cand_nodes = jnp.take_along_axis(top_i[t_of], order, axis=1)  # [P, M]
        cand_valid = jnp.isfinite(jnp.take_along_axis(pod_v, order, axis=1))
        # pinned pods: single candidate = the pinned row (still filter-checked)
        pinned = tb.pod_name_row >= 0
        pin_rows = jnp.clip(tb.pod_name_row, 0, n - 1)  # [P]
        cand_nodes = jnp.where(
            pinned[:, None],
            jnp.where(
                jnp.arange(m_c)[None, :] == 0,
                pin_rows[:, None],
                0,
            ),
            cand_nodes,
        )
        cand_slot = jnp.where(pinned[:, None], 0, cand_slot)
        pin_feas = jnp.take_along_axis(
            feasible0[t_of], pin_rows[:, None], axis=1
        )[:, 0]
        cand_valid = jnp.where(
            pinned[:, None],
            (jnp.arange(m_c)[None, :] == 0) & pin_feas[:, None],
            cand_valid,
        )
        # spec.nodeName names a node the cache doesn't know (row -2): the
        # NodeName filter fails everywhere -> unschedulable, never placed
        cand_valid = cand_valid & (tb.pod_name_row != -2)[:, None]
        cand_nodes = jnp.clip(cand_nodes, 0, n - 1)

        # ---- per-wave candidate-column statics (hoisted gathers) ----
        static_ok_c = jnp.take_along_axis(static_ok, top_i, axis=1)  # [TPL,M]
        free0_cols = free0[top_i]  # [TPL, M, R] batch-start free at columns
        port0_cols = snap.port_counts[top_i]  # [TPL, M, PV']
        dom_cols = jnp.moveaxis(dom_j[:, top_i], 1, 0)  # [TPL, J, M]
        cnt0_cols = jnp.moveaxis(cnt0[:, top_i], 1, 0)  # [TPL, J, M]
        # flat per-wave gather plan for dom_d at the candidate columns
        dom_cols_flat = jnp.clip(
            jnp.moveaxis(dom_cols, 0, 1).reshape(J, TPL * m_c), 0, v_cap - 1
        )  # [J, TPL*M]
        # pinned pods may name a row outside top-M: their per-wave checks
        # (resources, ports, AND pair verdicts) run per-pod at the pinned
        # row — the [J, P] column plan below keeps the pair re-check live
        # against in-batch commits, same as the candidate columns.
        if has_pinned:
            dom_pin = dom_j[:, pin_rows]  # [J, P]
            dom_pin_flat = jnp.clip(dom_pin, 0, v_cap - 1)
            cnt0_pin = cnt0[:, pin_rows]  # [J, P]
            pin_req = tpl.req[t_of]  # [P, R]
            pin_ports = tpl.port_mask[t_of]  # [P, PV']

        if score_refresh:
            # static pieces of the per-wave candidate re-score: the
            # NON-resource score residual at each candidate, plus the
            # batch-start nonzero/alloc cpu+mem columns there
            w_res = (
                weights[SC_LEAST_ALLOC] * least
                + weights[SC_MOST_ALLOC] * most
                + weights[SC_BALANCED] * balanced
                + weights[SC_REQ_TO_CAP] * rtc
            )  # [TPL, N]
            cand_resid = jnp.take_along_axis(
                (total_score - w_res)[t_of], cand_nodes, axis=1
            )  # [P, M]
            alloc_cpu_c = jnp.maximum(
                snap.allocatable[:, RES_CPU][cand_nodes].astype(jnp.float32),
                1.0,
            )
            alloc_mem_c = jnp.maximum(
                snap.allocatable[:, RES_MEM][cand_nodes].astype(jnp.float32),
                1.0,
            )
            nz_cpu0_c = snap.nonzero_req[:, RES_CPU][cand_nodes]
            nz_mem0_c = snap.nonzero_req[:, RES_MEM][cand_nodes]
            pod_nz_cpu = tpl.nonzero_req[:, RES_CPU][t_of][:, None]
            pod_nz_mem = tpl.nonzero_req[:, RES_MEM][t_of][:, None]

        # which pods participate in pair exclusivity (contributor or
        # hard-checker), per pair
        checks = jnp.zeros((TPL, J), bool)
        def scatter_pairs(checks, pairs, extra_mask=None):
            m = pairs >= 0 if extra_mask is None else (pairs >= 0) & extra_mask
            idx = jnp.clip(pairs, 0, J - 1)
            return checks.at[jnp.arange(TPL)[:, None], idx].max(m)

        checks = scatter_pairs(checks, pt.spr_pair, pt.spr_hard)
        checks = scatter_pairs(checks, pt.anti_pair)
        checks = checks | (pt.etm_match & (pt.kind == ETERM_ANTI_REQ)[None, :])
        # Exclusivity is only needed for pairs some template HARD-checks:
        # those verdicts can be invalidated by a same-wave contributor in the
        # same domain. Pure-affinity pairs (cnt>0 checks) are monotone under
        # additions, so their contributors commit freely — without this gate
        # a burst of one Deployment's affinity pods serializes to one commit
        # per wave.
        needs_excl = jnp.any(checks, axis=0)  # [J]
        participates = (checks | (pt.contrib != 0)) & needs_excl[None, :]
        is_contrib_tpl = pt.contrib != 0  # [TPL, J]
        uses_carveout = jnp.zeros((TPL, J), bool)
        uses_carveout = scatter_pairs(uses_carveout, pt.aff_pair, pt.aff_self)

        # resource matrix for prefix-fit: requests ⧺ port usage (capacity 1)
        PV = snap.port_counts.shape[1]
        req_ext_tpl = jnp.concatenate(
            [tpl.req.astype(jnp.int32), tpl.port_mask.astype(jnp.int32)], axis=1
        )  # [TPL, R+PV]

        # ================= Stage B: waves =================
        def wave(_, state):
            placed, chosen, req_d, port_d, dom_d, nz2_d = state
            free_d = free0 - req_d  # [N, R] (prefix-fit still needs full N)
            # ---- candidate-column re-checks: [TPL, M], never [TPL, N] ----
            free_c = free0_cols - req_d[top_i]  # [TPL, M, R]
            fits_w_c = jnp.all(
                (tpl.req[:, None, :] == 0) | (tpl.req[:, None, :] <= free_c),
                axis=-1,
            )  # [TPL, M]
            ports_w_c = jnp.any(
                tpl.port_mask[:, None, :]
                & ((port0_cols + port_d[top_i]) > 0),
                axis=-1,
            )  # [TPL, M]
            dd = jnp.take_along_axis(dom_d, dom_cols_flat, axis=1).reshape(
                J, TPL, m_c
            )  # [J, TPL, M] committed-delta at each column's domain
            cnt_w_cols = cnt0_cols + jnp.where(
                dom_cols >= 0, jnp.moveaxis(dd, 0, 1), 0.0
            )  # [TPL, J, M]
            sums_w = base_dom + dom_d  # [J, V]
            min_w = jnp.min(jnp.where(present_dom, sums_w, jnp.inf), axis=1)
            tot_w = tot0 + jnp.sum(dom_d, axis=1)

            sb, _, ao, ab, eb = jax.vmap(
                lambda t, cnt, dom: tpl_pair_verdicts(t, cnt, min_w, tot_w, dom)
            )(jnp.arange(TPL), cnt_w_cols, dom_cols)
            wave_feas_c = (
                static_ok_c & fits_w_c & ~ports_w_c & ~sb & ao & ~ab & ~eb
            )  # [TPL, M]

            cand_feas = wave_feas_c[t_of[:, None], cand_slot] & cand_valid
            if has_pinned:
                # pinned pods: live resource/port fit at the pinned row +
                # live pair verdicts there (row may be outside top-M; the
                # batch-start value would miss in-batch commits — a wave-1
                # contributor into domain D must block a wave-2 pinned pod
                # whose template requires anti-affinity on D)
                pin_free = free_d[pin_rows]  # [P, R]
                pin_fit = jnp.all(
                    (pin_req == 0) | (pin_req <= pin_free), axis=-1
                )
                pin_port_bad = jnp.any(
                    pin_ports & ((snap.port_counts + port_d)[pin_rows] > 0),
                    axis=-1,
                )
                dd_pin = jnp.take_along_axis(dom_d, dom_pin_flat, axis=1)
                cnt_pin = cnt0_pin + jnp.where(dom_pin >= 0, dd_pin, 0.0)
                sb_p, _, ao_p, ab_p, eb_p = jax.vmap(
                    lambda t: tpl_pair_verdicts(
                        t, cnt_pin, min_w, tot_w, dom_pin
                    )
                )(jnp.arange(TPL))  # each [TPL, P]
                pair_ok_pin = (~sb_p & ao_p & ~ab_p & ~eb_p)[
                    t_of, jnp.arange(P)
                ]  # [P]
                pin_ok_w = pin_fit & ~pin_port_bad & pair_ok_pin
                # replace (not AND): a pinned pod's single candidate is the
                # pinned row, whose verdict is pin_ok_w — slot 0 of the
                # template's column table is a different node entirely.
                # cand_valid already restricts pinned pods to slot 0 and
                # carries the batch-start full feasibility at the pinned
                # row.
                cand_feas = jnp.where(
                    pinned[:, None], cand_valid & pin_ok_w[:, None], cand_feas
                )  # [P, M]
            if score_refresh:
                # re-evaluate the resource scores at the candidates with
                # this wave's committed occupancy; the candidate list is
                # pre-shuffled within equal-static-score groups, so a
                # plain argmax inherits the uniform tie-break
                cpu_f_c = jnp.clip(
                    (nz_cpu0_c + nz2_d[:, 0][cand_nodes] + pod_nz_cpu)
                    .astype(jnp.float32)
                    / alloc_cpu_c,
                    0.0,
                    1.0,
                )
                mem_f_c = jnp.clip(
                    (nz_mem0_c + nz2_d[:, 1][cand_nodes] + pod_nz_mem)
                    .astype(jnp.float32)
                    / alloc_mem_c,
                    0.0,
                    1.0,
                )
                res_c = (
                    weights[SC_LEAST_ALLOC]
                    * (((1.0 - cpu_f_c) + (1.0 - mem_f_c)) * 50.0)
                    + weights[SC_MOST_ALLOC] * ((cpu_f_c + mem_f_c) * 50.0)
                    + weights[SC_BALANCED]
                    * ((1.0 - jnp.abs(cpu_f_c - mem_f_c)) * 100.0)
                    + weights[SC_REQ_TO_CAP] * _rtc(cpu_f_c, mem_f_c)
                )
                score_c = jnp.where(
                    cand_feas, cand_resid + res_c, -jnp.inf
                )  # [P, M]
                first = jnp.argmax(score_c, axis=1)
            else:
                first = jnp.argmax(cand_feas, axis=1)
            has = jnp.any(cand_feas, axis=1)
            cand_n = cand_nodes[jnp.arange(P), first]
            active = tb.pod_valid & ~placed & has

            # -- capacity prefix-fit in pod order --
            grp = jnp.where(active, cand_n, n)
            sort_key = grp * (P + 1) + jnp.arange(P)
            vals = req_ext_tpl[t_of] * active[:, None].astype(jnp.int32)
            order_c, excl = _group_prefix_sums(grp, sort_key, vals)
            free_ext = jnp.concatenate(
                [
                    free_d,
                    1 - jnp.minimum(snap.port_counts + port_d, 1),
                ],
                axis=1,
            )  # [N, R+PV]
            node_sorted = cand_n[order_c]
            req_sorted = req_ext_tpl[t_of][order_c]
            fit_sorted = jnp.all(
                excl + req_sorted <= free_ext[node_sorted], axis=1
            )
            fit_ok = jnp.zeros(P, bool).at[order_c].set(fit_sorted)

            # -- (pair, domain) exclusivity --
            pod_dom = dom_j[:, cand_n].T  # [P, J] domain of candidate per pair
            carve = (
                uses_carveout[t_of] & (tot_w == 0)[None, :] & active[:, None]
            )
            # carveout claims are exclusive regardless of the needs_excl gate
            # (two pods claiming "no matches anywhere" in different domains
            # would diverge from serial semantics)
            part = (participates[t_of] | carve) & active[:, None]  # [P, J]
            key_pd = jnp.where(
                carve,
                jnp.arange(J)[None, :] * (v_cap + 2) + v_cap + 1,
                jnp.arange(J)[None, :] * (v_cap + 2)
                + jnp.clip(pod_dom, 0, v_cap - 1),
            )
            part = part & ((pod_dom >= 0) | carve)
            is_contrib = (is_contrib_tpl[t_of] | carve) & part  # [P, J]
            dump = J * (v_cap + 2)
            flat_key = jnp.where(part, key_pd, dump).reshape(-1)
            flat_key_c = jnp.where(is_contrib, key_pd, dump).reshape(-1)
            pod_idx_mat = jnp.broadcast_to(
                jnp.arange(P)[:, None], (P, J)
            ).reshape(-1)
            nseg = dump + 1
            min_all = jax.ops.segment_min(pod_idx_mat, flat_key, num_segments=nseg)
            min_con = jax.ops.segment_min(
                pod_idx_mat, flat_key_c, num_segments=nseg
            )
            # contributor commits iff it is the group's lowest participant;
            # checker-only pods commit iff no contributor is committing in
            # their group this wave (group min is a checker)
            g_all = min_all[flat_key].reshape(P, J)
            g_con = min_con[flat_key].reshape(P, J)
            # serial-order guard for the carveout: in index order a lower
            # contributor to pair j would commit before the claimant, making
            # its tot==0 premise false — so block the claim this wave when
            # any lower-indexed active contributor exists pair-wide
            contrib_any = is_contrib_tpl[t_of] & active[:, None] & ~carve
            pair_key = jnp.where(
                contrib_any, jnp.arange(J)[None, :], J
            ).reshape(-1)
            min_contrib_pair = jax.ops.segment_min(
                pod_idx_mat, pair_key, num_segments=J + 1
            )[:J]
            carve_allowed = (
                jnp.arange(P)[:, None] < min_contrib_pair[None, :]
            )  # [P, J]
            ok_pair = jnp.where(
                is_contrib,
                g_all == pod_idx_mat.reshape(P, J),
                g_con > g_all,
            ) & (~carve | carve_allowed)
            dom_ok = jnp.all(ok_pair | ~part, axis=1)

            commit = active & fit_ok & dom_ok
            ci = jnp.where(commit, cand_n, n)  # OOB -> dropped
            req_d = req_d.at[ci].add(tpl.req[t_of], mode="drop")
            port_d = port_d.at[ci].add(
                tpl.port_mask[t_of].astype(jnp.int32), mode="drop"
            )
            nz2_d = nz2_d.at[ci].add(
                jnp.stack(
                    [
                        tpl.nonzero_req[:, RES_CPU],
                        tpl.nonzero_req[:, RES_MEM],
                    ],
                    axis=1,
                )[t_of],
                mode="drop",
            )
            contrib_p = pt.contrib[t_of] * commit[:, None]  # [P, J]
            dd_key = jnp.where(
                (pod_dom >= 0) & (contrib_p != 0),
                jnp.arange(J)[None, :] * v_cap + jnp.clip(pod_dom, 0, v_cap - 1),
                J * v_cap,
            ).reshape(-1)
            dom_d = (
                dom_d.reshape(-1)
                .at[dd_key]
                .add(contrib_p.reshape(-1), mode="drop")
                .reshape(J, v_cap)
            )
            placed = placed | commit
            chosen = jnp.where(commit, cand_n, chosen)
            return placed, chosen, req_d, port_d, dom_d, nz2_d

        state0 = (
            jnp.zeros(P, bool),
            jnp.full(P, -1, jnp.int32),
            jnp.zeros_like(snap.requested),
            jnp.zeros_like(snap.port_counts),
            jnp.zeros((J, v_cap), jnp.float32),
            jnp.zeros((n, 2), snap.nonzero_req.dtype),
        )
        # Static trip count on purpose: a data-dependent while_loop hangs the
        # axon PJRT tunnel (empirically — even a trivial one never returns).
        # The host picks n_waves per batch shape instead (scheduler.py).
        placed, chosen, req_d, port_d, dom_d, _nz2_d = jax.lax.fori_loop(
            0, n_waves, wave, state0
        )

        # ================= finalize: commit occupancy to snapshot ==========
        # Every field the host's add_pod touches is committed here (incl.
        # prio_req by priority band), so the scheduler's replay can skip the
        # dirty-row re-upload entirely (encoding.add_pod device_synced=True).
        ci = jnp.where(placed, chosen, n)
        band = jnp.clip(tb.pod_band, 0, snap.prio_req.shape[1] - 1)
        new_snap = snap._replace(
            requested=snap.requested.at[ci].add(tpl.req[t_of], mode="drop"),
            nonzero_req=snap.nonzero_req.at[ci].add(
                tpl.nonzero_req[t_of], mode="drop"
            ),
            sel_counts=snap.sel_counts.at[ci].add(
                tpl.match_sel[t_of].astype(jnp.int32), mode="drop"
            ),
            eterm_w=snap.eterm_w.at[ci].add(tpl.eterm_add[t_of], mode="drop"),
            port_counts=snap.port_counts.at[ci].add(
                tpl.port_mask[t_of].astype(jnp.int32), mode="drop"
            ),
            prio_req=snap.prio_req.at[ci, band].add(tpl.req[t_of], mode="drop"),
        )

        feas_cnt = jnp.where(tb.pod_valid, feas_cnt_tpl[t_of], 0)
        feas_cnt = jnp.where(
            pinned, jnp.where(pin_feas & tb.pod_valid, 1, 0), feas_cnt
        )
        # unknown pinned node: zero feasible so the pod FAILS (backoff +
        # unschedulable event) instead of deferring into a requeue hot-loop
        feas_cnt = jnp.where(tb.pod_name_row == -2, 0, feas_cnt)
        score_out = jnp.where(
            placed,
            total_score[t_of, jnp.clip(chosen, 0, n - 1)],
            -jnp.inf,
        )
        deferred = tb.pod_valid & ~placed & (feas_cnt > 0)
        return new_snap, WaveResult(
            chosen=jnp.where(placed, chosen, -1),
            placed=placed,
            deferred=deferred,
            feasible_count=feas_cnt,
            score=score_out,
            resolvable_tpl=resolvable_tpl,
            feasible_tpl=feasible0,
        )

    return kernel


@functools.lru_cache(maxsize=32)
def make_wave_kernel_jit(
    v_cap: int,
    m_cand: int = 128,
    n_waves: int = 8,
    hard_pod_affinity_weight: float = 1.0,
    use_pallas_fit: bool = False,
    score_refresh: bool = True,
    rtc_shape: tuple = DEFAULT_RTC_SHAPE,
    has_pinned: bool = True,
):
    return jax.jit(
        make_wave_kernel(
            v_cap,
            m_cand,
            n_waves,
            hard_pod_affinity_weight,
            use_pallas_fit,
            score_refresh,
            rtc_shape,
            has_pinned,
        ),
        donate_argnums=(0,),
    )


@functools.lru_cache(maxsize=32)
def make_wave_kernel_cb_jit(
    v_cap: int,
    m_cand: int = 128,
    n_waves: int = 8,
    hard_pod_affinity_weight: float = 1.0,
    use_pallas_fit: bool = False,
    score_refresh: bool = True,
    rtc_shape: tuple = DEFAULT_RTC_SHAPE,
    has_pinned: bool = True,
):
    """host_callback_binds variant of the wave kernel: identical compute,
    plus a ``jax.experimental.io_callback`` that posts the fast index
    payload (chosen/placed/deferred) to ops.hostcallback's ticket
    registry the moment the kernel resolves ON DEVICE — the depth-
    infinity micro-wave mode where the host never issues a device->host
    sync for the bind-critical data. `ticket` is a traced int32 scalar so
    distinct launches share one compiled variant. The full WaveResult is
    still returned: the trailing bulk validation and the failure paths
    (resolvable_tpl) read it the ordinary way."""
    from jax.experimental import io_callback

    from . import hostcallback

    base = make_wave_kernel(
        v_cap,
        m_cand,
        n_waves,
        hard_pod_affinity_weight,
        use_pallas_fit,
        score_refresh,
        rtc_shape,
        has_pinned,
    )

    def kernel_cb(snap, tb, pt, weights, rng, ticket):
        new_snap, res = base(snap, tb, pt, weights, rng)
        io_callback(
            hostcallback.deliver,
            None,
            ticket,
            res.chosen,
            res.placed,
            res.deferred,
        )
        return new_snap, res

    return jax.jit(kernel_cb, donate_argnums=(0,))
