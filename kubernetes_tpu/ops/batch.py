"""Pod-batch encoding: pending pods → fixed-shape PodBatch tensors.

The device analogue of the per-pod work the reference does at the top of the
scheduling cycle (PreFilter state construction: noderesources/fit.go:99,
podtopologyspread/filtering.go:43, interpodaffinity/filtering.go:51). All
string/selector work happens here once per pod; the kernel sees only integer
ids. Pods whose spec overflows the static buckets (more affinity terms than
`aff_terms`, etc.) are flagged for the host fallback path — the same escape
hatch the reference uses for extenders (generic_scheduler.go:421: device/fast
path narrows, slow path completes).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..api import objects as v1
from ..api.objects import (
    TAINT_NODE_UNSCHEDULABLE,
    Taint,
    compute_pod_resource_request,
    pod_host_ports,
    tolerations_tolerate_taint,
)
from ..api.selectors import (
    OP_IN,
    OP_NOT_IN,
    LabelSelector,
    Requirement,
)
from .encoding import (
    _OP_CODES,
    ETERM_ANTI_PREF,
    ETERM_AFF_PREF,
    ENC_OP_IN,
    PodBatch,
    PodPredicate,
    RES_PODS,
    SnapshotEncoder,
    zpad,
)

TOL_OP_EQUAL = 0
TOL_OP_EXISTS = 1
_TOL_EFFECT = {
    "": -1,
    v1.TAINT_NO_SCHEDULE: 0,
    v1.TAINT_PREFER_NO_SCHEDULE: 1,
    v1.TAINT_NO_EXECUTE: 2,
}


@dataclass
class EncodedBatch:
    batch: PodBatch
    pods: List[v1.Pod]  # row-aligned with the batch (padded rows absent)
    fallback: np.ndarray  # [P] bool — pod overflowed static buckets
    batch_np: Optional[PodBatch] = None  # host (numpy) mirror of `batch`;
    # device→host readbacks through the PJRT tunnel cost a full RTT, so
    # host-side consumers (pair-table build) must never np.asarray(batch)


class _PodEnc:
    """Per-pod intermediate encoding (python lists, turned into arrays later)."""

    def __init__(self) -> None:
        self.fallback = False


def _encode_expr(
    enc: SnapshotEncoder, r: v1.NodeSelectorRequirement, vals_cap: int
) -> Optional[Tuple[int, int, List[int], int]]:
    """(key_id, op, value_ids, numval). None => overflow (fallback)."""
    op = _OP_CODES.get(r.operator)
    if op is None or len(r.values) > vals_cap:
        return None
    key_id = enc.key_vocab.get(r.key)  # -2: unknown key == absent everywhere
    if key_id < 0:
        key_id = -2
    vids = [max(enc.val_vocab.get(v), -2) for v in r.values]
    num = 0
    if r.operator in ("Gt", "Lt"):
        try:
            num = int(r.values[0])
        except (ValueError, IndexError):
            return None
    return key_id, op, vids, num


def encode_pod_batch(
    enc: SnapshotEncoder, pods: Sequence[v1.Pod], pad_to: Optional[int] = None
) -> EncodedBatch:
    """Encode up to P pods. Interning of predicates/eterms happens first so
    all capacities are final before arrays are allocated."""
    c = enc.cfg
    P = pad_to or max(1, len(pods))
    assert len(pods) <= P

    # ---- pass 1: intern everything that can grow capacities ----------------
    per_pod: List[dict] = []
    for pod in pods:
        d: dict = {"fallback": False}
        ns = pod.metadata.namespace
        spec = pod.spec
        aff = spec.affinity

        # PVC-backed and direct-attach volumes need the host path: the
        # volume plugins (binding, restrictions, attach limits, zone) are
        # host-side post-filters, like reference extenders
        if any(
            vol.persistent_volume_claim
            or vol.gce_persistent_disk
            or vol.aws_elastic_block_store
            or vol.iscsi
            or vol.rbd
            or vol.azure_disk
            or vol.cinder
            for vol in spec.volumes
        ):
            d["fallback"] = True

        # topology spread
        spreads = []
        for tsc in spec.topology_spread_constraints[: c.spread_max]:
            key_id = enc.intern_key(tsc.topology_key)
            if tsc.label_selector is not None:
                sid = enc.intern_predicate(frozenset({ns}), tsc.label_selector)
                self_m = tsc.label_selector.matches(pod.metadata.labels)
            else:
                sid, self_m = -1, False
            spreads.append(
                (key_id, sid, tsc.max_skew, tsc.when_unsatisfiable == v1.DO_NOT_SCHEDULE, self_m)
            )
        if len(spec.topology_spread_constraints) > c.spread_max:
            d["fallback"] = True
        d["spreads"] = spreads

        # incoming interpod terms
        def pred_of(term: v1.PodAffinityTerm) -> PodPredicate:
            nss = frozenset(term.namespaces) if term.namespaces else frozenset({ns})
            return PodPredicate(nss, term.label_selector or LabelSelector())

        paff, panti, ppref = [], [], []
        if aff and aff.pod_affinity:
            for term in aff.pod_affinity.required:
                pred = pred_of(term)
                sid = enc.intern_predicate(pred.namespaces, pred.selector)
                paff.append(
                    (sid, enc.intern_key(term.topology_key), pred.matches(ns, pod.metadata.labels))
                )
            for wt in aff.pod_affinity.preferred:
                pred = pred_of(wt.term)
                sid = enc.intern_predicate(pred.namespaces, pred.selector)
                ppref.append((sid, enc.intern_key(wt.term.topology_key), float(wt.weight)))
        if aff and aff.pod_anti_affinity:
            for term in aff.pod_anti_affinity.required:
                pred = pred_of(term)
                sid = enc.intern_predicate(pred.namespaces, pred.selector)
                panti.append((sid, enc.intern_key(term.topology_key)))
            for wt in aff.pod_anti_affinity.preferred:
                pred = pred_of(wt.term)
                sid = enc.intern_predicate(pred.namespaces, pred.selector)
                ppref.append((sid, enc.intern_key(wt.term.topology_key), -float(wt.weight)))
        if len(paff) > c.pod_aff_max or len(panti) > c.pod_anti_max or len(ppref) > c.pod_pref_max:
            d["fallback"] = True
        d["paff"], d["panti"], d["ppref"] = (
            paff[: c.pod_aff_max],
            panti[: c.pod_anti_max],
            ppref[: c.pod_pref_max],
        )

        # the pod's own carried terms (for in-batch carry + eterm matching)
        d["eterm_ids"], d["eterm_ws"] = enc._pod_eterms(pod)

        # host ports
        ports = pod_host_ports(pod)
        d["port_ids"] = [enc.intern_port(proto, port) for (_, proto, port) in ports]

        per_pod.append(d)

    # ---- pass 2: fixed-shape arrays (capacities now final) -----------------
    # re-read the config: pass-1 interning may have GROWN capacities, and
    # _grow replaces enc.cfg with a new object — the `c` bound above would
    # silently allocate stale-shaped arrays (caught by the differential fuzz)
    c = enc.cfg
    S, T = c.s_cap, c.t_cap
    svc_mask = enc.service_sid_mask()
    b = {
        "valid": np.zeros(P, np.bool_),
        "req": np.zeros((P, c.r_cap), np.int32),
        "nonzero_req": np.zeros((P, c.r_cap), np.int32),
        "node_name_row": np.full(P, -1, np.int32),
        "tolerates_unschedulable": np.zeros(P, np.bool_),
        "ns_key": np.full((P, c.ns_max), -1, np.int32),
        "ns_op": np.full((P, c.ns_max), -1, np.int32),
        "ns_vals": np.full((P, c.ns_max, c.aff_vals), -2, np.int32),
        "ns_num": np.zeros((P, c.ns_max), np.int32),
        "aff_has": np.zeros(P, np.bool_),
        "aff_key": np.full((P, c.aff_terms, c.aff_exprs), -1, np.int32),
        "aff_op": np.full((P, c.aff_terms, c.aff_exprs), -1, np.int32),
        "aff_vals": np.full((P, c.aff_terms, c.aff_exprs, c.aff_vals), -2, np.int32),
        "aff_num": np.zeros((P, c.aff_terms, c.aff_exprs), np.int32),
        "aff_term_valid": np.zeros((P, c.aff_terms), np.bool_),
        "aff_match_name_row": np.full((P, c.aff_terms), -1, np.int32),
        "pref_key": np.full((P, c.pref_terms, c.aff_exprs), -1, np.int32),
        "pref_op": np.full((P, c.pref_terms, c.aff_exprs), -1, np.int32),
        "pref_vals": np.full((P, c.pref_terms, c.aff_exprs, c.aff_vals), -2, np.int32),
        "pref_num": np.zeros((P, c.pref_terms, c.aff_exprs), np.int32),
        "pref_weight": np.zeros((P, c.pref_terms), np.float32),
        "pref_term_valid": np.zeros((P, c.pref_terms), np.bool_),
        "tol_key": np.full((P, c.tol_max), -9, np.int32),
        "tol_op": np.full((P, c.tol_max), -1, np.int32),
        "tol_val": np.full((P, c.tol_max), -2, np.int32),
        "tol_effect": np.full((P, c.tol_max), -1, np.int32),
        "spread_key": np.full((P, c.spread_max), -1, np.int32),
        "spread_sid": np.full((P, c.spread_max), -1, np.int32),
        "spread_skew": np.zeros((P, c.spread_max), np.int32),
        "spread_hard": np.zeros((P, c.spread_max), np.bool_),
        "spread_self": np.zeros((P, c.spread_max), np.bool_),
        "paff_sid": np.full((P, c.pod_aff_max), -1, np.int32),
        "paff_key": np.full((P, c.pod_aff_max), -1, np.int32),
        "paff_self": np.zeros((P, c.pod_aff_max), np.bool_),
        "panti_sid": np.full((P, c.pod_anti_max), -1, np.int32),
        "panti_key": np.full((P, c.pod_anti_max), -1, np.int32),
        "ppref_sid": np.full((P, c.pod_pref_max), -1, np.int32),
        "ppref_key": np.full((P, c.pod_pref_max), -1, np.int32),
        "ppref_w": np.zeros((P, c.pod_pref_max), np.float32),
        "match_sel": np.zeros((P, S), np.bool_),
        "match_svc": np.zeros((P, S), np.bool_),
        "match_eterm": np.zeros((P, T), np.bool_),
        "eterm_add": np.zeros((P, T), np.float32),
        "port_mask": np.zeros((P, c.pv_cap), np.bool_),
        "image_ids": np.full((P, c.images_max), -1, np.int32),
        "image_total": np.zeros(P, np.float32),
        "ctrl_id": np.full(P, -1, np.int32),
        "priority": np.zeros(P, np.int32),
    }
    fallback = np.zeros(P, np.bool_)

    for i, pod in enumerate(pods):
        d = per_pod[i]
        ns = pod.metadata.namespace
        spec = pod.spec
        b["valid"][i] = True
        b["priority"][i] = pod.priority

        req = enc.encode_resources(compute_pod_resource_request(pod), ceil=True)
        nz = enc.encode_resources(
            compute_pod_resource_request(pod, non_zero=True), ceil=True
        )
        b["req"][i] = zpad(req, c.r_cap)
        b["nonzero_req"][i] = zpad(nz, c.r_cap)
        b["req"][i, RES_PODS] = 1
        b["nonzero_req"][i, RES_PODS] = 1

        if spec.node_name:
            row = enc.row_of(spec.node_name)
            b["node_name_row"][i] = row if row >= 0 else -2

        b["tolerates_unschedulable"][i] = tolerations_tolerate_taint(
            spec.tolerations, Taint(TAINT_NODE_UNSCHEDULABLE, "", v1.TAINT_NO_SCHEDULE)
        )

        # node_selector map (AND of In exprs)
        items = list(spec.node_selector.items())
        if len(items) > c.ns_max:
            d["fallback"] = True
            items = items[: c.ns_max]
        for j, (k, v) in enumerate(items):
            b["ns_key"][i, j] = max(enc.key_vocab.get(k), -2)
            b["ns_op"][i, j] = ENC_OP_IN
            b["ns_vals"][i, j, 0] = max(enc.val_vocab.get(v), -2)

        # required node affinity
        node_aff = spec.affinity.node_affinity if spec.affinity else None
        if node_aff and node_aff.required and node_aff.required.terms:
            terms = node_aff.required.terms
            if len(terms) > c.aff_terms:
                d["fallback"] = True
                terms = terms[: c.aff_terms]
            b["aff_has"][i] = True
            for t_i, term in enumerate(terms):
                b["aff_term_valid"][i, t_i] = True
                exprs = term.match_expressions
                if len(exprs) > c.aff_exprs:
                    d["fallback"] = True
                    exprs = exprs[: c.aff_exprs]
                for e_i, r in enumerate(exprs):
                    e = _encode_expr(enc, r, c.aff_vals)
                    if e is None:
                        d["fallback"] = True
                        continue
                    key_id, op, vids, num = e
                    b["aff_key"][i, t_i, e_i] = key_id
                    b["aff_op"][i, t_i, e_i] = op
                    for v_i, vid in enumerate(vids):
                        b["aff_vals"][i, t_i, e_i, v_i] = vid
                    b["aff_num"][i, t_i, e_i] = num
                # matchFields: only metadata.name In [x] supported
                for mf in term.match_fields:
                    if mf.key == "metadata.name" and mf.operator == OP_IN and len(mf.values) == 1:
                        row = enc.row_of(mf.values[0])
                        b["aff_match_name_row"][i, t_i] = row if row >= 0 else enc.cfg.n_cap
                    else:
                        d["fallback"] = True

        # preferred node affinity
        if node_aff and node_aff.preferred:
            prefs = node_aff.preferred
            if len(prefs) > c.pref_terms:
                d["fallback"] = True
                prefs = prefs[: c.pref_terms]
            for t_i, pt in enumerate(prefs):
                b["pref_term_valid"][i, t_i] = True
                b["pref_weight"][i, t_i] = float(pt.weight)
                exprs = pt.preference.match_expressions
                if len(exprs) > c.aff_exprs:
                    d["fallback"] = True
                    exprs = exprs[: c.aff_exprs]
                for e_i, r in enumerate(exprs):
                    e = _encode_expr(enc, r, c.aff_vals)
                    if e is None:
                        d["fallback"] = True
                        continue
                    key_id, op, vids, num = e
                    b["pref_key"][i, t_i, e_i] = key_id
                    b["pref_op"][i, t_i, e_i] = op
                    for v_i, vid in enumerate(vids):
                        b["pref_vals"][i, t_i, e_i, v_i] = vid
                    b["pref_num"][i, t_i, e_i] = num

        # tolerations
        tols = spec.tolerations
        if len(tols) > c.tol_max:
            d["fallback"] = True
            tols = tols[: c.tol_max]
        for j, tol in enumerate(tols):
            if tol.key == "":
                b["tol_key"][i, j] = -1  # wildcard
            else:
                b["tol_key"][i, j] = max(enc.key_vocab.get(tol.key), -2)
            b["tol_op"][i, j] = (
                TOL_OP_EXISTS if tol.operator == v1.TOLERATION_OP_EXISTS else TOL_OP_EQUAL
            )
            b["tol_val"][i, j] = max(enc.val_vocab.get(tol.value), -2)
            b["tol_effect"][i, j] = _TOL_EFFECT.get(tol.effect, -1)

        for j, (key_id, sid, skew, hard, self_m) in enumerate(d["spreads"]):
            b["spread_key"][i, j] = key_id
            b["spread_sid"][i, j] = sid
            b["spread_skew"][i, j] = skew
            b["spread_hard"][i, j] = hard
            b["spread_self"][i, j] = self_m

        for j, (sid, key_id, self_m) in enumerate(d["paff"]):
            b["paff_sid"][i, j] = sid
            b["paff_key"][i, j] = key_id
            b["paff_self"][i, j] = self_m
        for j, (sid, key_id) in enumerate(d["panti"]):
            b["panti_sid"][i, j] = sid
            b["panti_key"][i, j] = key_id
        for j, (sid, key_id, w) in enumerate(d["ppref"]):
            b["ppref_sid"][i, j] = sid
            b["ppref_key"][i, j] = key_id
            b["ppref_w"][i, j] = w

        # cross-match tensors
        b["match_sel"][i, : len(enc.sel_vocab)] = enc._match_vec(
            ns, pod.metadata.labels
        )
        b["match_svc"][i] = b["match_sel"][i] & svc_mask
        for t_i, et in enumerate(enc.eterm_vocab.items):
            b["match_eterm"][i, t_i] = et.predicate.matches(ns, pod.metadata.labels)
        for tid, w in zip(d["eterm_ids"], d["eterm_ws"]):
            b["eterm_add"][i, tid] += w

        for pid in d["port_ids"]:
            b["port_mask"][i, pid] = True

        # images
        imgs = []
        total = 0.0
        for cont in spec.containers:
            if cont.image:
                iid = enc.image_vocab.get(cont.image)
                if iid >= 0:
                    imgs.append(iid)
        imgs = sorted(set(imgs))[: c.images_max]
        for j, iid in enumerate(imgs):
            b["image_ids"][i, j] = iid

        # controller ref for NodePreferAvoidPods
        for ref in pod.metadata.owner_references:
            if ref.controller:
                b["ctrl_id"][i] = enc.avoid_vocab.get(f"{ref.kind}/{ref.name}")
                break

        fallback[i] = d["fallback"]

    batch = PodBatch(**{k: jnp.asarray(v) for k, v in b.items()})
    batch_np = PodBatch(**b)
    return EncodedBatch(
        batch=batch, pods=list(pods), fallback=fallback, batch_np=batch_np
    )
