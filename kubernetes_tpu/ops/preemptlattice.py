"""Vectorized victim selection: the preemption engine's batched pass.

The host ``Preemptor`` (scheduler/preemption.py) answers "which node, which
victims" with an O(pods x nodes x victims) walk: per candidate node it
clones the NodeInfo, removes every lower-priority pod, re-runs the filter
chain, then reprieves victims one by one. Under a 1k-pod high-priority
burst over a full cluster that walk IS the scheduling stall.

This kernel replaces the scan half of that work with ONE device pass over
a pinned snapshot generation, for a whole wave of unschedulable pods:

* **Priority-ascending cumulative-free scan.** The snapshot already holds
  requested resources banded by pod priority (``prio_req[N, PB, R]``,
  ``band_prio[PB]``). Bands sort ascending by priority; a cumulative sum
  over the sorted axis yields, per node, "resources freed by evicting
  every pod of the b cheapest priority bands". Because a preemptor of
  priority p may evict exactly the bands with priority < p — a PREFIX of
  the sorted axis — the minimal victim set per (pod, node) is the first
  prefix whose cumulative free fits the pod's request: one argmax over a
  [P, N, PB] boolean, no per-victim host work.

* **PDB budget column.** ``pdb_blocked[N, PB]`` (maintained from the
  disruption controller's published ``disruptions_allowed``, see
  ``SnapshotEncoder.update_pdb_blocked``) counts pods per band whose
  eviction would violate an exhausted budget. Its cumulative prefix is the
  kernel's first ranking criterion, so PDB-violating rows (nodes) are
  deprioritized exactly like ``pickOneNodeForPreemption``'s first
  criterion — as a RANKING signal. The exact per-victim budget countdown
  (list-order consumption, overlapping PDBs) stays in the host reprieve
  loop that validates the winner.

* **On-device top-K lexicographic node ranking.** Per pod, nodes rank by
  (pdb violations, max victim priority, sum of victim priorities, victim
  count) — criteria 1-4 of ``pickOneNodeForPreemption`` computed from the
  band prefixes — lowest row index breaking remaining ties, and the K
  best rows return ([P, K]-shaped readback, not [P, N] stat planes).

Division of labor (and the documented tie-breaks):

The kernel's stats are PRE-REPRIEVE band aggregates: the host oracle's
key is computed after the reprieve loop shrinks the victim set, and its
final criterion (latest victim start time) has no device column. The
engine therefore treats the kernel as a RANKER, never an oracle: the
scheduler hands the K ranked rows to ``Preemptor.preempt`` as the
candidate set, so the EXACT selection (filters + reprieve + PDB
countdown + the full 5-criterion node pick) runs on K nodes instead of
every resolvable node — and runs before any eviction, so a wrong
eviction is structurally impossible regardless of ranking quality. A
candidate set the oracle fully rejects is a counted disagreement that
falls back to the full host scan. Documented tie-break classes (the
"modulo" in the differential-corpus acceptance):

  1. equal-key nodes may resolve differently (the oracle breaks final
     ties in sorted-name order over ALL viable nodes; the engine over
     its K candidates);
  2. band-prefix vs post-reprieve ranking: when the reprieve refinement
     demotes every one of the K kernel-ranked rows below a node outside
     the list, the engine picks the best of its K (the chosen node's
     victim set is still that node's exact oracle selection — counted
     by the sampled differential oracle, never evicting wrongly).

Readback flows through ``validate_preempt_outputs`` (the kernel-output
guard discipline of ops/lattice.validate_batch_outputs) before anything
acts on it.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .encoding import DeviceSnapshot, PodBatch, RES_PODS
from .lattice import _pod_static

_I32_MAX = jnp.iinfo(jnp.int32).max


# ranked candidate rows per pod: the host oracle's exact per-node victim
# selection runs on AT MOST this many nodes per failed pod (vs every
# resolvable node in the host walk). 4 mirrors the guard-sample sizing:
# overwhelmingly the oracle's winner is the kernel's rank-1; the extra
# ranks absorb the band-prefix-vs-reprieve refinement cases.
PREEMPT_TOP_K = 4


class PreemptBatchResult(NamedTuple):
    """One batched victim-selection pass, [P]-shaped per pod."""

    node: Any  # [P] int32 top-ranked node row, -1 = preemption cannot help
    cand: Any  # [P, K] int32 ranked candidate rows (rank 0 == node), -1 pad
    threshold_prio: Any  # [P] int32 max victim priority (band threshold)
    victims: Any  # [P] int32 pod count of the minimal victim band prefix
    violations: Any  # [P] int32 PDB-blocked pods in that prefix (budget col)
    helpful: Any  # [P, N] bool — nodes where evicting lower-priority pods
    # makes the pod fit (the candidate-narrowing mask; superset refinement
    # of lattice.preempt_whatif: adds the minimal-prefix statistics)


def _preempt_select_impl(
    snap: DeviceSnapshot, batch: PodBatch, priority: jnp.ndarray
) -> PreemptBatchResult:
    statics = jax.vmap(lambda bp: _pod_static(snap, bp))(batch)
    static_ok = statics[0]  # [P, N] — UnschedulableAndUnresolvable boundary
    pb = snap.band_prio.shape[0]
    r_cap = snap.allocatable.shape[1]

    # sort bands ascending by priority; empty bands (I32_MAX) land last
    # and are never eligible (no real pod priority reaches I32_MAX)
    order = jnp.argsort(snap.band_prio)
    bp_sorted = snap.band_prio[order]  # [PB]
    prio_sorted = jnp.take(snap.prio_req, order, axis=1)  # [N, PB, R]
    pdb_sorted = jnp.take(snap.pdb_blocked, order, axis=1)  # [N, PB]
    counts_sorted = prio_sorted[:, :, RES_PODS]  # [N, PB] pods per band

    cumfree = jnp.cumsum(prio_sorted, axis=1)  # [N, PB, R]
    cum_cnt = jnp.cumsum(counts_sorted, axis=1)  # [N, PB]
    cum_viol = jnp.cumsum(pdb_sorted, axis=1)  # [N, PB]
    band_f = jnp.where(bp_sorted == _I32_MAX, 0, bp_sorted).astype(jnp.float32)
    cum_prio_sum = jnp.cumsum(
        band_f[None, :] * counts_sorted.astype(jnp.float32), axis=1
    )  # [N, PB] Σ victim priorities per prefix (f32: ranking, not oracle)

    free0 = snap.allocatable - snap.requested  # [N, R]
    # a preemptor of priority p may evict bands with priority < p: the
    # eligible set is a PREFIX of the sorted axis
    elig = bp_sorted[None, :] < priority[:, None]  # [P, PB]

    # fits[p, n, b]: evicting the first b+1 sorted bands makes pod p fit
    # node n. Band-static unroll keeps every intermediate [P, N]-shaped —
    # a broadcast [P, N, PB, R] compare would transiently cost GiBs at
    # bench scale (1k pods x 5k-row snapshots).
    fits_bands = []
    for b in range(pb):
        avail = free0 + cumfree[:, b, :]  # [N, R]
        ok = static_ok
        for r in range(r_cap):
            req_r = batch.req[:, r][:, None]  # [P, 1]
            ok = ok & ((req_r == 0) | (req_r <= avail[None, :, r]))
        # prefix must be eligible and non-empty (a fit with zero victims
        # is not a preemption — those pods never reach the failed set on
        # resource grounds, but static filters can put them here)
        ok = ok & elig[:, b][:, None] & (cum_cnt[None, :, b] > 0)
        fits_bands.append(ok)
    fits = jnp.stack(fits_bands, axis=2) & batch.valid[:, None, None]

    helpful = jnp.any(fits, axis=2)  # [P, N]
    bstar = jnp.argmax(fits, axis=2)  # first fitting prefix (minimal set)

    def at_bstar(a):  # [N, PB] -> [P, N] gathered at each pod's prefix
        arr = jnp.broadcast_to(a[None], bstar.shape + (pb,))
        return jnp.take_along_axis(arr, bstar[:, :, None], axis=2)[..., 0]

    vic_pn = at_bstar(cum_cnt)
    viol_pn = at_bstar(cum_viol)
    sum_pn = at_bstar(cum_prio_sum)
    maxp_pn = jnp.broadcast_to(bp_sorted[None, None, :], bstar.shape + (pb,))
    maxp_pn = jnp.take_along_axis(maxp_pn, bstar[:, :, None], axis=2)[..., 0]

    # top-K lexicographic node ranking (pickOneNodeForPreemption criteria
    # 1-4 on the band-prefix stats), lowest row index breaking remaining
    # ties: K passes of pick-then-mask. The HOST then runs the exact
    # oracle (reprieve + PDB countdown + start-time criterion) on just
    # these K rows — the ranking only has to land the oracle's winner in
    # the list, not reproduce its final refinement.
    n = helpful.shape[1]
    crits = (
        viol_pn.astype(jnp.float32),
        maxp_pn.astype(jnp.float32),
        sum_pn,
        vic_pn.astype(jnp.float32),
    )
    avail = helpful
    ranked = []
    for _ in range(PREEMPT_TOP_K):
        mask = avail
        for crit in crits:
            c = jnp.where(mask, crit, jnp.inf)
            best = jnp.min(c, axis=1, keepdims=True)
            mask = mask & (c == best)
        pick = jnp.argmax(mask, axis=1).astype(jnp.int32)
        got = jnp.any(mask, axis=1)
        ranked.append(jnp.where(got, pick, -1))
        avail = avail & ~(
            got[:, None] & (jnp.arange(n)[None, :] == pick[:, None])
        )
    cand = jnp.stack(ranked, axis=1)  # [P, K]
    node = cand[:, 0]
    found = node >= 0

    def at_node(a):  # [P, N] -> [P] gathered at the top-ranked row
        idx = jnp.clip(node, 0, a.shape[1] - 1)[:, None]
        return jnp.take_along_axis(a, idx, axis=1)[:, 0]

    zero = jnp.zeros_like(node)
    return PreemptBatchResult(
        node=node,
        cand=cand,
        threshold_prio=jnp.where(found, at_node(maxp_pn), zero),
        victims=jnp.where(found, at_node(vic_pn), zero),
        violations=jnp.where(found, at_node(viol_pn), zero),
        helpful=helpful,
    )


# non-donating on purpose: the pass READS a pinned snapshot generation a
# concurrent wave launch may be advancing past — fresh output buffers only
preempt_select = jax.jit(_preempt_select_impl)


# -- kernel-output guards (the lattice.validate_batch_outputs discipline) ----

GUARD_PREEMPT_ROW = "preempt_row_out_of_range"
GUARD_PREEMPT_EMPTY = "preempt_empty_victim_set"


def validate_preempt_outputs(node, victims, n_rows: int, cand=None):
    """Structural validation of a read-back preemption batch BEFORE any
    victim selection acts on it: every proposed row (top-ranked AND the
    lower-ranked candidates) must name a live node row (-1 is the only
    legitimate "can't help" / pad sentinel — any other negative or
    past-capacity index would mis-index row_names), and a proposed node
    must claim at least one victim (a zero-victim proposal is a corrupt
    prefix scan: nothing to evict cannot make an infeasible pod fit).
    Returns a trip reason or None."""
    node = np.asarray(node)
    proposed = node != -1
    planes = [node] if cand is None else [node, np.asarray(cand)]
    for plane in planes:
        rows = plane[plane != -1]
        if rows.size and (int(rows.min()) < 0 or int(rows.max()) >= n_rows):
            return GUARD_PREEMPT_ROW
    if not proposed.any():
        return None
    if victims is not None:
        v = np.asarray(victims)[proposed]
        if v.size and int(v.min()) < 1:
            return GUARD_PREEMPT_EMPTY
    return None
