"""Host-callback delivery of the wave kernel's fast index payload.

The depth-infinity variant of the split-phase readback
(`host_callback_binds` in KubeSchedulerConfiguration): instead of the
host issuing a device->host fetch for the chosen/placed/deferred index
vectors, the kernel itself posts them through a
``jax.experimental.io_callback`` the moment it resolves on device. The
scheduler allocates a ticket per launch, threads it through the kernel
as a traced scalar, and the callback lands the payload here; the resolve
path consumes it without ever blocking on a device sync — the device can
keep chaining wave N+1 while the host observes wave N.

The registry is a plain ticket-keyed dict + per-ticket Event. Callbacks
arrive on XLA's callback threads; consumers are the scheduling loop. A
ticket whose batch dies before resolution (launch failure, sibling
quarantine) is ``discard``ed so the registry can't grow unboundedly —
a late callback for a discarded ticket is dropped on the floor.
"""

from __future__ import annotations

import itertools
import threading
from typing import Dict, Optional, Tuple

import numpy as np

__all__ = ["new_ticket", "deliver", "ready", "take", "discard", "backlog"]

_lock = threading.Lock()
_payloads: Dict[int, Tuple[np.ndarray, np.ndarray, np.ndarray]] = {}
_events: Dict[int, threading.Event] = {}
_tickets = itertools.count(1)


def new_ticket() -> int:
    """Allocate a delivery slot; the caller must eventually take() or
    discard() it."""
    t = next(_tickets)
    with _lock:
        _events[t] = threading.Event()
    return t


def deliver(ticket, chosen, placed, deferred) -> None:
    """io_callback target: land one wave's fast index payload. Runs on
    an XLA callback thread — copies to host numpy and signals the
    consumer. A discarded ticket's late delivery is dropped."""
    t = int(np.asarray(ticket))
    payload = (
        np.asarray(chosen),
        np.asarray(placed),
        np.asarray(deferred),
    )
    with _lock:
        ev = _events.get(t)
        if ev is None:
            return
        _payloads[t] = payload
    ev.set()


def ready(ticket: int) -> bool:
    with _lock:
        return ticket in _payloads


def take(
    ticket: int, timeout: float = 0.0
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Consume a ticket's payload, waiting up to `timeout` seconds for
    the callback to fire. Returns None on timeout or unknown ticket (the
    caller falls back to a plain device fetch); either way the ticket is
    retired."""
    with _lock:
        ev = _events.get(ticket)
    if ev is None:
        return None
    if timeout > 0:
        ev.wait(timeout)
    with _lock:
        _events.pop(ticket, None)
        return _payloads.pop(ticket, None)


def discard(ticket: int) -> None:
    """Retire a ticket whose batch will never be resolved."""
    with _lock:
        _events.pop(ticket, None)
        _payloads.pop(ticket, None)


def backlog() -> int:
    """Outstanding (allocated, unconsumed) tickets — test/debug helper."""
    with _lock:
        return len(_events)
