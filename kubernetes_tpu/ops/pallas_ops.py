"""Pallas TPU kernels for the lattice's hottest inner op.

The wave kernel re-evaluates resource fit for every (template, node) each
conflict-resolution wave (`fits_w` in wavelattice.py) and folds scores over
the resource axis — a [TPL, N, R] broadcast XLA materializes per wave.
This module provides the fused alternative: one Pallas pass per node tile
computes the fit mask AND the least-allocated score without materializing
the [TPL, N, R] intermediate in HBM (SURVEY §2's "XLA/Mosaic-compiled
Pallas kernels" for the batched filter/score path).

Layout: resources ride the SUBLANE axis (R padded to 8) and nodes the LANE
axis (tiles of 128), per the TPU tiling table in the pallas guide; the
template axis is a small VMEM-resident broadcast.

Two entry points:
  * `fit_mask` — the mask alone, in the snapshot's natural [N, R] layout;
    THIS is what the wave kernel calls (config `use_pallas_fit`).
  * `fit_mask_least_alloc` — the mask fused with a least-allocated-style
    score in one pass; standalone and oracle-tested, but NOT wired into
    the wave kernel, deliberately: round 4 removed the score stage's only
    [TPL, N, R] intermediate (wavelattice now computes the cpu/mem
    fraction planes directly as [TPL, N] ops), so there is nothing heavy
    left for a fused score to save — the mask (`fit_mask`, re-evaluated
    every wave) remains the one op worth a Pallas pass. Kept as the
    template for future fused score work (e.g. extended-resource-heavy
    clusters where R grows past the pad).

`fit_mask_least_alloc(req, free, alloc)`:
    req   [TPL, R] i32   per-template requests
    free  [R, N]  i32    allocatable - requested, transposed
    alloc [R, N]  i32    allocatable, transposed
  ->
    mask  [TPL, N] bool  all-resources fit (req==0 columns always fit)
    score [TPL, N] f32   mean over requested resources of (free-req)/alloc

On CPU backends the kernel runs in interpreter mode (bit-accurate, slow) —
tests pin it against the jnp reference; `use_pallas` wiring in the wave
kernel is config-gated so enabling it on hardware is a one-flag change.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

BLOCK_N = 512  # nodes per tile (lane axis: multiple of 128)
R_PAD = 8  # resource sublanes


def _kernel(req_ref, free_ref, alloc_ref, mask_ref, score_ref):
    req = req_ref[:]  # [TPL, R]
    free = free_ref[:]  # [R, BN]
    alloc = alloc_ref[:]  # [R, BN]
    reqb = req[:, :, None]  # [TPL, R, 1]
    fits = (reqb == 0) | (reqb <= free[None, :, :])  # [TPL, R, BN]
    mask_ref[:] = jnp.all(fits, axis=1)  # [TPL, BN]
    # least-allocated: mean over REQUESTED resources of (free-req)/alloc
    a = jnp.maximum(alloc[None, :, :], 1).astype(jnp.float32)
    frac = (free[None, :, :] - reqb).astype(jnp.float32) / a
    w = (reqb > 0).astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(w, axis=1), 1.0)  # [TPL, BN]
    score_ref[:] = jnp.sum(frac * w, axis=1) / denom


@functools.partial(jax.jit, static_argnames=("interpret",))
def fit_mask_least_alloc(req, free, alloc, interpret: bool = False):
    """See module docstring. N must be a multiple of BLOCK_N (the callers'
    node capacity n_cap is a power of two >= 128)."""
    from jax.experimental import pallas as pl

    tpl = req.shape[0]
    r, n = free.shape
    assert r == R_PAD and req.shape[1] == R_PAD, (req.shape, free.shape)
    assert n % BLOCK_N == 0, n
    grid = (n // BLOCK_N,)
    return pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tpl, R_PAD), lambda i: (0, 0)),
            pl.BlockSpec((R_PAD, BLOCK_N), lambda i: (0, i)),
            pl.BlockSpec((R_PAD, BLOCK_N), lambda i: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((tpl, BLOCK_N), lambda i: (0, i)),
            pl.BlockSpec((tpl, BLOCK_N), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((tpl, n), jnp.bool_),
            jax.ShapeDtypeStruct((tpl, n), jnp.float32),
        ],
        interpret=interpret,
    )(req, free, alloc)


def fit_mask_least_alloc_reference(req, free, alloc):
    """Pure-jnp oracle (what XLA runs today): identical math, materialized
    [TPL, R, N] intermediate."""
    reqb = jnp.asarray(req)[:, :, None]
    free = jnp.asarray(free)[None, :, :]
    alloc = jnp.asarray(alloc)[None, :, :]
    mask = jnp.all((reqb == 0) | (reqb <= free), axis=1)
    a = jnp.maximum(alloc, 1).astype(jnp.float32)
    frac = (free - reqb).astype(jnp.float32) / a
    w = (reqb > 0).astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(w, axis=1), 1.0)
    score = jnp.sum(frac * w, axis=1) / denom
    return mask, score


def _mask_kernel(req_ref, free_ref, mask_ref):
    reqb = req_ref[:][:, :, None]  # [TPL, R, 1]
    fits = (reqb == 0) | (reqb <= free_ref[:][None, :, :])
    mask_ref[:] = jnp.all(fits, axis=1)


def fit_mask(req, free, interpret: bool = False):
    """[TPL, N] resource-fit mask, fused over node tiles (the wave
    kernel's `fits0`/`fits_w` without the [TPL, N, R] HBM intermediate).
    req [TPL, R] i32, free [N, R] i32 (natural layout; transposed and
    padded here at trace time, static shapes). Falls back to the jnp
    broadcast when the shapes don't tile (R > 8 after extended-resource
    growth, or N not 128-divisible)."""
    from jax.experimental import pallas as pl

    tpl, r = req.shape
    n = free.shape[0]
    block = next((b for b in (512, 256, 128) if n % b == 0), None)
    if r > R_PAD or block is None:
        reqb = req[:, :, None]
        return jnp.all((reqb == 0) | (reqb <= free.T[None]), axis=1)
    tpl_pad = max(8, tpl)
    rq = jnp.zeros((tpl_pad, R_PAD), jnp.int32).at[:tpl, :r].set(req)
    fr = jnp.zeros((R_PAD, n), jnp.int32).at[:r, :].set(free.T)
    out = pl.pallas_call(
        _mask_kernel,
        grid=(n // block,),
        in_specs=[
            pl.BlockSpec((tpl_pad, R_PAD), lambda i: (0, 0)),
            pl.BlockSpec((R_PAD, block), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((tpl_pad, block), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((tpl_pad, n), jnp.bool_),
        interpret=interpret,
    )(rq, fr)
    return out[:tpl]


def pad_inputs(req: np.ndarray, free: np.ndarray, alloc: np.ndarray):
    """Host helper: pad (req [TPL, R], free/alloc [N, R]) to the kernel's
    layout ([TPL, 8], [8, N'] transposed, N' multiple of BLOCK_N)."""
    tpl, r = req.shape
    n = free.shape[0]
    n_pad = ((n + BLOCK_N - 1) // BLOCK_N) * BLOCK_N
    rq = np.zeros((tpl, R_PAD), np.int32)
    rq[:, :r] = req
    fr = np.zeros((R_PAD, n_pad), np.int32)
    fr[:r, :n] = free.T
    al = np.zeros((R_PAD, n_pad), np.int32)
    al[:r, :n] = alloc.T
    return rq, fr, al, n
