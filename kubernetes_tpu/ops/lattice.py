"""The fused filter→score→select lattice: one XLA program per pod batch.

This kernel absorbs everything between Schedule entry and selectHost in the
reference hot path (generic_scheduler.go:150-235: findNodesThatFitPod +
prioritizeNodes + selectHost), for a whole batch of pods at once:

* **Stage A** (vmap over pods, carry-free): plugins whose verdict cannot be
  changed by in-batch placements — NodeName, NodeUnschedulable, NodeAffinity
  (+nodeSelector), TaintToleration, ImageLocality, NodePreferAvoidPods. These
  also define the "unresolvable" failure class the preemption pass needs
  (UnschedulableAndUnresolvable semantics, framework interface.go:54-99).

* **Stage B** (lax.scan over pods): plugins that read cluster occupancy —
  NodeResourcesFit, NodePorts, PodTopologySpread, InterPodAffinity — against
  snapshot + an in-batch carry (requested/sel_counts/eterm/port deltas of the
  pods already committed this batch). The scan IS the conflict resolution:
  it reproduces the reference's strictly-serial scheduleOne semantics while
  staying on-device, so a batch of P pods costs one kernel launch instead of
  P scheduling cycles.

Scores mirror framework.RunScorePlugins (framework.go:503-580): each plugin
produces a [N] score normalized to 0..100 over feasible nodes, then a
weighted sum. Host selects via on-device argmax with uniform random
tie-break (selectHost's reservoir sampling, generic_scheduler.go:235).

Sharding: every [N]- or [N,·]-shaped value may be sharded over the mesh's
"nodes" axis; reductions (max/argmax/segment sums over domains) become XLA
collectives over ICI under pjit (see parallel/sharded.py).
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .encoding import (
    DeviceSnapshot,
    EFFECT_NO_EXECUTE,
    EFFECT_NO_SCHEDULE,
    EFFECT_PREFER_NO_SCHEDULE,
    ENC_OP_EXISTS,
    ENC_OP_GT,
    ENC_OP_IN,
    ENC_OP_LT,
    ENC_OP_NOT_EXISTS,
    ENC_OP_NOT_IN,
    ETERM_AFF_PREF,
    ETERM_AFF_REQ,
    ETERM_ANTI_PREF,
    ETERM_ANTI_REQ,
    PodBatch,
    RES_CPU,
    RES_MEM,
)
from .batch import TOL_OP_EXISTS

INT_MIN = jnp.iinfo(jnp.int32).min

# Score component indices (fixed order; weights vector selects the profile).
SC_LEAST_ALLOC = 0
SC_MOST_ALLOC = 1
SC_BALANCED = 2
SC_REQ_TO_CAP = 3
SC_NODE_AFFINITY = 4
SC_TAINT = 5
SC_IMAGE = 6
SC_PREFER_AVOID = 7
SC_TOPO_SPREAD = 8
SC_INTERPOD = 9
SC_SELECTOR_SPREAD = 10  # DefaultPodTopologySpread (same-service pod count)
# heterogeneity/cost components (encoding's per-node column family):
# normalized-inverted within the feasible set, so a cheaper / lower-energy
# node scores higher; an unlabeled (all-zero) cluster scores flat
SC_COST = 11  # cost-per-hour (snap.cost_milli)
SC_ENERGY = 12  # energy proxy (snap.energy_milli)
NUM_SCORE_COMPONENTS = 13

# Default profile weights: all 1 except NodePreferAvoidPods=10000
# (algorithmprovider/registry.go:61-131).
DEFAULT_WEIGHTS = np.ones(NUM_SCORE_COMPONENTS, np.float32)
DEFAULT_WEIGHTS[SC_PREFER_AVOID] = 10000.0
# MostAllocated / RequestedToCapacityRatio are not in the default profile.
DEFAULT_WEIGHTS[SC_MOST_ALLOC] = 0.0
DEFAULT_WEIGHTS[SC_REQ_TO_CAP] = 0.0
# cost/energy are policy opt-ins, never part of the reference default
DEFAULT_WEIGHTS[SC_COST] = 0.0
DEFAULT_WEIGHTS[SC_ENERGY] = 0.0


def _profile(**overrides) -> np.ndarray:
    w = DEFAULT_WEIGHTS.copy()
    for name, val in overrides.items():
        w[globals()[name]] = val
    return w


# Named score policies: pluggable score matrices selected by a RUNTIME
# weight vector (a kernel input, not a compile-time constant — swapping
# policies never recompiles). `Scheduler.set_score_policy` accepts a name
# here or a raw [NUM_SCORE_COMPONENTS] vector; the ROADMAP-5 policy gym
# tunes these same vectors online.
WEIGHT_PROFILES = {
    "default": DEFAULT_WEIGHTS.copy(),
    # bin-pack: fill the fullest feasible node first
    "pack": _profile(SC_LEAST_ALLOC=0.0, SC_MOST_ALLOC=1.0),
    # spread: the default profile's LeastAllocated already spreads; name it
    "spread": DEFAULT_WEIGHTS.copy(),
    # heterogeneity/cost: cheapest feasible node dominates, pack breaks ties
    "cheapest": _profile(
        SC_LEAST_ALLOC=0.0, SC_MOST_ALLOC=1.0, SC_COST=100.0
    ),
    # energy-aware: minimize the fleet energy proxy, pack breaks ties
    "energy": _profile(
        SC_LEAST_ALLOC=0.0, SC_MOST_ALLOC=1.0, SC_ENERGY=100.0
    ),
}


def weights_for_policy(policy) -> np.ndarray:
    """Resolve a policy name or raw vector into a weight vector. Unknown
    names raise (a typo'd policy must fail loudly at config time, not
    schedule with silently-default weights). Raw vectors are validated
    for shape, dtype-coercibility AND finiteness here — a NaN/inf weight
    would otherwise poison every score in the next kernel launch and
    surface as an inscrutable guard trip instead of a ValueError at the
    call that introduced it (the seam the policy-gym promotion gate
    rejects poisoned candidates through)."""
    if isinstance(policy, str):
        try:
            return WEIGHT_PROFILES[policy].copy()
        except KeyError:
            raise ValueError(
                f"unknown score policy {policy!r}; known: "
                f"{sorted(WEIGHT_PROFILES)}"
            ) from None
    try:
        w = np.asarray(policy, np.float32)
    except (TypeError, ValueError) as e:
        raise ValueError(
            f"score weight vector is not float32-coercible: {e}"
        ) from None
    if w.shape != (NUM_SCORE_COMPONENTS,):
        raise ValueError(
            f"score weight vector must have shape ({NUM_SCORE_COMPONENTS},), "
            f"got {w.shape}"
        )
    if not np.isfinite(w).all():
        bad = np.flatnonzero(~np.isfinite(w)).tolist()
        raise ValueError(
            f"score weight vector has non-finite components at {bad}"
        )
    return w.copy()


# Names a promoted/tuned vector may never shadow: the built-in profiles
# are documented identities ("cheapest" must keep meaning cheapest).
_BUILTIN_PROFILES = frozenset(WEIGHT_PROFILES)


def register_weight_profile(
    name: str, vec, overwrite: bool = False
) -> np.ndarray:
    """Register a named weight profile at runtime so promoted vectors get
    STABLE names in metrics labels, SIGUSR2 dumps and the persisted
    score-policy object (the policy gym calls this before
    ``set_score_policy``; an HA standby calls it while adopting the
    persisted policy). The vector passes the full ``weights_for_policy``
    raw-vector validation; built-in profile names are reserved, and
    re-registering a tuned name requires ``overwrite=True`` unless the
    vector is unchanged (idempotent re-adoption)."""
    if not name or not isinstance(name, str):
        raise ValueError("profile name must be a non-empty string")
    w = weights_for_policy(np.asarray(vec))
    if name in _BUILTIN_PROFILES:
        raise ValueError(
            f"profile name {name!r} is reserved (built-in profile)"
        )
    existing = WEIGHT_PROFILES.get(name)
    if existing is not None and not overwrite and not np.array_equal(
        existing, w
    ):
        raise ValueError(
            f"profile {name!r} already registered with different weights "
            "(pass overwrite=True to replace)"
        )
    WEIGHT_PROFILES[name] = w.copy()
    return w

IMG_MIN_THRESHOLD = 23.0 * 1024 * 1024  # imagelocality minThreshold
IMG_MAX_THRESHOLD = 1000.0 * 1024 * 1024


class BatchResult(NamedTuple):
    chosen: Any  # [P] int32 node row, -1 = unschedulable (or invalid pod)
    score: Any  # [P] float32 winning weighted score
    feasible_count: Any  # [P] int32 number of feasible nodes at decision time
    resolvable: Any  # [P, N] bool — infeasible but preemption might help
    # (passes all UnschedulableAndUnresolvable-class filters)


# ---------------------------------------------------------------------------
# expression / selector evaluation (stage A primitives)
# ---------------------------------------------------------------------------


def _label_cols(snap: DeviceSnapshot, key: jnp.ndarray):
    """Gather per-node label value-id and numeric value for a key id.

    key < 0 (absent/unknown) yields value -1 / INT_MIN (label absent)."""
    k = jnp.clip(key, 0, snap.label_vals.shape[1] - 1)
    vals = snap.label_vals[:, k]
    nums = snap.label_numvals[:, k]
    absent = key < 0
    return (
        jnp.where(absent, -1, vals),
        jnp.where(absent, INT_MIN, nums),
    )


def _expr_mask(snap: DeviceSnapshot, key, op, vals, num) -> jnp.ndarray:
    """[N] bool: nodes matching a single NodeSelectorRequirement.

    Empty slot (op == -1) matches everything (AND identity)."""
    labval, labnum = _label_cols(snap, key)  # [N]
    has = labval >= 0
    in_set = jnp.any(labval[:, None] == vals[None, :], axis=1) & has
    has_num = labnum != INT_MIN
    result = jnp.select(
        [
            op == ENC_OP_IN,
            op == ENC_OP_NOT_IN,
            op == ENC_OP_EXISTS,
            op == ENC_OP_NOT_EXISTS,
            op == ENC_OP_GT,
            op == ENC_OP_LT,
        ],
        [
            in_set,
            ~in_set,  # NotIn: absent key also passes (selectors.py semantics)
            has,
            ~has,
            has_num & (labnum > num),
            has_num & (labnum < num),
        ],
        default=jnp.ones_like(has),
    )
    return jnp.where(op < 0, jnp.ones_like(result), result)


def _term_mask(snap, keys, ops, vals, nums, name_row) -> jnp.ndarray:
    """[N] bool for one NodeSelectorTerm: AND of expressions + matchFields."""
    ex = jax.vmap(lambda k, o, v, n: _expr_mask(snap, k, o, v, n))(
        keys, ops, vals, nums
    )  # [E, N]
    m = jnp.all(ex, axis=0)
    n = snap.valid.shape[0]
    rows = jnp.arange(n)
    name_ok = jnp.where(name_row == -1, True, rows == name_row)
    return m & name_ok


def _node_affinity_required(snap, bp) -> jnp.ndarray:
    """[N] bool: nodeSelector AND (OR of required nodeSelectorTerms).

    Mirrors PodMatchesNodeSelectorAndAffinityTerms
    (nodeaffinity/node_affinity.go:54 + v1helper)."""
    ns_ok = _term_mask(
        snap, bp.ns_key, bp.ns_op, bp.ns_vals, bp.ns_num, jnp.int32(-1)
    )
    terms = jax.vmap(
        lambda k, o, v, n, nr: _term_mask(snap, k, o, v, n, nr)
    )(bp.aff_key, bp.aff_op, bp.aff_vals, bp.aff_num, bp.aff_match_name_row)  # [T, N]
    terms = terms & bp.aff_term_valid[:, None]
    any_term = jnp.any(terms, axis=0)
    aff_ok = jnp.where(bp.aff_has, any_term, True)
    return ns_ok & aff_ok


def _node_affinity_score(snap, bp) -> jnp.ndarray:
    """[N] float: Σ weights of matched preferred terms (pre-normalization)."""
    terms = jax.vmap(
        lambda k, o, v, n: _term_mask(snap, k, o, v, n, jnp.int32(-1))
    )(bp.pref_key, bp.pref_op, bp.pref_vals, bp.pref_num)  # [PT, N]
    w = jnp.where(bp.pref_term_valid, bp.pref_weight, 0.0)
    return jnp.sum(terms.astype(jnp.float32) * w[:, None], axis=0)


def _taints(snap, bp) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """([N] bool tolerated-for-schedule, [N] float intolerable-prefer count).

    Filter: untolerated NoSchedule/NoExecute ⇒ infeasible
    (tainttoleration/taint_toleration.go:55-77, UnschedulableAndUnresolvable).
    Score: count of intolerable PreferNoSchedule taints (129-167)."""
    tk, tv, te = snap.taint_key, snap.taint_val, snap.taint_effect  # [N, TA]
    # toleration j tolerates taint slot (n, a)?
    def tol_matches(jk, jop, jv, je):
        key_ok = (jk == -1) | (jk == tk)
        val_ok = (jop == TOL_OP_EXISTS) | (jv == tv)
        eff_ok = (je == -1) | (je == te)
        return (jop >= 0) & key_ok & val_ok & eff_ok  # [N, TA]

    tol = jax.vmap(tol_matches)(bp.tol_key, bp.tol_op, bp.tol_val, bp.tol_effect)
    tolerated = jnp.any(tol, axis=0)  # [N, TA]
    active = tk >= 0
    hard = active & ((te == EFFECT_NO_SCHEDULE) | (te == EFFECT_NO_EXECUTE))
    ok = jnp.all(~hard | tolerated, axis=1)
    prefer = active & (te == EFFECT_PREFER_NO_SCHEDULE)
    intolerable = jnp.sum((prefer & ~tolerated).astype(jnp.float32), axis=1)
    return ok, intolerable


def _image_locality(snap, bp) -> jnp.ndarray:
    """[N] float 0..100 (imagelocality/image_locality.go:47)."""
    n_valid = jnp.maximum(jnp.sum(snap.valid.astype(jnp.float32)), 1.0)
    have = (snap.image_bytes > 0).astype(jnp.float32)  # [N, I]
    spread = jnp.sum(have, axis=0) / n_valid  # [I] fraction of nodes w/ image
    iid = jnp.clip(bp.image_ids, 0, snap.image_bytes.shape[1] - 1)  # [IM]
    use = (bp.image_ids >= 0).astype(jnp.float32)
    sizes = snap.image_bytes[:, iid] * use[None, :]  # [N, IM]
    scaled = sizes * spread[iid][None, :]
    total = jnp.sum(scaled, axis=1)  # [N]
    score = (
        (total - IMG_MIN_THRESHOLD)
        / (IMG_MAX_THRESHOLD - IMG_MIN_THRESHOLD)
        * 100.0
    )
    return jnp.clip(score, 0.0, 100.0)


def _prefer_avoid(snap, bp) -> jnp.ndarray:
    """[N] float: 0 if node's avoid-annotation lists the pod's controller,
    else 100 (nodepreferavoidpods/node_prefer_avoid_pods.go:39)."""
    a = jnp.clip(bp.ctrl_id, 0, snap.avoid.shape[1] - 1)
    avoided = snap.avoid[:, a] & (bp.ctrl_id >= 0)
    return jnp.where(avoided, 0.0, 100.0)


# ---------------------------------------------------------------------------
# stage B primitives (carry-dependent)
# ---------------------------------------------------------------------------


def _domain_ops(snap, key, weights, eligible, v_cap: int):
    """Per-topology-domain reduction for one topology key.

    Returns (node_domain_sum [N], min_over_eligible_domains scalar,
    total scalar, has_key [N]). `weights` [N] are summed per domain of
    label `key` over nodes where `eligible`; nodes lacking the key are
    excluded. This is the segment-sum form of the reference's
    TpPairToMatchNum maps (podtopologyspread/filtering.go:43-121)."""
    dom, _ = _label_cols(snap, key)  # [N] value-id or -1
    has_key = dom >= 0
    ok = has_key & eligible
    seg = jnp.where(ok, dom, v_cap)  # OOB -> dropped
    sums = jax.ops.segment_sum(
        jnp.where(ok, weights, 0.0), seg, num_segments=v_cap
    )  # [V]
    node_sum = jnp.where(has_key, sums[jnp.clip(dom, 0, v_cap - 1)], 0.0)
    present = (
        jax.ops.segment_max(ok.astype(jnp.int32), seg, num_segments=v_cap) > 0
    )
    min_dom = jnp.min(jnp.where(present, sums, jnp.inf))
    return node_sum, min_dom, jnp.sum(sums), has_key


def _gather_counts(counts, extra, sid):
    """[N] pod-match counts for predicate sid (<0 → zeros)."""
    s = jnp.clip(sid, 0, counts.shape[1] - 1)
    c = counts[:, s] + extra[:, s]
    return jnp.where(sid >= 0, c.astype(jnp.float32), 0.0)


def _pod_static(snap: DeviceSnapshot, bp) -> Tuple:
    """Stage A for one pod: static mask/score pieces. Returns
    (static_ok, ns_aff_mask, aff_score, prefer_cnt, img, avoid). Shared by
    the schedule kernel and the preemption what-if kernel (static_ok is
    exactly the UnschedulableAndUnresolvable boundary: nodes failing it
    cannot be helped by evictions, generic_scheduler.go:1033)."""
    n = snap.valid.shape[0]
    rows = jnp.arange(n)
    ns_aff = _node_affinity_required(snap, bp)
    taint_ok, prefer_cnt = _taints(snap, bp)
    unsched_ok = ~snap.unschedulable | bp.tolerates_unschedulable
    name_ok = jnp.where(
        bp.node_name_row == -1,
        True,
        jnp.where(bp.node_name_row < 0, False, rows == bp.node_name_row),
    )
    static_ok = snap.valid & ns_aff & taint_ok & unsched_ok & name_ok
    # Scores computed regardless of feasibility; normalization masks later.
    aff_score = _node_affinity_score(snap, bp)
    img = _image_locality(snap, bp)
    avoid = _prefer_avoid(snap, bp)
    return static_ok, ns_aff, aff_score, prefer_cnt, img, avoid


@functools.lru_cache(maxsize=32)
def make_schedule_batch_raw(v_cap: int, hard_pod_affinity_weight: float = 1.0):
    """Build the (unjitted) batch kernel for a given domain-segment capacity.

    Cached per (v_cap, weight); jitted by make_schedule_batch (single device)
    or parallel.sharded.make_sharded_schedule_batch (mesh)."""

    pod_static = _pod_static

    def step(snap: DeviceSnapshot, carry, xs, weights, rng):
        (req_x, nz_x, sel_x, et_x, port_x) = carry
        (bp, static_ok, ns_aff, aff_score, prefer_cnt, img, avoid, key) = xs
        n = snap.valid.shape[0]

        # --- NodeResourcesFit (noderesources/fit.go:181-250) ---------------
        used = snap.requested + req_x
        free = snap.allocatable - used
        fits = jnp.all((bp.req[None, :] == 0) | (bp.req[None, :] <= free), axis=1)

        # --- NodePorts (nodeports/node_ports.go) ---------------------------
        ports_used = snap.port_counts + port_x
        port_conflict = jnp.any(bp.port_mask[None, :] & (ports_used > 0), axis=1)

        # --- PodTopologySpread (podtopologyspread/filtering.go) ------------
        def spread_one(skey, sid, skew, hard, selfm):
            counts = _gather_counts(snap.sel_counts, sel_x, sid)
            node_sum, min_dom, _, has_key = _domain_ops(
                snap, skey, counts, ns_aff & snap.valid, v_cap
            )
            self_add = jnp.where(selfm, 1.0, 0.0)
            skewed = node_sum + self_add - jnp.where(
                jnp.isfinite(min_dom), min_dom, 0.0
            ) > skew.astype(jnp.float32)
            active = skey >= 0
            hard_bad = active & hard & (skewed | ~has_key)
            soft_pen = jnp.where(active & ~hard, node_sum, 0.0)
            return hard_bad, soft_pen

        hard_bad, soft_pen = jax.vmap(spread_one)(
            bp.spread_key, bp.spread_sid, bp.spread_skew, bp.spread_hard, bp.spread_self
        )  # [C, N]
        spread_ok = ~jnp.any(hard_bad, axis=0)
        spread_penalty = jnp.sum(soft_pen, axis=0)

        # --- InterPodAffinity: incoming pod's required terms ----------------
        def aff_term(sid, tkey, selfm):
            counts = _gather_counts(snap.sel_counts, sel_x, sid)
            node_sum, _, total, has_key = _domain_ops(
                snap, tkey, counts, snap.valid, v_cap
            )
            ok = (node_sum > 0) | ((total == 0) & selfm & has_key)
            return jnp.where(sid >= 0, ok, True)

        aff_ok = jnp.all(
            jax.vmap(aff_term)(bp.paff_sid, bp.paff_key, bp.paff_self), axis=0
        )

        def anti_term(sid, tkey):
            counts = _gather_counts(snap.sel_counts, sel_x, sid)
            node_sum, _, _, has_key = _domain_ops(snap, tkey, counts, snap.valid, v_cap)
            bad = has_key & (node_sum > 0)
            return jnp.where(sid >= 0, bad, False)

        anti_bad = jnp.any(
            jax.vmap(anti_term)(bp.panti_sid, bp.panti_key), axis=0
        )

        # --- existing pods' terms (eterms) ---------------------------------
        def eterm_one(t):
            w = snap.eterm_w[:, t] + et_x[:, t]
            node_sum, _, _, has_key = _domain_ops(
                snap, snap.eterm_topo_key[t], w, snap.valid, v_cap
            )
            matches = bp.match_eterm[t]
            kind = snap.eterm_kind[t]
            anti_req_bad = matches & (kind == ETERM_ANTI_REQ) & has_key & (node_sum > 0)
            sgn = jnp.select(
                [kind == ETERM_ANTI_PREF, kind == ETERM_AFF_PREF, kind == ETERM_AFF_REQ],
                [-1.0, 1.0, hard_pod_affinity_weight],
                default=0.0,
            )
            score = jnp.where(matches, sgn * node_sum, 0.0)
            return anti_req_bad, score

        t_cap = snap.eterm_w.shape[1]
        e_bad, e_score = jax.vmap(eterm_one)(jnp.arange(t_cap))  # [T, N]
        eterm_bad = jnp.any(e_bad, axis=0)
        interpod_score = jnp.sum(e_score, axis=0)

        # incoming pod's preferred terms
        def ppref_one(sid, tkey, w):
            counts = _gather_counts(snap.sel_counts, sel_x, sid)
            node_sum, _, _, _ = _domain_ops(snap, tkey, counts, snap.valid, v_cap)
            return jnp.where(sid >= 0, w * node_sum, 0.0)

        interpod_score = interpod_score + jnp.sum(
            jax.vmap(ppref_one)(bp.ppref_sid, bp.ppref_key, bp.ppref_w), axis=0
        )

        # --- combine mask ---------------------------------------------------
        feasible = (
            static_ok
            & fits
            & ~port_conflict
            & spread_ok
            & aff_ok
            & ~anti_bad
            & ~eterm_bad
        )
        # preemption-candidate nodes: fail only resolvable filters
        resolvable = static_ok & ~feasible

        # --- scores (normalized 0..100 over feasible, framework.go:503-580) -
        def norm_max(x):
            mx = jnp.max(jnp.where(feasible, x, -jnp.inf))
            safe = jnp.where(jnp.isfinite(mx) & (mx > 0), mx, 1.0)
            return jnp.clip(x / safe * 100.0, 0.0, 100.0)

        def norm_invert(x):  # lower raw -> higher score
            mx = jnp.max(jnp.where(feasible, x, -jnp.inf))
            safe = jnp.where(jnp.isfinite(mx) & (mx > 0), mx, 1.0)
            ok = jnp.isfinite(mx) & (mx > 0)
            return jnp.where(ok, (safe - x) / safe * 100.0, 100.0)

        # resource scores include the incoming pod (least_allocated.go:77-99)
        nz_used = snap.nonzero_req + nz_x + bp.nonzero_req[None, :]
        alloc = jnp.maximum(snap.allocatable.astype(jnp.float32), 1.0)
        frac = jnp.clip(nz_used.astype(jnp.float32) / alloc, 0.0, 1.0)
        cpu_f, mem_f = frac[:, RES_CPU], frac[:, RES_MEM]
        least = ((1.0 - cpu_f) * 100.0 + (1.0 - mem_f) * 100.0) / 2.0
        most = (cpu_f * 100.0 + mem_f * 100.0) / 2.0
        balanced = (1.0 - jnp.abs(cpu_f - mem_f)) * 100.0
        # requested-to-capacity-ratio, default shape {0:0, 100:10} scaled to
        # 0..100 (requested_to_capacity_ratio.go:33 with default buckets)
        util = (cpu_f + mem_f) / 2.0 * 100.0
        rtc = util / 100.0 * 10.0 * 10.0

        # interpod/prefer-style normalization: shift to >= 0 then max-scale
        # (interpodaffinity/scoring.go:287-310 normalizes by max |score|)
        ip = interpod_score
        ip_max = jnp.max(jnp.where(feasible, jnp.abs(ip), 0.0))
        ip_norm = jnp.where(ip_max > 0, ip / ip_max * 100.0, 0.0)

        # DefaultPodTopologySpread: same-service pods per node via the
        # service-derived sel_counts columns; MAX over matching services
        # matches the host's any()-dedup when services don't overlap (the
        # common case — overlapping services score each pod once there too)
        svc_cnt = jnp.max(
            jnp.where(
                bp.match_svc[None, :],
                (snap.sel_counts + sel_x).astype(jnp.float32),
                0.0,
            ),
            axis=1,
        )  # [N]

        comps = jnp.stack(
            [
                least,
                most,
                balanced,
                rtc,
                norm_max(aff_score),
                norm_invert(prefer_cnt),
                img,
                avoid,
                norm_invert(spread_penalty),
                ip_norm,
                norm_invert(svc_cnt),
                # heterogeneity/cost columns: cheaper / lower-energy nodes
                # score higher within the feasible set
                norm_invert(snap.cost_milli.astype(jnp.float32)),
                norm_invert(snap.energy_milli.astype(jnp.float32)),
            ]
        )  # [K, N]
        total_score = jnp.sum(comps * weights[:, None], axis=0)

        # --- select: argmax with uniform random tie-break -------------------
        noise = jax.random.uniform(key, (n,))
        keyed = jnp.where(feasible, total_score, -jnp.inf)
        best = jnp.max(keyed)
        is_best = feasible & (keyed == best)
        pick_key = jnp.where(is_best, noise, -1.0)
        chosen = jnp.argmax(pick_key).astype(jnp.int32)
        feas_count = jnp.sum(feasible.astype(jnp.int32))
        ok = (feas_count > 0) & bp.valid
        chosen = jnp.where(ok, chosen, -1)

        # --- commit to carry -------------------------------------------------
        idx = jnp.maximum(chosen, 0)
        gate = ok.astype(jnp.int32)
        gate_f = ok.astype(jnp.float32)
        req_x = req_x.at[idx].add(bp.req * gate)
        nz_x = nz_x.at[idx].add(bp.nonzero_req * gate)
        sel_x = sel_x.at[idx].add(bp.match_sel.astype(jnp.int32) * gate)
        et_x = et_x.at[idx].add(bp.eterm_add * gate_f)
        port_x = port_x.at[idx].add(bp.port_mask.astype(jnp.int32) * gate)

        new_carry = (req_x, nz_x, sel_x, et_x, port_x)
        out = (chosen, jnp.where(ok, best, -jnp.inf), feas_count, resolvable)
        return new_carry, out

    def schedule_batch(
        snap: DeviceSnapshot, batch: PodBatch, weights: jnp.ndarray, rng: jnp.ndarray
    ) -> BatchResult:
        n = snap.valid.shape[0]
        p = batch.valid.shape[0]
        statics = jax.vmap(lambda bp: pod_static(snap, bp))(batch)
        keys = jax.random.split(rng, p)
        carry0 = (
            jnp.zeros_like(snap.requested),
            jnp.zeros_like(snap.nonzero_req),
            jnp.zeros_like(snap.sel_counts),
            jnp.zeros_like(snap.eterm_w),
            jnp.zeros_like(snap.port_counts),
        )
        xs = (batch,) + statics + (keys,)
        _, (chosen, score, feas, resolvable) = jax.lax.scan(
            lambda c, x: step(snap, c, x, weights, None), carry0, xs
        )
        return BatchResult(
            chosen=chosen, score=score, feasible_count=feas, resolvable=resolvable
        )

    return schedule_batch


@functools.lru_cache(maxsize=32)
def make_schedule_batch(v_cap: int, hard_pod_affinity_weight: float = 1.0):
    """Single-device jitted batch kernel (cached per capacity)."""
    return jax.jit(make_schedule_batch_raw(v_cap, hard_pod_affinity_weight))


def _preempt_whatif(
    snap: DeviceSnapshot, batch: PodBatch, priority: jnp.ndarray
) -> jnp.ndarray:
    """Batched masked preemption what-if (SURVEY §7.6): for every (pod, node)
    pair, would the pod fit if all pods of lower priority were evicted?

    Replaces the serial per-node host scan of selectVictimsOnNode
    (generic_scheduler.go:850-877 parallel what-if) with one device pass.
    The mask is OPTIMISTIC: it accounts resources (via the priority-banded
    requested matrix) and the static UnschedulableAndUnresolvable filters,
    but not affinity/spread constraints contributed by would-be victims —
    the host reprieve loop does the exact plugin re-check on the (few)
    surviving candidates, so false positives cost time, never correctness.
    """
    statics = jax.vmap(lambda bp: _pod_static(snap, bp))(batch)
    static_ok = statics[0]  # [P, N]

    # removable[p, n, r] = Σ_b [band_prio[b] < prio_p] · prio_req[n, b, r]
    # (priority passed separately: template batches carry per-pod priority
    # outside the template tensors)
    removable_band = snap.band_prio[None, :] < priority[:, None]  # [P, B]
    removable = jnp.einsum(
        "pb,nbr->pnr",
        removable_band.astype(jnp.int32),
        snap.prio_req,
    )
    free = (
        snap.allocatable[None, :, :]
        - snap.requested[None, :, :]
        + removable
    )  # [P, N, R]
    req = batch.req[:, None, :]  # [P, 1, R]
    fits = jnp.all((req == 0) | (req <= free), axis=-1)  # [P, N]
    # a node already holding >= 1 lower-priority pod is the only kind where
    # eviction helps; removable pods count shows as the PODS column
    has_victims = jnp.any(removable > 0, axis=-1)
    return static_ok & fits & has_victims & batch.valid[:, None]


preempt_whatif = jax.jit(_preempt_whatif)


# -- kernel-output guards (scheduler data-plane self-defense) ----------------

GUARD_ROW_RANGE = "row_out_of_range"
GUARD_NONFINITE = "nonfinite_score"
# split-phase readback: the trailing bulk transfer died after the fast
# index payload already drove assumes — the batch's device commits are
# unverifiable and must quarantine/unwind
GUARD_TRAILING_LOSS = "trailing_readback_loss"


class KernelGuardTrip(RuntimeError):
    """A batch's read-back results failed validation: the whole batch must
    be quarantined to the host fallback path and the device snapshot
    rebuilt (its commits for this batch are suspect)."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"kernel guard trip: {reason} {detail}".rstrip())
        self.reason = reason


def validate_batch_outputs(chosen, placed, score, n_rows: int):
    """Cheap structural validation of a read-back batch result BEFORE any
    placement is acted on: every placed pod's chosen row must name a live
    node row (negative or past-capacity indices would mis-index
    row_names — numpy's negative wrap silently picks the WRONG node), and
    its score must be finite (a NaN/Inf in the score matrix poisons the
    argmax for the whole column). Returns a trip reason or None."""
    placed = np.asarray(placed, dtype=bool)
    if not placed.any():
        return None
    rows = np.asarray(chosen)[placed]
    if rows.size and (int(rows.min()) < 0 or int(rows.max()) >= n_rows):
        return GUARD_ROW_RANGE
    if score is not None:
        s = np.asarray(score)[placed]
        if not np.isfinite(s).all():
            return GUARD_NONFINITE
    return None


def validate_trailing_score(score, placed):
    """Split-phase trailing validation: the fast index payload was
    validated (and acted on) with score=None; when the bulk score vector
    lands it must agree that every placed pod scored finite — a NaN/Inf
    here means the argmax the fast payload reported was computed over a
    poisoned column. Returns a trip reason or None."""
    placed = np.asarray(placed, dtype=bool)
    if score is None or not placed.any():
        return None
    s = np.asarray(score)[placed]
    if not np.isfinite(s).all():
        return GUARD_NONFINITE
    return None
