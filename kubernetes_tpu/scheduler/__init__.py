"""TPU-native kube-scheduler.

Public surface: Scheduler (top loop), KubeSchedulerConfiguration,
GenericScheduler (host algorithm), the framework plugin API, cache & queue.
"""

from .config import KubeSchedulerConfiguration, ProfileConfig  # noqa: F401
from .core import FitError, GenericScheduler, ScheduleResult  # noqa: F401
from .scheduler import Scheduler  # noqa: F401
