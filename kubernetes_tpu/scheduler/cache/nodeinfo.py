"""NodeInfo: per-node aggregate state + the per-cycle Snapshot.

Host twin of reference pkg/scheduler/nodeinfo/node_info.go:48 (NodeInfo,
Resource, AddPod/RemovePod/calculateResource) and
internal/cache/snapshot.go:31. The host plugins (oracle/fallback path)
consume these; the device path consumes the columnar encoding built from the
same mutations (ops/encoding.py) — both are fed by SchedulerCache so they
cannot drift.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from ...api import objects as v1
from ...api.resources import ResourceList
from ...api.objects import compute_pod_resource_request, pod_host_ports


class NodeInfo:
    def __init__(self, node: Optional[v1.Node] = None):
        self.node: Optional[v1.Node] = node
        self.pods: List[v1.Pod] = []
        self.pods_with_affinity: List[v1.Pod] = []
        self.requested = ResourceList()
        self.non_zero_requested = ResourceList()
        self.allocatable = node.allocatable() if node else ResourceList()
        self.used_ports: Dict[Tuple[str, str, int], int] = {}
        self.generation: int = 0
        # bumped ONLY by informer-driven mutations (node spec change,
        # foreign pod add/remove) — scheduler assumes leave it alone. The
        # oracle guard keys on it: kernel placements are checked against
        # nodes whose EXTERNAL state is unchanged since launch, while
        # sibling-batch assumes (state the device chain already saw) do
        # not exempt a node from the check.
        self.ext_generation: int = 0

    def set_node(self, node: v1.Node) -> None:
        self.node = node
        self.allocatable = node.allocatable()

    def add_pod(self, pod: v1.Pod) -> None:
        self.add_pod_precomputed(
            pod,
            compute_pod_resource_request(pod),
            compute_pod_resource_request(pod, non_zero=True),
            pod_host_ports(pod),
            _has_affinity(pod),
        )

    def add_pod_precomputed(
        self,
        pod: v1.Pod,
        req: ResourceList,
        non_zero_req: ResourceList,
        host_ports,
        has_affinity: bool,
    ) -> None:
        """add_pod with the spec-derived aggregates precomputed: template
        siblings in a bulk assume share one computation (the fingerprint
        pins requests/ports/affinity per template, ops/templates.py:82)."""
        self.requested.add(req)
        self.non_zero_requested.add(non_zero_req)
        self.pods.append(pod)
        if has_affinity:
            self.pods_with_affinity.append(pod)
        for hp in host_ports:
            self.used_ports[hp] = self.used_ports.get(hp, 0) + 1

    def remove_pod(self, pod_key: str) -> Optional[v1.Pod]:
        for i, p in enumerate(self.pods):
            if p.metadata.key == pod_key:
                self.pods.pop(i)
                self.requested.sub(compute_pod_resource_request(p))
                self.non_zero_requested.sub(
                    compute_pod_resource_request(p, non_zero=True)
                )
                self.pods_with_affinity = [
                    q for q in self.pods_with_affinity if q.metadata.key != pod_key
                ]
                for hp in pod_host_ports(p):
                    c = self.used_ports.get(hp, 0) - 1
                    if c <= 0:
                        self.used_ports.pop(hp, None)
                    else:
                        self.used_ports[hp] = c
                return p
        return None

    @property
    def name(self) -> str:
        return self.node.metadata.name if self.node else ""

    def clone(self) -> "NodeInfo":
        c = NodeInfo()
        c.node = self.node
        c.pods = list(self.pods)
        c.pods_with_affinity = list(self.pods_with_affinity)
        c.requested = self.requested.copy()
        c.non_zero_requested = self.non_zero_requested.copy()
        c.allocatable = self.allocatable.copy()
        c.used_ports = dict(self.used_ports)
        c.generation = self.generation
        c.ext_generation = self.ext_generation
        return c


def _has_affinity(pod: v1.Pod) -> bool:
    a = pod.spec.affinity
    return a is not None and (
        a.pod_affinity is not None or a.pod_anti_affinity is not None
    )


def zone_interleave(node_infos: List[NodeInfo]) -> List[NodeInfo]:
    """Zone-aware iteration order (internal/cache/node_tree.go): nodes are
    grouped by failure zone and emitted round-robin across zones, so the
    host path's adaptive sampling + round-robin start index spreads
    sequential placements over zones instead of exhausting one zone first.
    The device path doesn't need this (it scores ALL nodes every batch);
    it shapes only the host fallback's truncated scan."""
    zones: Dict[str, List[NodeInfo]] = {}
    for ni in node_infos:
        labels = ni.node.metadata.labels if ni.node is not None else {}
        zone = (
            labels.get("topology.kubernetes.io/zone")
            or labels.get("failure-domain.beta.kubernetes.io/zone")
            or labels.get("zone")
            or ""
        )
        zones.setdefault(zone, []).append(ni)
    out: List[NodeInfo] = []
    buckets = list(zones.values())
    i = 0
    while buckets:
        buckets = [b for b in buckets if b]
        for b in buckets:
            if i < len(b):
                out.append(b[i])
        buckets = [b for b in buckets if len(b) > i + 1]
        i += 1
    return out


class Snapshot:
    """Immutable-per-cycle view (SharedLister): nodeInfoMap + zone-aware
    ordered list + affinity sublist (snapshot.go:31, node_tree.go,
    HavePodsWithAffinityList)."""

    def __init__(self, node_infos: Optional[List[NodeInfo]] = None):
        self.node_info_list: List[NodeInfo] = zone_interleave(node_infos or [])
        self.node_info_map: Dict[str, NodeInfo] = {
            ni.name: ni for ni in self.node_info_list
        }
        self.have_pods_with_affinity_list: List[NodeInfo] = [
            ni for ni in self.node_info_list if ni.pods_with_affinity
        ]
        self.generation: int = 0

    @classmethod
    def from_literals(
        cls, pods: List[v1.Pod], nodes: List[v1.Node]
    ) -> "Snapshot":
        """Test-injection constructor (internalcache.NewSnapshot,
        snapshot.go:51): build snapshot state from literal pods/nodes."""
        infos = {n.metadata.name: NodeInfo(n) for n in nodes}
        for p in pods:
            if p.spec.node_name and p.spec.node_name in infos:
                infos[p.spec.node_name].add_pod(p)
        return cls(list(infos.values()))

    def get(self, node_name: str) -> Optional[NodeInfo]:
        return self.node_info_map.get(node_name)

    def list_pods(self) -> List[v1.Pod]:
        return [p for ni in self.node_info_list for p in ni.pods]

    def __len__(self) -> int:
        return len(self.node_info_list)
