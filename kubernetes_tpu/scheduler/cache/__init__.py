"""Scheduler cache: NodeInfo aggregates, assume/expire protocol, snapshots."""

from .nodeinfo import NodeInfo, Snapshot  # noqa: F401
from .cache import SchedulerCache  # noqa: F401
