"""Cache debugger: on-signal dump + cache-vs-informer consistency compare.

Reference: pkg/scheduler/internal/cache/debugger/{debugger.go:57,
comparer.go, dumper.go, signal.go:25} — SIGUSR2 triggers (a) a dump of the
cached NodeInfos and queued pods, (b) a comparison of the scheduler cache
against informer ground truth. The TPU build adds a third check: the host
columnar mirror against what the device snapshot was built from (a
host/device divergence here means the kernel is scheduling against stale
state).
"""

from __future__ import annotations

import logging
import signal
from typing import List, Tuple

logger = logging.getLogger("kubernetes_tpu.scheduler.debugger")


class CacheDebugger:
    def __init__(self, scheduler):
        self.sched = scheduler

    # -- comparer (comparer.go) ---------------------------------------------

    def compare(self) -> Tuple[List[str], List[str]]:
        """(missed, redundant) node/pod keys: cache vs informer truth."""
        problems_nodes: List[str] = []
        problems_pods: List[str] = []
        informers = self.sched.informer_factory
        node_keys = {
            n.metadata.name for n in informers.informer("nodes").indexer.list()
        }
        cache = self.sched.cache
        with cache.lock:
            cached_nodes = set(cache._nodes.keys())
            cached_pods = set(cache._pod_to_node.keys())
        missed = node_keys - cached_nodes
        redundant = cached_nodes - node_keys
        if missed:
            problems_nodes.append(f"cache missing nodes: {sorted(missed)}")
        if redundant:
            problems_nodes.append(f"cache has extra nodes: {sorted(redundant)}")

        scheduled_pod_keys = {
            p.metadata.key
            for p in informers.informer("pods").indexer.list()
            if p.spec.node_name
        }
        missed_p = scheduled_pod_keys - cached_pods
        redundant_p = cached_pods - scheduled_pod_keys
        # assumed-but-unbound pods are legitimately cache-only
        with cache.lock:
            assumed = set(cache._assumed.keys())
        redundant_p -= assumed
        if missed_p:
            problems_pods.append(f"cache missing pods: {sorted(missed_p)}")
        if redundant_p:
            problems_pods.append(f"cache has extra pods: {sorted(redundant_p)}")
        return problems_nodes, problems_pods

    # -- dumper (dumper.go) --------------------------------------------------

    def dump(self) -> str:
        cache = self.sched.cache
        queue = self.sched.queue
        lines = ["Dump of cached NodeInfo:"]
        with cache.lock:
            for name in sorted(cache._nodes):
                ni = cache._nodes[name]
                lines.append(f"  node {name}: {len(ni.pods)} pods")
        lines.append("Dump of scheduling queue:")
        for section, keys in queue.pending_pods().items():
            lines.append(f"  {section}: {keys}")
        rt = getattr(self.sched, "_ridethrough", None)
        if rt is not None:
            lines.append("Dump of degraded-store ride-through state:")
            for k, v in rt.state().items():
                lines.append(f"  {k}: {v}")
        repl = replication_health_lines()
        if repl:
            lines.append("Dump of API-store replication/consensus state:")
            lines.extend(repl)
        ride = ridethrough_health_lines()
        if ride:
            lines.append("Dump of control-plane ride-through gauges:")
            lines.extend(ride)
        from ..antientropy import dataplane_health_lines

        # refresh the retire-stall watchdog before rendering: a leaked
        # reader pin must show up in THIS dump even if no lease traffic
        # (and no audit pass) has run since the generation was superseded
        enc = getattr(cache, "encoder", None)
        if enc is not None:
            enc.check_retire_stalls()
        plane = dataplane_health_lines()
        if plane:
            lines.append("Dump of data-plane self-defense state:")
            lines.extend(plane)
        from ...autoscaler.controller import autoscaler_health_lines

        auto = autoscaler_health_lines()
        if auto:
            lines.append("Dump of cluster-autoscaler state:")
            lines.extend(auto)
        from ...controller.evictionbudget import eviction_budget_health_lines
        from ...descheduler.controller import descheduler_health_lines

        defrag = descheduler_health_lines() + eviction_budget_health_lines()
        if defrag:
            lines.append(
                "Dump of descheduler / shared eviction-budget state:"
            )
            lines.extend(defrag)
        from ...apiserver.cacher import readpath_health_lines

        readpath = readpath_health_lines()
        if readpath:
            lines.append("Dump of read-path (watch cache / flow control) state:")
            lines.extend(readpath)
        from ...apiserver.client import serving_health_lines
        from ...apiserver.frontend import frontend_health_lines

        serving = serving_health_lines() + frontend_health_lines()
        if serving:
            lines.append(
                "Dump of serving-tier (REST connection pool / follower "
                "read) state:"
            )
            lines.extend(serving)
        from ...relay import relay_health_lines

        relay = relay_health_lines()
        if relay:
            lines.append(
                "Dump of serving-relay (shared-memory frame ring / "
                "fan-out worker) state:"
            )
            lines.extend(relay)
        from ..preemption import preemption_health_lines

        preempt = preemption_health_lines()
        if preempt:
            lines.append("Dump of priority/preemption engine state:")
            lines.extend(preempt)
        from ..ha import ha_health_lines

        ha = ha_health_lines()
        if ha:
            lines.append(
                "Dump of scheduler-HA / leader-election state "
                f"(this replica: {getattr(self.sched, '_ha_identity', '?')}):"
            )
            lines.extend(ha)
        from ...tuner.policy import tuner_health_lines

        tuner = tuner_health_lines()
        if tuner:
            lines.append("Dump of policy-gym (self-tuning scheduler) state:")
            lines.extend(tuner)
        disk = disk_health_lines()
        if disk:
            lines.append("Dump of WAL / disk-fault state:")
            lines.extend(disk)
        from ...utils import tracing as tracing_mod

        lines.append("Dump of per-pod scheduling traces (slowest first):")
        lines.extend(tracing_mod.tracer.render_lines(8))
        trc = tracing_mod.health_lines()
        if trc:
            lines.append("Dump of tracing pipeline state:")
            lines.extend(trc)
        return "\n".join(lines)

    # -- signal hookup (signal.go:25) ---------------------------------------

    def listen_for_signal(self, signum: int = signal.SIGUSR2) -> None:
        def handler(_sig, _frame):
            logger.info(self.dump())
            nodes, pods = self.compare()
            for p in nodes + pods:
                logger.warning("cache comparison: %s", p)
            if not nodes and not pods:
                logger.info("cache comparison: consistent with informers")

        signal.signal(signum, handler)


def replication_health_lines() -> List[str]:
    """The consensus/replication gauges (runtime/consensus.py publishes
    commit_index, quorum_state, per-follower lag under ``apiserver_``)
    rendered for the SIGUSR2 dump: a wedged cluster — writes 503ing,
    followers lagging, quorum lost — is diagnosable from one signal with
    no log access. Empty when this process runs no replicated store."""
    from ...utils.metrics import metrics

    lines: List[str] = []
    for name, labels, value in metrics.snapshot_gauges("apiserver_"):
        annotation = ""
        if name == "apiserver_quorum_state":
            annotation = "healthy" if value else "DEGRADED (writes 503)"
        lines.append(
            metrics.format_series_line(name, labels, value, annotation)
        )
    return lines


def ridethrough_health_lines() -> List[str]:
    """The degraded-mode ride-through gauges — pending-bind buffer depth
    and breaker state (scheduler/ridethrough.py), eviction-limiter and
    partial-disruption state (controller/nodelifecycle.py) — rendered for
    the SIGUSR2 dump so a paused pipeline is diagnosable from one signal.
    Empty when none of those components has published state yet."""
    from ...utils.metrics import metrics

    lines: List[str] = []
    for prefix in ("scheduler_pending_binds", "scheduler_bind_breaker",
                   "node_lifecycle_"):
        for name, labels, value in metrics.snapshot_gauges(prefix):
            annotation = ""
            if name == "scheduler_bind_breaker_state":
                annotation = "OPEN (dispatch paused)" if value else "closed"
            elif name == "node_lifecycle_partial_disruption":
                annotation = (
                    "HALTED (evictions paused)" if value else "normal"
                )
            lines.append(
                metrics.format_series_line(name, labels, value, annotation)
            )
    return lines


def disk_health_lines() -> List[str]:
    """The durability gauges and counters (runtime/wal.py publishes sink
    fail-stop / fsync-stall / corruption state under ``wal_``, the store
    publishes its disk read-only state and free-space probe under
    ``store_disk_``) rendered for the SIGUSR2 dump: a store that went
    read-only for disk reasons — failed sink, ENOSPC, corrupt recovery —
    is diagnosable from one signal with no log access. Empty when this
    process runs no WAL-backed store."""
    from ...utils.metrics import metrics

    lines: List[str] = []
    for prefix in ("wal_", "store_disk_"):
        for name, labels, value in metrics.snapshot_gauges(prefix):
            annotation = ""
            if name == "wal_sink_failed":
                annotation = (
                    "FAIL-STOPPED (writes 503 until failover)"
                    if value else "healthy"
                )
            elif name == "store_disk_state":
                annotation = {
                    0.0: "ok",
                    1.0: "DISK PRESSURE (read-only, auto-reopens)",
                    2.0: "DISK FAILED (read-only, permanent)",
                }.get(value, "?")
            elif name in ("wal_recovered_corrupt", "store_disk_corrupt"):
                annotation = (
                    "CORRUPT (refusing promotion until resynced)"
                    if value else "clean"
                )
            elif name == "wal_fsync_stalled":
                annotation = "STALLED" if value else "ok"
            lines.append(
                metrics.format_series_line(name, labels, value, annotation)
            )
        for name, labels, value in metrics.snapshot_counters(prefix):
            lines.append(metrics.format_series_line(name, labels, value, ""))
    return lines


def audit_device_vs_masters(enc, dev, masters, fields=("requested", "sel_counts", "port_counts")):
    """Compare a fetched device snapshot against the host masters and print
    row/column/value diagnostics for every differing field. Shared by the
    soak driver and the mismatch reproducer so their reports can't drift.
    Returns the list of differing field names. Caller holds the cache lock
    (the row_names/_pods reads must be consistent with the arrays)."""
    import numpy as np

    bad = []
    for f in fields:
        d = np.asarray(getattr(dev, f))
        m = np.asarray(getattr(masters, f))
        if np.array_equal(d, m):
            continue
        bad.append(f)
        rows = sorted(set(np.nonzero(d != m)[0].tolist()))
        print(f"AUDIT {f}: {len(rows)} rows differ", flush=True)
        for r in rows[:4]:
            if d[r].ndim:
                cols = np.nonzero(d[r] != m[r])[0]
                dv, mv = d[r][cols[:8]].tolist(), m[r][cols[:8]].tolist()
                cshow = cols[:8].tolist()
            else:
                cshow, dv, mv = "-", d[r], m[r]
            print(
                f"  row={r} node={enc.row_names[r] if r < len(enc.row_names) else '?'} "
                f"cols={cshow} dev={dv} mst={mv} "
                f"host_pods={len(enc._pods.get(r, {}))}",
                flush=True,
            )
    return bad
