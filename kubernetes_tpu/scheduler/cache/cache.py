"""Scheduler cache: aggregated live cluster state + assume/expire protocol.

Host twin of reference pkg/scheduler/internal/cache/cache.go:59 with the
TPU-critical addition: every mutation is forwarded to the columnar
SnapshotEncoder, so the HBM-resident snapshot is the same delta stream the
host NodeInfos see (the generation-number incremental-snapshot idea of
UpdateSnapshot, cache.go:203-303, realised as device scatters).

Assume protocol (cache.go:344 AssumePod / FinishBinding / ForgetPod, 30s TTL
wired at scheduler.go:240): optimistic placement before the API bind lands;
confirmed by the informer's scheduled-pod Add, expired by a janitor loop.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from ...api import objects as v1
from ...ops.encoding import EncodingConfig, SnapshotEncoder
from ...testing.lockgraph import named_lock, track_attrs
from .nodeinfo import NodeInfo, Snapshot, _has_affinity

logger = logging.getLogger("kubernetes_tpu.scheduler.cache")


@dataclass
class _AssumedInfo:
    pod: v1.Pod
    node_name: str
    deadline: Optional[float]  # None until finish_binding arms the TTL


class SchedulerCache:
    def __init__(
        self,
        ttl_seconds: float = 30.0,
        encoder: Optional[SnapshotEncoder] = None,
        encoding_config: Optional[EncodingConfig] = None,
    ):
        # named for the lock-order watchdog (testing/lockgraph.py): the
        # cache lock orders BEFORE the encoder's generation bookkeeping
        # lock (encoder.gen_lock), everywhere
        self.lock = named_lock("scheduler.cache")
        self._nodes: Dict[str, NodeInfo] = {}
        self._pod_to_node: Dict[str, str] = {}
        # pods scheduled to nodes the cache hasn't seen yet (informer start
        # races the node list; WAL recovery replays pods first): parked
        # here and replayed into the NodeInfo + encoder when the node
        # arrives — the reference's implicit-NodeInfo reconcile
        # (internal/cache/cache.go AddPod on an unknown node)
        self._orphans: Dict[str, Dict[str, v1.Pod]] = {}
        self._assumed: Dict[str, _AssumedInfo] = {}
        self._ttl = ttl_seconds
        self.encoder = encoder or SnapshotEncoder(encoding_config)
        self._generation = 0
        # informer-driven mutations only (node spec changes, foreign pod
        # add/remove) — scheduler assumes don't move it. The oracle guard
        # compares per-node ext_generation against its launch capture to
        # tell post-launch churn from kernel corruption.
        self._ext_generation = 0
        # name -> last handed-out clone (generation-tagged) for the
        # incremental update_snapshot below
        self._snap_clones: Dict[str, NodeInfo] = {}
        self._stop = threading.Event()
        self._janitor: Optional[threading.Thread] = None

    # -- nodes --------------------------------------------------------------

    def add_node(self, node: v1.Node) -> None:
        with self.lock:
            name = node.metadata.name
            ni = self._nodes.get(name)
            if ni is None:
                ni = NodeInfo(node)
                self._nodes[name] = ni
            else:
                ni.set_node(node)
            self._bump(ni)
            self._bump_ext(name)
            self.encoder.add_node(node)
            # replay pods that arrived before their node did
            for pod in self._orphans.pop(name, {}).values():
                ni.add_pod(pod)
                self.encoder.add_pod(name, pod)

    def update_node(self, node: v1.Node) -> None:
        self.add_node(node)

    def remove_node(self, node_name: str) -> None:
        with self.lock:
            self._nodes.pop(node_name, None)
            self.encoder.remove_node(node_name)
            self._generation += 1

    # -- pods ---------------------------------------------------------------

    def add_pod(self, pod: v1.Pod) -> None:
        """A scheduled pod appeared via the informer. Confirms the assume if
        one is outstanding (expired assumes re-add cleanly)."""
        key = pod.metadata.key
        with self.lock:
            a = self._assumed.pop(key, None)
            if a is not None:
                if a.node_name == pod.spec.node_name:
                    # confirmation: host+device state already reflect it;
                    # swap the stored pod for the API's copy
                    ni = self._nodes.get(a.node_name)
                    if ni is not None:
                        ni.remove_pod(key)
                        ni.add_pod(pod)
                        self._bump(ni)
                    else:
                        # node vanished mid-bind: park for a possible re-add
                        self._orphans.setdefault(a.node_name, {})[key] = pod
                    self._pod_to_node[key] = pod.spec.node_name
                    return
                # scheduled somewhere else than assumed: undo and re-add
                self._remove_pod_internal(key, a.node_name)
                self._bump_ext(a.node_name)
            elif key in self._pod_to_node:
                # re-delivered add (an informer Replace relist after a
                # watch flap replays every listed object): treat as an
                # update — NodeInfo/encoder appends don't dedup, so a
                # blind re-add would double-count the pod's resources
                self._remove_pod_internal(key, self._pod_to_node[key])
            # _add_pod_internal stamps ext_generation (device_synced
            # defaults False) — it is the single stamping point for adds
            self._add_pod_internal(pod)

    def update_pod(self, pod: v1.Pod) -> None:
        key = pod.metadata.key
        with self.lock:
            if key in self._assumed and pod.spec.node_name:
                # bind confirmation arriving as an UPDATE event (the usual
                # shape: unscheduled -> scheduled MODIFIED): route through
                # add_pod's confirmation branch instead of remove+re-add —
                # the re-add would dirty the node row and force a full-row
                # re-upload at the next flush for state the device already
                # holds (the kernel committed it). Only for updates that
                # CARRY a node: an unscheduled-shaped update of an assumed
                # pod must not consume the assume (add_pod's mismatch
                # branch would free the node and strand the pod)
                self.add_pod(pod)
                return
            old_node = self._pod_to_node.get(key)
            if old_node is not None:
                self._remove_pod_internal(key, old_node)
                self._bump_ext(old_node)
            if pod.spec.node_name:
                # ext stamped inside _add_pod_internal
                self._add_pod_internal(pod)

    def remove_pod(self, pod: v1.Pod) -> None:
        key = pod.metadata.key
        with self.lock:
            self._assumed.pop(key, None)
            node = self._pod_to_node.get(key)
            if node is not None:
                self._remove_pod_internal(key, node)
                self._bump_ext(node)

    def _add_pod_internal(
        self,
        pod: v1.Pod,
        device_synced: bool = False,
        prio_band: Optional[int] = None,
        proto: Optional[tuple] = None,
    ) -> None:
        node = pod.spec.node_name
        ni = self._nodes.get(node)
        if ni is None:
            # pod on a node the cache hasn't seen: park it for add_node's
            # replay (update_node races and recovery both hit this)
            self._pod_to_node[pod.metadata.key] = node
            self._orphans.setdefault(node, {})[pod.metadata.key] = pod
            return
        ni.add_pod(pod)
        self._bump(ni)
        if not device_synced:
            # host-path assumes (and informer adds) are occupancy no
            # in-flight device batch has seen: stamp ext_generation so
            # the oracle guard skips the node (node_churn) instead of
            # reading the unseen pod as kernel corruption and falsely
            # latching the device path off. Device-synced (wave) assumes
            # must NOT stamp — their chain saw the placement, so an
            # oracle disagreement there stays a real signal.
            self._bump_ext(node)
        self._pod_to_node[pod.metadata.key] = node
        self.encoder.add_pod(
            node, pod, device_synced=device_synced, prio_band=prio_band,
            proto=proto,
        )

    def _remove_pod_internal(self, key: str, node: str) -> None:
        ni = self._nodes.get(node)
        if ni is not None:
            if ni.remove_pod(key) is not None:
                self._bump(ni)
        # encoder removal is deliberately NOT gated on the NodeInfo still
        # holding the pod: after a host/device divergence (a mid-wave
        # encoder failure unwound the NodeInfo but the entry survived, or
        # vice versa) the gated form leaked phantom device occupancy
        # forever — cleanup_expired would revert the host NodeInfo while
        # the encoder row kept counting the expired assume. remove_pod is
        # a no-op when the encoder has no row/entry for the key.
        self.encoder.remove_pod(node, key)
        orphans = self._orphans.get(node)
        if orphans is not None:
            orphans.pop(key, None)
            if not orphans:
                del self._orphans[node]
        self._pod_to_node.pop(key, None)

    # -- assume protocol -----------------------------------------------------

    def assume_pod(
        self,
        pod: v1.Pod,
        node_name: str,
        device_synced: bool = False,
        prio_band: Optional[int] = None,
        proto: Optional[tuple] = None,
    ) -> None:
        """device_synced=True: the placement came from the wave kernel, whose
        finalize already committed the pod's occupancy into the device
        snapshot — replay host-side only (ops/encoding.add_pod). prio_band
        pins the priority band the kernel committed prio_req under (a band
        relabel between encode and replay would otherwise diverge).
        proto: encoder.pod_proto() from a template sibling (bulk binds
        compute the spec-derived encoding once per template)."""
        key = pod.metadata.key
        with self.lock:
            if key in self._assumed or key in self._pod_to_node:
                raise ValueError(f"pod {key} already assumed/added")
            assumed = pod.deep_copy()
            assumed.spec.node_name = node_name
            self._add_pod_internal(
                assumed,
                device_synced=device_synced,
                prio_band=prio_band,
                proto=proto,
            )
            self._assumed[key] = _AssumedInfo(assumed, node_name, None)

    def assume_pods_bulk(self, items: list) -> list:
        """Assume a whole wave of device-committed placements under ONE
        lock acquisition, with vectorized encoder scatters. items =
        [(pod, node_name, band, proto)]; returns a per-item error-message
        list (None = assumed). Entries that fail the duplicate/unknown-
        node checks are skipped without affecting the rest."""
        errors: list = [None] * len(items)
        enc_items: list = []
        # template siblings share a proto object; the spec-derived host
        # aggregates (requests, ports, affinity) are identical per template
        # (fingerprint pins them, ops/templates.py:82) — compute them once
        tmpl_pre: dict = {}
        with self.lock:
            for i, (pod, node_name, band, proto) in enumerate(items):
                key = pod.metadata.key
                if key in self._assumed or key in self._pod_to_node:
                    errors[i] = f"pod {key} already assumed/added"
                    continue
                assumed = v1.assume_copy(pod, node_name)
                ni = self._nodes.get(node_name)
                if ni is None:
                    # unknown node: track mapping only (matches add path)
                    self._pod_to_node[key] = node_name
                    self._assumed[key] = _AssumedInfo(assumed, node_name, None)
                    continue
                pre_key = id(proto) if proto is not None else None
                pre = tmpl_pre.get(pre_key) if pre_key is not None else None
                if pre is None:
                    pre = (
                        v1.compute_pod_resource_request(pod),
                        v1.compute_pod_resource_request(pod, non_zero=True),
                        v1.pod_host_ports(pod),
                        _has_affinity(pod),
                    )
                    if pre_key is not None:
                        tmpl_pre[pre_key] = pre
                ni.add_pod_precomputed(assumed, *pre)
                self._bump(ni)
                self._pod_to_node[key] = node_name
                self._assumed[key] = _AssumedInfo(assumed, node_name, None)
                enc_items.append(
                    (
                        i,
                        node_name,
                        assumed,
                        # same fallback as add_pod: an unpinned band is
                        # derived from the pod's priority, never 0
                        band
                        if band is not None
                        else self.encoder._band_of(assumed.priority),
                        proto,
                    )
                )
            if enc_items:
                try:
                    self.encoder.add_pods_bulk(
                        [item[1:] for item in enc_items]
                    )
                except Exception:
                    # bulk pass 1 raises BEFORE any master write, so the
                    # per-pod path can safely redo the whole wave — the
                    # NodeInfo/_assumed state above is already correct
                    logger.exception(
                        "bulk encoder scatter failed; per-pod fallback"
                    )
                    for i, node_name, assumed, band, proto in enc_items:
                        try:
                            self.encoder.add_pod(
                                node_name,
                                assumed,
                                device_synced=True,
                                prio_band=band,
                                proto=proto,
                            )
                        except KeyError:
                            pass  # node unknown to the encoder: row-less
                        except Exception as exc:
                            # a non-KeyError here used to propagate MID-WAVE
                            # with NodeInfo/_assumed already committed for
                            # every item: the raiser's host state kept the
                            # pod while the encoder (and the device row the
                            # kernel committed) silently diverged, and the
                            # remaining items never assumed at all. Unwind
                            # THIS pod's host state, surface a per-item
                            # error (the caller requeues it), and hand the
                            # row to the anti-entropy repairer — the device
                            # still holds the kernel's commit for a pod the
                            # masters no longer carry.
                            logger.exception(
                                "per-pod encoder replay failed for %s on %s",
                                assumed.metadata.key,
                                node_name,
                            )
                            key = assumed.metadata.key
                            # entry first, WITHOUT subtracting: the add may
                            # have half-applied its master increments
                            self.encoder.drop_pod_entry(node_name, key)
                            self._assumed.pop(key, None)
                            self._remove_pod_internal(key, node_name)
                            self.encoder.repair_row(node_name)
                            errors[i] = (
                                f"encoder replay failed for {key}: {exc}"
                            )
        return errors

    def finish_binding(self, pod: v1.Pod) -> None:
        """Arms the expiry TTL (cache.go FinishBinding)."""
        with self.lock:
            a = self._assumed.get(pod.metadata.key)
            if a is not None:
                a.deadline = time.monotonic() + self._ttl

    def forget_pod(self, pod: v1.Pod) -> None:
        with self.lock:
            a = self._assumed.pop(pod.metadata.key, None)
            if a is not None:
                self._remove_pod_internal(pod.metadata.key, a.node_name)

    def is_assumed(self, pod_key: str) -> bool:
        with self.lock:
            return pod_key in self._assumed

    def assumed_keys(self) -> List[str]:
        """Sorted outstanding-assume keys under the lock: the O(assumed)
        accessor pollers want (a `dump()` poll would serialize the whole
        cache per probe while holding the lock everyone else needs)."""
        with self.lock:
            return sorted(self._assumed)

    def has_pod(self, pod_key: str) -> bool:
        """True if the pod is assumed or placed (any node)."""
        with self.lock:
            return pod_key in self._assumed or pod_key in self._pod_to_node

    def cleanup_expired(self, now: Optional[float] = None) -> int:
        now = now if now is not None else time.monotonic()
        with self.lock:
            expired = [
                k
                for k, a in self._assumed.items()
                if a.deadline is not None and a.deadline < now
            ]
            for k in expired:
                a = self._assumed.pop(k)
                self._remove_pod_internal(k, a.node_name)
            return len(expired)

    def start_janitor(self, period: float = 1.0) -> None:
        if self._janitor is not None:
            return
        def loop():
            while not self._stop.wait(period):
                self.cleanup_expired()
        self._janitor = threading.Thread(target=loop, daemon=True, name="cache-janitor")
        self._janitor.start()

    def stop(self) -> None:
        self._stop.set()

    # -- snapshots ----------------------------------------------------------

    def _bump(self, ni: NodeInfo) -> None:
        self._generation += 1
        ni.generation = self._generation

    def _bump_ext(self, node_name: Optional[str]) -> None:
        """Stamp a mutation NO in-flight device chain has seen (informer
        events, host-path assumes). Kept separate from _bump:
        device-synced wave assumes move `generation` (snapshot
        incrementality) but must NOT move `ext_generation`, or pipelined
        sibling-batch commits would exempt their nodes from the oracle
        guard exactly under sustained wave load."""
        ni = self._nodes.get(node_name) if node_name else None
        if ni is not None:
            self._ext_generation += 1
            ni.ext_generation = self._ext_generation

    def update_snapshot(self) -> Snapshot:
        """Host snapshot for oracle/fallback/preemption paths. NodeInfos are
        cloned so the cycle sees immutable state (snapshot.go semantics).

        Incremental by node generation (the reference's
        cache.UpdateSnapshot, cache.go:200): only nodes whose generation
        moved since the last call are re-cloned — the host path re-snapshots
        per pod (scheduleOne semantics), and a full 5k-node clone per pod
        would dominate small-batch latency. Cycles never mutate snapshot
        NodeInfos (preemption/nominated simulation clone first), so reuse
        across snapshots is safe."""
        with self.lock:
            cached = self._snap_clones
            fresh: Dict[str, NodeInfo] = {}
            for name, ni in self._nodes.items():
                old = cached.get(name)
                if old is not None and old.generation == ni.generation:
                    fresh[name] = old
                else:
                    fresh[name] = ni.clone()
            self._snap_clones = fresh
            snap = Snapshot(list(fresh.values()))
            snap.generation = self._generation
            return snap

    def device_snapshot(self):
        """Flush pending deltas, return HBM-resident DeviceSnapshot."""
        with self.lock:
            return self.encoder.flush()

    @property
    def node_count(self) -> int:
        with self.lock:
            return len(self._nodes)

    def pod_count(self) -> int:
        with self.lock:
            return sum(len(ni.pods) for ni in self._nodes.values())

    def node_names(self) -> List[str]:
        with self.lock:
            return list(self._nodes.keys())

    def get_node_info(self, name: str) -> Optional[NodeInfo]:
        with self.lock:
            return self._nodes.get(name)

    def node_infos(self) -> Dict[str, NodeInfo]:
        """One-lock snapshot of the NodeInfo map (references, not clones)
        — the autoscaler's per-pass utilization scan takes the cache lock
        once instead of once per node."""
        with self.lock:
            return dict(self._nodes)

    def dump(self) -> dict:
        """Debugger support (internal/cache/debugger): cache contents."""
        with self.lock:
            return {
                "nodes": {
                    n: [p.metadata.key for p in ni.pods]
                    for n, ni in self._nodes.items()
                },
                "assumed": sorted(self._assumed.keys()),
            }


# lockset sanitizer (testing/lockgraph.py Eraser mode): the maps every
# informer handler, wave commit, janitor sweep, and autoscaler scan
# shares — guarded by `scheduler.cache`, now machine-checked in chaos
track_attrs(
    SchedulerCache,
    "_nodes",
    "_pod_to_node",
    "_assumed",
    "_orphans",
    "_snap_clones",
    "_generation",
    "_ext_generation",
)
