"""Profiles: schedulerName → framework instance (+ recorder).

profile.Map equivalent (reference pkg/scheduler/profile/profile.go:39,58,61):
one Framework per profile so several virtual schedulers share one process;
pods select a profile via spec.scheduler_name (profileForPod,
scheduler.go:741)."""

from __future__ import annotations

from typing import Dict, Optional

from ..client.events import EventRecorder
from .config import KubeSchedulerConfiguration, ProfileConfig
from .framework.registry import PluginSet, Registry, default_plugin_set, default_registry
from .framework.runtime import Framework


class Profile:
    def __init__(self, name: str, framework: Framework, recorder: EventRecorder):
        self.name = name
        self.framework = framework
        self.recorder = recorder


class ProfileMap(dict):
    def for_pod(self, pod) -> Optional[Profile]:
        return self.get(pod.spec.scheduler_name)


def new_profile_map(
    cfg: KubeSchedulerConfiguration,
    context: dict,
    registry: Optional[Registry] = None,
    server=None,
) -> ProfileMap:
    m = ProfileMap()
    reg = registry or default_registry()
    for pc in cfg.profiles:
        ps = pc.plugin_set or default_plugin_set()
        if pc.score_weights:
            ps.score = [
                (name, pc.score_weights.get(name, w)) for name, w in ps.score
            ]
        fw = Framework(registry=reg, plugin_set=ps, context=context)
        rec = EventRecorder(server, component=pc.scheduler_name)
        m[pc.scheduler_name] = Profile(pc.scheduler_name, fw, rec)
    return m
