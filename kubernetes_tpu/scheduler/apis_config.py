"""Versioned ComponentConfig loading + legacy Policy translation.

Reference: pkg/scheduler/apis/config/{types.go,v1alpha1,v1alpha2} (the
--config file path), scheme-based conversion, and the legacy Policy JSON
(legacy_types.go) whose predicate/priority names map onto framework plugins
via pkg/scheduler/framework/plugins/legacy_registry.go:148,183.

Input is a dict (parsed JSON — or YAML if available) with an `apiVersion`
of kubescheduler.config.k8s.io/v1alpha1 or /v1alpha2; both convert into the
internal KubeSchedulerConfiguration. Policy files (`kind: Policy`) convert
their predicate/priority lists into a PluginSet.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from ..client.leaderelection import LeaderElectionConfig
from .config import KubeSchedulerConfiguration, ProfileConfig
from .extender import ExtenderConfig, ExtenderManagedResource
from .framework.registry import PluginSet, default_plugin_set

SUPPORTED_VERSIONS = (
    "kubescheduler.config.k8s.io/v1alpha1",
    "kubescheduler.config.k8s.io/v1alpha2",
)

# legacy_registry.go:148 — predicate name -> plugin name
PREDICATE_TO_PLUGIN: Dict[str, str] = {
    "PodFitsResources": "NodeResourcesFit",
    "PodFitsHostPorts": "NodePorts",
    "HostName": "NodeName",
    "MatchNodeSelector": "NodeAffinity",
    "NoDiskConflict": "VolumeRestrictions",
    "NoVolumeZoneConflict": "VolumeZone",
    "MaxEBSVolumeCount": "EBSLimits",
    "MaxGCEPDVolumeCount": "GCEPDLimits",
    "MaxAzureDiskVolumeCount": "AzureDiskLimits",
    "MaxCinderVolumeCount": "CinderLimits",
    "MaxCSIVolumeCountPred": "NodeVolumeLimits",
    "CheckVolumeBinding": "VolumeBinding",
    "PodToleratesNodeTaints": "TaintToleration",
    "CheckNodeUnschedulable": "NodeUnschedulable",
    "EvenPodsSpreadPred": "PodTopologySpread",
    "MatchInterPodAffinity": "InterPodAffinity",
    "CheckNodeLabelPresence": "NodeLabel",
    "CheckServiceAffinity": "ServiceAffinity",
}

# "GeneralPredicates" expands to the basic node checks (legacy_registry.go)
GENERAL_PREDICATES = [
    "NodeResourcesFit",
    "NodeName",
    "NodePorts",
    "NodeAffinity",
]

# legacy_registry.go:183 — priority name -> plugin name
PRIORITY_TO_PLUGIN: Dict[str, str] = {
    "LeastRequestedPriority": "NodeResourcesLeastAllocated",
    "MostRequestedPriority": "NodeResourcesMostAllocated",
    "BalancedResourceAllocation": "NodeResourcesBalancedAllocation",
    "RequestedToCapacityRatioPriority": "RequestedToCapacityRatio",
    "SelectorSpreadPriority": "DefaultPodTopologySpread",
    "ServiceSpreadingPriority": "DefaultPodTopologySpread",
    "InterPodAffinityPriority": "InterPodAffinity",
    "NodeAffinityPriority": "NodeAffinity",
    "TaintTolerationPriority": "TaintToleration",
    "ImageLocalityPriority": "ImageLocality",
    "NodePreferAvoidPodsPriority": "NodePreferAvoidPods",
    "EvenPodsSpreadPriority": "PodTopologySpread",
    "ResourceLimitsPriority": "NodeResourceLimits",
    "NodeLabelPriority": "NodeLabel",
}

# plugins that also need a pre-filter / pre-score stage when enabled
_NEEDS_PRE_FILTER = {
    "NodeResourcesFit",
    "NodePorts",
    "PodTopologySpread",
    "InterPodAffinity",
    "ServiceAffinity",
}
_NEEDS_PRE_SCORE = {
    "PodTopologySpread",
    "InterPodAffinity",
    "TaintToleration",
    "NodeResourceLimits",
    "DefaultPodTopologySpread",
}


class ConfigError(ValueError):
    pass


def load_config_file(path: str) -> KubeSchedulerConfiguration:
    with open(path) as f:
        text = f.read()
    try:
        data = json.loads(text)
    except json.JSONDecodeError:
        try:
            import yaml  # type: ignore

            data = yaml.safe_load(text)
        except ImportError as e:
            raise ConfigError(
                "config file is not JSON and PyYAML is unavailable"
            ) from e
    return config_from_dict(data)


def config_from_dict(data: dict) -> KubeSchedulerConfiguration:
    if data.get("kind") == "Policy":
        return policy_to_config(data)
    api_version = data.get("apiVersion", SUPPORTED_VERSIONS[-1])
    if api_version not in SUPPORTED_VERSIONS:
        raise ConfigError(f"unsupported apiVersion {api_version!r}")
    cfg = KubeSchedulerConfiguration()
    if "disablePreemption" in data:
        cfg.disable_preemption = bool(data["disablePreemption"])
    if "percentageOfNodesToScore" in data:
        cfg.percentage_of_nodes_to_score = int(data["percentageOfNodesToScore"])
    if "podInitialBackoffSeconds" in data:
        cfg.pod_initial_backoff_seconds = float(data["podInitialBackoffSeconds"])
    if "podMaxBackoffSeconds" in data:
        cfg.pod_max_backoff_seconds = float(data["podMaxBackoffSeconds"])
    le = data.get("leaderElection") or {}
    if le.get("leaderElect"):
        cfg.leader_election = LeaderElectionConfig(
            lease_duration=float(le.get("leaseDuration", 15.0)),
            renew_deadline=float(le.get("renewDeadline", 10.0)),
            retry_period=float(le.get("retryPeriod", 2.0)),
        )
    profiles = []
    if api_version.endswith("v1alpha2") and data.get("profiles"):
        for p in data["profiles"]:
            profiles.append(
                ProfileConfig(
                    scheduler_name=p.get("schedulerName", "default-scheduler"),
                    plugin_set=_plugins_overlay(p.get("plugins")),
                )
            )
    elif data.get("schedulerName"):  # v1alpha1 single-profile field
        profiles.append(ProfileConfig(scheduler_name=data["schedulerName"]))
    if profiles:
        cfg.profiles = profiles
    for e in data.get("extenders", []) or []:
        cfg.extenders.append(_extender_from_dict(e))
    cfg.validate()
    return cfg


def _plugins_overlay(plugins: Optional[dict]) -> Optional[PluginSet]:
    """v1alpha2 per-extension-point enabled/disabled overlay on defaults."""
    if not plugins:
        return None
    ps = default_plugin_set()
    point_attr = {
        "queueSort": "queue_sort",
        "preFilter": "pre_filter",
        "filter": "filter",
        "preScore": "pre_score",
        "score": "score",
        "reserve": "reserve",
        "permit": "permit",
        "preBind": "pre_bind",
        "bind": "bind",
        "postBind": "post_bind",
        "unreserve": "unreserve",
    }
    for point, attr in point_attr.items():
        overlay = plugins.get(point)
        if not overlay:
            continue
        current = getattr(ps, attr)
        disabled = {d.get("name") for d in overlay.get("disabled", [])}
        if "*" in disabled:
            current = []
        elif attr == "score":
            current = [(n, w) for n, w in current if n not in disabled]
        else:
            current = [n for n in current if n not in disabled]
        for en in overlay.get("enabled", []):
            name = en["name"]
            if attr == "score":
                current.append((name, float(en.get("weight", 1))))
            elif name not in current:
                current.append(name)
        setattr(ps, attr, current)
    return ps


def _extender_from_dict(e: dict) -> ExtenderConfig:
    return ExtenderConfig(
        url_prefix=e.get("urlPrefix", ""),
        filter_verb=e.get("filterVerb", ""),
        prioritize_verb=e.get("prioritizeVerb", ""),
        bind_verb=e.get("bindVerb", ""),
        preempt_verb=e.get("preemptVerb", ""),
        weight=float(e.get("weight", 1)),
        http_timeout=float(e.get("httpTimeout", 30)),
        node_cache_capable=bool(e.get("nodeCacheCapable", False)),
        managed_resources=[
            ExtenderManagedResource(
                name=m.get("name", ""),
                ignored_by_scheduler=bool(m.get("ignoredByScheduler", False)),
            )
            for m in e.get("managedResources", []) or []
        ],
        ignorable=bool(e.get("ignorable", False)),
    )


def policy_to_config(policy: dict) -> KubeSchedulerConfiguration:
    """Legacy Policy JSON → internal config (createFromConfig,
    factory.go:239 + legacy_registry.go name mapping)."""
    cfg = KubeSchedulerConfiguration()
    cfg.profiles = [
        ProfileConfig(plugin_set=policy_to_plugin_set(policy))
    ]
    for e in policy.get("extenders", []) or []:
        cfg.extenders.append(_extender_from_dict(e))
    if "hardPodAffinitySymmetricWeight" in policy:
        cfg.hard_pod_affinity_weight = float(
            policy["hardPodAffinitySymmetricWeight"]
        )
    cfg.validate()
    return cfg


def policy_to_plugin_set(policy: dict) -> PluginSet:
    predicates = policy.get("predicates")
    priorities = policy.get("priorities")
    ps = default_plugin_set()
    if predicates is not None:
        filters: List[str] = []
        for pred in predicates:
            name = pred.get("name", "")
            if name == "GeneralPredicates":
                for plug in GENERAL_PREDICATES:
                    if plug not in filters:
                        filters.append(plug)
                continue
            plug = PREDICATE_TO_PLUGIN.get(name)
            if plug is None:
                raise ConfigError(f"unknown Policy predicate {name!r}")
            if plug not in filters:
                filters.append(plug)
        ps.filter = filters
        ps.pre_filter = [p for p in filters if p in _NEEDS_PRE_FILTER]
    if priorities is not None:
        scores: List[Tuple[str, float]] = []
        for pri in priorities:
            name = pri.get("name", "")
            plug = PRIORITY_TO_PLUGIN.get(name)
            if plug is None:
                raise ConfigError(f"unknown Policy priority {name!r}")
            scores.append((plug, float(pri.get("weight", 1))))
        ps.score = scores
        ps.pre_score = [p for p, _ in scores if p in _NEEDS_PRE_SCORE]
    return ps
