"""Preemption: victim selection + node choice when a pod cannot schedule.

Mirrors reference generic_scheduler.go Preempt(:270):
nodesWherePreemptionMightHelp(:1033) — candidates are nodes whose failure was
NOT UnschedulableAndUnresolvable (the device lattice returns this directly as
the `resolvable` mask, further narrowed by the batched device what-if,
ops/lattice.py preempt_whatif) → selectVictimsOnNode(:940) — remove
lower-priority pods, re-filter, then reprieve victims (PDB-violating ones
first, then by priority) → pickOneNodeForPreemption(:721) — lexicographic
tie-break whose first criterion is fewest PDB violations.

PDB budgets come from the disruption controller's published
status.disruptions_allowed (controller/disruption.py), matching
filterPodsWithPDBViolation (generic_scheduler.go:1089).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from ..api import objects as v1
from .cache.nodeinfo import NodeInfo, Snapshot
from .core import FitError
from .framework.interface import Code, CycleState, Status, is_success
from .framework.runtime import Framework


from ..api.selectors import match_labels as _match_labels


def filter_pods_with_pdb_violation(
    pods: List[v1.Pod], pdbs: List[v1.PodDisruptionBudget]
) -> Tuple[List[v1.Pod], List[v1.Pod]]:
    """Split candidate victims into (violating, non_violating): a pod
    violates if evicting it would push any matching PDB past its
    disruptionsAllowed (budget consumed in list order, like the reference's
    per-PDB countdown, generic_scheduler.go:1089)."""
    budget = {
        id(pdb): pdb.status.disruptions_allowed for pdb in pdbs
    }
    violating: List[v1.Pod] = []
    non_violating: List[v1.Pod] = []
    for pod in pods:
        matched = [
            pdb
            for pdb in pdbs
            if pdb.metadata.namespace == pod.metadata.namespace
            and _match_labels(pdb.spec.selector, pod.metadata.labels)
        ]
        if any(budget[id(pdb)] <= 0 for pdb in matched):
            violating.append(pod)
        else:
            for pdb in matched:
                budget[id(pdb)] -= 1
            non_violating.append(pod)
    return violating, non_violating


class Preemptor:
    def __init__(
        self,
        framework: Framework,
        pdb_lister: Optional[Callable] = None,
        extenders: Optional[list] = None,
    ):
        self.framework = framework
        self._pdbs = pdb_lister
        self.extenders = extenders or []

    def preempt(
        self,
        pod: v1.Pod,
        snapshot: Snapshot,
        fit_error: Optional[FitError] = None,
        candidate_nodes: Optional[List[str]] = None,
    ) -> Tuple[str, List[v1.Pod]]:
        """Returns (node_name, victims) or ("", []) when preemption won't help."""
        if not pod_eligible_to_preempt_others(pod, snapshot):
            return "", []
        if candidate_nodes is None:
            candidate_nodes = self._nodes_where_preemption_might_help(fit_error, snapshot)
        pdbs = list(self._pdbs()) if self._pdbs is not None else []
        victims_by_node: Dict[str, List[v1.Pod]] = {}
        violations_by_node: Dict[str, int] = {}
        for name in candidate_nodes:
            ni = snapshot.get(name)
            if ni is None or ni.node is None:
                continue
            result = self._select_victims_on_node(pod, ni, pdbs)
            if result is not None:
                victims_by_node[name], violations_by_node[name] = result
        if not victims_by_node:
            return "", []
        victims_by_node = self._process_preemption_with_extenders(
            pod, victims_by_node
        )
        if not victims_by_node:
            return "", []
        node = pick_one_node_for_preemption(
            victims_by_node, snapshot, violations_by_node
        )
        return node, victims_by_node.get(node, [])

    def _process_preemption_with_extenders(
        self, pod: v1.Pod, victims_by_node: Dict[str, List[v1.Pod]]
    ) -> Dict[str, List[v1.Pod]]:
        """processPreemptionWithExtenders (generic_scheduler.go:316): each
        preemption-capable interested extender narrows the candidate map."""
        for ext in self.extenders:
            if not victims_by_node:
                break
            if not ext.supports_preemption() or not ext.is_interested(pod):
                continue
            try:
                accepted = ext.process_preemption(pod, victims_by_node)
            except Exception:
                if ext.is_ignorable():
                    continue
                return {}
            new_map: Dict[str, List[v1.Pod]] = {}
            for node, names in accepted.items():
                old = victims_by_node.get(node)
                if old is None:
                    continue
                keep = set(names)
                new_map[node] = [p for p in old if p.metadata.name in keep]
            victims_by_node = new_map
        return victims_by_node

    def _nodes_where_preemption_might_help(
        self, fit_error: Optional[FitError], snapshot: Snapshot
    ) -> List[str]:
        if fit_error is None:
            return [ni.name for ni in snapshot.node_info_list]
        out = []
        for ni in snapshot.node_info_list:
            st = fit_error.filtered_nodes_statuses.get(ni.name)
            if st is None or st.code != Code.UNSCHEDULABLE_AND_UNRESOLVABLE:
                out.append(ni.name)
        return out

    def _select_victims_on_node(
        self, pod: v1.Pod, ni: NodeInfo, pdbs: List[v1.PodDisruptionBudget]
    ) -> Optional[Tuple[List[v1.Pod], int]]:
        """selectVictimsOnNode(:940): remove all lower-priority pods; if the
        pod then fits, reprieve victims — PDB-violating candidates first so
        budgeted pods survive when possible, then highest-priority-first.
        Returns (victims, numPDBViolations)."""
        node_copy = ni.clone()
        state = CycleState()
        st = self.framework.run_pre_filter_plugins(state, pod)
        if not is_success(st):
            return None
        potential = [p for p in node_copy.pods if p.priority < pod.priority]
        if not potential:
            return None
        for victim in potential:
            node_copy.remove_pod(victim.metadata.key)
            self.framework.run_pre_filter_extension_remove_pod(
                state, pod, victim, node_copy
            )
        if not is_success(self.framework.run_filter_plugins(state, pod, node_copy)):
            return None

        def reprieve(victim: v1.Pod) -> bool:
            node_copy.add_pod(victim)
            self.framework.run_pre_filter_extension_add_pod(
                state, pod, victim, node_copy
            )
            if is_success(self.framework.run_filter_plugins(state, pod, node_copy)):
                return True
            node_copy.remove_pod(victim.metadata.key)
            self.framework.run_pre_filter_extension_remove_pod(
                state, pod, victim, node_copy
            )
            return False

        violating, non_violating = filter_pods_with_pdb_violation(potential, pdbs)
        by_prio = lambda p: (-p.priority, p.status.start_time or 0)  # noqa: E731
        victims: List[v1.Pod] = []
        n_violations = 0
        for victim in sorted(violating, key=by_prio):
            if not reprieve(victim):
                victims.append(victim)
                n_violations += 1
        for victim in sorted(non_violating, key=by_prio):
            if not reprieve(victim):
                victims.append(victim)
        return (victims, n_violations) if victims else None


def pod_eligible_to_preempt_others(pod: v1.Pod, snapshot: Snapshot) -> bool:
    """podEligibleToPreemptOthers (:840): a preemptionPolicy of Never
    (from the pod's PriorityClass via admission) disqualifies outright;
    a pod that already nominated a node where a lower-priority victim is
    terminating waits instead of preempting again."""
    if pod.spec.preemption_policy == "Never":
        return False
    nominated = pod.status.nominated_node_name
    if nominated:
        ni = snapshot.get(nominated)
        if ni is not None:
            for p in ni.pods:
                if p.metadata.deletion_timestamp is not None and p.priority < pod.priority:
                    return False
    return True


def preemption_health_lines() -> List[str]:
    """The priority/preemption engine's counters/gauges (batched victim-
    selection passes, vector hits vs host fallbacks, guard trips, sampled
    oracle divergences, legacy preemption_* counters) rendered for the
    SIGUSR2 dump: whether the engine is on the vector happy path or
    degraded to the host walk is diagnosable from one signal. Empty until
    the first preemption attempt publishes a series."""
    from ..utils.metrics import metrics

    lines: List[str] = []
    for prefix in ("scheduler_preemption_", "preemption_"):
        for name, labels, value in metrics.snapshot_counters(prefix):
            lines.append(metrics.format_series_line(name, labels, value))
        for name, labels, value in metrics.snapshot_gauges(prefix):
            lines.append(metrics.format_series_line(name, labels, value))
    return lines


def pick_one_node_for_preemption(
    victims_by_node: Dict[str, List[v1.Pod]],
    snapshot: Snapshot,
    violations_by_node: Optional[Dict[str, int]] = None,
) -> str:
    """pickOneNodeForPreemption(:721) — lexicographic criteria:
    1. fewest PDB violations
    2. lowest maximum victim priority
    3. lowest sum of victim priorities
    4. fewest victims
    5. latest maximum start time among victims
    6. first in iteration order (reference: random among remainder)
    """
    violations_by_node = violations_by_node or {}

    def key(name: str):
        victims = victims_by_node[name]
        max_prio = max((p.priority for p in victims), default=-(2**31))
        sum_prio = sum(p.priority for p in victims)
        starts = [p.status.start_time or 0.0 for p in victims]
        latest_start = max(starts, default=0.0)
        return (
            violations_by_node.get(name, 0),
            max_prio,
            sum_prio,
            len(victims),
            -latest_start,
        )

    return min(sorted(victims_by_node.keys()), key=key)
