"""ComponentConfig: versioned scheduler configuration.

KubeSchedulerConfiguration equivalent (reference
pkg/scheduler/apis/config/types.go:46,111,178): leader election, profiles,
DisablePreemption, PercentageOfNodesToScore (0 ⇒ adaptive),
Pod{Initial,Max}BackoffSeconds — plus the TPU-native knobs (device batch
size/window, encoding capacities)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..client.leaderelection import LeaderElectionConfig
from ..ops.encoding import EncodingConfig
from .extender import ExtenderConfig


@dataclass
class ProfileConfig:
    scheduler_name: str = "default-scheduler"
    # plugin overrides: None = algorithm-provider defaults (a PluginSet)
    plugin_set: Optional[object] = None
    score_weights: Optional[Dict[str, float]] = None


@dataclass
class KubeSchedulerConfiguration:
    leader_election: Optional[LeaderElectionConfig] = None
    disable_preemption: bool = False
    percentage_of_nodes_to_score: int = 0  # 0 => adaptive 50 - n/125
    pod_initial_backoff_seconds: float = 1.0
    pod_max_backoff_seconds: float = 10.0
    profiles: List[ProfileConfig] = field(
        default_factory=lambda: [ProfileConfig()]
    )
    extenders: List["ExtenderConfig"] = field(default_factory=list)
    hard_pod_affinity_weight: float = 1.0
    # RequestedToCapacityRatio piecewise shape ((utilization%, 0..10), ...);
    # None = the default {0%:0, 100%:10}. Threaded into BOTH the host
    # plugin and the device kernels (static per profile — a distinct shape
    # is a distinct kernel variant), so non-default profiles stay
    # device/host-consistent (requested_to_capacity_ratio.go:33)
    rtc_shape: Optional[List[Tuple[float, float]]] = None
    coscheduling_permit_timeout: float = 30.0  # gang quorum wait (Permit)
    # --- TPU-native section -------------------------------------------------
    use_device: bool = True  # TPUBatchScore profile gate
    use_mesh: bool = True  # shard the snapshot over all visible devices
    # (node-axis pjit; single-device processes run the unsharded kernel)
    # 0 = auto: 4096 on TPU backends (the kernel is template-shaped — the
    # pod axis appears only in small per-pod vectors, so a 4x batch costs
    # ~nothing on device and divides the fixed per-cycle sync cost by 4),
    # 1024 on CPU where kernel compute DOES scale with the batch
    device_batch_size: int = 0
    device_batch_window: float = 0.01  # linger to let bursts accumulate (tunnel
    # RTT dwarfs 10ms; fuller batches amortize it); the former is adaptive —
    # it ships early once arrivals go idle (~3 ms), so this is a burst cap,
    # not a per-pod latency floor
    # batches at or below this size take the HOST path (the reference-shaped
    # per-pod scheduleOne) when the cluster is small enough that the Python
    # chain beats a device cycle (kernel + >=1 readback RTT). This is part
    # of the low-load p99 story (r4 verdict #4): the 450 ms kernel must not
    # serve a 1-pod batch. At larger clusters the host chain is SLOWER than
    # the kernel, so the gate is two-sided; big clusters use the small-pad
    # kernel variant with a narrow candidate list instead. 0 disables.
    small_batch_host_max: int = 4
    small_batch_host_node_max: int = 256
    # m_cand for the small padded-batch bucket (<=256 pods): a narrow
    # candidate list cuts the per-wave [P, M]-scaling cost ~4x for the
    # latency-sensitive tiny batches; 32 candidates per pod is ample when
    # the whole batch is 256 pods (the big bucket keeps wave_m_cand)
    wave_m_cand_small: int = 32
    # wave-pipeline depth: up to depth-1 launched batches stay in flight and
    # resolve in ONE combined device->host readback (the donated snapshot
    # chains batches on-device, so the tunnel RTT is paid once per depth-1
    # batches instead of once per batch). 1 = fully synchronous, 2 = the old
    # depth-1 pipeline. Sustained-load readbacks/batch = 1/(depth-1).
    # 0 = auto: the scheduler measures the device->host readback RTT at
    # start and picks 6 when the readback is expensive (remote/tunneled
    # device) or 2 when it is sub-ms (local device / CPU, where deep
    # pipelining only adds latency and host/device CPU contention).
    pipeline_depth: int = 0
    # split-phase readback (round 17): the kernel's chosen/placed/deferred
    # index payload (a few KB) streams back through an async device->host
    # copy started AT DISPATCH, so the bind-critical resolve never joins
    # with the bulk score/audit tensors — those trail in a second transfer
    # the guards consume off the critical path (a late disagreement
    # quarantines + unwinds through the suspect-row machinery). None =
    # auto (on); False restores the round-16 combined readback.
    split_phase_readback: Optional[bool] = None
    # depth-infinity micro-waves (experimental): deliver the fast index
    # payload through a jax.experimental.io_callback fired ON DEVICE the
    # moment the kernel resolves, so the host observes wave N without
    # issuing any device->host sync call at all. Off by default — the
    # async-copy fast path already removes the readback join, and the
    # callback variant is a separate jit cache entry per kernel shape.
    host_callback_binds: bool = False
    # bound on trailing bulk readbacks awaiting validation: past this the
    # oldest is force-drained (one blocking readback) rather than letting
    # unvalidated payloads — and their generation pins — pile up behind a
    # slow tunnel
    trailing_readback_max: int = 8
    encoding: EncodingConfig = field(default_factory=EncodingConfig)
    bind_workers: int = 16
    assume_ttl_seconds: float = 30.0
    # wave kernel (ops/wavelattice.py): vectorized bulk pass + W commit waves
    use_wave: bool = True  # False => serial scan lattice (oracle-exact)
    # route the wave kernel's resource-fit mask (fits0 + per-wave fits_w)
    # through the fused Pallas kernel (ops/pallas_ops.py) instead of the
    # XLA broadcast. None = auto: ON for TPU (measured on v5e, r5: 3185
    # vs 1696 pods/s on SchedulingPodAffinity/5000 — the fused mask avoids
    # materializing the [TPL, N, R] broadcast in HBM), OFF on CPU where
    # pallas runs interpreted. Explicit True/False overrides.
    use_pallas_fit: Optional[bool] = None
    # per-wave resource-score refresh at candidate nodes: later waves see
    # in-batch commits in their packing decisions (serial fidelity) for
    # O(P·M) gathers per wave. None = auto: ON for TPU backends (the cost
    # is noise next to the [TPL, N] stages there) and OFF on CPU, where
    # the same gathers are ~25% of kernel wall (measured: 898 -> 665
    # pods/s on the CPU A/B with it forced on). Explicit True/False
    # overrides; False is the round-3 behavior. Pinned by
    # test_wave_score_refresh_sees_in_batch_commits either way.
    wave_score_refresh: Optional[bool] = None
    # debug: cross-check every device placement against the HOST filter
    # chain per cycle (SURVEY §5's per-cycle verify mode — the live
    # analogue of the offline differential fuzz). Costs a host snapshot +
    # plugin run per placement; off outside debugging
    verify_cycles: bool = False
    # top-M candidate nodes per template. 0 = auto: 256 on CPU (r5 sweep,
    # per-wave cost scales with M x P: PodAffinity 978 -> 1513-1558
    # pods/s at 5k nodes, AntiAffinity +41%, Spreading +56%, everything
    # still fully scheduled — pods that miss the narrow list defer and
    # retry in the next batch's fresh waves); 512 on TPU, where the auto
    # batch is 4096 and a zone-concentrated single-template burst needs
    # enough distinct targets per batch (the hardware wavesweep arm
    # settles it). Explicit values override.
    wave_m_cand: int = 0
    # conflict-resolution waves for batches with hard (anti-affinity/
    # spread) pairs; static trip count — every such batch pays all waves
    # (the axon tunnel hangs on data-dependent while_loops). Batches
    # whose PRESENT templates carry no hard pairs use min(2,
    # wave_n_waves) (scheduler._batch_waves; measured 2020 vs 1602
    # pods/s on CPU at 5k nodes). Retuned 32 -> 16 (r5 sweep: 8 measured
    # marginally faster still, but 16 keeps headroom for dense hard-pair
    # shapes the sweep didn't cover).
    wave_n_waves: int = 16
    sync_batch_bind: bool = True  # bulk bind in-cycle when no permit/prebind
    # degraded-store ride-through (scheduler/ridethrough.py): placements
    # whose bind 503s retryably park here (pods stay assumed, HBM snapshot
    # stays warm) while the breaker pauses dispatch; beyond capacity the
    # overflow unwinds through backoff like a failed bind
    pending_bind_capacity: int = 8192
    # --- data-plane self-defense (scheduler/antientropy.py, guards) ---------
    # validate every read-back batch before assume: chosen rows in range,
    # scores finite, plus the sampled host-oracle feasibility re-check
    # below; a violation quarantines the batch to the host fallback path
    # and forces a device snapshot rebuild (wrong placements become
    # structurally impossible — at worst a wave runs at host speed)
    kernel_output_guards: bool = True
    # pods per committed wave re-checked against the host filter chain's
    # pre-batch-sound subset (the online analogue of the differential
    # fuzz's oracle); 0 disables the sampled oracle (range/finite checks
    # stay on)
    guard_sample_per_wave: int = 4
    # snapshot anti-entropy: background auditor period (0 disables),
    # sampled rows per pass, and the consecutive-drifting-pass count that
    # escalates targeted re-scatter repair to a full snapshot rebuild
    antientropy_period_s: float = 5.0
    antientropy_sample_rows: int = 64
    antientropy_rebuild_after: int = 3
    # device-loss ride-through: bounded jittered retries for kernel
    # launches/readbacks that die with a device-loss error, and the
    # consecutive-loss count after which the device path is abandoned for
    # the host path (a chip that passes probes but fails every kernel
    # must not retry forever)
    device_retry_attempts: int = 2
    device_loss_disable_after: int = 3
    # --- priority & preemption (ops/preemptlattice.py) ----------------------
    # named score policy (ops/lattice.WEIGHT_PROFILES: "default", "pack",
    # "cheapest", "energy") or "" = derive weights from the profile's
    # score-plugin set. Policies are runtime weight VECTORS (a kernel
    # input), swappable live via Scheduler.set_score_policy.
    score_policy: str = ""
    # policy gym (tuner/): record real waves, replay candidate weight
    # vectors against them in a background loop, and promote winners
    # through a shadow A/B gate (persisted as the ScorePolicy API object
    # so failover adopts the tuned vector). Off by default — the tuner is
    # an opt-in control loop, not a scheduling dependency.
    tune_policy: bool = False
    # vectorized victim selection: one batched device pass ranks candidate
    # (node, victim-band) choices for a whole wave of unschedulable pods;
    # the host oracle (Preemptor._select_victims_on_node) still validates
    # the chosen node and selects the EXACT victim set before any
    # eviction. False = the per-pod host scan only (the pre-ISSUE-15 path)
    vector_preemption: bool = True
    # unschedulable pods per batch whose vector choice is ALSO checked
    # against the full host-path Preemptor scan (the sampled differential
    # oracle; a divergence beyond the documented tie-breaks counts in
    # scheduler_preemption_oracle_divergence_total and the oracle's answer
    # wins). 0 disables sampling (the per-node exact check stays on)
    preempt_verify_sample: int = 2

    def validate(self) -> None:
        if self.percentage_of_nodes_to_score < 0 or self.percentage_of_nodes_to_score > 100:
            raise ValueError("percentageOfNodesToScore must be in [0,100]")
        if self.pod_initial_backoff_seconds <= 0:
            raise ValueError("podInitialBackoffSeconds must be positive")
        if self.pod_max_backoff_seconds < self.pod_initial_backoff_seconds:
            raise ValueError("podMaxBackoffSeconds must be >= podInitialBackoffSeconds")
        if not self.profiles:
            raise ValueError("at least one profile required")
        names = [p.scheduler_name for p in self.profiles]
        if len(set(names)) != len(names):
            raise ValueError("duplicate profile schedulerName")
        if self.device_batch_size < 0:
            raise ValueError("device_batch_size must be >= 1, or 0 for auto")
        if self.pipeline_depth < 0:
            raise ValueError("pipeline_depth must be >= 1, or 0 for auto")
        if self.trailing_readback_max < 1:
            raise ValueError("trailing_readback_max must be >= 1")
        if self.pending_bind_capacity < 1:
            raise ValueError("pending_bind_capacity must be >= 1")
        if self.guard_sample_per_wave < 0:
            raise ValueError("guard_sample_per_wave must be >= 0")
        if self.antientropy_period_s < 0:
            raise ValueError("antientropy_period_s must be >= 0 (0 disables)")
        if self.antientropy_sample_rows < 1:
            raise ValueError("antientropy_sample_rows must be >= 1")
        if self.antientropy_rebuild_after < 1:
            raise ValueError("antientropy_rebuild_after must be >= 1")
        if self.device_retry_attempts < 0:
            raise ValueError("device_retry_attempts must be >= 0")
        if self.device_loss_disable_after < 1:
            raise ValueError("device_loss_disable_after must be >= 1")
        if self.preempt_verify_sample < 0:
            raise ValueError("preempt_verify_sample must be >= 0")
        if self.score_policy:
            from ..ops.lattice import weights_for_policy

            weights_for_policy(self.score_policy)  # raises on unknown names
        if self.leader_election is not None:
            self.leader_election.validate()
