"""The Scheduler: event pipeline → batched device cycles → assume → bind.

Top-loop equivalent of reference pkg/scheduler/scheduler.go:79 (Scheduler),
:363 (Run), :548 (scheduleOne), re-shaped around the TPU data plane:

  reference                           this build
  ---------                           ----------
  queue.Pop one pod                   queue.pop_batch(P) — batch former
  UpdateSnapshot (generation diff)    encoder.flush() — device row scatter
  findNodesThatFitPod / prioritize    one fused lattice kernel for the batch
  (16 goroutines over nodes)          (vmap/scan over pods×nodes on device)
  selectHost                          on-device argmax + random tie-break
  assume + async bind goroutine       assume + bind worker pool (unchanged)
  preempt on FitError                 host preemption seeded by the kernel's
                                      resolvable mask (see preemption.py)

Pods whose spec overflows the static device encoding run the host fallback
path (core.GenericScheduler) — same plugins, same outcome, lower throughput;
mirrors how the reference lets extenders post-process a narrowed node set
(generic_scheduler.go:421).
"""

from __future__ import annotations

import itertools
import logging
import random
import threading
import time
from contextlib import contextmanager
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..api import objects as v1
from ..client.apiserver import APIServer, LeaderFenced, NotFound, NotPrimary
from ..client.informers import SharedInformerFactory
from ..runtime.consensus import DegradedWrites
from ..controller.volume_scheduling import VolumeBinder
from ..api.objects import Binding
from ..ops.batch import encode_pod_batch
from ..ops.encoding import ETERM_ANTI_REQ as _ETERM_ANTI_REQ
from ..ops.preemptlattice import validate_preempt_outputs
from ..ops.templates import TemplateCache, build_pair_table
from ..ops.wavelattice import make_wave_kernel_jit
from ..ops import hostcallback
from ..ops.lattice import (
    GUARD_TRAILING_LOSS,
    KernelGuardTrip,
    NUM_SCORE_COMPONENTS,
    SC_BALANCED,
    SC_IMAGE,
    SC_INTERPOD,
    SC_LEAST_ALLOC,
    SC_MOST_ALLOC,
    SC_NODE_AFFINITY,
    SC_PREFER_AVOID,
    SC_REQ_TO_CAP,
    SC_SELECTOR_SPREAD,
    SC_TAINT,
    SC_TOPO_SPREAD,
    make_schedule_batch,
    validate_batch_outputs,
    validate_trailing_score,
    weights_for_policy,
)
from ..parallel.sharded import (
    call_with_device_retry,
    device_retry_delay,
    is_device_loss_error,
)
from ..utils.metrics import metrics
from ..utils.trace import Trace
from ..utils.tracing import tracer
from .cache.cache import SchedulerCache
from .config import KubeSchedulerConfiguration
from .core import FitError, GenericScheduler
from .extender import build_extenders
from .framework.interface import Code, CycleState, is_success
from .preemption import Preemptor
from .profile import ProfileMap, new_profile_map
from .queue import PriorityQueue, QueuedPodInfo
from .ridethrough import COUNTER_RECONCILED, BindRideThrough, PendingBind
from .ha import (
    COUNTER_ADOPTIONS,
    COUNTER_FENCED_BINDS,
    COUNTER_PROMOTIONS,
    COUNTER_STANDBY_FLUSHES,
    COUNTER_STANDBY_WARMUPS,
    GAUGE_ROLE,
    GAUGE_STANDBY_SNAPSHOT_AGE,
)
from . import eventhandlers

logger = logging.getLogger("kubernetes_tpu.scheduler")

# wave pipeline observability: batches launched-but-unresolved right now,
# the high-water mark since start (the "≥2 waves in flight" acceptance
# gauge), and the configured/auto-probed pipeline depth
GAUGE_WAVE_INFLIGHT = "scheduler_wave_inflight"
GAUGE_WAVE_INFLIGHT_MAX = "scheduler_wave_inflight_max"
GAUGE_WAVE_PIPELINE_DEPTH = "scheduler_wave_pipeline_depth"
# split-phase readback counters (round 17): fast = index-payload fetches
# (the bind-critical resolve), blocking = fetches that actually had to
# wait on the device (the readbacks_per_bind numerator), trailing = bulk
# score fetches consumed off the critical path, hostcb = fast payloads
# delivered by the kernel's own io_callback (no host-issued sync at all)
COUNTER_WAVE_FAST_READBACKS = "scheduler_wave_fast_readbacks_total"
COUNTER_WAVE_BLOCKING_READBACKS = "scheduler_wave_readbacks_blocking_total"
COUNTER_WAVE_TRAILING_READBACKS = "scheduler_wave_trailing_readbacks_total"
COUNTER_WAVE_TRAILING_UNWOUND = "scheduler_wave_trailing_unwound_assumes_total"
COUNTER_WAVE_HOSTCB = "scheduler_wave_hostcb_deliveries_total"
GAUGE_WAVE_TRAILING_BACKLOG = "scheduler_wave_trailing_backlog"


def _device_ready(arr) -> bool:
    """True when a device array's value is already materialized (its
    fetch would not block). Host numpy (or anything without is_ready,
    e.g. an injector-substituted array) counts as ready."""
    is_ready = getattr(arr, "is_ready", None)
    if is_ready is None:
        return True
    try:
        return bool(is_ready())
    except Exception:
        return True


@contextmanager
def _stage_timer(stage: str):
    """Feed the bench's stage_breakdown_s (encode vs kernel time per batch).

    Records wall AND this-thread CPU time: on a saturated box a stage's
    wall inflates with GIL/scheduler starvation from unrelated threads,
    which is unattributable from wall alone (the r5 soak recorded a 30 s
    'finish' wall whose actual work was ~0.7 s). The CPU series is the
    work; the wall minus CPU is time spent descheduled or blocked."""
    t0 = time.monotonic()
    c0 = time.thread_time()
    try:
        yield
    finally:
        metrics.observe(
            "scheduling_stage_duration_seconds",
            time.monotonic() - t0,
            {"stage": stage},
        )
        metrics.observe(
            "scheduling_stage_cpu_seconds",
            time.thread_time() - c0,
            {"stage": stage},
        )

class _InFlightBatch:
    """A wave batch whose kernel is dispatched but whose results haven't
    been read back yet (pipeline depth 1)."""

    __slots__ = (
        "pis", "eb", "row_names", "res", "moves0", "trace", "t_start",
        "snapshot", "launch_gen", "wave_tid", "t_launched", "weights",
        "rng_key", "ticket", "trailing",
    )

    def __init__(
        self, pis, eb, row_names, res, moves0, trace, t_start, snapshot=None,
        launch_gen=0, wave_tid="", t_launched=0.0, weights=None, rng_key=None,
        ticket=None,
    ):
        self.pis = pis
        self.eb = eb
        self.row_names = row_names
        self.res = res
        self.moves0 = moves0
        self.trace = trace
        self.t_start = t_start
        # per-wave trace (utils/tracing.py): the fan-in id the N pod
        # traces of this batch reference, plus the launch-complete stamp
        # the resolve path closes the shared `device` span against
        self.wave_tid = wave_tid
        self.t_launched = t_launched
        # host snapshot captured AT LAUNCH (verify_cycles only): the state
        # the device encoding was built from — verifying against resolve-
        # time state would report informer churn as device/host mismatches
        self.snapshot = snapshot
        # cache EXTERNAL generation at launch: the oracle guard skips nodes
        # whose ext_generation moved past this (informer churn after the
        # encoding was captured is not a kernel-correctness signal).
        # Scheduler assumes don't move ext_generation, so sibling-batch
        # commits — state the device chain already saw — keep their nodes
        # eligible for the check
        self.launch_gen = launch_gen
        # the exact weight vector + PRNG key the kernel launched with:
        # the policy-gym replay buffer records them at commit so a
        # differential replay reproduces THIS launch, not whatever the
        # live policy is by then
        self.weights = weights
        self.rng_key = rng_key
        # host_callback_binds: the delivery-registry ticket the kernel's
        # io_callback posts this batch's fast index payload under
        self.ticket = ticket
        # split-phase readback: the _TrailingReadback registered at fast
        # commit (None when nothing was placed, or in combined mode) —
        # whoever consumes it finishes the wave trace
        self.trailing = None


class _TrailingReadback:
    """The bulk half of one batch's split-phase resolve: the score
    vector whose fetch + validation trail the bind-critical commit. The
    entry holds a generation pin from fast-commit until its readback
    lands (the graftlint lease discipline: a late disagreement must
    still be able to name suspect rows in the generation the fast
    payload committed into), and remembers enough of the fast decision
    (placed mask + to_bind tuples) to unwind it."""

    __slots__ = (
        "score", "placed", "to_bind", "launch_gen", "wave_tid", "pin",
        "binds_issued", "quarantined", "gated", "t_registered", "path",
    )

    def __init__(
        self, score, placed, to_bind, launch_gen, wave_tid, pin,
        path="wave",
    ):
        self.score = score
        self.placed = placed
        self.to_bind = to_bind
        self.launch_gen = launch_gen
        self.wave_tid = wave_tid
        self.pin = pin
        # False until this entry's batch dispatched its binds: an unwind
        # before then reverts assumes (nothing left the process); after,
        # the bound pods stay and only the snapshot quarantines
        self.binds_issued = False
        self.quarantined = False
        # True only while this entry's own pre-bind gate is draining:
        # tells _unwind_trailing the gate owns the assume revert (it has
        # the per-pod assume errors), preventing a double requeue
        self.gated = False
        self.t_registered = time.monotonic()
        self.path = path

    def ready(self) -> bool:
        return _device_ready(self.score)


_SCORE_NAME_TO_COMPONENT = {
    "NodeResourcesLeastAllocated": SC_LEAST_ALLOC,
    "NodeResourcesMostAllocated": SC_MOST_ALLOC,
    "NodeResourcesBalancedAllocation": SC_BALANCED,
    "RequestedToCapacityRatio": SC_REQ_TO_CAP,
    "NodeAffinity": SC_NODE_AFFINITY,
    "TaintToleration": SC_TAINT,
    "ImageLocality": SC_IMAGE,
    "NodePreferAvoidPods": SC_PREFER_AVOID,
    "PodTopologySpread": SC_TOPO_SPREAD,
    "InterPodAffinity": SC_INTERPOD,
    "DefaultPodTopologySpread": SC_SELECTOR_SPREAD,
}


class _FencedBindSurface:
    """The API surface handed to bind plugins (the framework context's
    ``server``): ``bind_pod``/``bind_pods`` funnel through the scheduler's
    fence-attaching seam (``_bind_pods_fenced``) so the per-pod plugin
    path carries the SAME leadership fence as batch binds — the store (or
    the REST /binding route) rejects a deposed replica's bind with
    LeaderFenced before anything applies. Every other attribute proxies to
    the real server, so out-of-tree plugins built against the APIServer
    surface keep working unchanged."""

    def __init__(self, sched: "Scheduler"):
        self._sched = sched

    def bind_pod(self, binding) -> None:
        errs = self._sched._bind_pods_fenced([binding])
        err = errs[0] if errs else None
        if err is None:
            return
        if isinstance(err, Exception):
            raise err
        raise RuntimeError(str(err))

    def bind_pods(self, bindings, fence=None) -> list:
        # a caller-supplied fence is ignored on purpose: the scheduler's
        # armed fence is the one source of truth for its own binds
        return self._sched._bind_pods_fenced(bindings)

    def __getattr__(self, name: str):
        return getattr(self._sched.server, name)


class Scheduler:
    def __init__(
        self,
        server: APIServer,
        config: Optional[KubeSchedulerConfiguration] = None,
    ):
        self.cfg = config or KubeSchedulerConfiguration()
        self.cfg.validate()
        self.server = server
        self.cache = SchedulerCache(
            ttl_seconds=self.cfg.assume_ttl_seconds,
            encoding_config=self.cfg.encoding,
        )
        self._snapshot = None  # latest host snapshot (fallback/preemption)
        self.volume_binder = VolumeBinder(server)
        # which transport enforces the leadership bind fence for this
        # scheduler: "rest" when the (cache-unwrapped) backend is a
        # RESTClient — the /binding route validates the X-Leadership-Fence
        # header — else "local" (the in-process store's bind lock). Labels
        # scheduler_ha_fenced_binds_total so a deployment can see WHERE
        # its zombies are being stopped.
        from ..apiserver.client import RESTClient

        self._bind_transport = (
            "rest"
            if isinstance(getattr(server, "store", server), RESTClient)
            else "local"
        )
        context = {
            # bind plugins get the fence-attaching surface, not the raw
            # server: every per-pod DefaultBinder bind funnels through
            # _bind_pods_fenced exactly like batch binds (reads and
            # non-bind writes pass through untouched)
            "server": _FencedBindSurface(self),
            "snapshot_getter": lambda: self._snapshot,
            "hard_pod_affinity_weight": self.cfg.hard_pod_affinity_weight,
            "volume_binder": self.volume_binder,
            "csinode_getter": self._csinode,
            "services_lister": lambda: server.list("services")[0],
            "selectors_for_pod": self._selectors_for_pod,
            "coscheduling_permit_timeout": self.cfg.coscheduling_permit_timeout,
            # extender managedResources flagged ignoredByScheduler: the
            # extender owns their accounting (fit.go IgnoredResources)
            "ignored_extended_resources": frozenset(
                m.name
                for e in self.cfg.extenders
                for m in e.managed_resources
                if m.ignored_by_scheduler
            ),
            "rtc_shape": self.cfg.rtc_shape,
        }
        # static per profile: part of the kernel-variant key so a custom
        # shape compiles its own variant and matches the host plugin
        self._rtc_shape = tuple(
            sorted(tuple(p) for p in (self.cfg.rtc_shape or ()))
        ) or None
        self.profiles: ProfileMap = new_profile_map(self.cfg, context, server=server)
        # queue order comes from the default profile's QueueSort plugin
        # (Configurator wires profiles[0].QueueSortFunc into the queue,
        # factory.go:127; coscheduling overrides it to keep gangs adjacent)
        default_fw = next(iter(self.profiles.values())).framework
        self.queue = PriorityQueue(
            less=default_fw.queue_sort_less,
            pod_initial_backoff=self.cfg.pod_initial_backoff_seconds,
            pod_max_backoff=self.cfg.pod_max_backoff_seconds,
        )
        self.informer_factory = SharedInformerFactory(server)
        self.extenders = build_extenders(self.cfg.extenders)
        self._algo: Dict[str, GenericScheduler] = {
            name: GenericScheduler(
                p.framework,
                self.cfg.percentage_of_nodes_to_score,
                extenders=self.extenders,
            )
            for name, p in self.profiles.items()
        }
        def list_pdbs():
            try:
                pdbs, _ = self.server.list("poddisruptionbudgets")
                return pdbs
            except Exception:
                return []

        self._preemptors = {
            name: Preemptor(
                p.framework, pdb_lister=list_pdbs, extenders=self.extenders
            )
            for name, p in self.profiles.items()
        }
        # one home for the PDB read both the vectorized engine (budget
        # column refresh) and the divergence key share with the Preemptors
        self._list_pdbs = list_pdbs
        self._bind_pool = ThreadPoolExecutor(
            max_workers=self.cfg.bind_workers, thread_name_prefix="binder"
        )
        self._stop = threading.Event()
        self._sched_thread: Optional[threading.Thread] = None
        self._rng_counter = itertools.count()
        self._rng_key = jax.random.PRNGKey(0)
        self._mesh = None  # set by start() when >1 device is visible
        # wave pipeline: launched-but-unresolved batches, oldest first. The
        # donated snapshot chains batches on-device, so up to
        # cfg.pipeline_depth-1 batches stay in flight and resolve with ONE
        # combined device->host readback — the ~65 ms tunnel RTT is paid
        # once per depth-1 batches, and the newest batch's device time still
        # overlaps the readback + host bind work (the TPU-shaped analogue
        # of the reference's async binding goroutine overlapping the next
        # scheduleOne, scheduler.go:666, taken to its batch conclusion).
        self._pending: List[_InFlightBatch] = []
        self._wave_inflight_peak = 0  # high-water mark of len(_pending)
        # split-phase readback (round 17): resolve on the fast index
        # payload alone (async-copied at dispatch), validate the trailing
        # bulk score off the critical path. auto = on; False restores the
        # combined readback (the A/B baseline arm).
        self._split_phase = (
            self.cfg.split_phase_readback
            if self.cfg.split_phase_readback is not None
            else True
        )
        # trailing bulk readbacks registered at fast commit, oldest
        # first; drained non-blocking before each launch and in the
        # loop's idle beat (scheduling-loop thread only)
        self._trailing: List[_TrailingReadback] = []
        # resolved by start() when cfg.pipeline_depth == 0 (auto)
        self._pipeline_depth = self.cfg.pipeline_depth or 2
        # auto batch size: TPU backends take the big batch (template-shaped
        # kernel: near-free on device, divides the fixed sync cost), CPU
        # keeps the small one (its kernel compute scales with the batch)
        self._batch_size = self.cfg.device_batch_size or (
            4096 if jax.default_backend() == "tpu" else 1024
        )
        # the latency (ragged-tail) kernel bucket: one home for the value
        # the batch-fill policy, the launch bucketing, and the standby
        # warm-up all reason about
        self._small_bucket = min(256, self._batch_size)
        # auto: serial-fidelity refresh where it's free (TPU); the same
        # [P, M] per-wave gathers are ~25% of CPU kernel wall
        self._score_refresh = (
            self.cfg.wave_score_refresh
            if self.cfg.wave_score_refresh is not None
            else jax.default_backend() == "tpu"
        )
        # auto: the fused pallas fit mask wins on real TPU (r5 A/B: 3185
        # vs 1696 pods/s) but runs interpreted (slow) on CPU
        self._use_pallas_fit = (
            self.cfg.use_pallas_fit
            if self.cfg.use_pallas_fit is not None
            else jax.default_backend() == "tpu"
        )
        # auto m_cand: 256 measured best on CPU at 5k nodes (+55% over
        # 512, r5 sweep); TPU keeps 512 — its auto batch is 4096 and a
        # zone-concentrated single-template burst needs enough distinct
        # targets per batch (the TPU wavesweep arm will settle it on
        # hardware). Explicit values override.
        self._m_cand = self.cfg.wave_m_cand or (
            512 if jax.default_backend() == "tpu" else 256
        )
        self._busy = False  # scheduling loop mid-batch (wait_for_idle)
        # degraded-store ride-through (ridethrough.py): binds refused with
        # a retryable 503 park here while the pods stay assumed; the
        # breaker pauses batch dispatch until the store reopens
        self._ridethrough = BindRideThrough(
            capacity=self.cfg.pending_bind_capacity
        )
        # data-plane self-defense state: the anti-entropy auditor
        # (started in start()), the device-down latch (host-path fallback
        # after unrecoverable device loss), and the consecutive-failure
        # counters that decide when retrying stops being worth it
        self._auditor = None
        self._device_down = False
        self._consecutive_device_loss = 0
        self._consecutive_guard_trips = 0
        self._weights = self._build_weights()
        self._score_policy_name = (
            self.cfg.score_policy
            if isinstance(self.cfg.score_policy, str) and self.cfg.score_policy
            else "default"
        )
        # policy-gym attachment point (tuner/waves.WaveRingBuffer when a
        # PolicyTuner is running): device paths record committed waves
        # here; None = recording off, zero hot-path cost
        self.wave_recorder = None
        self._tpl_cache = TemplateCache(self.cache.encoder)
        self._pair_cache: Optional[tuple] = None  # (sig, table)
        # scheduler HA (ha.py): the leadership fencing token armed by
        # promote() — every batch bind carries it so a zombie ex-leader's
        # late binds are rejected at the store — plus the warm-standby
        # refresh loop state (keeps the HBM snapshot tracking informer
        # churn while no scheduling loop runs)
        self._bind_fence = None
        # process-wide shared eviction budget (controller/evictionbudget.
        # EvictionBudget), injected by the process wiring when this
        # scheduler coexists with other evictors: preemption victim
        # deletes then spend the SAME bucket as nodelifecycle drains and
        # descheduler waves. None (default) = unthrottled preemption, the
        # pre-budget behavior every bench and single-evictor rig keeps.
        self.eviction_budget = None
        self._ha_identity = "scheduler-0"
        self._standby_stop = threading.Event()
        self._standby_thread: Optional[threading.Thread] = None
        self._standby_last_fresh: Optional[float] = None
        # a Cacher created FOR this scheduler (cmd/scheduler.run): stop()
        # tears it down with us, or every run/stop cycle would leak one
        # store watch per kind plus the bookmark thread
        self._owned_read_cache = None
        eventhandlers.add_all_event_handlers(self)

    # -- wiring --------------------------------------------------------------

    def _csinode(self, name: str):
        try:
            return self.server.get("csinodes", "", name)
        except NotFound:
            return None

    def _selectors_for_pod(self, pod: v1.Pod):
        """Selectors of Services matching the pod (SelectorSpread's lister —
        getSelectors in default_pod_topology_spread.go:43)."""
        from ..api.selectors import selector_from_match_labels
        from .framework.plugins.helpers import services_matching_pod

        services, _ = self.server.list("services")
        return [
            selector_from_match_labels(sel)
            for sel in services_matching_pod(services, pod)
        ]

    def _build_weights(self) -> np.ndarray:
        # an explicit score policy (name or raw vector) overrides the
        # profile-derived weights wholesale: policies ARE weight vectors
        # (ops/lattice.WEIGHT_PROFILES), a kernel input — never a recompile
        if self.cfg.score_policy:
            return weights_for_policy(self.cfg.score_policy)
        w = np.zeros(NUM_SCORE_COMPONENTS, np.float32)
        default = next(iter(self.profiles.values()))
        for name, weight in default.framework.plugin_set.score:
            idx = _SCORE_NAME_TO_COMPONENT.get(name)
            if idx is not None:
                w[idx] = weight
        return w

    def set_score_policy(self, policy) -> None:
        """Swap the live score policy at runtime: `policy` is a name from
        ops/lattice.WEIGHT_PROFILES or a raw [NUM_SCORE_COMPONENTS]
        vector. The weight vector is a per-launch kernel INPUT, so the
        swap takes effect on the next wave with zero recompilation —
        the seam the ROADMAP-5 policy gym promotes tuned vectors through.
        In-flight waves keep the vector they launched with."""
        self._weights = weights_for_policy(policy)
        previous = self._score_policy_name
        self._score_policy_name = (
            policy if isinstance(policy, str) else "custom"
        )
        metrics.inc("scheduler_score_policy_swaps_total")
        from ..tuner.policy import set_active_policy_gauge

        set_active_policy_gauge(self._score_policy_name, previous)

    def _adopt_persisted_score_policy(self) -> None:
        """Adopt the ScorePolicy API object the policy gym persisted, if
        one exists and validates — the restart/failover half of the
        promotion gate (a tuned vector must survive its promoter). Never
        raises: a degraded store or invalid object is a counted skip
        (tuner_policy_adoptions_total{outcome=...}) and the current
        weights stand."""
        from ..tuner.policy import adopt_persisted_policy

        try:
            name = adopt_persisted_policy(self.server)
        except Exception:
            logger.exception("persisted score-policy adoption failed")
            return
        if name is None:
            return
        changed = name != self._score_policy_name
        # apply even when the name matches: adoption just re-registered
        # the persisted VECTOR under that name, and this process's copy
        # may predate the promotion that wrote it
        self.set_score_policy(name)
        if changed:
            logger.warning(
                "scheduler %s adopted persisted score policy %r",
                self._ha_identity, name,
            )

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        """informers → WaitForCacheSync → queue/janitor/scheduling loops
        (app.Run, cmd/kube-scheduler/app/server.go:142). Equivalent to
        _bringup() + promote() with no standby phase in between — the
        non-HA path every existing caller keeps."""
        self._bringup()
        self.promote()

    def _bringup(self) -> None:
        """The leader/standby-shared device bring-up: informers →
        WaitForCacheSync → presized encoder → mesh sharding → warm
        scatter programs. After this the HBM snapshot mirrors the synced
        cluster; nothing schedules yet."""
        self.informer_factory.start()
        self.informer_factory.wait_for_cache_sync()
        # presize device capacities from the synced node count so the wave
        # kernel compiles once instead of re-compiling on capacity growth
        n_nodes = max(
            self.cache.node_count,
            len(self.informer_factory.informer("nodes").indexer),
        )
        with self.cache.lock:
            self.cache.encoder.presize_for_cluster(max(n_nodes, 1))
        # multi-chip: shard the snapshot over every visible device (node
        # axis), production wave kernel included — SURVEY §7.6
        self._mesh = None
        if self.cfg.use_device and self.cfg.use_mesh and len(jax.devices()) > 1:
            from ..parallel.mesh import make_mesh, replicated, snapshot_shardings

            self._mesh = make_mesh()
            with self.cache.lock:
                self.cache.encoder.set_sharding(
                    snapshot_shardings(self._mesh), replicated(self._mesh)
                )
        if self.cfg.pipeline_depth == 0 and self.cfg.use_device:
            self._pipeline_depth = self._auto_pipeline_depth()
        metrics.set_gauge(
            GAUGE_WAVE_PIPELINE_DEPTH, float(self._pipeline_depth)
        )
        if self.cfg.use_device:
            # compile the two dirty-row scatter programs at bring-up: each
            # is a ~2 s XLA compile through the tunnel that would otherwise
            # land mid-burst the first time that pad size appears
            try:
                with self.cache.lock:
                    self.cache.encoder.warm_scatter_programs()
            except Exception:
                logger.exception("scatter warmup failed")

    def promote(self, fence=None) -> None:
        """Leadership start: arm the bind fence, adopt whatever the
        previous leader left mid-flight, then start the scheduling loops
        (auditor, queue flushers, janitor, the batch loop). Called by
        start() directly in the non-HA path (fence None, no standby) and
        by the election winner after start_standby()."""
        was_standby = self._standby_thread is not None
        self._stop_standby_loop()
        self._bind_fence = fence
        if was_standby or fence is not None:
            # the PR-3 bind-outcome discipline, triggered by a leadership
            # transition instead of a store reopen
            t0 = time.monotonic()
            counts = self._adopt_pending()
            metrics.inc(COUNTER_PROMOTIONS)
            logger.warning(
                "scheduler %s promoted to leader in %.0f ms: adopted "
                "%d landed binds, %d in-flight pods to place (fenced), "
                "%d gone",
                self._ha_identity,
                (time.monotonic() - t0) * 1e3,
                counts["bound"], counts["pending"], counts["gone"],
            )
        metrics.set_gauge(GAUGE_ROLE, 1.0, {"identity": self._ha_identity})
        # adopt the persisted tuned score policy (tuner/policy.py): both
        # cold starts (start() routes through here) and HA promotions
        # pick up the gym's promoted vector instead of reverting to the
        # config default — degraded/absent store is a counted skip
        self._adopt_persisted_score_policy()
        if self.cfg.use_device and self.cfg.antientropy_period_s > 0:
            from .antientropy import SnapshotAntiEntropy

            # quiescence gate — a SEMANTIC gate only, since the
            # generational snapshot made the mechanics safe (the audit's
            # gather pins a generation no launch can donate): an in-flight
            # wave batch legitimately holds device commits the masters
            # haven't replayed yet, and a master-vs-device diff would
            # "repair" the kernel's own work away. _busy is set under the
            # queue lock BEFORE the first pod leaves the queue and cleared
            # only after the batch fully resolves, so a lock-held re-check
            # of these flags is race-free against the launch path (which
            # takes the cache lock after _busy is set).
            self._auditor = SnapshotAntiEntropy(
                self.cache.encoder,
                lock=self.cache.lock,
                quiesced=lambda: (
                    not self._pending
                    and not self._busy
                    and not self._device_down
                ),
                period_s=self.cfg.antientropy_period_s,
                sample_rows=self.cfg.antientropy_sample_rows,
                rebuild_after=self.cfg.antientropy_rebuild_after,
            )
            self._auditor.start()
        self.queue.run()
        self.cache.start_janitor()
        self._sched_thread = threading.Thread(
            target=self._scheduling_loop, daemon=True, name="scheduler"
        )
        self._sched_thread.start()

    # -- warm standby (scheduler HA, ha.py) -----------------------------------

    def start_standby(
        self, identity: str = "scheduler-0", refresh_period_s: float = 0.25
    ) -> None:
        """Warm-standby mode: informers tail the (shared) watch cache into
        the scheduler cache and queue, the HBM snapshot is built and kept
        in lockstep with informer churn by a refresh loop, and the wave /
        serial kernels are pre-compiled — so promote() starts binding in
        well under one autoscaler period instead of after a full rebuild
        plus a compile storm. NO scheduling loop runs: the standby
        acquires nothing and writes nothing."""
        self._ha_identity = identity
        self._bringup()
        if self.cfg.use_device:
            try:
                self.warm_standby_kernels()
            except Exception:
                # a failed pre-compile costs promotion latency, never
                # correctness: the leader path compiles lazily as before
                logger.exception("standby kernel pre-warm failed")
        metrics.set_gauge(GAUGE_ROLE, 0.0, {"identity": identity})
        self._standby_last_fresh = time.monotonic()
        metrics.set_gauge(
            GAUGE_STANDBY_SNAPSHOT_AGE, 0.0, {"identity": identity}
        )
        self._standby_stop.clear()
        self._standby_thread = threading.Thread(
            target=self._standby_loop,
            args=(refresh_period_s,),
            daemon=True,
            name=f"standby-{identity}",
        )
        self._standby_thread.start()
        logger.info(
            "scheduler %s standing by: cache synced (%d nodes), snapshot "
            "warm, kernels compiled", identity, self.cache.node_count,
        )

    def _standby_loop(self, period_s: float) -> None:
        """Keep the standby's device snapshot tracking the informer
        stream: scatter pending encoder deltas every tick so the dirty-row
        backlog at promotion is bounded by one period, and publish the
        snapshot's freshness age for the SIGUSR2 dump."""
        while not self._standby_stop.wait(period_s):
            try:
                if self.cfg.use_device and not self._device_down:
                    if self.cache.encoder.has_pending_updates:
                        self.cache.device_snapshot()  # flush under the lock
                        metrics.inc(COUNTER_STANDBY_FLUSHES)
                    self._standby_last_fresh = time.monotonic()
                elif not self.cfg.use_device:
                    # host-only scheduling: the cache IS the state, there
                    # is no device snapshot to go stale
                    self._standby_last_fresh = time.monotonic()
                # _device_down: deliberately do NOT advance — the snapshot
                # really is going stale, and this gauge exists precisely
                # to make a cold standby visible before a promotion
            except Exception:
                logger.exception("standby snapshot refresh failed")
            if self._standby_last_fresh is not None:
                metrics.set_gauge(
                    GAUGE_STANDBY_SNAPSHOT_AGE,
                    max(0.0, time.monotonic() - self._standby_last_fresh),
                    {"identity": self._ha_identity},
                )

    def _stop_standby_loop(self) -> None:
        self._standby_stop.set()
        t, self._standby_thread = self._standby_thread, None
        if t is not None:
            t.join(timeout=5.0)

    def warm_standby_kernels(self) -> None:
        """Pre-compile the kernels the leader path needs first: the
        small-bucket wave kernel variant and the serial batch kernel, plus
        (via _bringup) the scatter/gather programs. Uses one synthetic
        unsatisfiable pod — a resource request no node can hold — so both
        kernels trace and compile real shapes while committing nothing;
        if the readback ever shows a placement anyway, the device
        snapshot is invalidated and rebuilt from the host masters rather
        than trusted with a ghost pod."""
        warm_pod = v1.Pod(
            metadata=v1.ObjectMeta(
                name="standby-warmup", namespace="kube-system"
            ),
            spec=v1.PodSpec(
                containers=[v1.Container(requests={"cpu": "1000000"})]
            ),
        )
        small = self._small_bucket
        with self.cache.lock:
            eb = self._tpl_cache.encode([warm_pod], pad_to=small)
            ptab = self._pair_table(eb)
            n_waves, batch_has_hard = self._batch_waves(eb)
            n_waves = min(n_waves, 2)  # the small no-hard bucket's count
            snap = self.cache.encoder.flush()
            enc_cfg = self.cache.encoder.cfg
        m_cand = min(self.cfg.wave_m_cand_small, self._m_cand)
        if self._mesh is not None:
            from ..parallel.sharded import make_sharded_wave_kernel

            kern = make_sharded_wave_kernel(
                enc_cfg.v_cap,
                m_cand,
                n_waves,
                self.cfg.hard_pod_affinity_weight,
                self._mesh,
                self._use_pallas_fit,
                self._score_refresh or batch_has_hard,
                self._rtc_shape,
                False,
            )
        else:
            from ..ops.wavelattice import DEFAULT_RTC_SHAPE

            kern = make_wave_kernel_jit(
                enc_cfg.v_cap,
                m_cand,
                n_waves,
                self.cfg.hard_pod_affinity_weight,
                self._use_pallas_fit,
                self._score_refresh or batch_has_hard,
                self._rtc_shape or DEFAULT_RTC_SHAPE,
                False,
            )
        self._rng_key, sub = jax.random.split(self._rng_key)
        new_snap, res = self._launch_wave_kernel(
            kern, snap, eb.batch, ptab, np.asarray(self._weights), sub
        )
        placed = jax.device_get(res.placed)
        with self.cache.lock:
            if np.asarray(placed).any():
                # the "unsatisfiable" pod somehow placed (encoding clamp):
                # never trust the warm launch's snapshot with a ghost pod.
                # (The launch's donation lease already installed it as the
                # live generation — rebuild over it from the host masters.)
                logger.error(
                    "standby warm-up pod was placed by the kernel; "
                    "rebuilding the device snapshot from the host masters"
                )
                self.cache.encoder.invalidate_device()
                self.cache.encoder.flush()
        # the serial batch kernel (the host-side fallback device variant)
        kern2 = make_schedule_batch(
            enc_cfg.v_cap, self.cfg.hard_pod_affinity_weight
        )
        with self.cache.lock:
            eb2 = encode_pod_batch(
                self.cache.encoder, [warm_pod], pad_to=1
            )
            snap2 = self.cache.encoder.flush()
        self._rng_key, sub2 = jax.random.split(self._rng_key)
        self._run_serial_kernel(kern2, snap2, eb2.batch, sub2)
        metrics.inc(COUNTER_STANDBY_WARMUPS)

    def _adopt_pending(self) -> Dict[str, int]:
        """Leader-adoption pass: the PR-3 pending-bind reconciler's
        outcome discipline applied at a leadership transition. Every pod
        the informers queued is read back from the STORE (the only
        authority that survives the old leader): bind landed → finish
        (cache it, drop it from the queue — never re-placed), never
        landed → stays queued and the first wave places it with a fenced
        bind (the store's already-bound + uid + leadership checks make a
        double-bind structurally impossible even against a zombie), pod
        gone → forget. Any pending binds buffered by an earlier leading
        stint of THIS process drain through the store-reopen reconciler
        unchanged."""
        counts = {"bound": 0, "pending": 0, "gone": 0}
        infos = self.queue.pending_pod_infos()
        # read-back strategy: per-pod authoritative gets for a small
        # backlog, ONE authoritative store list for a large one (a 10k-pod
        # failover must not pay 10k sequential store-lock round-trips
        # before the scheduling loop starts — promotion latency is the
        # whole point of the warm standby). `.store` unwraps a Cacher to
        # the raw store; a cache-served list could lag the dead leader's
        # final bind events.
        by_key = None
        if len(infos) > 64:
            try:
                pods, _ = getattr(self.server, "store", self.server).list(
                    "pods"
                )
                by_key = {p.metadata.key: p for p in pods}
            except Exception:
                logger.exception(
                    "adoption bulk read-back failed; per-pod fallback"
                )
        for pi in infos:
            pod = pi.pod
            try:
                if by_key is not None:
                    cur = by_key.get(pod.metadata.key)
                    if cur is not None and cur.metadata.uid != pod.metadata.uid:
                        cur = None  # same name, different pod: ours is gone
                else:
                    cur = self._read_back_pod(pod)
            except Exception:
                # store unreachable mid-promotion: leave the pod queued —
                # normal scheduling plus the ride-through buffer own it
                logger.exception(
                    "adoption read-back failed for %s; leaving queued",
                    pod.metadata.key,
                )
                continue
            # deletes are uid-guarded: the informer runs concurrently, and
            # a pod deleted+recreated between our queue snapshot and this
            # read-back must not lose its FRESH queue entry to a stale key
            if cur is None:
                self.queue.delete_if_uid(pod)
                tracer.discard(pi.trace_id)
                outcome = "gone"
            elif cur.spec.node_name:
                # the dead leader's bind landed: finish it — the cache
                # (and therefore the device snapshot) takes the placement,
                # the queue forgets the pod, and it is never re-placed
                self.queue.delete_if_uid(pod)
                self.cache.add_pod(cur)
                tracer.finish(
                    pi.trace_id, outcome="adopted", node=cur.spec.node_name
                )
                outcome = "bound"
            else:
                outcome = "pending"
            counts[outcome] += 1
            metrics.inc(COUNTER_ADOPTIONS, {"outcome": outcome})
        if self._ridethrough.depth:
            # leftover parked binds from this process's previous stint:
            # same read-back discipline, the reopen reconciler already
            # implements it
            self._reconcile_pending_binds()
        return counts

    def _auto_pipeline_depth(self) -> int:
        """Pick the wave-pipeline depth from the measured device->host
        readback RTT: a tunneled/remote device (tens of ms per sync) wants
        the deep pipeline so one readback amortizes over many batches; a
        local device or the CPU backend (sub-ms) wants the shallow one —
        deep pipelining there only adds pod latency and, on CPU, host vs
        device compute contention."""
        try:
            d = jax.device_put(np.zeros(16, np.float32))
            jax.device_get(d + 1)  # warmup: first d2h shifts tunnel regime
            rtts = []
            for _ in range(3):
                r = d + 1
                t0 = time.monotonic()
                jax.device_get(r)
                rtts.append(time.monotonic() - t0)
            rtt_ms = sorted(rtts)[1] * 1e3
        except Exception:
            logger.exception("pipeline-depth RTT probe failed; using depth 2")
            return 2
        # r5 hardware A/B on the tunneled v5e (~5-20 ms RTT): depth 2 beat
        # the deep pipeline 2709 vs 1631 pods/s with p99 205 vs 1301 ms —
        # chaining 5 batches on-device delays assume/bind past the point
        # the saved readbacks repay. Deep only for truly high-RTT links.
        return 6 if rtt_ms > 25.0 else 2

    def stop(self) -> None:
        self._stop.set()
        self._stop_standby_loop()
        if self._auditor is not None:
            self._auditor.stop()
        self.queue.close()
        self.cache.stop()
        self.informer_factory.stop()
        # join the scheduling loop FIRST: a cycle still running could park
        # new permit-waiters after the reject sweep below, or submit binds
        # into a shut-down pool
        if self._sched_thread is not None:
            self._sched_thread.join(timeout=10.0)
        # outstanding trailing readbacks hold generation pins; consume
        # them (the loop is dead, so nobody else will release them)
        if self._trailing:
            self._drain_trailing(block=True)
        if self._owned_read_cache is not None:
            self._owned_read_cache.stop()
        # release parked permit-waiters or the drain below would block on
        # their (up to 30s) wait timeouts
        for p in self.profiles.values():
            for wp in p.framework.iterate_waiting_pods():
                wp.reject("scheduler shutting down")
        # drain in-flight binds BEFORE flushing recorders: a bind finishing
        # after the flush would drop its Scheduled event into a buffer
        # nobody serves
        self._bind_pool.shutdown(wait=True)
        for p in self.profiles.values():
            rec = getattr(p, "recorder", None)
            if rec is not None and hasattr(rec, "flush"):
                rec.flush(timeout=2.0)
                rec.stop()

    def wait_for_idle(self, timeout: float = 30.0) -> bool:
        """Test helper: wait until no pending pods remain. Requires the
        idle condition to hold across two samples so the scheduling loop's
        pop->launch gap (queue drained, batch not yet in flight) can't be
        mistaken for quiescence."""

        def idle() -> bool:
            # breaker open counts as busy even at depth 0: drain() zeroes
            # the depth for the whole reconcile pass, and entries may yet
            # be restored — the breaker only resets after a full drain
            return (
                len(self.queue) == 0
                and not self._pending
                and not self._trailing
                and not self._busy
                and not self._ridethrough.open
                and self._ridethrough.depth == 0
                and not self.cache.encoder.has_pending_updates
            )

        deadline = time.time() + timeout
        while time.time() < deadline:
            if idle():
                time.sleep(0.02)
                if idle():
                    return True
                continue
            time.sleep(0.01)
        return (
            len(self.queue) == 0
            and not self._pending
            and not self._busy
            and not self._ridethrough.open
            and self._ridethrough.depth == 0
        )

    # -- the loop ------------------------------------------------------------

    def _mark_busy(self) -> None:
        self._busy = True

    def _scheduling_loop(self) -> None:
        while not self._stop.is_set():
            # Circuit breaker: the store refused binds with a retryable
            # 503. Pause batch dispatch (informers, queue, and the HBM
            # snapshot stay warm) and probe for recovery; the queue keeps
            # accumulating instead of failing waves into unschedulableQ.
            if self._ridethrough.open:
                self._ride_through_degraded()
                continue
            # Batch-fill policy: the wave kernel's cycle cost is nearly
            # batch-size-independent (per-wave [TPL, N] work dominates), so
            # burst throughput = fill per kernel. With a batch in flight and
            # a MID-SIZE backlog queued (more than the small-bucket pad,
            # less than a full batch), resolve the in-flight batch FIRST:
            # its readback + bind work overlaps the device compute, and the
            # burst keeps accumulating toward a full batch instead of being
            # split into runt kernels (a 267-pod launch pays the same
            # ~cycle as a 4096-pod one). A full queue keeps the eager
            # depth-N pipeline exactly as before; with nothing in flight
            # don't block or linger — a lone low-load pod ships immediately.
            #
            # BELOW the small-bucket pad the batch is a runt either way, so
            # waiting a cycle to fatten it only adds latency: launch NOW and
            # let the new batch chain on the in-flight one's donated
            # generation (the launch path resolves the oldest batch right
            # after dispatch, so its compute overlaps the readback + binds).
            # This is the trickle-load payoff of the generational pipeline —
            # steady-state pod latency drops from ~2 wave cycles (wait out
            # the in-flight batch, then pay your own) to ~1 — and it only
            # became safe when wave launches stopped serializing against
            # audits/what-ifs on the device lock.
            backlog = self.queue.active_len()
            if (
                self._pending
                and self._small_bucket < backlog < self._batch_size
            ):
                self._busy = True
                try:
                    self._resolve_pending()
                except Exception:
                    # _resolve_oldest's contract is "never raises", but a
                    # failure here must degrade to a logged skip, not kill
                    # the scheduling thread for the life of the process
                    logger.exception("early batch resolve failed")
                finally:
                    self._busy = False
            inflight = bool(self._pending)
            # on_first marks the loop busy UNDER the queue lock before the
            # first pod leaves the queue, so wait_for_idle can never
            # observe "queue empty, nothing in flight" while a popped
            # batch is still on its way into the pipeline
            pis = self.queue.pop_batch(
                self._batch_size,
                timeout=0.0 if inflight else 0.2,
                window=0.0 if inflight else self.cfg.device_batch_window,
                on_first=self._mark_busy,
            )
            if not pis:
                if self._pending:
                    # stay busy across the drain: _resolve_oldest detaches
                    # the in-flight batches before the readback, so without
                    # this an observer would see "queue empty, nothing
                    # pending" while placements are still being replayed
                    self._busy = True
                    try:
                        self._resolve_pending()
                    finally:
                        self._busy = False
                elif self._trailing:
                    # idle with trailing bulk readbacks outstanding:
                    # consume them now (blocking — nothing else to do)
                    # so late validation can't dangle past quiescence
                    self._busy = True
                    try:
                        self._drain_trailing(block=True)
                    finally:
                        self._busy = False
                else:
                    self._busy = False
                continue
            try:
                self.schedule_pod_batch(pis)
            except Exception:
                logger.exception("scheduling batch failed")
                moves = self.queue.moves_snapshot()
                for pi in pis:
                    self.queue.add_unschedulable_if_not_present(pi, moves)
            finally:
                self._busy = False

    # -- degraded-store ride-through (ridethrough.py) -------------------------

    def _ride_through_degraded(self) -> None:
        """Breaker-open tick: flush in-flight wave batches (their binds
        buffer too — the kernels already committed on-device), wait one
        jittered probe interval, then try to drain the pending-bind
        buffer. The breaker closes only when the buffer fully drains."""
        if self._pending:
            self._busy = True
            try:
                self._resolve_pending()
            except Exception:
                logger.exception("degraded-mode pipeline flush failed")
            finally:
                self._busy = False
        if self._stop.wait(self._ridethrough.next_probe_delay()):
            return
        # cheap introspection first: an in-process store exposes its write
        # gate — while it still reports degraded, skip the write probe
        gate = getattr(self.server, "write_gate", None)
        if gate is not None and getattr(gate, "degraded", False):
            return
        if self._reconcile_pending_binds():
            self._ridethrough.reset()
            logger.warning(
                "store writes reopened: pending-bind buffer drained, "
                "resuming batch dispatch"
            )

    def _buffer_pending_binds(self, entries: List[PendingBind]) -> None:
        accepted, overflow = self._ridethrough.buffer(entries)
        if accepted:
            for e in accepted:
                tracer.event(e.pi.trace_id, "bind.parked")
            logger.warning(
                "store degraded: buffered %d pending binds "
                "(dispatch paused until writes reopen)", len(accepted),
            )
        for e in overflow:
            # bounded buffer: past capacity the placement unwinds like a
            # failed bind — backoff retries it once the store recovers
            self.cache.forget_pod(e.pi.pod)
            self._release_permits(e.pi.pod)
            self.queue.requeue_backoff(e.pi)

    def _reconcile_pending_binds(self) -> bool:
        """Drain the pending-bind buffer against the (possibly recovered)
        store. Each pod is read back FIRST: an applied-but-unacked bind
        (QuorumLost) must be detected, never blindly replayed — and the
        retry itself is uid-fenced by the store's binding check, so a
        duplicated attempt can never double-bind. Returns True when the
        buffer fully drained."""
        entries = self._ridethrough.drain()
        if not entries:
            return True
        still_degraded: List[PendingBind] = []
        for e in entries:
            if still_degraded:
                # store went (or stayed) degraded mid-pass: keep the rest
                # buffered untouched for the next probe
                still_degraded.append(e)
                continue
            try:
                self._reconcile_one(e, still_degraded)
            except Exception:
                # anything unclassified (REST connection refused mid-
                # failover, NotPrimary, ...): the store is not usable yet.
                # Keep the entry — and the scheduling thread — alive; the
                # next probe retries.
                logger.exception(
                    "pending-bind reconcile failed for %s; retrying later",
                    e.pi.pod.metadata.key,
                )
                still_degraded.append(e)
        if still_degraded:
            self._ridethrough.restore(still_degraded)
            return False
        return True

    def _read_back_pod(self, pod: v1.Pod):
        """Authoritative store read-back, uid-fenced: the current object
        for pod's key, or None when it is gone — including the same-name-
        different-pod case (ours was deleted and the name reused). Shared
        by the reopen reconciler and the leader-adoption pass so their
        outcome semantics cannot drift."""
        try:
            cur = self.server.get(
                "pods", pod.metadata.namespace, pod.metadata.name
            )
        except NotFound:
            return None
        if cur.metadata.uid != pod.metadata.uid:
            return None  # same name, different pod: ours is gone
        return cur

    def _reconcile_one(
        self, e: PendingBind, still_degraded: List[PendingBind]
    ) -> None:
        pod = e.pi.pod

        def outage_span() -> None:
            # the parked bind's whole outage wait is a first-class span:
            # a pod that rode through a degraded store shows WHERE the
            # seconds went instead of an unexplained e2e tail
            tracer.add_span(
                e.pi.trace_id, "outage.wait", e.buffered_at, time.monotonic()
            )

        cur = self._read_back_pod(pod)
        if cur is None:
            # deleted while buffered, or lost with a failed primary
            self.cache.forget_pod(pod)
            self._release_permits(pod)
            metrics.inc(COUNTER_RECONCILED, {"outcome": "gone"})
            tracer.discard(e.pi.trace_id)
            return
        if cur.spec.node_name:
            if cur.spec.node_name == e.node_name:
                # the bind LANDED — only its ack was lost
                outage_span()
                self._record_bound(
                    e.pi, e.node_name, e.profile, outcome="landed"
                )
            else:
                # bound elsewhere (another path won): drop our assume;
                # the informer's scheduled-add owns the cache entry
                self.cache.forget_pod(pod)
                self._release_permits(pod)
                metrics.inc(COUNTER_RECONCILED, {"outcome": "foreign"})
                tracer.finish(e.pi.trace_id, outcome="foreign")
            return
        # not bound: the write never applied (or didn't survive
        # failover) — replay once, uid-fenced
        binding = Binding(
            pod_name=pod.metadata.name,
            pod_namespace=pod.metadata.namespace,
            pod_uid=pod.metadata.uid,
            target_node=e.node_name,
        )
        try:
            errs = self._bind_pods_fenced([binding])
            err = errs[0] if errs else None
        except DegradedWrites as exc:
            err = exc
        except LeaderFenced:
            # deposed mid-reconcile: the replay belongs to the new leader
            self._on_fenced_binds([e.pi])
            return
        if isinstance(err, DegradedWrites):
            still_degraded.append(e)
        elif err is None:
            outage_span()
            self._record_bound(
                e.pi, e.node_name, e.profile, outcome="rebound"
            )
        elif isinstance(err, NotFound):
            # deleted between the read-back and the replay: same as gone —
            # requeueing would park a ghost in unschedulableQ forever (its
            # informer delete already fired)
            self.cache.forget_pod(pod)
            self._release_permits(pod)
            metrics.inc(COUNTER_RECONCILED, {"outcome": "gone"})
            tracer.discard(e.pi.trace_id)
        else:
            self.cache.forget_pod(pod)
            metrics.inc(COUNTER_RECONCILED, {"outcome": "lost_requeued"})
            self._handle_failure(
                e.pi, self.queue.moves_snapshot(), message=str(err), error=True
            )

    def _bind_pods_fenced(self, bindings) -> list:
        """Every scheduler-originated batch bind funnels here: when a
        leadership fence is armed (promote(fence=...)), the token rides
        along and the store rejects the whole batch with LeaderFenced if
        this process's grant has been superseded. Callers own the
        DegradedWrites / LeaderFenced handling."""
        if self._bind_fence is not None:
            return self.server.bind_pods(bindings, fence=self._bind_fence)  # graftlint: degraded-ok(fence-attaching seam; both callers catch DegradedWrites/LeaderFenced at their call sites)
        return self.server.bind_pods(bindings)  # graftlint: degraded-ok(fence-attaching seam; both callers catch DegradedWrites/LeaderFenced at their call sites)

    def _check_fence_live(self) -> None:
        """Best-effort fence pre-check for bind writes the store cannot
        validate atomically — an extender binds OUT OF PROCESS, so the
        only check available is re-reading the lease just before handing
        it the pod. Raises LeaderFenced when this replica's grant was
        superseded; an unreadable lease (degraded store, REST blip) lets
        the bind proceed — the pre-check narrows the zombie window, the
        store-validated fence on every in-tree bind closes it."""
        f = self._bind_fence
        if f is None:
            return
        try:
            lease = self.server.get("leases", f.namespace, f.name)
        except NotFound:
            lease = None
        except Exception:
            return
        if (
            lease is None
            or lease.holder_identity != f.identity
            or lease.lease_transitions != f.transitions
        ):
            raise LeaderFenced(
                f"extender bind fenced: lease {f.namespace}/{f.name} now "
                f"held by {getattr(lease, 'holder_identity', None)!r} at "
                f"transition {getattr(lease, 'lease_transitions', None)} "
                f"(caller's token: {f.identity!r} at {f.transitions})"
            )

    def check_eviction_fence(self) -> None:
        """Public fence seam for out-of-pipeline evictors (the
        descheduler): plain pod deletes/evictions are store writes with
        no atomic fence validation, so a consolidation wave re-reads the
        lease through the same best-effort pre-check preemption victim
        deletes use. Raises LeaderFenced when this replica's grant was
        superseded; no-op when no fence is armed (single-replica rigs)."""
        self._check_fence_live()

    def fragmentation_score(self) -> float:
        """Stranded-capacity fragmentation of the LIVE fleet: free
        capacity sitting on partially-used nodes / total free capacity,
        from the encoder's host masters (ops/encoding.utilization_stats)
        through the same arithmetic the tuner scores replayed waves with
        (tuner/scoring.fragmentation_score). Published as the
        scheduler_fragmentation_score gauge — the descheduler's planning
        signal and the policy gym's consolidation actuator: one
        definition, three consumers."""
        from ..tuner.scoring import fragmentation_score as _frag

        with self.cache.lock:
            st = self.cache.encoder.utilization_stats()
        score = _frag(st.free_frac, st.used_any, st.valid)
        metrics.set_gauge("scheduler_fragmentation_score", score)
        return score

    def _on_fenced_binds(self, entries) -> None:
        """We are a zombie ex-leader: a newer grant exists and the store
        refused our binds. Drop the placements (the new leader owns these
        pods now — re-placing or requeueing them here would just race it)
        and count, so the chaos ledger can prove zero double-binds."""
        metrics.inc(
            COUNTER_FENCED_BINDS,
            {"path": self._bind_transport},
            by=float(len(entries)),
        )
        logger.error(
            "bind batch of %d rejected by the leadership fence: this "
            "scheduler (%s) has been superseded; dropping the placements",
            len(entries), self._ha_identity,
        )
        for pi in entries:
            # the zombie's view of its own fencing: the store-side stamp
            # under the same id is recorded by the store process
            tracer.event(pi.trace_id, "bind.fenced")
            tracer.finish(pi.trace_id, outcome="fenced")
            self.cache.forget_pod(pi.pod)
            self._release_permits(pi.pod)

    def _release_permits(self, pod: v1.Pod) -> None:
        """Unwind paths that drop a buffered placement without a full
        _handle_failure must still tell permit plugins the pod is gone —
        a gang-quorum plugin may hold siblings parked on its reservation
        (the same hook _handle_failure fires)."""
        prof = self.profiles.for_pod(pod)
        if prof is None:
            return
        for name in prof.framework.plugin_set.permit:
            hook = getattr(
                prof.framework.plugin(name), "handle_scheduling_failure", None
            )
            if hook is not None:
                try:
                    hook(pod)
                except Exception:
                    logger.exception("permit release hook %s", name)

    def _record_bound(
        self, pi: QueuedPodInfo, node_name: str, prof, outcome: Optional[str] = None
    ) -> None:
        """Post-bind bookkeeping shared by the in-cycle bulk path and the
        ride-through reconciler."""
        self.cache.finish_binding(pi.pod)
        metrics.observe(
            "pod_scheduling_duration_seconds",
            time.monotonic() - pi.initial_attempt_timestamp,
            exemplar=pi.trace_id or None,
        )
        metrics.inc("schedule_attempts_total", {"result": "scheduled"})
        tracer.finish(pi.trace_id, outcome=outcome or "bound", node=node_name)
        if outcome:
            metrics.inc(COUNTER_RECONCILED, {"outcome": outcome})
        prof.recorder.eventf(
            pi.pod, "Normal", "Scheduled", "Binding",
            f"Successfully assigned {pi.pod.metadata.key} to {node_name}",
        )

    def schedule_pod_batch(self, pis: List[QueuedPodInfo]) -> None:
        trace = Trace("schedule_batch", pods=len(pis))
        t_start = time.monotonic()
        # close every pod's queue-wait span (last queue ENTRY -> cycle
        # start) in ONE ring acquisition; requeued pods accumulate one
        # `queue` span per attempt, which is the honest attribution
        # (trace_queued_at, not timestamp: readd() refreshes only the
        # former — see QueuedPodInfo)
        tracer.add_spans(
            [(pi.trace_id, "queue", pi.trace_queued_at, t_start)
             for pi in pis]
        )
        moves0 = self.queue.moves_snapshot()
        known: List[QueuedPodInfo] = []
        extender_pis: List[QueuedPodInfo] = []
        for pi in pis:
            if self.profiles.for_pod(pi.pod) is None:
                logger.error(
                    "no profile for scheduler name %s", pi.pod.spec.scheduler_name
                )
                continue
            # extender-interested pods need the host path: an out-of-process
            # veto can't be folded into the device mask
            if any(e.is_interested(pi.pod) for e in self.extenders):
                extender_pis.append(pi)
                continue
            known.append(pi)
        if extender_pis:
            # host path reads the host cache: in-flight replays must land
            self._resolve_pending()
        for pi in extender_pis:
            # _schedule_one_host re-snapshots per pod
            self._schedule_one_host(pi, moves0)
        if not known:
            return
        # the device-down latch (unrecoverable device loss) degrades every
        # batch to the host path — correctness over throughput
        use_device = self.cfg.use_device and not self._device_down
        if (
            0 < len(known) <= self.cfg.small_batch_host_max
            and self.cache.node_count <= self.cfg.small_batch_host_node_max
            and use_device
        ):
            # low-load latency path for SMALL clusters: a tiny batch on the
            # device path pays a full cycle (kernel + >=1 readback RTT) for
            # a handful of pods; the host scheduleOne at <=256 nodes costs
            # single-digit ms (snapshot clones are generation-incremental).
            # At thousands of nodes the Python filter chain is SLOWER than
            # the kernel — big clusters stay on the device path and get the
            # small-pad/m_cand variant instead. Device state stays
            # consistent: the host path resolves in-flight batches and its
            # binds dirty the encoder rows like any informer write.
            self._resolve_pending()
            for pi in known:
                self._schedule_one_host(pi, moves0)
            trace.log_if_long(0.1)
            return
        if use_device and self.cfg.use_wave:
            self._schedule_batch_wave(known, moves0, trace, t_start)
        elif use_device:
            self._resolve_pending()
            self._schedule_batch_device(known, moves0, trace, t_start)
            trace.log_if_long(0.1)
        else:
            self._resolve_pending()
            self._snapshot = self.cache.update_snapshot()
            for pi in known:
                self._schedule_one_host(pi, moves0)
            trace.log_if_long(0.1)

    # -- device path ---------------------------------------------------------

    @staticmethod
    def _pad(n: int) -> int:
        p = 1
        while p < n:
            p *= 2
        return p

    def _schedule_batch_device(
        self, pis: List[QueuedPodInfo], moves0: int, trace: Trace, t_start: float
    ) -> None:
        # device-loss ride-through, serial-path edition (launch+readback
        # are one synchronous call here): bounded jittered retries, then
        # the _handle_device_loss ladder (transient re-upload / mesh
        # shrink / latch off) and the host path for this batch — nothing
        # is assumed before the readback succeeds, so quarantining loses
        # zero pods. Each attempt re-encodes AND re-flushes under the
        # lock: informer churn during the retry sleep can remap encoder
        # rows, and a stale eb/row_names would decode the kernel's row
        # choices against the wrong nodes (same reason the wave wrapper
        # re-encodes per retry).
        attempts = 0
        while True:
            with self.cache.lock, _stage_timer("encode"):
                eb = encode_pod_batch(
                    self.cache.encoder,
                    [pi.pod for pi in pis],
                    pad_to=self._pad(len(pis)),
                )
                snap = self.cache.encoder.flush()
                enc_cfg = self.cache.encoder.cfg
                row_names = list(self.cache.encoder.row_names)
                launch_gen = self.cache._ext_generation
            trace.step("encoded+flushed")
            kern = make_schedule_batch(
                enc_cfg.v_cap, self.cfg.hard_pod_affinity_weight
            )
            self._rng_key, sub = jax.random.split(self._rng_key)
            w_launch = np.asarray(self._weights)
            try:
                with _stage_timer("kernel"):
                    res, chosen, score = self._run_serial_kernel(
                        kern, snap, eb.batch, sub, w_launch
                    )
                self._consecutive_device_loss = 0
                break
            except Exception as e:  # noqa: BLE001 — classifier filters
                if not is_device_loss_error(e):
                    raise
                with self.cache.lock:
                    self.cache.encoder.invalidate_device()
                # same metric semantics as launch/readback: recovered
                # blips count as retries, loss_total only on terminal
                if attempts < self.cfg.device_retry_attempts:
                    attempts += 1
                    metrics.inc(
                        "scheduler_device_retries_total",
                        {"stage": "serial"},
                    )
                    delay = device_retry_delay(attempts)
                    logger.warning(
                        "device loss on serial batch kernel (%s); retry "
                        "%d/%d in %.0f ms with a fresh encode + snapshot "
                        "upload",
                        e, attempts, self.cfg.device_retry_attempts,
                        delay * 1e3,
                    )
                    time.sleep(delay)
                    continue
                metrics.inc(
                    "scheduler_device_loss_total", {"stage": "serial"}
                )
                logger.error(
                    "device loss on serial batch kernel persists after "
                    "%d retries (%s): batch of %d pods degrades to the "
                    "host path", attempts, e, len(pis),
                )
                self._handle_device_loss(e)
                self._snapshot = self.cache.update_snapshot()
                for pi in pis:
                    self._schedule_one_host(pi, moves0)
                return
        trace.step("kernel")
        algo_dur = time.monotonic() - t_start
        if self.cfg.kernel_output_guards:
            # mask with `!= -1`, not `>= 0`: -1 is the kernel's ONLY
            # legitimate unplaced sentinel, so any other negative index
            # is corruption that must trip GUARD_ROW_RANGE — a `>= 0`
            # mask would silently route a sign-flipped row (and its
            # poisoned score) into the unschedulable/preemption path
            reason = validate_batch_outputs(
                chosen, np.asarray(chosen) != -1, score, len(row_names)
            )
            if reason:
                # serial path (no pipeline): quarantine this batch to the
                # host path and rebuild the snapshot — nothing assumed yet
                metrics.inc("kernel_guard_trips_total", {"reason": reason})
                logger.error(
                    "kernel output guard tripped (%s) on the serial device "
                    "path: batch of %d pods degrades to the host path",
                    reason, len(pis),
                )
                with self.cache.lock:
                    self.cache.encoder.invalidate_device()
                # a persistently poisoned device must latch OFF here too,
                # not loop launch → trip → full re-upload per batch forever
                self._consecutive_guard_trips += 1
                if (
                    self._consecutive_guard_trips
                    >= self.cfg.device_loss_disable_after
                ):
                    logger.error(
                        "%d consecutive kernel guard trips: abandoning the "
                        "device path for the host path",
                        self._consecutive_guard_trips,
                    )
                    self._set_device_down()
                self._snapshot = self.cache.update_snapshot()
                for pi in pis:
                    self._schedule_one_host(pi, moves0)
                return
            self._consecutive_guard_trips = 0

        fallback_pis: List[QueuedPodInfo] = []
        failed: List = []  # (pi, batch_index or -1)
        resolvable = None
        serial_placed: dict = {}  # id(pi) -> node (tuner wave record)
        serial_to_bind: List = []  # (pi, node_name) decode-first, bind after
        for i, pi in enumerate(pis):
            if eb.fallback[i]:
                fallback_pis.append(pi)
                continue
            row = int(chosen[i])
            if row < 0:
                if resolvable is None:
                    resolvable = np.asarray(res.resolvable)
                failed.append((pi, i))
                continue
            node_name = row_names[row]
            if node_name is None:
                failed.append((pi, -1))
                continue
            serial_to_bind.append((pi, node_name))
        # split-phase serial: the fast chosen-index payload was acted on
        # with score=None; register the trailing bulk validation before
        # any bind leaves the process, and take one last non-blocking
        # look — on CPU the score has usually landed by now, so the
        # common case still validates before the first bind
        entry = None
        if self._split_phase and score is None and serial_to_bind:
            entry = self._register_trailing(
                res.score,
                np.asarray(chosen) != -1,
                [(pi, node, None, None) for pi, node in serial_to_bind],
                launch_gen, None, path="serial",
            )
        if entry is not None and self._trailing_gate(entry):
            for pi, _node in serial_to_bind:
                tracer.event(pi.trace_id, "serial.trailing_unwound")
                self.queue.requeue_backoff(pi)
        else:
            for pi, node_name in serial_to_bind:
                metrics.observe(
                    "scheduling_algorithm_duration_seconds", algo_dur
                )
                self._assume_and_bind(pi, node_name, t_start)
                serial_placed[id(pi)] = node_name
            if entry is not None:
                entry.binds_issued = True
        self._record_wave_for_tuner(
            pis, serial_placed, w_launch, sub, launch_gen, path="serial"
        )
        if fallback_pis or failed:
            self._snapshot = self.cache.update_snapshot()
        for pi in fallback_pis:
            self._schedule_one_host(pi, moves0)
        if failed:
            # one batched device what-if narrows every failed pod's candidates
            whatif = None
            try:
                from ..ops.lattice import preempt_whatif

                with self.cache.lock:
                    snap2 = self.cache.encoder.flush()
                whatif = np.asarray(
                    preempt_whatif(snap2, eb.batch, eb.batch.priority)
                )
            except Exception:
                logger.exception("preempt what-if kernel failed")
            for pi, i in failed:
                # i < 0: decode anomaly (node vanished mid-cycle) — pass
                # None so the preemptor does its own full scan
                candidates: Optional[List[str]] = None
                if i >= 0 and resolvable is not None:
                    mask = resolvable[i]
                    # shapes can differ if a node joined between the batch
                    # encode and the what-if re-flush (encoder row growth)
                    if whatif is not None and whatif.shape[1] == mask.shape[0]:
                        mask = mask & whatif[i]
                    candidates = [
                        row_names[r]
                        for r in np.nonzero(mask)[0]
                        if row_names[r]
                    ]
                self._handle_failure(
                    pi,
                    moves0,
                    message=f"0/{self.cache.node_count} nodes are available",
                    candidate_nodes=candidates,
                )

    # -- wave device path -----------------------------------------------------

    def _pair_table(self, eb):
        """Pair table cached by (template set, vocab) signature. The wave
        count is derived separately per batch (_batch_waves)."""
        enc = self.cache.encoder
        sig = (
            eb.num_templates,
            # rows_gen distinguishes DIFFERENT template sets that happen
            # to share count + vocab sizes (the >max_templates churn
            # rebuild re-registers from one batch without growing any
            # vocab) — a stale pair table would enforce the wrong pairs
            self._tpl_cache.rows_gen,
            self._tpl_cache._vocab_sig,
            len(enc.sel_vocab),
            len(enc.eterm_vocab),
        )
        if self._pair_cache is not None and self._pair_cache[0] == sig:
            return self._pair_cache[1]
        table, overflow = build_pair_table(enc, eb.tpl_np, eb.num_templates)
        if overflow:
            logger.warning("pair table overflow; kernel capacity grew")
        self._pair_cache = (sig, table)
        return table

    def _batch_waves(self, eb) -> tuple:
        """(wave count, has_hard) for THIS batch, from the templates
        actually present in it (NOT the whole accumulated template cache —
        one historical hard-pair template must not pin every later
        soft-only burst to the full wave count). No-hard batches:
        prefix-fit packing commits many pods per node per wave, so
        conflicts drain in 1-2 waves even at 4096-pod bursts; losers
        defer and retry next batch. Measured (r5, CPU 5k nodes,
        PodAffinity): 2 waves 2020 pods/s vs 4 waves 1602, all scheduled,
        same batch count. Hard-pair batches keep the configured count —
        and get the per-wave score refresh regardless of backend (see
        _schedule_batch_wave): without it the candidate columns chase
        batch-start domain counts while in-batch commits fill the
        low-count domains, and a 5k-node hard-spread storm was measured
        converging bimodally (7 vs 88 pods/s) on CPU."""
        enc = self.cache.encoder
        b = eb.tpl_np
        present = np.unique(eb.pod_tpl_np[eb.pod_tpl_np >= 0])
        if present.size == 0:
            return min(2, self.cfg.wave_n_waves), False
        anti_kinds = [
            tid
            for tid in range(len(enc.eterm_vocab))
            if enc.eterm_vocab.items[tid].kind == _ETERM_ANTI_REQ
        ]
        has_hard = (
            bool(
                np.any(
                    (b.spread_key[present] >= 0) & b.spread_hard[present]
                )
            )
            or bool(np.any(b.panti_sid[present] >= 0))
            or any(
                bool(np.any(b.match_eterm[present, tid]))
                for tid in anti_kinds
            )
        )
        if has_hard:
            return self.cfg.wave_n_waves, True
        return min(2, self.cfg.wave_n_waves), False

    def _schedule_batch_wave(
        self, pis: List[QueuedPodInfo], moves0: int, trace: Trace, t_start: float
    ) -> None:
        """Device-loss ride-through wrapper around the wave launch:
        a launch that dies with a device-loss error gets bounded jittered
        retries — each retry re-encodes and re-flushes from the host
        masters (the failed launch may have consumed the donated snapshot,
        and node rows can move between attempts) — then falls through to
        _handle_device_loss (mesh shrink to survivors, or the host path).
        Nothing is assumed before a launch succeeds, so the requeue on
        give-up loses zero pods."""
        attempts = 0
        while True:
            try:
                self._schedule_batch_wave_once(pis, moves0, trace, t_start)
                self._consecutive_device_loss = 0
                return
            except Exception as e:  # noqa: BLE001 — classifier filters
                if not is_device_loss_error(e):
                    raise
                with self.cache.lock:
                    self.cache.encoder.invalidate_device()
                # metric semantics match the readback wrapper: a blip a
                # retry recovers from counts as a RETRY; loss_total is
                # reserved for terminal (ladder-escalating) losses
                if attempts < self.cfg.device_retry_attempts:
                    attempts += 1
                    metrics.inc(
                        "scheduler_device_retries_total",
                        {"stage": "launch"},
                    )
                    delay = device_retry_delay(attempts)
                    logger.warning(
                        "device loss on wave launch (%s); retry %d/%d "
                        "in %.0f ms with a fresh snapshot upload",
                        e, attempts, self.cfg.device_retry_attempts,
                        delay * 1e3,
                    )
                    time.sleep(delay)
                    continue
                metrics.inc(
                    "scheduler_device_loss_total", {"stage": "launch"}
                )
                logger.error(
                    "wave launch failed with device loss after %d retries: %s",
                    attempts, e,
                )
                self._handle_device_loss(e)
                for pi in pis:
                    self.queue.requeue_backoff(pi)
                return

    def _launch_wave_kernel(self, kern, snap, batch, ptab, weights, key):
        """Seam for the deterministic fault injector
        (testing/device_faults.py): every wave launch goes through here.

        The launch DONATES the snapshot buffers, so it runs inside the
        encoder's donation lease: the lease seals the live generation —
        or, when a reader (audit gather, what-if overlay) holds a pin on
        it, hands the kernel a fresh copy so the pinned buffers survive —
        and installs the kernel's output snapshot as the next generation.
        No lock is held across the dispatch: gathers on pinned
        generations overlap wave launches freely (the round-8 deadlock
        interleaving is now ordinary pipelining). `snap` stays in the
        seam signature for the injector but the lease's snapshot is
        authoritative — they differ exactly when a reader pinned between
        flush and launch."""
        enc = self.cache.encoder
        with enc.donation_lease() as dl:
            # kern arrives as a parameter, so the donation is invisible
            # to static analysis at this call — the marker makes it the
            # checked donation site (graftlint donation pass)
            new_snap, res = kern(dl.snap, batch, ptab, weights, key)  # graftlint: donating-call
            if self._split_phase:
                # split-phase: start BOTH device->host copies at dispatch.
                # The few-KB index payload (chosen/placed/deferred) lands
                # the moment the kernel resolves — the fast resolve below
                # never joins with it over a fresh RTT — and the bulk
                # score streams behind it for the trailing validation.
                # Inside the donation lease on purpose (graftlint fastpath
                # rule): the early transfer is tied to the generation
                # lifecycle it reads from, and the trailing entry keeps a
                # pin until its half lands.
                try:
                    res.chosen.copy_to_host_async()
                    res.placed.copy_to_host_async()
                    res.deferred.copy_to_host_async()
                    res.score.copy_to_host_async()
                except Exception:
                    # sharded outputs on exotic meshes may not support the
                    # async copy; the fetch below degrades to a plain
                    # (blocking) device_get — correctness unchanged
                    logger.debug(
                        "async fast-path copy unavailable", exc_info=True
                    )
            dl.result = new_snap
        return new_snap, res

    def _fetch_wave_results(self, batches: List["_InFlightBatch"]):
        """Seam for the fault injector: the combined device->host readback
        for k in-flight batches (the non-split-phase path)."""
        metrics.inc(COUNTER_WAVE_BLOCKING_READBACKS)
        metrics.inc("scheduler_wave_readbacks_total")
        return jax.device_get(
            [
                (b.res.chosen, b.res.placed, b.res.deferred, b.res.score)
                for b in batches
            ]
        )

    def _fetch_wave_index(self, batches: List["_InFlightBatch"]):
        """Seam for the fault injector: the split-phase FAST readback —
        just the index payload (chosen, placed, deferred) per batch. The
        async copy started at dispatch means this usually consumes an
        already-landed transfer; a host-callback ticket beats even that
        (the kernel pushed the payload itself). Blocking fetches (payload
        not materialized yet — the resolve overtook the kernel) count
        separately: they are the readbacks_per_bind numerator."""
        metrics.inc(COUNTER_WAVE_FAST_READBACKS)
        out: List = []
        for b in batches:
            payload = None
            if b.ticket is not None:
                payload = hostcallback.take(b.ticket, timeout=2.0)
                if payload is not None:
                    metrics.inc(COUNTER_WAVE_HOSTCB)
            out.append(payload)
        missing = [i for i, p in enumerate(out) if p is None]
        if missing:
            if not all(
                _device_ready(batches[i].res.chosen)
                and _device_ready(batches[i].res.placed)
                and _device_ready(batches[i].res.deferred)
                for i in missing
            ):
                # the resolve overtook the transfer: this fetch is a real
                # host-blocking device sync — the only kind the legacy
                # readbacks_total series (and readbacks_per_bind) counts
                metrics.inc(COUNTER_WAVE_BLOCKING_READBACKS)
                metrics.inc("scheduler_wave_readbacks_total")
            got = jax.device_get(
                [
                    (
                        batches[i].res.chosen,
                        batches[i].res.placed,
                        batches[i].res.deferred,
                    )
                    for i in missing
                ]
            )
            for i, p in zip(missing, got):
                out[i] = p
        return out

    def _fetch_wave_bulk(self, entries: List["_TrailingReadback"]):
        """Seam for the fault injector: the split-phase TRAILING readback
        — the bulk score payload for registered trailing entries."""
        return jax.device_get([e.score for e in entries])

    # -- split-phase trailing validation --------------------------------------

    def _register_trailing(
        self, score, placed, to_bind, launch_gen, wave_tid, path="wave"
    ) -> "_TrailingReadback":
        """Register one batch's trailing bulk readback at fast commit.
        The entry pins the live generation (released when its readback
        lands) and the backlog is bounded: past trailing_readback_max the
        oldest entry is force-drained with a blocking fetch."""
        pin = None
        try:
            pin = self.cache.encoder.pin_generation().acquire()
        except Exception:
            # pin failure must not block the fast path — the unwind can
            # still invalidate + mark suspect rows without it
            logger.exception("trailing generation pin failed")
        entry = _TrailingReadback(
            score, np.asarray(placed, dtype=bool), list(to_bind),
            launch_gen, wave_tid, pin, path,
        )
        self._trailing.append(entry)
        overflow = len(self._trailing) - self.cfg.trailing_readback_max
        if overflow > 0:
            metrics.inc(COUNTER_WAVE_BLOCKING_READBACKS)
            self._drain_trailing(block=True, limit=overflow)
        metrics.set_gauge(
            GAUGE_WAVE_TRAILING_BACKLOG, float(len(self._trailing))
        )
        return entry

    def _trailing_gate(self, entry: "_TrailingReadback") -> bool:
        """Pre-bind gate (called by _assume_and_bind_bulk between assume
        and bind): consume whatever trailing payloads already landed —
        including this batch's own, when the kernel finished — and report
        whether THIS batch must unwind. Non-blocking: a slow tunnel's
        trailing payload is consumed on a later drain instead of stalling
        the bind-critical path."""
        entry.gated = True
        try:
            self._drain_trailing(block=False)
        finally:
            entry.gated = False
        return entry.quarantined

    def _drain_trailing(
        self, block: bool = False, limit: Optional[int] = None
    ) -> None:
        """Consume registered trailing readbacks, oldest first; never
        raises. block=False stops at the first entry whose bulk payload
        hasn't materialized yet."""
        n = 0
        while self._trailing:
            if limit is not None and n >= limit:
                break
            entry = self._trailing[0]
            if not block and not entry.quarantined and not entry.ready():
                break
            self._trailing.pop(0)
            n += 1
            try:
                self._consume_trailing(entry)
            except Exception:
                logger.exception("trailing readback consumption failed")
                self._release_trailing_pin(entry)
        metrics.set_gauge(
            GAUGE_WAVE_TRAILING_BACKLOG, float(len(self._trailing))
        )

    def _consume_trailing(self, entry: "_TrailingReadback") -> None:
        if entry.quarantined:
            # an elder sibling's trailing trip already condemned this
            # entry (same suspect snapshot chain): nothing to validate
            self._release_trailing_pin(entry)
            tracer.finish(entry.wave_tid, outcome="trailing_sibling")
            return
        t0 = time.monotonic()
        try:
            with _stage_timer("trailing"):
                score = call_with_device_retry(
                    lambda: self._fetch_wave_bulk([entry]),
                    attempts=self.cfg.device_retry_attempts,
                    on_retry=lambda n, e: metrics.inc(
                        "scheduler_device_retries_total",
                        {"stage": "trailing"},
                    ),
                )[0]
            metrics.inc(COUNTER_WAVE_TRAILING_READBACKS)
        except Exception as e:
            logger.exception("trailing bulk readback failed")
            if is_device_loss_error(e):
                metrics.inc(
                    "scheduler_device_loss_total", {"stage": "trailing"}
                )
            self._unwind_trailing(entry, GUARD_TRAILING_LOSS, str(e))
            return
        finally:
            self._release_trailing_pin(entry)
        reason = None
        if self.cfg.kernel_output_guards:
            reason = validate_trailing_score(score, entry.placed)
        if reason is not None:
            self._unwind_trailing(entry, reason)
            return
        self._consecutive_guard_trips = 0
        t1 = time.monotonic()
        tracer.add_span(entry.wave_tid, "trailing", t0, t1)
        tracer.finish(entry.wave_tid, outcome="committed")

    def _release_trailing_pin(self, entry: "_TrailingReadback") -> None:
        pin, entry.pin = entry.pin, None
        if pin is not None:
            try:
                pin.release()
            except Exception:
                logger.exception("trailing generation pin release failed")

    def _unwind_trailing(
        self, entry: "_TrailingReadback", reason: str, detail: str = ""
    ) -> None:
        """The trailing bulk payload disagrees with (or never reached)
        the fast index payload the batch already acted on. Quarantine:
        count the trip, mark every row the fast payload committed into
        suspect (the anti-entropy auditor re-checks + repairs them from
        the host masters), force a device snapshot rebuild, and condemn
        every younger trailing entry (their kernels chained on the same
        suspect snapshot). If this batch's binds have NOT left the
        process yet (the pre-bind gate caught it), revert its assumes
        and requeue — zero wrong bindings; already-bound pods passed the
        fast-phase row/oracle guards and stay."""
        entry.quarantined = True
        metrics.inc("kernel_guard_trips_total", {"reason": reason})
        logger.error(
            "trailing readback validation tripped (%s%s): batch "
            "quarantined, snapshot rebuild forced%s",
            reason, f" {detail}" if detail else "",
            "" if entry.binds_issued else "; assumes unwound",
        )
        with self.cache.lock:
            enc = self.cache.encoder
            for _pi, node_name, _band, _proto in entry.to_bind:
                row = enc._row_by_name.get(node_name)
                if row is not None:
                    enc.suspect_rows.add(row)
            enc.invalidate_device()
        if not entry.binds_issued and not entry.gated:
            for pi, _node, _band, _proto in entry.to_bind:
                try:
                    self.cache.forget_pod(pi.pod)
                except Exception:
                    logger.exception("trailing unwind forget failed")
                metrics.inc(COUNTER_WAVE_TRAILING_UNWOUND)
                tracer.event(pi.trace_id, "wave.trailing_unwound")
                self.queue.requeue_backoff(pi)
        tracer.finish(entry.wave_tid, outcome=f"trailing_trip:{reason}")
        for e in self._trailing:
            if not e.quarantined:
                e.quarantined = True
                metrics.inc(
                    "kernel_guard_trips_total",
                    {"reason": "sibling_quarantine"},
                )
        self._consecutive_guard_trips += 1
        if (
            self._consecutive_guard_trips
            >= self.cfg.device_loss_disable_after
        ):
            logger.error(
                "%d consecutive kernel guard trips: abandoning the "
                "device path for the host path",
                self._consecutive_guard_trips,
            )
            self._set_device_down()

    def _schedule_batch_wave_once(
        self, pis: List[QueuedPodInfo], moves0: int, trace: Trace, t_start: float
    ) -> None:
        """Launch the wave kernel for this batch; resolve the PREVIOUS
        in-flight batch while this one computes (depth-1 pipeline)."""
        # consume any trailing bulk payload that already landed BEFORE the
        # donation below: draining releases the entries' generation pins,
        # so the steady-state launch donates in place instead of paying a
        # copy-on-pin snapshot clone every wave
        if self._trailing:
            self._drain_trailing(block=False)
        # two padded-batch buckets: ragged tails use a small lattice, bursts
        # the full one. Exactly two jit variants per wave count — each extra
        # bucket is another multi-second XLA compile on first use
        small = self._small_bucket
        pad = small if len(pis) <= small else self._batch_size
        # tiny batches ride the narrow-candidate variant: per-wave cost
        # scales with m_cand, and a 1-pod low-load cycle should not pay
        # the 128-candidate list sized for 4096-pod bursts
        small_bucket = pad == small and small < self._batch_size
        m_cand = (
            min(self.cfg.wave_m_cand_small, self._m_cand)
            if small_bucket
            else self._m_cand
        )
        # encode → drain-check → flush must be ATOMIC under the cache lock:
        # a dirty-row scatter uploads full rows from the host masters, which
        # must already include the in-flight batch's replayed placements or
        # the scatter would erase its on-device commits; and the pod batch's
        # node-row references must be captured under the same lock as the
        # snapshot they index (node remove+re-add can reuse a row). Draining
        # happens OUTSIDE the lock (readback + binds), then re-encode.
        # cheap pre-check so the common drain case pays one encode, not two
        # (the locked re-check below remains authoritative: encode itself
        # can intern predicates and dirty rows)
        if self._pending and self.cache.encoder.has_pending_updates:
            self._resolve_pending()
        while True:
            with self.cache.lock, _stage_timer("encode"):
                eb = self._tpl_cache.encode([pi.pod for pi in pis], pad_to=pad)
                trace.step("tpl-encode")
                ptab = self._pair_table(eb)
                n_waves, batch_has_hard = self._batch_waves(eb)
                if small_bucket and not batch_has_hard:
                    # latency bucket, no hard pairs present: ≤256 pods
                    # across the cluster rarely conflict, and a deferred
                    # loser just requeues — 2 waves suffice and halve the
                    # small-cycle cost
                    n_waves = min(n_waves, 2)
                trace.step("pair-table")
                if (
                    not self._pending
                    or not self.cache.encoder.has_pending_updates
                ):
                    snap = self.cache.encoder.flush()
                    enc_cfg = self.cache.encoder.cfg
                    row_names = list(self.cache.encoder.row_names)
                    # verify_cycles: the host view the device encoding was
                    # built from — cloned under the SAME lock as the flush,
                    # or informer churn in between would read as phantom
                    # device/host mismatches
                    verify_snap = (
                        self.cache.update_snapshot()
                        if self.cfg.verify_cycles
                        else None
                    )
                    launch_gen = self.cache._ext_generation
                    break
            self._resolve_pending()
        trace.step("flush")
        # static pinnedness: compiling the pinned-row plan only into
        # batches that carry pinned pods keeps the common path lean (two
        # variants max per config; pod_name_row is host-resident numpy)
        has_pinned = bool((eb.batch.pod_name_row >= 0).any())
        if self._mesh is not None:
            from ..parallel.sharded import make_sharded_wave_kernel

            kern = make_sharded_wave_kernel(
                enc_cfg.v_cap,
                m_cand,
                n_waves,
                self.cfg.hard_pod_affinity_weight,
                self._mesh,
                self._use_pallas_fit,
                # hard-pair batches get the per-wave refresh on EVERY
                # backend: in-batch commits fill the low-count domains the
                # batch-start candidate columns chase, and a CPU hard-
                # spread storm measured bimodal convergence without it
                self._score_refresh or batch_has_hard,
                self._rtc_shape,
                has_pinned,
            )
        else:
            from ..ops.wavelattice import DEFAULT_RTC_SHAPE

            variant = (
                enc_cfg.v_cap,
                m_cand,
                n_waves,
                self.cfg.hard_pod_affinity_weight,
                self._use_pallas_fit,
                self._score_refresh or batch_has_hard,
                self._rtc_shape or DEFAULT_RTC_SHAPE,
                has_pinned,
            )
            kern = make_wave_kernel_jit(*variant)
        ticket = None
        if self.cfg.host_callback_binds and self._mesh is None:
            # depth-infinity micro-waves: the kernel posts its own fast
            # index payload through io_callback under this ticket — the
            # resolve consumes the delivery instead of issuing any sync
            from ..ops.wavelattice import make_wave_kernel_cb_jit

            cb_kern = make_wave_kernel_cb_jit(*variant)
            ticket = hostcallback.new_ticket()
            t_arr = np.int32(ticket)

            def kern(s, b, p, w, k, _cb=cb_kern, _t=t_arr):
                return _cb(s, b, p, w, k, _t)

        self._rng_key, sub = jax.random.split(self._rng_key)
        w_launch = np.asarray(self._weights)
        t_launch0 = time.monotonic()
        try:
            new_snap, res = self._launch_wave_kernel(
                kern, snap, eb.batch, ptab, w_launch, sub
            )
        except Exception:
            if ticket is not None:
                hostcallback.discard(ticket)
            with self.cache.lock:
                self.cache.encoder.invalidate_device()
            raise
        trace.step("launch")
        t_launched = time.monotonic()
        # wave-level trace: ONE record for the kernel launch the whole
        # batch shares — each pod's span chain carries `wave=<id>` so a
        # slow wave explains its N slow pods in one lookup
        wave_tid = tracer.start(
            "wave", f"wave/{len(pis)}pods", t0=t_start, pods=len(pis)
        )
        tracer.add_span(wave_tid, "encode", t_start, t_launch0)
        tracer.add_span(wave_tid, "launch", t_launch0, t_launched)
        tracer.add_span_many(
            [pi.trace_id for pi in pis], "encode", t_start, t_launched,
            wave=wave_tid,
        )
        # the donation lease inside _launch_wave_kernel already installed
        # new_snap as the live generation — nothing to publish here
        self._pending.append(
            _InFlightBatch(
                pis, eb, row_names, res, moves0, trace, t_start, verify_snap,
                launch_gen, wave_tid, t_launched, w_launch, sub, ticket,
            )
        )
        metrics.inc("scheduler_wave_batches_total")
        metrics.set_gauge(GAUGE_WAVE_INFLIGHT, float(len(self._pending)))
        if len(self._pending) > self._wave_inflight_peak:
            self._wave_inflight_peak = len(self._pending)
            metrics.set_gauge(
                GAUGE_WAVE_INFLIGHT_MAX, float(self._wave_inflight_peak)
            )
        if len(self._pending) >= self._pipeline_depth:
            # pipeline full: ONE combined readback resolves every batch but
            # the newest, which stays in flight so its device time overlaps
            # the readback + the host-side bind work below
            keep = 0 if self._pipeline_depth == 1 else 1
            self._resolve_oldest(len(self._pending) - keep)
        elif self._split_phase and len(self._pending) > 1:
            # continuous micro-waves: any older wave whose fast index
            # payload ALREADY landed (async copy started at dispatch, or
            # the kernel's own io_callback) commits now instead of
            # waiting for the pipeline to fill — its pods stop paying the
            # pipeline-fill wait, and the device keeps computing the
            # newest wave while the host binds. Never the newest: its
            # device time is what overlaps this host work.
            n_ready = 0
            for b in self._pending[:-1]:
                if not self._fast_payload_ready(b):
                    break
                n_ready += 1
            if n_ready:
                self._resolve_oldest(n_ready)

    def _fast_payload_ready(self, b: "_InFlightBatch") -> bool:
        if b.ticket is not None and hostcallback.ready(b.ticket):
            return True
        return (
            _device_ready(b.res.chosen)
            and _device_ready(b.res.placed)
            and _device_ready(b.res.deferred)
        )

    def _resolve_pending(self) -> None:
        self._resolve_oldest(len(self._pending))

    def _resolve_oldest(self, k: int) -> None:
        """Resolve the k oldest in-flight batches with ONE combined
        device->host readback; never raises. Placements of ALL k batches
        are replayed into the host cache (and bound) before any batch's
        failure handling runs — the fallback/preemption paths read the host
        cache, and an unreplayed sibling batch would let them grant the
        same capacity twice."""
        if k <= 0:
            return
        batches, self._pending = self._pending[:k], self._pending[k:]
        metrics.set_gauge(GAUGE_WAVE_INFLIGHT, float(len(self._pending)))
        split = self._split_phase
        t_rb0 = time.monotonic()
        with _stage_timer("kernel"):
            try:
                # transient device/tunnel blips get bounded jittered
                # retries (the fetched refs are re-gettable — no donation
                # on the read side) before the loss path takes over.
                # Split mode fetches ONLY the index payload here; the bulk
                # score trails through _fetch_wave_bulk off this path.
                fetch = (
                    self._fetch_wave_index
                    if split
                    else self._fetch_wave_results
                )
                fetched = call_with_device_retry(
                    lambda: fetch(batches),
                    attempts=self.cfg.device_retry_attempts,
                    on_retry=lambda n, e: metrics.inc(
                        "scheduler_device_retries_total",
                        {"stage": "readback"},
                    ),
                )
                self._consecutive_device_loss = 0
            except Exception as e:
                for b in batches:
                    if b.ticket is not None:
                        hostcallback.discard(b.ticket)
                    tracer.finish(b.wave_tid, outcome="readback_failed")
                    for pi in b.pis:
                        tracer.event(pi.trace_id, "readback.failed")
                # device/tunnel error: the kernels' on-device commits are
                # unknowable — rebuild HBM from the host masters and retry
                with self.cache.lock:
                    self.cache.encoder.invalidate_device()
                logger.exception(
                    "wave pipeline readback failed (%d batches)", len(batches)
                )
                lost = is_device_loss_error(e)
                if lost:
                    metrics.inc(
                        "scheduler_device_loss_total", {"stage": "readback"}
                    )
                    self._handle_device_loss(e)
                moves = self.queue.moves_snapshot()
                for b in batches:
                    for pi in b.pis:
                        if self.cache.has_pod(pi.pod.metadata.key):
                            continue
                        if lost:
                            # infrastructure failure, not pod
                            # unschedulability: backoff retries in 1-10 s
                            # instead of sitting out unschedulableQ's
                            # 30-60 s leftover flush
                            self.queue.requeue_backoff(pi)
                        else:
                            self.queue.add_unschedulable_if_not_present(pi, moves)
                return
        t_rb1 = time.monotonic()
        for b in batches:
            # fan-in: the shared device wait (launch -> resolve entry) and
            # the combined readback land on the wave trace AND on every
            # pod trace riding it, in two ring acquisitions per batch
            tracer.add_span(b.wave_tid, "device", b.t_launched, t_rb0)
            tracer.add_span(b.wave_tid, "readback", t_rb0, t_rb1)
            tids = [pi.trace_id for pi in b.pis]
            tracer.add_span_many(tids, "device", b.t_launched, t_rb0)
            tracer.add_span_many(tids, "readback", t_rb0, t_rb1)
        tails = []
        quarantined = False
        for b, arrays in zip(batches, fetched):
            if quarantined:
                # an older sibling's output failed validation: this
                # batch's kernel chained on the same suspect snapshot —
                # don't act on its results, just reschedule the pods
                # (same accounting as the still-pending batches
                # _on_guard_trip pulls, or the blast-radius counters
                # undercount exactly under sustained pipelined load)
                metrics.inc(
                    "kernel_guard_trips_total",
                    {"reason": "sibling_quarantine"},
                )
                tracer.finish(b.wave_tid, outcome="sibling_quarantine")
                tails.append(None)
                for pi in b.pis:
                    tracer.event(pi.trace_id, "wave.quarantined")
                    self.queue.readd(pi)
                continue
            if split:
                # fast payload only: score arrives with the trailing bulk
                # readback — validation/decode below run with score=None
                arrays = (*arrays, None)
            try:
                tails.append(self._commit_batch(b, arrays, t_rb1))
                if b.trailing is None:
                    # combined mode — or a split batch that placed
                    # nothing: the guard story is complete right here.
                    # With a trailing entry registered, the trip counter
                    # resets only when the TRAILING validation passes
                    # (else a poisoned device alternating commit/unwind
                    # would never latch off).
                    self._consecutive_guard_trips = 0
                    tracer.finish(b.wave_tid, outcome="committed")
            except KernelGuardTrip as trip:
                quarantined = True
                tracer.finish(b.wave_tid, outcome=f"guard_trip:{trip.reason}")
                self._on_guard_trip(trip)
                # the violating batch degrades to the host path (nothing
                # was assumed for it): _finish_batch host-schedules every
                # pod — at worst the wave runs at host speed, wrong
                # placements are structurally impossible
                tails.append((list(b.pis), []))
            except Exception:
                logger.exception("committing wave batch failed")
                tracer.finish(b.wave_tid, outcome="commit_failed")
                tails.append(None)
                moves = self.queue.moves_snapshot()
                for pi in b.pis:
                    if not self.cache.has_pod(pi.pod.metadata.key):
                        self.queue.add_unschedulable_if_not_present(pi, moves)
        for b, tail in zip(batches, tails):
            if tail is None:
                continue
            try:
                self._finish_batch(b, tail[0], tail[1])
            except Exception:
                logger.exception("resolving wave batch failures failed")
                moves = self.queue.moves_snapshot()
                for pi in tail[0]:
                    if not self.cache.has_pod(pi.pod.metadata.key):
                        self.queue.add_unschedulable_if_not_present(pi, moves)
                for pi, _i in tail[1]:
                    self.queue.add_unschedulable_if_not_present(pi, moves)

    def _commit_batch(
        self, p: "_InFlightBatch", arrays, t_rb1: Optional[float] = None
    ) -> tuple:
        """Act on one read-back batch's placements: assume + bind, re-add
        deferred pods. Returns (fallback_pis, failed) for _finish_batch.
        Raises KernelGuardTrip when the batch's outputs fail validation —
        BEFORE any placement is assumed or any pod requeued.

        t_rb1: the combined readback's completion stamp — the pod traces'
        `guard` span runs from it to the assume hand-off, so waiting out
        an earlier sibling's commit is attributed, not lost in a gap."""
        pis, eb, row_names = p.pis, p.eb, p.row_names
        chosen, placed, deferred, score = arrays
        trace, t_start = p.trace, p.t_start
        trace.step("kernel")
        algo_dur = time.monotonic() - t_start
        metrics.observe("scheduling_algorithm_duration_seconds", algo_dur)
        if self.cfg.kernel_output_guards:
            # structural validation first: the decode loop below indexes
            # row_names[chosen[i]] — a wild index from a corrupted kernel
            # would either crash the commit or (negative wrap) silently
            # pick the WRONG node
            reason = validate_batch_outputs(
                chosen, placed, score, len(row_names)
            )
            if reason:
                raise KernelGuardTrip(reason)

        to_bind: List = []  # (pi, node_name, prio_band, proto)
        protos: dict = {}  # template -> shared encoder proto
        fallback_pis: List[QueuedPodInfo] = []
        failed: List = []  # (pi, tpl_index)
        deferred_pis: List[QueuedPodInfo] = []
        for i, pi in enumerate(pis):
            if eb.fallback[i]:
                fallback_pis.append(pi)
                continue
            if placed[i]:
                node_name = row_names[int(chosen[i])]
                if node_name is None:
                    failed.append((pi, i))
                    continue
                t = int(eb.pod_tpl_np[i])
                proto = protos.get(t)
                if proto is None:
                    # one spec-derived encoding per template, shared by
                    # every sibling in the batch (same fingerprint =>
                    # identical proto). Under the cache lock: the encoder's
                    # vocabs are mutated by informer threads through locked
                    # cache methods, and an intern between _match_vec and
                    # the proto's vocab-length stamp would smuggle a short
                    # match_vec past add_pod's staleness guard
                    with self.cache.lock:
                        proto = protos[t] = self.cache.encoder.pod_proto(
                            pi.pod
                        )
                to_bind.append(
                    (pi, node_name, int(eb.pod_band_np[i]), proto)
                )
            elif deferred[i]:
                deferred_pis.append(pi)
            else:
                failed.append((pi, i))
        if self.cfg.kernel_output_guards and self.cfg.guard_sample_per_wave:
            # sampled host-oracle re-check (the online analogue of
            # tests/test_fuzz_differential.py's oracle): a sample of this
            # wave's placements must pass the pre-batch-sound host filter
            # subset against the live cache. Runs BEFORE any queue/assume
            # side effect so a trip quarantines a fully-unacted batch.
            bad = self._guard_oracle_sample(to_bind, p.launch_gen)
            if bad is not None:
                raise KernelGuardTrip("oracle_infeasible", bad)
        # stall breaker: a batch that placed NOTHING but deferred pods is
        # structurally contended (e.g. a hard-spread burst whose every
        # candidate domain is serialized) — an immediate readd would hot-
        # loop the identical batch through a full wave cycle each time.
        # Route the deferred pods through BACKOFF (they are retryable, not
        # unschedulable: no condition/event, 1-10 s retry, and move events
        # re-activate backoffQ normally).
        for pi in deferred_pis:
            tracer.event(pi.trace_id, "wave.deferred")
            if to_bind:
                self.queue.readd(pi)
            else:
                self.queue.requeue_backoff(pi)
        if t_rb1 is not None:
            # guard = readback done -> assume hand-off (output validation,
            # decode, oracle sample, and any elder-sibling commit wait)
            tracer.add_span_many(
                [pi.trace_id for pi, _n, _b, _p in to_bind],
                "guard", t_rb1, time.monotonic(),
            )

        entry = None
        if self._split_phase and (
            to_bind or bool(np.asarray(placed, dtype=bool).any())
        ):
            # split-phase trailing half: the bulk score payload validates
            # off the critical path. Registered BEFORE assume so the
            # pre-bind gate below can catch an own-batch disagreement
            # while the assumes are still revertible.
            entry = p.trailing = self._register_trailing(
                p.res.score, placed, to_bind, p.launch_gen, p.wave_tid,
            )

        if self.cfg.verify_cycles and to_bind:
            try:
                self._verify_placements(to_bind, p.snapshot)
            except Exception:
                # a diagnostic must never affect scheduling: an exception
                # here would requeue a fully successful batch while the
                # device snapshot keeps its commits
                logger.exception("verify_cycles cross-check failed")
        self._assume_and_bind_bulk(
            to_bind, t_start, device_synced=True,
            trailing_gate=(
                (lambda: self._trailing_gate(entry))
                if entry is not None
                else None
            ),
        )
        trace.step("assume+bind")
        if entry is not None and not entry.quarantined:
            entry.binds_issued = True
        if entry is None or not entry.quarantined:
            self._record_wave_for_tuner(
                p.pis,
                {id(pi): node for pi, node, _b, _pr in to_bind},
                p.weights,
                p.rng_key,
                p.launch_gen,
                path="wave",
            )
        return fallback_pis, failed

    def _record_wave_for_tuner(
        self, pis, placed_by_id, weights, rng_key, launch_gen, path
    ) -> None:
        """Feed the policy gym's replay ring (tuner/waves.py) with a
        committed batch: pod specs, the launch weight vector + PRNG key,
        and the placements production actually took. Outside every lock,
        one guarded append — recording must never perturb scheduling."""
        rec = self.wave_recorder
        if rec is None or weights is None:
            return
        try:
            pods = [pi.pod for pi in pis]
            placements = [placed_by_id.get(id(pi), "") for pi in pis]
            rec.record_wave(
                pods,
                weights,
                placements,
                rng_key=rng_key,
                launch_gen=launch_gen,
                path=path,
            )
        except Exception:
            logger.exception("wave recording failed (scheduling unaffected)")

    # Bound on full preemption scans per resolved batch: with the
    # per-(template, priority) dedup below the bound only engages when a
    # batch fails across MANY distinct templates at once; the skipped pods
    # retry preemption on their next cycle (the reference bounds work the
    # same way — one nominated node per pod per cycle,
    # pkg/scheduler/core/generic_scheduler.go:270).
    _MAX_PREEMPT_SCANS_PER_BATCH = 128

    def _finish_batch(
        self, p: "_InFlightBatch", fallback_pis: List, failed: List
    ) -> None:
        """Host fallback + failure/preemption handling for one committed
        batch (runs after EVERY sibling batch's placements are replayed).

        Storm path (soak lesson, r4): a full cluster fails WHOLE batches of
        one template. Failure handling is deduplicated at template
        granularity — one preemption scan per (template, priority) per
        unchanged snapshot, not one per pod — and the unschedulable
        condition write is skipped when the stored condition already says
        exactly the same thing, so a 1024-pod unschedulable batch costs one
        scan + zero redundant API writes instead of 1024 scans + 2048
        writes."""
        eb, row_names, res, moves0 = p.eb, p.row_names, p.res, p.moves0
        with _stage_timer("finish"):
            if fallback_pis or failed:
                # the host paths below read the host cache; a NEWER in-flight
                # batch holds device-committed placements the cache can't see
                # yet — resolve it first or fallback/preemption would grant the
                # same capacity twice (bounded recursion: pending is detached
                # before each resolve)
                with _stage_timer("finish.resolve"):
                    self._resolve_pending()
                with _stage_timer("finish.snapshot"):
                    self._snapshot = self.cache.update_snapshot()
            if fallback_pis:
                with _stage_timer("finish.fallback"):
                    for pi in fallback_pis:
                        self._schedule_one_host(pi, moves0)
            if failed:
                with _stage_timer("finish.failed"):
                    self._finish_failed(p, failed)
        p.trace.log_if_long(0.1)

    def _finish_failed(self, p: "_InFlightBatch", failed: List) -> None:
        eb, row_names, res, moves0 = p.eb, p.row_names, p.res, p.moves0
        resolvable_tpl = jax.device_get(res.resolvable_tpl)
        pod_tpl = eb.pod_tpl_np
        pod_prio = eb.pod_prio_np
        # vectorized victim selection (ops/preemptlattice): ONE batched
        # pass over a (template, priority)-grouped gather of the batch
        # ranks candidate nodes and minimal victim-band prefixes for
        # every failed pod; the per-pod host work below shrinks to the
        # exact oracle check on the chosen node. None (disabled / guard
        # trip / kernel error) falls back to the optimistic what-if mask
        # + the per-pod host walk — the pre-ISSUE-15 path.
        vec = self._vector_preempt_batch(eb, failed, pod_tpl, pod_prio)
        whatif_tpl = None
        if vec is None:
            # batched masked what-if (one device call for ALL failed
            # pods): per-template optimistic preemption mask, priority =
            # max over the batch's pods of that template so the mask
            # stays a superset for every pod
            whatif_tpl = self._preempt_whatif_tpl(eb, failed, pod_tpl)
        # (template, priority) groups whose scan on the CURRENT snapshot
        # found no viable node: siblings share the spec, so their scans
        # are provably identical — skip them. A successful preemption
        # mutates the cluster (victims deleted), which can unblock other
        # groups: clear the memo.
        hopeless: set = set()
        scans = 0
        verified = 0
        # in-batch fan-out: a wave's failed pods are overwhelmingly
        # sibling specs, and within one batch `self._snapshot` is stale —
        # victims already claimed by an earlier sibling still look
        # evictable, so without this every sibling would nominate the
        # SAME node and the batch would free exactly one node per wave
        # (measured: 89/1000 burst pods bound in 25 min). `targeted`
        # tracks nodes whose victims this batch already claimed; each
        # sibling consumes the next untargeted candidate from its group's
        # kernel ranking, so a 1k-pod burst nominates ~1k DISTINCT nodes
        # in one batched pass.
        targeted: set = set()
        group_cands: Dict[tuple, List[str]] = {}
        # the wave's resolvable masks live in the LAUNCH row space; the
        # preempt kernel ran on the post-flush one. Intersecting the two
        # is only meaningful when no churn remapped rows in between —
        # otherwise the helpful mask must not narrow the (oracle-
        # validated) fallback candidate list against the wrong nodes.
        vec_same_rows = (
            vec is not None
            and vec["row_names"][: len(row_names)] == list(row_names)
        )
        for pi, i in failed:
            t = int(pod_tpl[i])
            group = (t, int(pod_prio[i]))
            rows_mask = resolvable_tpl[t]
            vector_choice = None
            saturated = False
            if vec is not None:
                g = vec["group_of"].get(group)
                helpful = vec["helpful"]
                # vec_names is the row space the preempt kernel actually
                # ran on (captured under the lock WITH its flush) — the
                # wave-launch row_names may be stale if informer churn
                # remapped rows while the wave was in flight
                vec_names = vec["row_names"]
                if (
                    g is not None
                    and vec_same_rows
                    and helpful.shape[1] == rows_mask.shape[0]
                ):
                    rows_mask = rows_mask & helpful[g]
                if g is not None and int(vec["node"][g]) >= 0:
                    if group not in group_cands:
                        # the group's full candidate ranking: the kernel's
                        # top-K rows first, then every other helpful row
                        # in row order — the fan-out tail for groups with
                        # more siblings than K
                        ranked = [
                            int(r)
                            for r in vec["cand"][g]
                            if 0 <= int(r) < len(vec_names)
                            and vec_names[int(r)]
                        ]
                        seen = set(ranked)
                        tail = [
                            int(r)
                            for r in np.nonzero(helpful[g])[0]
                            if int(r) < len(vec_names)
                            and vec_names[int(r)]
                            and int(r) not in seen
                        ]
                        group_cands[group] = [
                            vec_names[r] for r in ranked + tail
                        ]
                    avail = [
                        n for n in group_cands[group] if n not in targeted
                    ]
                    if avail:
                        # the oracle's exact selection runs on just these
                        # (≤K) untargeted rows instead of every
                        # resolvable node
                        vector_choice = avail[: len(vec["cand"][g])]
                    else:
                        # every node this group's eviction could free is
                        # already claimed by an earlier sibling: skip this
                        # round — the pod retries next wave against a
                        # snapshot that reflects the evictions
                        saturated = True
                        metrics.inc(
                            "scheduler_preemption_fallback_total",
                            {"reason": "batch_saturated"},
                        )
            elif (
                whatif_tpl is not None
                and whatif_tpl.shape[1] == rows_mask.shape[0]
            ):
                rows_mask = rows_mask & whatif_tpl[t]
            rows = np.nonzero(rows_mask)[0]
            candidates = [
                row_names[r]
                for r in rows
                if row_names[r] and row_names[r] not in targeted
            ]
            # an attempt with a vector choice costs an exact check on ≤K
            # nodes; a full host scan runs only on fallback (no vector
            # answer) or for the sampled differential oracle below. The
            # hopeless memo covers both: siblings of a rejected group
            # would re-fail identically on the unchanged snapshot.
            attempt_would_run = bool(candidates) or vector_choice is not None
            skip = saturated or (
                attempt_would_run
                and (
                    group in hopeless
                    or scans >= self._MAX_PREEMPT_SCANS_PER_BATCH
                )
            )
            verify_full = (
                vector_choice is not None
                and not skip
                and verified < self.cfg.preempt_verify_sample
            )
            preempted = self._handle_failure(
                pi,
                moves0,
                message=f"0/{self.cache.node_count} nodes are available",
                candidate_nodes=candidates,
                skip_preemption=skip,
                vector_choice=vector_choice,
                verify_full=verify_full,
            )
            if verify_full:
                verified += 1
            if preempted:
                targeted.add(preempted)
            if attempt_would_run and not skip:
                if vector_choice is None or verify_full:
                    scans += 1  # bound the expensive full walks only
                if preempted:
                    hopeless.clear()
                else:
                    hopeless.add(group)

    # pre-batch-sound plugins: anti-monotone (or invariant) under in-batch
    # commits, so a device placement MUST pass them on the pre-batch host
    # snapshot. Inter-pod terms are excluded — batch-mates legitimately
    # CREATE affinity feasibility (carveout chains)
    _VERIFY_PLUGINS = (
        "NodeUnschedulable",
        "NodeName",
        "NodePorts",
        "NodeAffinity",
        "TaintToleration",
        "NodeResourcesFit",
    )

    def _verify_placements(self, to_bind: List, snapshot) -> None:
        """Per-cycle device-vs-host cross-check (SURVEY §5): run the host
        filter chain's pre-batch-sound subset for every placement the
        kernel committed, against the snapshot captured AT LAUNCH (the
        state the device encoding saw); a FAIL verdict means the device
        encoding and the host plugins disagree — counted and logged, never
        acted on (the live analogue of tests/test_fuzz_differential.py).
        Debug mode: the launch-time snapshot clone is the cost."""
        if snapshot is None:
            return
        for pi, node_name, _band, _proto in to_bind:
            ni = snapshot.node_info_map.get(node_name)
            if ni is None:
                continue
            fail = self._check_placement(pi, ni)
            if fail is not None:
                name, st = fail
                metrics.inc(
                    "scheduler_verify_mismatch_total", {"plugin": name}
                )
                logger.error(
                    "verify_cycles: device placed %s on %s but host "
                    "plugin %s says %s",
                    pi.pod.metadata.key,
                    node_name,
                    name,
                    st.message or st.code,
                )

    def _check_placement(self, pi, ni):
        """Run the pre-batch-sound host filter subset (_VERIFY_PLUGINS)
        for one kernel placement. Returns (plugin_name, status) on the
        first failure, else None. Shared by the diagnostic cross-check
        (_verify_placements) and the acting oracle guard."""
        prof = self.profiles.for_pod(pi.pod)
        if prof is None:
            return None
        fw = prof.framework
        state = CycleState()
        for name in self._VERIFY_PLUGINS:
            if not fw.has_filter_plugin(name):
                continue
            st = fw.plugin(name).filter(state, pi.pod, ni)
            if not is_success(st):
                return name, st
        return None

    def _guard_oracle_sample(
        self, to_bind: List, launch_gen: int
    ) -> Optional[str]:
        """Re-check a deterministic sample of this wave's placements
        against the host filter chain's pre-batch-sound subset
        (_VERIFY_PLUGINS), on the LIVE cache NodeInfos under the cache
        lock. By the time a batch commits, every older batch's placements
        have been replayed into the cache, so the cache equals the state
        this batch's kernel encoding saw — EXCEPT for mutations no device
        chain saw: nodes the informer touched after launch (cordon,
        taint, external bind) AND host-path assumes (fallback pods
        scheduled between this batch's launch and commit). Both stamp
        ext_generation past `launch_gen` and are skipped, because a
        placement that was sound at encode time failing against NEWER
        node state is churn, not kernel corruption — acting on it would
        quarantine a correct batch and (after device_loss_disable_after
        consecutive waves) falsely latch the device path off.
        Sibling-batch DEVICE assumes deliberately do NOT move
        ext_generation: the device chain saw those placements, so a
        disagreement there is a real kernel signal.
        Returns a human-readable detail string on violation, else None."""
        k = min(self.cfg.guard_sample_per_wave, len(to_bind))
        if k <= 0:
            return None
        step = max(1, len(to_bind) // k)
        sample = to_bind[::step][:k]
        with self.cache.lock:
            for pi, node_name, _band, _proto in sample:
                ni = self.cache._nodes.get(node_name)
                if ni is None:
                    # node vanished mid-flight (informer remove): the
                    # assume path parks this as an orphan — not a kernel
                    # correctness signal
                    continue
                if ni.ext_generation > launch_gen:
                    metrics.inc(
                        "kernel_guard_oracle_skips_total",
                        {"reason": "node_churn"},
                    )
                    continue
                fail = self._check_placement(pi, ni)
                if fail is not None:
                    name, st = fail
                    return (
                        f"{pi.pod.metadata.key} on {node_name}: "
                        f"{name} says {st.message or st.code}"
                    )
        return None

    def _on_guard_trip(self, trip: KernelGuardTrip) -> None:
        """A batch's outputs failed validation: count it, force a device
        snapshot rebuild (its commits are suspect), and pull every NEWER
        in-flight batch out of the pipeline unread — their kernels
        chained on the same suspect snapshot. Their pods requeue
        un-assumed (zero loss); repeated trips latch the device down."""
        metrics.inc("kernel_guard_trips_total", {"reason": trip.reason})
        logger.error(
            "kernel output guard tripped (%s): batch quarantined to the "
            "host path, snapshot rebuild forced", trip
        )
        with self.cache.lock:
            self.cache.encoder.invalidate_device()
        pending, self._pending = self._pending, []
        for b in pending:
            metrics.inc(
                "kernel_guard_trips_total", {"reason": "sibling_quarantine"}
            )
            if b.ticket is not None:
                hostcallback.discard(b.ticket)
            tracer.finish(b.wave_tid, outcome="sibling_quarantine")
            for pi in b.pis:
                tracer.event(pi.trace_id, "wave.quarantined")
                self.queue.readd(pi)
        self._consecutive_guard_trips += 1
        if self._consecutive_guard_trips >= self.cfg.device_loss_disable_after:
            logger.error(
                "%d consecutive kernel guard trips: abandoning the device "
                "path for the host path", self._consecutive_guard_trips,
            )
            self._set_device_down()

    def _set_device_down(self) -> None:
        self._device_down = True
        metrics.set_gauge("scheduler_device_down", 1.0)

    def _handle_device_loss(self, exc: BaseException) -> None:
        """Unrecoverable-by-retry device loss. Escalation ladder: shrink
        the mesh to the surviving devices (re-shard the snapshot, drop the
        jit caches keyed on the dead mesh), ride out a fully-transient
        blip with just the forced re-upload, or — nothing usable, or
        losses keep repeating — latch the device path off and serve from
        the host path."""
        self._consecutive_device_loss += 1
        metrics.set_gauge(
            "scheduler_device_consecutive_loss",
            float(self._consecutive_device_loss),
        )
        if self._consecutive_device_loss >= self.cfg.device_loss_disable_after:
            logger.error(
                "%d consecutive device-loss events without a successful "
                "launch: abandoning the device path",
                self._consecutive_device_loss,
            )
            self._set_device_down()
            return
        if self._mesh is not None:
            from ..parallel import sharded
            from ..parallel.mesh import (
                largest_pow2_prefix,
                make_mesh,
                replicated,
                single_device_shardings,
                snapshot_shardings,
                surviving_devices,
            )

            devices = list(self._mesh.devices.flat)
            survivors = surviving_devices(devices, probe=self._device_probe)
            usable = largest_pow2_prefix(survivors)
            if len(survivors) == len(devices):
                # every chip answers: a transient transfer failure — the
                # invalidate already queued a full re-upload
                logger.warning(
                    "device loss looks transient (%d/%d devices respond): "
                    "keeping the mesh, snapshot re-uploads",
                    len(survivors), len(devices),
                )
                return
            if usable:
                # the jit caches hold kernels compiled for the DEAD mesh:
                # clear them before any launch against the new one
                sharded.make_sharded_wave_kernel.cache_clear()
                sharded.make_sharded_schedule_batch.cache_clear()
                new_mesh = make_mesh(usable) if len(usable) > 1 else None
                with self.cache.lock:
                    if new_mesh is not None:
                        self.cache.encoder.set_sharding(
                            snapshot_shardings(new_mesh),
                            replicated(new_mesh),
                        )
                    else:
                        # one survivor: pin uploads to IT — unpinned
                        # (None, None) device_puts go to the JAX default
                        # device, which may be the dead one
                        self.cache.encoder.set_sharding(
                            *single_device_shardings(usable[0])
                        )
                self._mesh = new_mesh
                self._pair_cache = None
                metrics.inc("scheduler_mesh_shrinks_total")
                metrics.set_gauge(
                    "scheduler_mesh_devices", float(max(len(usable), 1))
                )
                logger.error(
                    "mesh shrunk to %d surviving device(s) after device "
                    "loss (%s); snapshot re-sharded", len(usable), exc,
                )
                return
            logger.error(
                "no surviving devices after device loss (%s): host path", exc
            )
            self._set_device_down()
            return
        # single-device: probe it once — if even a trivial round-trip
        # fails the device is gone
        try:
            if self._device_probe(None):
                logger.warning(
                    "device loss looks transient (probe ok): snapshot "
                    "re-uploads on the next flush"
                )
                return
        except Exception:
            pass
        self._set_device_down()

    def _run_serial_kernel(self, kern, snap, batch, key, weights=None):
        """Launch + readback of the serial batch kernel — one synchronous
        call, split out as an injectable seam for the chaos fault
        injector (mirrors _launch_wave_kernel/_fetch_wave_results).
        ``weights`` pins the exact launch vector (the tuner records it
        for differential replay); None reads the live policy.

        Split-phase mode: only the small chosen-index vector is fetched
        on the critical path (its device→host copy was started at
        dispatch); the bulk score tensor streams back behind it and is
        validated by the trailing machinery — the caller sees score=None
        and registers a _TrailingReadback."""
        if weights is None:
            weights = np.asarray(self._weights)
        res = kern(snap, batch, weights, key)
        if self._split_phase:
            with self.cache.encoder.pin_generation():
                try:
                    res.chosen.copy_to_host_async()
                    res.score.copy_to_host_async()
                except Exception:
                    logger.debug(
                        "async readback start failed", exc_info=True
                    )
                metrics.inc(COUNTER_WAVE_BLOCKING_READBACKS)
                chosen = np.asarray(jax.device_get(res.chosen))
            return res, chosen, None
        chosen, score = jax.device_get((res.chosen, res.score))
        return res, chosen, score

    @staticmethod
    def _device_probe(device) -> bool:
        """One tiny put/get round-trip (injectable via monkeypatching for
        chaos tests; device=None probes the default device)."""
        from ..parallel.mesh import _default_probe

        return _default_probe(device)

    # pad buckets for the (template, priority)-grouped preemption batch:
    # every distinct pad is a kernel compile, and failed-group counts are
    # small (distinct specs x priority tiers, not pods)
    _PREEMPT_PAD_BUCKETS = (16, 128)

    def _run_preempt_kernel(self, snap, batch, prios: np.ndarray) -> dict:
        """Launch + readback of the vectorized victim-selection kernel —
        one synchronous call, split out as an injectable seam for the
        differential tests' seeded-disagreement corruption (mirrors
        _run_serial_kernel)."""
        from ..ops.preemptlattice import preempt_select

        res = preempt_select(snap, batch, np.asarray(prios, np.int32))
        node, cand, thr, vic, viol, helpful = jax.device_get(
            (res.node, res.cand, res.threshold_prio, res.victims,
             res.violations, res.helpful)
        )
        return {
            "node": np.asarray(node),
            "cand": np.asarray(cand),
            "threshold": np.asarray(thr),
            "victims": np.asarray(vic),
            "violations": np.asarray(viol),
            "helpful": np.asarray(helpful),
        }

    def _vector_preempt_batch(
        self, eb, failed: List, pod_tpl: np.ndarray, pod_prio: np.ndarray
    ) -> Optional[dict]:
        """ONE batched victim-selection pass for a resolved wave's failed
        pods (ops/preemptlattice.preempt_select): failed pods group by
        (template, priority) — siblings share the whole answer — the
        template tensors gather into a [G]-row PodBatch, and the kernel
        ranks (node, minimal victim-band prefix) per group against a
        freshly-flushed snapshot whose PDB budget column was just
        refreshed from the disruption controller's published budgets.
        Readback passes through validate_preempt_outputs (the kernel-
        output guard discipline) — a trip, a kernel error, or the config
        gate returns None and the caller falls back to the host walk;
        nothing is ever evicted from this result without the per-node
        host-oracle check in _attempt_preemption."""
        if (
            not self.cfg.vector_preemption
            or self.cfg.disable_preemption
            or self._device_down
            or not self.cfg.use_device
        ):
            return None
        try:
            groups: Dict[tuple, int] = {}
            t_idx: List[int] = []
            g_prio: List[int] = []
            for pi, i in failed:
                if i < 0:
                    continue  # decode anomaly: host walk handles it
                key = (int(pod_tpl[i]), int(pod_prio[i]))
                if key not in groups:
                    groups[key] = len(t_idx)
                    t_idx.append(key[0])
                    g_prio.append(key[1])
            if not groups:
                return None
            pad = self._PREEMPT_PAD_BUCKETS[-1]
            for b in self._PREEMPT_PAD_BUCKETS:
                if len(t_idx) <= b:
                    pad = b
                    break
            if len(t_idx) > pad:
                # more distinct groups than the widest bucket: the tail
                # falls back to the host walk (counted, never silent)
                metrics.inc(
                    "scheduler_preemption_fallback_total",
                    {"reason": "group_overflow"},
                )
                t_idx, g_prio = t_idx[:pad], g_prio[:pad]
                groups = {k: g for k, g in groups.items() if g < pad}
            idx = np.zeros(pad, np.int32)
            idx[: len(t_idx)] = t_idx
            prios = np.zeros(pad, np.int32)
            prios[: len(g_prio)] = g_prio
            # the PDB list can be a store round-trip (REST-backed server):
            # never hold the cache lock across it
            pdbs = list(self._list_pdbs()) if self._list_pdbs else []
            with self.cache.lock:
                # _finish_batch drains the pipeline before failure
                # handling, so no newer batch's un-replayed device commits
                # can be erased by this flush
                assert not self._pending
                self.cache.encoder.update_pdb_blocked(pdbs)
                snap = self.cache.encoder.flush()
                # decode rows against the SAME row space the kernel ran
                # on: informer churn during the in-flight wave can remap
                # encoder rows, so the wave-launch row_names must never
                # decode this pass's output (the serial-path re-encode
                # discipline, PR-4 second review)
                vec_row_names = list(self.cache.encoder.row_names)
                n_rows = len(vec_row_names)
            gathered = jax.tree.map(
                lambda a: jnp.take(a, idx, axis=0), eb.batch.tpl
            )
            gathered = gathered._replace(
                valid=gathered.valid & (jnp.arange(pad) < len(t_idx))
            )
            t0 = time.monotonic()
            vec = self._run_preempt_kernel(snap, gathered, prios)
            dt = time.monotonic() - t0
            metrics.inc("scheduler_preemption_batches_total")
            metrics.observe("scheduler_preemption_select_duration_seconds", dt)
            metrics.set_gauge(
                "scheduler_preemption_last_select_ms", round(dt * 1e3, 3)
            )
            reason = validate_preempt_outputs(
                vec["node"], vec["victims"], n_rows, cand=vec["cand"]
            )
            if reason:
                metrics.inc(
                    "scheduler_preemption_guard_trips_total",
                    {"reason": reason},
                )
                logger.error(
                    "preemption kernel output guard tripped (%s): victim "
                    "selection for this batch degrades to the host walk",
                    reason,
                )
                return None
            vec["group_of"] = groups
            vec["row_names"] = vec_row_names
            return vec
        except Exception:
            logger.exception(
                "vectorized victim selection failed; host walk"
            )
            metrics.inc(
                "scheduler_preemption_fallback_total",
                {"reason": "kernel_error"},
            )
            return None

    def _preempt_whatif_tpl(self, eb, failed: List, pod_tpl: np.ndarray):
        """[TPL, N] optimistic preemption mask for the batch's templates
        (ops/lattice.preempt_whatif), or None when unavailable."""
        try:
            from ..ops.lattice import preempt_whatif

            prios = np.zeros(eb.batch.tpl.valid.shape[0], np.int32)
            pod_prio = eb.pod_prio_np
            for pi, i in failed:
                t = int(pod_tpl[i])
                prios[t] = max(prios[t], int(pod_prio[i]))
            with self.cache.lock:
                # _finish_batch drains the pipeline before the failed
                # block, so no newer batch can be in flight here and flush's
                # scatter cannot erase un-replayed device commits
                assert not self._pending
                snap = self.cache.encoder.flush()
            return np.asarray(preempt_whatif(snap, eb.batch.tpl, prios))
        except Exception:
            logger.exception("preempt what-if kernel failed; using resolvable only")
            return None

    def _assume_and_bind_bulk(
        self, to_bind: List, t_start: float, device_synced: bool = False,
        trailing_gate=None,
    ) -> None:
        """Assume + bind a whole wave of placements ((pi, node, band,
        proto) tuples; proto may be None for host-path placements). When
        the profile has no permit/prebind/postbind plugins and the binder
        is the default, the binds collapse into one batch API call (the
        in-cycle fast path — async per-pod binding remains for
        plugin-bearing profiles, matching the reference's
        goroutine-per-bind at scheduler.go:666)."""
        if not to_bind:
            return
        t_a0 = time.monotonic()
        # ONE lock acquisition + vectorized encoder scatters for the whole
        # wave (device_synced path); the host fallback path still assumes
        # per pod through the same cache method semantics
        if device_synced:
            errors = self.cache.assume_pods_bulk(
                [(pi.pod, node_name, band, proto)
                 for pi, node_name, band, proto in to_bind]
            )
        else:
            errors = []
            for pi, node_name, band, proto in to_bind:
                try:
                    self.cache.assume_pod(
                        pi.pod,
                        node_name,
                        device_synced=False,
                        prio_band=band,
                        proto=proto,
                    )
                    errors.append(None)
                except ValueError as e:
                    errors.append(str(e))
        tracer.add_span_many(
            [pi.trace_id
             for (pi, _n, _b, _p), err in zip(to_bind, errors)
             if err is None],
            "assume", t_a0, time.monotonic(),
        )
        if trailing_gate is not None and trailing_gate():
            # split-phase last-look: between assume and bind the trailing
            # bulk payload (ours or an elder sibling's on the same
            # snapshot chain) arrived and failed validation. The binds
            # have NOT left the process — revert every assume and requeue
            # instead of issuing bindings off a condemned fast payload.
            for (pi, _node, _band, _proto), err in zip(to_bind, errors):
                if err is not None:
                    self._handle_failure(
                        pi, self.queue.moves_snapshot(),
                        message=err, error=True,
                    )
                    continue
                try:
                    self.cache.forget_pod(pi.pod)
                except Exception:
                    logger.exception("trailing gate unwind forget failed")
                metrics.inc(COUNTER_WAVE_TRAILING_UNWOUND)
                tracer.event(pi.trace_id, "wave.trailing_unwound")
                self.queue.requeue_backoff(pi)
            return
        simple: List = []
        for (pi, node_name, band, proto), err in zip(to_bind, errors):
            pod = pi.pod
            if err is not None:
                if device_synced:
                    # the kernel already committed this placement on-device;
                    # with no host replay the row must be re-uploaded
                    with self.cache.lock:
                        self.cache.encoder.mark_row_dirty(node_name)
                self._handle_failure(
                    pi, self.queue.moves_snapshot(), message=err, error=True
                )
                continue
            prof = self.profiles.for_pod(pod)
            ps = prof.framework.plugin_set
            plain = (
                self.cfg.sync_batch_bind
                and not ps.reserve
                and not ps.permit
                and not ps.pre_bind
                and not ps.post_bind
                and ps.bind == ["DefaultBinder"]
            )
            self.queue.delete_nominated_if_exists(pod)
            if plain:
                simple.append((pi, node_name, prof))
            else:
                self._assume_and_bind_after_assume(pi, node_name, t_start)
        if not simple:
            return
        bindings = [
            Binding(
                pod_name=pi.pod.metadata.name,
                pod_namespace=pi.pod.metadata.namespace,
                pod_uid=pi.pod.metadata.uid,
                target_node=node_name,
            )
            for pi, node_name, _ in simple
        ]
        b0 = time.monotonic()
        try:
            errors = self._bind_pods_fenced(bindings)
        except DegradedWrites as e:
            # in-process store: the gate refused before applying anything
            # (Degraded — safe to replay) or the whole batch applied but
            # missed its quorum ack (QuorumLost — outcome unknown). Either
            # way the wave is NOT failed: park every placement.
            errors = [e] * len(bindings)
        except LeaderFenced:
            # zombie ex-leader: the store holds a newer leadership grant.
            # Nothing applied — drop every placement and stand down.
            self._on_fenced_binds([pi for pi, _n, _p in simple])
            return
        t_b1 = time.monotonic()
        bind_dur = t_b1 - b0
        e2e = t_b1 - t_start
        tracer.add_span_many(
            [pi.trace_id
             for (pi, _n, _p), err in zip(simple, errors)
             if err is None],
            "bind", b0, t_b1,
        )
        to_buffer: List[PendingBind] = []
        for (pi, node_name, prof), err in zip(simple, errors):
            if err is None:
                metrics.observe("binding_duration_seconds", bind_dur)
                # exemplar: the tail samples carry the trace id, so the
                # histogram's p99 resolves to this pod's full waterfall
                metrics.observe(
                    "e2e_scheduling_duration_seconds", e2e,
                    exemplar=pi.trace_id or None,
                )
                # queue-entry → bound, incl. queue wait (reference
                # pod_scheduling_duration_seconds, metrics.go:51-231) — the
                # honest per-pod number the latency bench reports
                self._record_bound(pi, node_name, prof)
            elif isinstance(err, DegradedWrites):
                # retryable store refusal (incl. QuorumLost, where THIS
                # bind applied but wasn't acked — the reconciler's
                # read-back discriminates): the pod stays assumed — its
                # assume TTL is unarmed, so the reservation holds for
                # the whole outage
                to_buffer.append(PendingBind(pi, node_name, prof))
            else:
                self.cache.forget_pod(pi.pod)
                self._handle_failure(
                    pi, self.queue.moves_snapshot(), message=str(err), error=True
                )
        if to_buffer:
            self._buffer_pending_binds(to_buffer)

    def _assume_and_bind_after_assume(
        self, pi: QueuedPodInfo, node_name: str, t_start: float
    ) -> None:
        """Plugin-bearing profile: run reserve/permit then async bind (the
        pod is already assumed)."""
        t_a0 = time.monotonic()
        pod = pi.pod
        prof = self.profiles.for_pod(pod)
        fw = prof.framework
        state = CycleState()
        st = fw.run_reserve_plugins(state, pod, node_name)
        if not is_success(st):
            self.cache.forget_pod(pod)
            self._handle_failure(pi, self.queue.moves_snapshot(), message=st.message, error=True)
            return
        st = fw.run_permit_plugins(state, pod, node_name)
        if st is not None and st.code not in (Code.SUCCESS, Code.WAIT):
            self.cache.forget_pod(pod)
            fw.run_unreserve_plugins(state, pod, node_name)
            self._handle_failure(pi, self.queue.moves_snapshot(), message=st.message)
            return
        self._stamp_bind_submit(pi, t_a0)
        try:
            self._bind_pool.submit(
                self._bind_async, pi, node_name, state, t_start
            )
        except RuntimeError:
            # pool shut down mid-cycle (stop racing a final batch): unwind
            # like a failed bind so the reservation doesn't leak
            self.cache.forget_pod(pod)
            fw.run_unreserve_plugins(state, pod, node_name)
            self._handle_failure(
                pi, self.queue.moves_snapshot(), message="scheduler shutting down"
            )

    # -- host fallback path ---------------------------------------------------

    def _schedule_one_host(self, pi: QueuedPodInfo, moves0: int) -> None:
        t0 = time.monotonic()
        pod = pi.pod
        prof = self.profiles.for_pod(pod)
        algo = self._algo[prof.name]
        # fresh snapshot per cycle so earlier assumes in this batch are seen
        # (scheduleOne snapshots per pod, generic_scheduler.go:142)
        self._snapshot = self.cache.update_snapshot()
        state = CycleState()
        try:
            result = algo.schedule(
                pod, self._snapshot, state, self._nominated_pods_for_node
            )
        except FitError as fe:
            metrics.observe("scheduling_algorithm_duration_seconds", time.monotonic() - t0)
            self._handle_failure(pi, moves0, message=str(fe), fit_error=fe)
            return
        except Exception as e:
            # cycle error (e.g. required extender unreachable): backoff and
            # retry without attempting preemption
            metrics.observe("scheduling_algorithm_duration_seconds", time.monotonic() - t0)
            self._handle_failure(pi, moves0, message=str(e), error=True)
            return
        metrics.observe("scheduling_algorithm_duration_seconds", time.monotonic() - t0)
        # the span starts at cycle ENTRY (t0), not at algo.schedule: the
        # per-cycle snapshot clone is real latency and must be attributed
        tracer.add_span(pi.trace_id, "algo", t0, time.monotonic())
        self._assume_and_bind(pi, result.suggested_host, t0)

    def _nominated_pods_for_node(self, node_name: str) -> List[v1.Pod]:
        keys = self.queue.nominated_pods_for_node(node_name)
        out = []
        pods_informer = self.informer_factory.informer("pods")
        for k in keys:
            p = pods_informer.get(k)
            if p is not None:
                out.append(p)
        return out

    # -- assume + bind --------------------------------------------------------

    def _pod_has_pvcs(self, pod: v1.Pod) -> bool:
        return any(vol.persistent_volume_claim for vol in pod.spec.volumes)

    def _assume_volumes(self, pi: QueuedPodInfo, node_name: str) -> bool:
        """VolumeBinder.AssumePodVolumes before Reserve (scheduler.go:615).
        Returns False (after recording the failure) when no volume plan
        exists for the chosen node."""
        pod = pi.pod
        if not self._pod_has_pvcs(pod):
            return True
        if self._snapshot is None:
            self._snapshot = self.cache.update_snapshot()
        ni = self._snapshot.get(node_name)
        if ni is None:
            return True
        try:
            self.volume_binder.assume_pod_volumes(pod, ni.node)
        except Exception as e:
            self._handle_failure(pi, self.queue.moves_snapshot(), message=str(e), error=True)
            return False
        return True

    def _stamp_bind_submit(self, pi: QueuedPodInfo, t_a0: float) -> None:
        """Close the per-pod `assume` span (reserve/assume/permit work on
        the scheduling thread) and stamp the bind-pool hand-off moment:
        _bind_async starts its `bind` span there, so pool queue wait is
        attributed to `bind` instead of vanishing into a span hole."""
        now = time.monotonic()
        tracer.add_span(pi.trace_id, "assume", t_a0, now)
        pi._bind_submitted_at = now

    def _assume_and_bind(self, pi: QueuedPodInfo, node_name: str, t_start: float) -> None:
        t_a0 = time.monotonic()
        pod = pi.pod
        prof = self.profiles.for_pod(pod)
        fw = prof.framework
        state = CycleState()
        if not self._assume_volumes(pi, node_name):
            return
        st = fw.run_reserve_plugins(state, pod, node_name)
        if not is_success(st):
            self.volume_binder.forget_pod_volumes(pod)
            self._handle_failure(pi, self.queue.moves_snapshot(), message=st.message, error=True)
            return
        try:
            self.cache.assume_pod(pod, node_name)
        except ValueError as e:
            self.volume_binder.forget_pod_volumes(pod)
            self._handle_failure(pi, self.queue.moves_snapshot(), message=str(e), error=True)
            return
        self.queue.delete_nominated_if_exists(pod)
        st = fw.run_permit_plugins(state, pod, node_name)
        if st is not None and st.code not in (Code.SUCCESS, Code.WAIT):
            self.cache.forget_pod(pod)
            self.volume_binder.forget_pod_volumes(pod)
            fw.run_unreserve_plugins(state, pod, node_name)
            self._handle_failure(pi, self.queue.moves_snapshot(), message=st.message)
            return
        self._stamp_bind_submit(pi, t_a0)
        try:
            self._bind_pool.submit(
                self._bind_async, pi, node_name, state, t_start
            )
        except RuntimeError:
            # pool shut down mid-cycle (stop racing a final batch): unwind
            # like a failed bind so the reservation doesn't leak
            self.cache.forget_pod(pod)
            fw.run_unreserve_plugins(state, pod, node_name)
            self._handle_failure(
                pi, self.queue.moves_snapshot(), message="scheduler shutting down"
            )

    def _bind_async(self, pi: QueuedPodInfo, node_name: str, state, t_start) -> None:
        """binding cycle (async goroutine at scheduler.go:666)."""
        pod = pi.pod
        prof = self.profiles.for_pod(pod)
        fw = prof.framework
        b0 = time.monotonic()
        # span start: the hand-off stamp (pool queue wait belongs to the
        # bind stage); the binding_duration metric keeps b0 semantics
        t_span0 = getattr(pi, "_bind_submitted_at", None) or b0
        try:
            st = fw.wait_on_permit(pod)
            if not is_success(st):
                raise RuntimeError(f"permit: {st.message}")
            # bindVolumes before PreBind (scheduler.go:454,704)
            if self._pod_has_pvcs(pod):
                self.volume_binder.bind_pod_volumes(pod, node_name)
            st = fw.run_pre_bind_plugins(state, pod, node_name)
            if not is_success(st):
                raise RuntimeError(f"prebind: {st.message}")
            # extendersBinding (scheduler.go:496,517): first interested
            # binder extender wins; else in-tree bind plugins
            ext_binder = next(
                (
                    e
                    for e in self.extenders
                    if e.is_binder() and e.is_interested(pod)
                ),
                None,
            )
            if ext_binder is not None:
                # an extender binds out of process — the store can't
                # validate the fence atomically, so pre-check the lease
                # right before handing the pod over (best-effort: the
                # in-tree paths stay store-fenced)
                self._check_fence_live()
                ext_binder.bind(pod, node_name)
            else:
                # DefaultBinder binds through the _FencedBindSurface in
                # the framework context: the write funnels into
                # _bind_pods_fenced and carries the leadership fence
                st = fw.run_bind_plugins(state, pod, node_name)
                if not is_success(st):
                    raise RuntimeError(f"bind: {st.message}")
            self.cache.finish_binding(pod)
            fw.run_post_bind_plugins(state, pod, node_name)
            t_done = time.monotonic()
            tracer.add_span(pi.trace_id, "bind", t_span0, t_done)
            metrics.observe("binding_duration_seconds", t_done - b0)
            metrics.observe(
                "e2e_scheduling_duration_seconds", t_done - t_start,
                exemplar=pi.trace_id or None,
            )
            metrics.observe(
                "pod_scheduling_duration_seconds",
                t_done - pi.initial_attempt_timestamp,
                exemplar=pi.trace_id or None,
            )
            metrics.inc("schedule_attempts_total", {"result": "scheduled"})
            tracer.finish(pi.trace_id, outcome="bound", node=node_name)
            prof.recorder.eventf(
                pod, "Normal", "Scheduled", "Binding",
                f"Successfully assigned {pod.metadata.key} to {node_name}",
            )
        except LeaderFenced:
            # deposed mid-async-bind: the new leader owns this pod now.
            # Unreserve and drop the placement — never requeue or retry
            # (racing the new leader is exactly what the fence forbids).
            self.volume_binder.forget_pod_volumes(pod)
            fw.run_unreserve_plugins(state, pod, node_name)
            self._on_fenced_binds([pi])
        except DegradedWrites as e:
            if not self._pod_has_pvcs(pod):
                # retryable store refusal mid-async-bind: park the
                # placement (the pod stays assumed/reserved) instead of
                # failing it — the reconciler finishes or unwinds it when
                # writes reopen. PVC pods fall through to the generic
                # unwind: their volume-bind writes may be half-applied
                # and need a full fresh cycle.
                self._buffer_pending_binds([PendingBind(pi, node_name, prof)])
                return
            self.cache.forget_pod(pod)
            self.volume_binder.forget_pod_volumes(pod)
            fw.run_unreserve_plugins(state, pod, node_name)
            self._handle_failure(pi, self.queue.moves_snapshot(), message=str(e), error=True)
        except Exception as e:
            self.cache.forget_pod(pod)
            self.volume_binder.forget_pod_volumes(pod)
            fw.run_unreserve_plugins(state, pod, node_name)
            self._handle_failure(pi, self.queue.moves_snapshot(), message=str(e), error=True)

    # -- failure path ---------------------------------------------------------

    def _handle_failure(
        self,
        pi: QueuedPodInfo,
        moves0: int,
        message: str = "",
        fit_error: Optional[FitError] = None,
        candidate_nodes: Optional[List[str]] = None,
        error: bool = False,
        skip_preemption: bool = False,
        vector_choice: Optional[List[str]] = None,
        verify_full: bool = False,
    ) -> str:
        """Returns the nominated node name when a preemption was
        performed (cluster mutated), else '' — callers that only care
        whether the cluster changed use it as a bool; _finish_batch's
        fan-out also needs WHICH node to mark targeted."""
        pod = pi.pod
        prof = self.profiles.for_pod(pod)
        tracer.event(
            pi.trace_id, "error" if error else "unschedulable", message
        )
        metrics.inc(
            "schedule_attempts_total",
            {"result": "error" if error else "unschedulable"},
        )
        prof.recorder.eventf(
            pod, "Warning", "FailedScheduling", "Scheduling", message
        )
        # permit plugins may hold siblings of this pod parked (gang quorum);
        # tell them the member failed so reservations release promptly
        for name in prof.framework.plugin_set.permit:
            hook = getattr(
                prof.framework.plugin(name), "handle_scheduling_failure", None
            )
            if hook is not None:
                try:
                    hook(pod)
                except Exception:
                    logger.exception("permit failure hook %s", name)
        self._set_pod_unschedulable_condition(pod, message)
        preempted = ""
        if not error and not self.cfg.disable_preemption and not skip_preemption:
            try:
                preempted = self._attempt_preemption(
                    pod, prof, fit_error, candidate_nodes,
                    vector_choice=vector_choice,
                    verify_full=verify_full,
                )
            except (DegradedWrites, NotPrimary):
                # degraded store: victim deletes / nominations can't land;
                # the pod requeues and preemption retries after recovery —
                # the skip stamps the pod's OWN trace id so a preemption-
                # delayed pod's waterfall shows where the time went
                tracer.event(pi.trace_id, "preempt.degraded_skip")
                metrics.inc(
                    "scheduler_degraded_write_skips_total",
                    {"write": "preemption"},
                )
        self.queue.add_unschedulable_if_not_present(pi, moves0)
        return preempted

    def _set_pod_unschedulable_condition(self, pod: v1.Pod, message: str) -> None:
        def mutate(p):
            for c in p.status.conditions:
                if c.type == v1.COND_POD_SCHEDULED:
                    if (
                        c.status == "False"
                        and c.reason == "Unschedulable"
                        and c.message == message
                    ):
                        # no-op write suppression (the reference's
                        # podutil.UpdatePodCondition returns false on an
                        # identical condition and the caller skips the
                        # PATCH): in an unschedulable storm every re-failed
                        # pod would otherwise rewrite the same condition —
                        # an API write + watch fan-out per pod per cycle
                        return None
                    c.status = "False"
                    c.reason = "Unschedulable"
                    c.message = message
                    return p
            p.status.conditions.append(
                v1.PodCondition(
                    type=v1.COND_POD_SCHEDULED,
                    status="False",
                    reason="Unschedulable",
                    message=message,
                )
            )
            return p

        try:
            self.server.guaranteed_update(
                "pods", pod.metadata.namespace, pod.metadata.name, mutate
            )
        except NotFound:
            pass
        except (DegradedWrites, NotPrimary):
            # best-effort status write: while the store is read-only the
            # condition is skipped, not retried — failing the failure
            # handler here would turn one outage into a requeue storm
            metrics.inc(
                "scheduler_degraded_write_skips_total", {"write": "condition"}
            )

    def _preempt_choice_cooptimal(
        self, victims: List, ovictims: List
    ) -> bool:
        """Documented tie-break check for the sampled differential
        oracle: the vector engine's choice counts as AGREEING with the
        full host walk when the two exact victim sets tie on
        pickOneNodeForPreemption criteria 1-4 (PDB violations, max
        victim priority, priority sum, victim count) — the engine breaks
        such ties by row order where the oracle uses start time / name
        order, and the band-prefix ranking may legitimately land on a
        co-optimal node. Anything beyond that is a real divergence."""
        from .preemption import filter_pods_with_pdb_violation

        pdbs = list(self._list_pdbs()) if self._list_pdbs else []

        def key(vs):
            violating, _ = filter_pods_with_pdb_violation(list(vs), pdbs)
            return (
                len(violating),
                max((v.priority for v in vs), default=-(2 ** 31)),
                sum(v.priority for v in vs),
                len(vs),
            )

        return key(victims) == key(ovictims)

    def _attempt_preemption(
        self,
        pod,
        prof,
        fit_error,
        candidate_nodes: Optional[List[str]],
        vector_choice: Optional[List[str]] = None,
        verify_full: bool = False,
    ) -> str:
        """sched.preempt (scheduler.go:392): find victims, delete them, set
        NominatedNodeName. Returns the nominated node ('' if none).

        vector_choice = the batched kernel pass's ranked candidate node
        names (ops/preemptlattice top-K): the host oracle then runs its
        EXACT selection (filters + reprieve + PDB countdown + the full
        5-criterion node pick) on those K nodes instead of walking every
        candidate — a fully-rejected candidate set is a counted
        disagreement that falls back to the full walk, so a kernel
        ranking error costs time, never a wrong eviction. verify_full
        additionally runs the full walk and compares (the sampled
        differential oracle); on divergence beyond the documented
        tie-breaks the oracle's answer wins."""
        if self._snapshot is None:
            self._snapshot = self.cache.update_snapshot()
        preemptor = self._preemptors[prof.name]
        tid = tracer.trace_for_pod(pod.metadata.key)
        node, victims = "", []
        with tracer.span(tid, "preempt.select"):
            if vector_choice is not None:
                node, victims = preemptor.preempt(
                    pod, self._snapshot, fit_error, vector_choice
                )
                if node:
                    metrics.inc("scheduler_preemption_vector_hits_total")
                else:
                    # the exact oracle rejected the kernel's ranked
                    # winner (reprieve/PDB refinement, or a seeded
                    # disagreement in tests): host walk, zero evictions
                    # from the rejected proposal
                    metrics.inc(
                        "scheduler_preemption_fallback_total",
                        {"reason": "oracle_reject"},
                    )
            if verify_full or not node:
                # candidate_nodes semantics: None = unknown (scan per
                # fit_error / all nodes); a list — possibly empty — is the
                # device pass's narrowed candidate set and is
                # authoritative (empty = hopeless). The VERIFY walk (node
                # already accepted) must see the same universe the engine
                # drew from — candidate_nodes was intersected with the
                # wave-launch resolvable mask, so a node the wave's own
                # binds just filled can be in vector_choice but not
                # candidates; comparing across different universes would
                # count a legitimate pick as a divergence and discard it
                verify_nodes = candidate_nodes
                if node and candidate_nodes is not None:
                    verify_nodes = sorted(
                        set(candidate_nodes) | set(vector_choice or [])
                    )
                onode, ovictims = preemptor.preempt(
                    pod, self._snapshot, fit_error, verify_nodes
                )
                if not node:
                    node, victims = onode, ovictims
                elif onode != node or (
                    {v.metadata.key for v in ovictims}
                    != {v.metadata.key for v in victims}
                ):
                    if not onode or not self._preempt_choice_cooptimal(
                        victims, ovictims
                    ):
                        metrics.inc(
                            "scheduler_preemption_oracle_divergence_total"
                        )
                        logger.warning(
                            "vector preemption diverged from the host "
                            "oracle for %s (vector %s, oracle %s): using "
                            "the oracle's answer",
                            pod.metadata.key, node, onode or "<none>",
                        )
                        node, victims = onode, ovictims
        if not node:
            return ""
        # zombie-fence pre-check (the PR-10 _check_fence_live seam):
        # victim deletes are plain store writes with no atomic fence
        # validation, so a superseded leader re-reads the lease before
        # evicting — the new leader's scheduler owns preemption now
        try:
            self._check_fence_live()
        except LeaderFenced:
            metrics.inc("scheduler_preemption_fenced_total")
            return ""
        with tracer.span(tid, "preempt.delete", victims=len(victims)):
            for victim in victims:
                if (
                    self.eviction_budget is not None
                    and not self.eviction_budget.try_acquire(actor="preemption")
                ):
                    # shared eviction budget dry: abort the attempt — the
                    # preemptor pod stays pending and retries; pressing on
                    # would let a preemption storm ride over the cluster's
                    # configured eviction rate alongside nodelifecycle and
                    # descheduler spends
                    metrics.inc("scheduler_preemption_budget_deferred_total")
                    return ""
                try:
                    self.server.delete(
                        "pods", victim.metadata.namespace, victim.metadata.name
                    )
                    prof.recorder.eventf(
                        victim, "Normal", "Preempted", "Preempting",
                        f"by {pod.metadata.key} on node {node}",
                    )
                    metrics.inc("preemption_victims_total")
                except NotFound:
                    pass
                except (DegradedWrites, NotPrimary):
                    # read-only store: abort the attempt (counted skip, the
                    # PR-3 discipline) — the preemptor pod stays pending and
                    # retries once writes reopen; pressing on would nominate
                    # a node whose victims were never actually evicted
                    metrics.inc(
                        "scheduler_degraded_write_skips_total",
                        {"write": "preempt_delete"},
                    )
                    return ""
        metrics.inc("preemption_attempts_total")

        def mutate(p):
            p.status.nominated_node_name = node
            return p

        with tracer.span(tid, "preempt.nominate"):
            try:
                self.server.guaranteed_update(
                    "pods", pod.metadata.namespace, pod.metadata.name, mutate
                )
            except NotFound:
                return node
            except (DegradedWrites, NotPrimary):
                metrics.inc(
                    "scheduler_degraded_write_skips_total",
                    {"write": "nominate"},
                )
                return node  # victims are gone; nomination is best-effort
            self.queue.add_nominated_pod(pod, node)
        return node
