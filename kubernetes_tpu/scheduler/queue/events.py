"""Queue-flush event names (reference internal/queue/events.go:20-72).

Each cluster change that could make an unschedulable pod schedulable moves
pods out of unschedulableQ (MoveAllToActiveOrBackoffQueue). In the TPU build
the same events also mark the device snapshot dirty (the encoder delta)."""

ASSIGNED_POD_ADD = "AssignedPodAdd"
ASSIGNED_POD_UPDATE = "AssignedPodUpdate"
ASSIGNED_POD_DELETE = "AssignedPodDelete"
NODE_ADD = "NodeAdd"
NODE_SPEC_UNSCHEDULABLE_CHANGE = "NodeSpecUnschedulableChange"
NODE_ALLOCATABLE_CHANGE = "NodeAllocatableChange"
NODE_LABEL_CHANGE = "NodeLabelChange"
NODE_TAINT_CHANGE = "NodeTaintChange"
NODE_CONDITION_CHANGE = "NodeConditionChange"
PV_ADD = "PvAdd"
PV_UPDATE = "PvUpdate"
PVC_ADD = "PvcAdd"
PVC_UPDATE = "PvcUpdate"
SERVICE_ADD = "ServiceAdd"
SERVICE_UPDATE = "ServiceUpdate"
SERVICE_DELETE = "ServiceDelete"
STORAGE_CLASS_ADD = "StorageClassAdd"
CSI_NODE_ADD = "CSINodeAdd"
CSI_NODE_UPDATE = "CSINodeUpdate"
NODE_DELETE = "NodeDelete"
UNSCHEDULABLE_TIMEOUT = "UnschedulableTimeout"
