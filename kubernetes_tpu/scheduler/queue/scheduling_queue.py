"""PriorityQueue: the three-part scheduling queue.

Reference pkg/scheduler/internal/queue/scheduling_queue.go:117-152:
  * activeQ     — heap ordered by the QueueSort plugin (priority desc, FIFO)
  * podBackoffQ — heap by backoff expiry; backoff 1s→10s doubling (:643)
  * unschedulableQ — map, flushed by events (MoveAllToActiveOrBackoffQueue
    :494) or after 60s (flushUnschedulableQLeftover)
plus the nominated-pods map for preemption.

TPU addition: `pop_batch(max_n, window)` pops up to a device batch of pods in
one call (the batch former of SURVEY.md §7 stage 4) — the reference pops one
pod per cycle; the device path amortizes one kernel launch over the batch.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ...api import objects as v1
from ...testing.lockgraph import named_lock, track_attrs
from ...utils.tracing import tracer
from .heap import Heap


@dataclass
class QueuedPodInfo:
    pod: v1.Pod
    timestamp: float = field(default_factory=time.monotonic)
    attempts: int = 0
    initial_attempt_timestamp: float = field(default_factory=time.monotonic)
    backoff_expiry: float = 0.0
    # minted at queue admission (utils/tracing.py): the id every span of
    # this pod's lifecycle — and its cross-process bind stamp — lands under
    trace_id: str = ""
    # when this pod LAST entered a queue, for the `queue` span only:
    # readd() must refresh it without touching `timestamp` (which orders
    # the heap — resetting it would demote a deferred pod behind fresh
    # arrivals), or a deferred pod's next queue span re-spans from its
    # original admission and double-counts the prior cycle as queue wait
    trace_queued_at: float = field(default_factory=time.monotonic)

    @property
    def key(self) -> str:
        return self.pod.metadata.key


class PriorityQueue:
    def __init__(
        self,
        less: Optional[Callable[[QueuedPodInfo, QueuedPodInfo], bool]] = None,
        pod_initial_backoff: float = 1.0,
        pod_max_backoff: float = 10.0,
        unschedulable_timeout: float = 60.0,
    ):
        # named for the lock-order watchdog + lockset sanitizer
        # (testing/lockgraph.py); _cond shares the SAME lock, so both
        # spellings record as "scheduler.queue"
        self._lock = named_lock("scheduler.queue")
        self._cond = threading.Condition(self._lock)
        if less is None:
            less = lambda a, b: (
                (a.pod.priority, -a.timestamp) > (b.pod.priority, -b.timestamp)
            )
        self._active = Heap(lambda pi: pi.key, less)
        self._backoff = Heap(
            lambda pi: pi.key, lambda a, b: a.backoff_expiry < b.backoff_expiry
        )
        self._unschedulable: Dict[str, QueuedPodInfo] = {}
        self._initial_backoff = pod_initial_backoff
        self._max_backoff = pod_max_backoff
        self._unsched_timeout = unschedulable_timeout
        self._nominated: Dict[str, str] = {}  # pod key -> node name
        self._nominated_by_node: Dict[str, set] = {}
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self.moves = 0  # MoveAllToActiveOrBackoffQueue invocations (metrics)

    # -- lifecycle ----------------------------------------------------------

    def run(self) -> None:
        """Start flush loops (scheduling_queue.go:234: backoff every 1s,
        unschedulable leftover every 30s)."""
        for period, fn in ((1.0, self.flush_backoff_completed), (30.0, self._flush_unschedulable_leftover)):
            t = threading.Thread(
                target=self._loop, args=(period, fn), daemon=True
            )
            t.start()
            self._threads.append(t)

    def _loop(self, period: float, fn) -> None:
        while not self._stop.wait(period):
            fn()

    def close(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()

    # -- adds ---------------------------------------------------------------

    def add(self, pod: v1.Pod) -> None:
        # mint the trace OUTSIDE the queue lock (tracing.ring is a leaf,
        # but the admit itself needs nothing the lock guards).
        # admit_lag_s: object creation (wall) -> queue admit — the
        # store->watch->cacher->informer delivery leg, recorded as an
        # ATTRIBUTE (wall-clock delta), never mixed into monotonic spans
        tid = tracer.start(
            "pod",
            pod.metadata.key,
            admit_lag_s=round(
                max(time.time() - pod.metadata.creation_timestamp, 0.0), 6
            ),
        )
        with self._cond:
            pi = QueuedPodInfo(pod, trace_id=tid)
            self._active.add(pi)
            self._backoff.delete_by_key(pi.key)
            self._unschedulable.pop(pi.key, None)
            self._cond.notify()

    def readd(self, pi: QueuedPodInfo) -> None:
        """Return a popped-but-unprocessed pod to activeQ preserving its
        QueuedPodInfo (used for wave-deferred pods: feasible nodes existed
        but in-batch contention ran out of waves — not a scheduling failure,
        so no backoff and no attempt decay)."""
        with self._cond:
            pi.attempts = max(pi.attempts - 1, 0)
            pi.trace_queued_at = time.monotonic()
            self._active.add(pi)
            self._cond.notify()

    def add_unschedulable_if_not_present(
        self, pi: QueuedPodInfo, moves_at_failure: int
    ) -> None:
        """Failed pod re-entry (AddUnschedulableIfNotPresent:300): if a move
        event fired while the pod was being scheduled, it goes to backoffQ
        (something changed — retry soon); else unschedulableQ."""
        tracer.event(pi.trace_id, "queue.unschedulable")
        with self._cond:
            key = pi.key
            if key in self._active or key in self._backoff or key in self._unschedulable:
                return
            pi.timestamp = time.monotonic()
            pi.trace_queued_at = pi.timestamp
            if self.moves != moves_at_failure:
                pi.backoff_expiry = self._backoff_time(pi)
                self._backoff.add(pi)
            else:
                self._unschedulable[key] = pi

    def _backoff_time(self, pi: QueuedPodInfo) -> float:
        """Backoff expiry relative to the pod's LAST FAILURE (pi.timestamp
        — every failure path stamps it), not to "now". The reference's
        podBackoffQ keys expiry on lastFailure + backoffDuration
        (scheduling_queue.go isPodBackingoff): a move event must flush a
        pod whose backoff already elapsed straight to activeQ. The old
        now-relative form re-armed the full backoff on every
        MoveAllToActiveOrBackoffQueue, so a pod that had sat in
        unschedulableQ for minutes still waited out a fresh 1-10 s after
        the node-add that could place it — breaking the autoscaler's
        "pending pods bind within one period" guarantee."""
        d = self._initial_backoff * (2 ** max(pi.attempts - 1, 0))
        return pi.timestamp + min(d, self._max_backoff)

    def requeue_backoff(self, pi: QueuedPodInfo) -> None:
        """Re-queue a RETRYABLE pod through backoffQ (not unschedulableQ):
        it was feasible but lost a structural contention (e.g. an
        all-deferred hard-spread batch) — an immediate readd would hot-loop
        the identical conflict, and unschedulableQ would mislabel it (and
        sit out the flush interval). Backoff retries in 1-10 s."""
        tracer.event(pi.trace_id, "queue.backoff")
        with self._cond:
            if (
                pi.key in self._active
                or pi.key in self._backoff
                or pi.key in self._unschedulable
            ):
                return
            pi.timestamp = time.monotonic()
            pi.trace_queued_at = pi.timestamp
            pi.backoff_expiry = self._backoff_time(pi)
            self._backoff.add(pi)

    # -- pops ---------------------------------------------------------------

    def pop(
        self, timeout: Optional[float] = None, on_pop=None
    ) -> Optional[QueuedPodInfo]:
        """on_pop: invoked UNDER the queue lock before the first item is
        removed — the scheduler marks itself busy there, so no observer
        can ever see "queue empty and scheduler not busy" between a pop
        and the popped batch entering the in-flight pipeline."""
        with self._cond:
            deadline = None if timeout is None else time.monotonic() + timeout
            while len(self._active) == 0 and not self._stop.is_set():
                rem = None if deadline is None else deadline - time.monotonic()
                if rem is not None and rem <= 0:
                    return None
                self._cond.wait(rem if rem is None or rem < 0.1 else 0.1)
            if self._stop.is_set():
                return None
            if on_pop is not None:
                on_pop()
            pi = self._active.pop()
            if pi is not None:
                pi.attempts += 1
            return pi

    def pop_batch(
        self,
        max_n: int,
        timeout: Optional[float] = None,
        window: float = 0.0,
        on_first=None,
    ) -> List[QueuedPodInfo]:
        """Pop up to max_n pods: block for the first, then drain without
        blocking (optionally lingering up to `window` seconds to let a burst
        accumulate — the gang/batch former).

        The linger is ADAPTIVE (r4 verdict #4): it holds only while the
        producer is actively producing. Once no new pod has arrived for
        `idle_gap` the batch ships immediately — a lone low-load pod pays
        ~3 ms of former latency instead of the full window, while a burst
        mid-arrival keeps accumulating up to `window`."""
        idle_gap = min(0.003, window) if window > 0 else 0.0
        first = self.pop(timeout, on_pop=on_first)
        if first is None:
            return []
        out = [first]
        deadline = time.monotonic() + window
        last_arrival = time.monotonic()
        while len(out) < max_n:
            with self._cond:
                pi = self._active.pop()
                if pi is not None:
                    pi.attempts += 1
                    out.append(pi)
                    last_arrival = time.monotonic()
                    continue
            now = time.monotonic()
            if window > 0 and now < deadline and now - last_arrival < idle_gap:
                time.sleep(0.0005)
                continue
            break
        return out

    # -- event-driven movement ----------------------------------------------

    def move_all_to_active_or_backoff(self, event: str) -> None:
        """(scheduling_queue.go:494) — every unschedulable pod re-enters
        either backoffQ (still backing off) or activeQ."""
        with self._cond:
            self.moves += 1
            now = time.monotonic()
            for key, pi in list(self._unschedulable.items()):
                expiry = self._backoff_time(pi)
                if expiry > now:
                    pi.backoff_expiry = expiry
                    self._backoff.add(pi)
                else:
                    self._active.add(pi)
                del self._unschedulable[key]
            self._cond.notify_all()

    def flush_backoff_completed(self) -> None:
        with self._cond:
            now = time.monotonic()
            while True:
                pi = self._backoff.peek()
                if pi is None or pi.backoff_expiry > now:
                    break
                self._backoff.pop()
                self._active.add(pi)
                self._cond.notify()

    def _flush_unschedulable_leftover(self) -> None:
        with self._cond:
            now = time.monotonic()
            moved = False
            for key, pi in list(self._unschedulable.items()):
                if now - pi.timestamp > self._unsched_timeout:
                    del self._unschedulable[key]
                    pi.backoff_expiry = self._backoff_time(pi)
                    if pi.backoff_expiry > now:
                        self._backoff.add(pi)
                    else:
                        self._active.add(pi)
                        moved = True
            if moved:
                self._cond.notify_all()

    # -- update/delete (informer-driven) ------------------------------------

    def update(self, old: Optional[v1.Pod], new: v1.Pod) -> None:
        with self._cond:
            key = new.metadata.key
            # the queue's own heaps, not the API store
            for q in (self._active, self._backoff):
                pi = q.get(key)
                if pi is not None:
                    pi.pod = new
                    q.update(pi)
                    return
            pi = self._unschedulable.get(key)
            if pi is not None:
                pi.pod = new
                # spec update may make it schedulable again
                if _significant_update(old, new):
                    del self._unschedulable[key]
                    self._active.add(pi)
                    self._cond.notify()

    def delete(self, pod: v1.Pod) -> None:
        with self._cond:
            key = pod.metadata.key
            tid = ""
            for q in (self._active, self._backoff):
                pi = q.get(key)
                if pi is not None:
                    tid = pi.trace_id
            pi = self._unschedulable.get(key)
            if pi is not None:
                tid = pi.trace_id
            self._active.delete_by_key(key)
            self._backoff.delete_by_key(key)
            self._unschedulable.pop(key, None)
            self.delete_nominated_if_exists(pod)
        # pod deleted while queued: no lifecycle left to attribute
        tracer.discard(tid)

    def delete_if_uid(self, pod: v1.Pod) -> bool:
        """Delete the queued entry for pod's key ONLY while it still
        holds the same uid. The leader-adoption pass runs concurrently
        with informer delete/recreate churn: a blind by-key delete could
        remove a RECREATED pod's fresh entry and strand it (the informer
        stream itself is ordered, so its own handlers don't need this)."""
        with self._cond:
            key = pod.metadata.key
            uid = pod.metadata.uid
            for q in (self._active, self._backoff):
                pi = q.get(key)
                if pi is not None:
                    if pi.pod.metadata.uid != uid:
                        return False
                    q.delete_by_key(key)
                    self.delete_nominated_if_exists(pod)
                    return True
            pi = self._unschedulable.get(key)
            if pi is not None and pi.pod.metadata.uid == uid:
                del self._unschedulable[key]
                self.delete_nominated_if_exists(pod)
                return True
            return False

    # -- nominated pods ------------------------------------------------------

    def add_nominated_pod(self, pod: v1.Pod, node_name: str) -> None:
        with self._lock:
            key = pod.metadata.key
            self.delete_nominated_if_exists(pod)
            self._nominated[key] = node_name
            self._nominated_by_node.setdefault(node_name, set()).add(key)

    def delete_nominated_if_exists(self, pod: v1.Pod) -> None:
        with self._lock:
            key = pod.metadata.key
            node = self._nominated.pop(key, None)
            if node is not None:
                self._nominated_by_node.get(node, set()).discard(key)

    def nominated_pods_for_node(self, node_name: str) -> List[str]:
        with self._lock:
            return sorted(self._nominated_by_node.get(node_name, set()))

    # -- introspection -------------------------------------------------------

    def moves_snapshot(self) -> int:
        """The move-event counter, read under the queue lock. The
        scheduler captures it before a scheduling attempt and compares at
        failure time (AddUnschedulableIfNotPresent's movesAtFailure);
        the bare attribute is for lock-holding internals only — the
        lockset sanitizer caught the scheduler reading it bare."""
        with self._lock:
            return self.moves

    def unschedulable_pod_infos(self) -> List[QueuedPodInfo]:
        """Snapshot of unschedulableQ (the autoscaler's scale-up input):
        pods the scheduler proved don't fit the CURRENT cluster. Read-only
        — entries stay queued; the autoscaler's node-add events flush them
        back to activeQ through the normal move machinery."""
        with self._lock:
            return list(self._unschedulable.values())

    def pending_pod_infos(self) -> List[QueuedPodInfo]:
        """Snapshot of EVERY queued pod (activeQ + backoffQ +
        unschedulableQ): the leader-adoption pass reads each back from
        the store on promotion. Read-only — entries stay queued; the
        adoption pass deletes the ones the store says are bound or gone
        through the normal delete path."""
        with self._lock:
            return (
                self._active.list()
                + self._backoff.list()
                + list(self._unschedulable.values())
            )

    def pending_pods(self) -> dict:
        with self._lock:
            return {
                "active": [pi.key for pi in self._active.list()],
                "backoff": [pi.key for pi in self._backoff.list()],
                "unschedulable": sorted(self._unschedulable.keys()),
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._active) + len(self._backoff) + len(self._unschedulable)

    def active_len(self) -> int:
        """Pods poppable RIGHT NOW (activeQ only — backoff/unschedulable
        pods are not available to the batch former)."""
        with self._lock:
            return len(self._active)


# lockset sanitizer (testing/lockgraph.py Eraser mode): the queue's
# heaps, the unschedulable/nominated maps, and the move counter are the
# shared state every scheduler/informer/autoscaler thread touches —
# chaos suites assert their lockset never goes empty
track_attrs(
    PriorityQueue,
    "_active",
    "_backoff",
    "_unschedulable",
    "_nominated",
    "_nominated_by_node",
    "moves",
)


def _significant_update(old: Optional[v1.Pod], new: v1.Pod) -> bool:
    """UpdatePodInSchedulingQueue / isPodUpdated: ignore pure status churn."""
    if old is None:
        return True
    return (
        old.spec != new.spec
        or old.metadata.labels != new.metadata.labels
        or old.metadata.annotations != new.metadata.annotations
    )
