"""Heap with map index: O(log n) push/pop + O(1) lookup/delete by key.

Equivalent of reference pkg/scheduler/internal/heap/heap.go (used by both
activeQ and podBackoffQ). Lazy-deletion strategy: removed/updated entries are
tombstoned and skipped at pop."""

from __future__ import annotations

import heapq
import itertools
from typing import Any, Callable, Dict, List, Optional, Tuple


class Heap:
    def __init__(self, key_func: Callable[[Any], str], less: Callable[[Any, Any], bool]):
        self._key = key_func
        self._less = less
        self._heap: List[_Entry] = []
        self._items: Dict[str, "_Entry"] = {}
        self._counter = itertools.count()

    def add(self, item: Any) -> None:
        key = self._key(item)
        old = self._items.get(key)
        if old is not None:
            old.valid = False
        e = _Entry(item, self._less, next(self._counter))
        self._items[key] = e
        heapq.heappush(self._heap, e)

    update = add

    def delete(self, item: Any) -> None:
        self.delete_by_key(self._key(item))

    def delete_by_key(self, key: str) -> None:
        e = self._items.pop(key, None)
        if e is not None:
            e.valid = False

    def get(self, key: str) -> Optional[Any]:
        e = self._items.get(key)
        return e.item if e else None

    def peek(self) -> Optional[Any]:
        while self._heap and not self._heap[0].valid:
            heapq.heappop(self._heap)
        return self._heap[0].item if self._heap else None

    def pop(self) -> Optional[Any]:
        while self._heap:
            e = heapq.heappop(self._heap)
            if e.valid:
                del self._items[self._key(e.item)]
                return e.item
        return None

    def list(self) -> List[Any]:
        return [e.item for e in self._items.values()]

    def __len__(self) -> int:
        return len(self._items)

    def __contains__(self, key: str) -> bool:
        return key in self._items


class _Entry:
    __slots__ = ("item", "_less", "seq", "valid")

    def __init__(self, item, less, seq):
        self.item = item
        self._less = less
        self.seq = seq
        self.valid = True

    def __lt__(self, other: "_Entry") -> bool:
        if self._less(self.item, other.item):
            return True
        if self._less(other.item, self.item):
            return False
        return self.seq < other.seq
