"""Scheduling queue: activeQ/backoffQ/unschedulableQ with event-driven flush."""

from .heap import Heap  # noqa: F401
from .scheduling_queue import PriorityQueue, QueuedPodInfo  # noqa: F401
from . import events  # noqa: F401
