"""Informer event handlers: API events → cache + queue (+ device deltas).

Mirrors reference pkg/scheduler/eventhandlers.go:350-460 addAllEventHandlers:
scheduled-pod events maintain the cache (and therefore the device snapshot,
via the encoder); unscheduled-pod events maintain the queue; node events do
both and flush the unschedulable queue with the matching event name
(internal/queue/events.go) so pods retry when the cluster changes.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from ..api import objects as v1
from .queue import events as qevents

if TYPE_CHECKING:
    from .scheduler import Scheduler


def _is_scheduled(pod: v1.Pod) -> bool:
    return bool(pod.spec.node_name)


def add_all_event_handlers(sched: "Scheduler") -> None:
    pods = sched.informer_factory.informer("pods")
    nodes = sched.informer_factory.informer("nodes")

    # -- scheduled pods -> cache (eventhandlers.go: assignedPod filter) ------
    pods.add_handler(
        on_add=lambda p: _on_scheduled_add(sched, p),
        on_update=lambda old, new: _on_scheduled_update(sched, old, new),
        on_delete=lambda p: _on_scheduled_delete(sched, p),
        filter_fn=_is_scheduled,
    )

    # -- unscheduled pods -> queue (responsibleForPod filter) ----------------
    def responsible(pod: v1.Pod) -> bool:
        return not _is_scheduled(pod) and sched.profiles.for_pod(pod) is not None

    pods.add_handler(
        on_add=lambda p: _on_pending_add(sched, p),
        on_update=lambda old, new: _on_pending_update(sched, old, new),
        on_delete=lambda p: sched.queue.delete(p),
        filter_fn=responsible,
    )

    # -- nodes ---------------------------------------------------------------
    nodes.add_handler(
        on_add=lambda n: _on_node_add(sched, n),
        on_update=lambda old, new: _on_node_update(sched, old, new),
        on_delete=lambda n: _on_node_delete(sched, n),
    )

    # -- services -> SelectorSpread's device columns -------------------------
    # A Service's selector is interned as a service-derived predicate so the
    # kernel's DefaultPodTopologySpread score can count same-service pods
    # through sel_counts; interning grows the vocab, which invalidates
    # cached templates (their fingerprints embed vocab lengths). Deletes
    # can't shrink the vocab — bump the template cache's external sig so
    # match_svc masks rebuild without the dead service.
    services = sched.informer_factory.informer("services")
    services.add_handler(
        on_add=lambda s: _on_service_add(sched, s),
        on_update=lambda old, new: _on_service_update(sched, old, new),
        on_delete=lambda s: _on_service_delete(sched, s),
    )


def _on_scheduled_add(sched, pod):
    sched.cache.add_pod(pod)
    sched.queue.delete(pod)  # it may still sit in a queue from a race
    sched.queue.move_all_to_active_or_backoff(qevents.ASSIGNED_POD_ADD)


def _on_scheduled_update(sched, old, new):
    sched.cache.update_pod(new)
    sched.queue.move_all_to_active_or_backoff(qevents.ASSIGNED_POD_UPDATE)


def _on_scheduled_delete(sched, pod):
    sched.cache.remove_pod(pod)
    sched.queue.move_all_to_active_or_backoff(qevents.ASSIGNED_POD_DELETE)


def _on_pending_add(sched, pod):
    # skip pods this scheduler has already assumed (skipPodUpdate,
    # eventhandlers.go: the optimistic cache owns them now)
    if sched.cache.is_assumed(pod.metadata.key):
        return
    if pod.metadata.deletion_timestamp is None:
        sched.queue.add(pod)


def _on_pending_update(sched, old, new):
    if sched.cache.is_assumed(new.metadata.key):
        return
    sched.queue.update(old, new)


def _node_event(old: v1.Node, new: v1.Node) -> str:
    if old.spec.unschedulable != new.spec.unschedulable:
        return qevents.NODE_SPEC_UNSCHEDULABLE_CHANGE
    if old.status.allocatable != new.status.allocatable:
        return qevents.NODE_ALLOCATABLE_CHANGE
    if old.metadata.labels != new.metadata.labels:
        return qevents.NODE_LABEL_CHANGE
    if old.spec.taints != new.spec.taints:
        return qevents.NODE_TAINT_CHANGE
    return qevents.NODE_CONDITION_CHANGE


def _on_node_add(sched, node):
    sched.cache.add_node(node)
    sched.queue.move_all_to_active_or_backoff(qevents.NODE_ADD)


def _on_node_update(sched, old, new):
    sched.cache.update_node(new)
    sched.queue.move_all_to_active_or_backoff(_node_event(old, new))


def _on_node_delete(sched, node):
    sched.cache.remove_node(node.metadata.name)
    sched.queue.move_all_to_active_or_backoff(qevents.NODE_DELETE)


def _register_service(sched: "Scheduler", svc) -> bool:
    sel = getattr(svc.spec, "selector", None)
    if not sel:
        return False
    from ..api.selectors import selector_from_match_labels

    with sched.cache.lock:
        enc = sched.cache.encoder
        before = len(enc.service_sids)
        enc.register_service_predicate(
            svc.metadata.namespace, selector_from_match_labels(sel)
        )
        return len(enc.service_sids) != before


def _rebuild_service_sids(sched: "Scheduler") -> None:
    """Recompute the service-derived sid set from the LIVE services (the
    vocab can't shrink, but a deleted/retargeted service must drop out of
    the match_svc masks)."""
    from ..api.selectors import selector_from_match_labels

    try:
        services, _ = sched.server.list("services")
    except Exception:
        services = []
    with sched.cache.lock:
        enc = sched.cache.encoder
        enc.service_sids.clear()
        for s in services:
            sel = getattr(s.spec, "selector", None)
            if sel:
                enc.register_service_predicate(
                    s.metadata.namespace, selector_from_match_labels(sel)
                )
    sched._tpl_cache.extra_sig += 1  # cached match_svc masks are stale


def _on_service_add(sched, svc):
    if _register_service(sched, svc):
        sched._tpl_cache.extra_sig += 1
    sched.queue.move_all_to_active_or_backoff(qevents.SERVICE_ADD)


def _on_service_update(sched, old, new):
    if getattr(old.spec, "selector", None) != getattr(new.spec, "selector", None):
        _rebuild_service_sids(sched)
    sched.queue.move_all_to_active_or_backoff(qevents.SERVICE_UPDATE)


def _on_service_delete(sched, svc):
    _rebuild_service_sids(sched)
    sched.queue.move_all_to_active_or_backoff(qevents.SERVICE_DELETE)
