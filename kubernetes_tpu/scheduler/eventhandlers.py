"""Informer event handlers: API events → cache + queue (+ device deltas).

Mirrors reference pkg/scheduler/eventhandlers.go:350-460 addAllEventHandlers:
scheduled-pod events maintain the cache (and therefore the device snapshot,
via the encoder); unscheduled-pod events maintain the queue; node events do
both and flush the unschedulable queue with the matching event name
(internal/queue/events.go) so pods retry when the cluster changes.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from ..api import objects as v1
from .queue import events as qevents

if TYPE_CHECKING:
    from .scheduler import Scheduler


def _is_scheduled(pod: v1.Pod) -> bool:
    return bool(pod.spec.node_name)


def add_all_event_handlers(sched: "Scheduler") -> None:
    pods = sched.informer_factory.informer("pods")
    nodes = sched.informer_factory.informer("nodes")

    # -- scheduled pods -> cache (eventhandlers.go: assignedPod filter) ------
    pods.add_handler(
        on_add=lambda p: _on_scheduled_add(sched, p),
        on_update=lambda old, new: _on_scheduled_update(sched, old, new),
        on_delete=lambda p: _on_scheduled_delete(sched, p),
        filter_fn=_is_scheduled,
    )

    # -- unscheduled pods -> queue (responsibleForPod filter) ----------------
    def responsible(pod: v1.Pod) -> bool:
        return not _is_scheduled(pod) and sched.profiles.for_pod(pod) is not None

    pods.add_handler(
        on_add=lambda p: _on_pending_add(sched, p),
        on_update=lambda old, new: _on_pending_update(sched, old, new),
        on_delete=lambda p: sched.queue.delete(p),
        filter_fn=responsible,
    )

    # -- nodes ---------------------------------------------------------------
    nodes.add_handler(
        on_add=lambda n: _on_node_add(sched, n),
        on_update=lambda old, new: _on_node_update(sched, old, new),
        on_delete=lambda n: _on_node_delete(sched, n),
    )


def _on_scheduled_add(sched, pod):
    sched.cache.add_pod(pod)
    sched.queue.delete(pod)  # it may still sit in a queue from a race
    sched.queue.move_all_to_active_or_backoff(qevents.ASSIGNED_POD_ADD)


def _on_scheduled_update(sched, old, new):
    sched.cache.update_pod(new)
    sched.queue.move_all_to_active_or_backoff(qevents.ASSIGNED_POD_UPDATE)


def _on_scheduled_delete(sched, pod):
    sched.cache.remove_pod(pod)
    sched.queue.move_all_to_active_or_backoff(qevents.ASSIGNED_POD_DELETE)


def _on_pending_add(sched, pod):
    # skip pods this scheduler has already assumed (skipPodUpdate,
    # eventhandlers.go: the optimistic cache owns them now)
    if sched.cache.is_assumed(pod.metadata.key):
        return
    if pod.metadata.deletion_timestamp is None:
        sched.queue.add(pod)


def _on_pending_update(sched, old, new):
    if sched.cache.is_assumed(new.metadata.key):
        return
    sched.queue.update(old, new)


def _node_event(old: v1.Node, new: v1.Node) -> str:
    if old.spec.unschedulable != new.spec.unschedulable:
        return qevents.NODE_SPEC_UNSCHEDULABLE_CHANGE
    if old.status.allocatable != new.status.allocatable:
        return qevents.NODE_ALLOCATABLE_CHANGE
    if old.metadata.labels != new.metadata.labels:
        return qevents.NODE_LABEL_CHANGE
    if old.spec.taints != new.spec.taints:
        return qevents.NODE_TAINT_CHANGE
    return qevents.NODE_CONDITION_CHANGE


def _on_node_add(sched, node):
    sched.cache.add_node(node)
    sched.queue.move_all_to_active_or_backoff(qevents.NODE_ADD)


def _on_node_update(sched, old, new):
    sched.cache.update_node(new)
    sched.queue.move_all_to_active_or_backoff(_node_event(old, new))


def _on_node_delete(sched, node):
    sched.cache.remove_node(node.metadata.name)
    sched.queue.move_all_to_active_or_backoff(qevents.NODE_DELETE)
