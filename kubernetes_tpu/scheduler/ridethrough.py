"""Degraded-store ride-through for the scheduler's bind pipeline.

PR 1 made the API store honest under quorum loss: writes fail fast with a
retryable 503 (DegradedWrites — the gate refused BEFORE applying) or with
QuorumLost (THIS write applied locally but missed its ack window: outcome
unknown). This module makes the scheduler ride that window out instead of
failing whole bind waves into the unschedulable queue:

  * **pending-bind buffer**: placements whose bind hit a retryable store
    error park here, keyed by pod UID, while the pods STAY assumed in the
    scheduler cache (the assume TTL is only armed by finish_binding, so a
    buffered assume never expires and the HBM snapshot stays warm).
    One entry per UID — a duplicated retry can never create two bind
    attempts for one pod.
  * **circuit breaker**: the first buffered wave trips it; while open the
    scheduling loop pauses batch dispatch (informers and the device
    snapshot keep updating) and probes for recovery on a jittered
    backoff. The scheduler's reconciler drains the buffer when writes
    reopen: read each pod back, decide "bind landed → finish_binding" vs
    "bind lost → retry once, uid-fenced" vs "pod gone → forget".

The reference has no direct equivalent (its binds are per-pod POSTs with
client-go retries); the closest analogue is the kubelet status manager's
syncBatch retry loop. Here the unit of loss is a whole device wave, so the
buffer is the difference between a blip and a storm.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..testing.lockgraph import named_lock, track_attrs
from ..utils.metrics import metrics

# gauges (rendered by /metrics and the SIGUSR2 debugger dump)
GAUGE_PENDING_BINDS = "scheduler_pending_binds"
GAUGE_BREAKER_STATE = "scheduler_bind_breaker_state"  # 1 = open (paused)
COUNTER_BUFFERED = "scheduler_pending_binds_buffered_total"
COUNTER_OVERFLOW = "scheduler_pending_bind_overflow_total"
COUNTER_BREAKER_TRIPS = "scheduler_bind_breaker_trips_total"
COUNTER_RECONCILED = "scheduler_bind_reconcile_total"  # label: outcome
HIST_PAUSED_S = "scheduler_bind_breaker_open_duration_seconds"

BREAKER_OPEN = 1.0
BREAKER_CLOSED = 0.0


@dataclass
class PendingBind:
    """One buffered placement: the pod is assumed in the cache on
    node_name. Whether the bind applied (QuorumLost: applied-but-
    unacked) or never did (Degraded: refused up front) is NOT tracked —
    the reconciler reads every pod back before any retry, which is the
    only answer that survives a failover anyway."""

    pi: Any  # QueuedPodInfo
    node_name: str
    profile: Any
    buffered_at: float = field(default_factory=time.monotonic)

    @property
    def uid(self) -> str:
        return self.pi.pod.metadata.uid


class BindRideThrough:
    """Pending-bind buffer + dispatch circuit breaker (one lock, shared
    by the scheduling loop and the async bind pool)."""

    def __init__(
        self,
        capacity: int = 8192,
        probe_initial_s: float = 0.2,
        probe_max_s: float = 1.0,
    ):
        self.capacity = capacity
        self._probe_initial = probe_initial_s
        self._probe_max = probe_max_s
        self._probe_delay = probe_initial_s
        # named for the lock-order watchdog + lockset sanitizer
        self._lock = named_lock("scheduler.ridethrough")
        self._entries: Dict[str, PendingBind] = {}  # pod UID -> entry
        self._open = False
        self._opened_at: Optional[float] = None
        self._publish_locked()

    # -- buffer ---------------------------------------------------------------

    def buffer(
        self, entries: List[PendingBind]
    ) -> Tuple[List[PendingBind], List[PendingBind]]:
        """Park entries (deduped by UID) and trip the breaker. Returns
        (accepted, overflow) — overflow entries did NOT fit and the
        caller must unwind them (forget + requeue)."""
        accepted: List[PendingBind] = []
        overflow: List[PendingBind] = []
        with self._lock:
            for e in entries:
                if e.uid in self._entries:
                    continue  # duplicate retry of an already-buffered pod
                if len(self._entries) >= self.capacity:
                    overflow.append(e)
                    continue
                self._entries[e.uid] = e
                accepted.append(e)
            tripped = not self._open and bool(self._entries)
            if tripped:
                self._open = True
                self._opened_at = time.monotonic()
                self._probe_delay = self._probe_initial
            self._publish_locked()
        if accepted:
            metrics.inc(COUNTER_BUFFERED, by=float(len(accepted)))
        if overflow:
            metrics.inc(COUNTER_OVERFLOW, by=float(len(overflow)))
        if tripped:
            metrics.inc(COUNTER_BREAKER_TRIPS)
        return accepted, overflow

    def drain(self) -> List[PendingBind]:
        """Atomically take every buffered entry for a reconcile pass
        (oldest first). Un-reconciled entries come back via restore()."""
        with self._lock:
            out = sorted(self._entries.values(), key=lambda e: e.buffered_at)
            self._entries.clear()
            self._publish_locked()
            return out

    def restore(self, entries: List[PendingBind]) -> None:
        """Put back entries a reconcile pass could not complete (store
        still degraded). A fresh entry buffered for the same UID
        mid-pass wins the slot — both mean the same thing (read back
        before any retry)."""
        with self._lock:
            for e in entries:
                self._entries.setdefault(e.uid, e)
            self._publish_locked()

    # -- breaker --------------------------------------------------------------

    @property
    def open(self) -> bool:
        with self._lock:
            return self._open

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._entries)

    def next_probe_delay(self) -> float:
        """Jittered, growing probe interval while open (0.2 s → 1 s cap):
        fast enough that recovery is noticed well inside the 5 s
        resume-placing budget, slow enough not to hammer a down store."""
        with self._lock:
            d = self._probe_delay
            self._probe_delay = min(self._probe_delay * 1.5, self._probe_max)
        return d * (1.0 + random.uniform(-0.2, 0.2))

    def reset(self) -> None:
        """Close the breaker (buffer drained; writes are flowing). A
        no-op while entries remain — an async binder can buffer a new
        entry between the reconciler's drain and this reset, and closing
        then would strand it (nothing re-probes once closed)."""
        with self._lock:
            if not self._open or self._entries:
                return
            self._open = False
            opened_at, self._opened_at = self._opened_at, None
            self._probe_delay = self._probe_initial
            self._publish_locked()
        if opened_at is not None:
            metrics.observe(HIST_PAUSED_S, time.monotonic() - opened_at)

    # -- introspection --------------------------------------------------------

    def state(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "breaker": "open" if self._open else "closed",
                "pending_binds": len(self._entries),
                "open_for_s": (
                    round(time.monotonic() - self._opened_at, 3)
                    if self._opened_at is not None
                    else 0.0
                ),
            }

    def _publish_locked(self) -> None:
        metrics.set_gauge(GAUGE_PENDING_BINDS, float(len(self._entries)))
        metrics.set_gauge(
            GAUGE_BREAKER_STATE, BREAKER_OPEN if self._open else BREAKER_CLOSED
        )


# lockset sanitizer (testing/lockgraph.py Eraser mode): the buffer and
# breaker state are shared by the scheduling loop, the async bind pool,
# and the reconciler — one lock, machine-checked
track_attrs(
    BindRideThrough,
    "_entries",
    "_open",
    "_opened_at",
    "_probe_delay",
)
