"""Scheduler framework: plugin API, registry, host runtime.

The host-side twin of the device lattice. Mirrors the reference's
pkg/scheduler/framework/v1alpha1 plugin contract (interface.go): the same
extension points, Status codes and CycleState, with host plugins serving
three roles: (1) semantic oracle for differential tests against the kernels,
(2) fallback path for pods whose spec overflows the device encoding,
(3) preemption what-if evaluation.
"""

from .interface import (  # noqa: F401
    Status,
    Code,
    CycleState,
    Plugin,
    FilterPlugin,
    PreFilterPlugin,
    ScorePlugin,
    PostFilterPlugin,
    PermitPlugin,
    ReservePlugin,
    BindPlugin,
    QueueSortPlugin,
    MAX_NODE_SCORE,
    MIN_NODE_SCORE,
)
from .registry import Registry, default_registry  # noqa: F401
from .runtime import Framework  # noqa: F401
