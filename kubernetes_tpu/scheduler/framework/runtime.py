"""Host framework runtime: runs plugin chains for one pod.

The host twin of framework/v1alpha1/framework.go (RunFilterPlugins:424,
RunScorePlugins:503-580: score → normalize → weight). Where the reference
fans out over goroutines, the host path here is a plain loop — the bulk path
is the device lattice; this runtime exists for fallback pods, preemption
what-ifs, and as the differential-test oracle. Permit plugins park pods in a
waiting map exactly like waitingPodsMap (waiting_pods_map.go).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from .interface import (
    Code,
    CycleState,
    MAX_NODE_SCORE,
    Status,
    is_success,
)
from .registry import PluginSet, Registry, default_plugin_set, default_registry


class WaitingPod:
    def __init__(self, pod, plugins_with_timeouts: Dict[str, float]):
        self.pod = pod
        self._pending = dict(plugins_with_timeouts)
        self._event = threading.Event()
        self._status: Optional[Status] = None
        self._lock = threading.Lock()
        self.deadline = time.monotonic() + (
            max(plugins_with_timeouts.values()) if plugins_with_timeouts else 0
        )

    def allow(self, plugin_name: str) -> None:
        with self._lock:
            self._pending.pop(plugin_name, None)
            if not self._pending and not self._event.is_set():
                self._status = None
                self._event.set()

    def reject(self, msg: str = "") -> None:
        with self._lock:
            if not self._event.is_set():
                self._status = Status.unschedulable(msg)
                self._event.set()

    def wait(self, timeout: float) -> Optional[Status]:
        if self._event.wait(timeout):
            return self._status
        return Status.unschedulable("permit wait timeout")


class Framework:
    """One instance per profile (profile.Map, profile/profile.go:39)."""

    def __init__(
        self,
        registry: Optional[Registry] = None,
        plugin_set: Optional[PluginSet] = None,
        context: Optional[dict] = None,
    ):
        self.registry = registry or default_registry()
        self.plugin_set = plugin_set or default_plugin_set()
        self.context = dict(context) if context else {}
        # plugins that signal other waiting pods (coscheduling's quorum
        # cascade) need their owning framework's waitingPodsMap
        self.context.setdefault("framework_getter", lambda: self)
        self._instances: Dict[str, object] = {}
        self.waiting_pods: Dict[str, WaitingPod] = {}
        self._waiting_lock = threading.Lock()

    def plugin(self, name: str):
        inst = self._instances.get(name)
        if inst is None:
            factory = self.registry.get(name)
            if factory is None:
                raise KeyError(f"plugin {name} not registered")
            inst = factory(self.context)
            self._instances[name] = inst
        return inst

    # -- queue sort ---------------------------------------------------------

    def queue_sort_less(self, pi1, pi2) -> bool:
        qs = self.plugin(self.plugin_set.queue_sort[0])
        return qs.less(pi1, pi2)

    # -- filter chain --------------------------------------------------------

    def run_pre_filter_plugins(self, state: CycleState, pod) -> Optional[Status]:
        for name in self.plugin_set.pre_filter:
            st = self.plugin(name).pre_filter(state, pod)
            if not is_success(st):
                st.message = f"{name}: {st.message}"
                return st
        return None

    def run_filter_plugins(self, state: CycleState, pod, node_info) -> Optional[Status]:
        """First failure wins, but UnschedulableAndUnresolvable upgrades and
        stops the chain (framework.go:424 RunFilterPlugins)."""
        result: Optional[Status] = None
        for name in self.plugin_set.filter:
            st = self.plugin(name).filter(state, pod, node_info)
            if not is_success(st):
                st.message = f"{name}: {st.message}"
                if st.code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE:
                    return st
                if st.code == Code.ERROR:
                    return st
                if result is None:
                    result = st
                # keep evaluating? reference stops at first failure unless
                # runAllFilters; default stops.
                return result
        return result

    def run_pre_filter_extension_add_pod(
        self, state: CycleState, pod_to_schedule, pod_to_add, node_info
    ) -> Optional[Status]:
        for name in self.plugin_set.pre_filter:
            plug = self.plugin(name)
            if plug.has_extensions():
                st = plug.add_pod(state, pod_to_schedule, pod_to_add, node_info)
                if not is_success(st):
                    return st
        return None

    def run_pre_filter_extension_remove_pod(
        self, state: CycleState, pod_to_schedule, pod_to_remove, node_info
    ) -> Optional[Status]:
        for name in self.plugin_set.pre_filter:
            plug = self.plugin(name)
            if plug.has_extensions():
                st = plug.remove_pod(state, pod_to_schedule, pod_to_remove, node_info)
                if not is_success(st):
                    return st
        return None

    # -- score chain ---------------------------------------------------------

    def run_pre_score_plugins(self, state: CycleState, pod, nodes) -> Optional[Status]:
        for name in self.plugin_set.pre_score:
            plug = self.plugin(name)
            if hasattr(plug, "pre_score"):
                st = plug.pre_score(state, pod, nodes)
                if not is_success(st):
                    return st
        return None

    def run_score_plugins(
        self, state: CycleState, pod, node_names: List[str], snapshot
    ) -> Dict[str, float]:
        """score → normalize → weight → sum (framework.go:503-580)."""
        totals = {n: 0.0 for n in node_names}
        for name, weight in self.plugin_set.score:
            plug = self.plugin(name)
            scores: List[Tuple[str, float]] = []
            for n in node_names:
                s, st = plug.score(state, pod, n, snapshot=snapshot)
                if not is_success(st):
                    raise RuntimeError(f"score plugin {name} failed: {st.message}")
                scores.append((n, s))
            plug.normalize_scores(state, pod, scores)
            for n, s in scores:
                if s < 0 or s > MAX_NODE_SCORE:
                    s = max(0.0, min(float(MAX_NODE_SCORE), s))
                totals[n] += weight * s
        return totals

    # -- reserve / permit / bind ---------------------------------------------

    def run_reserve_plugins(self, state, pod, node_name) -> Optional[Status]:
        for name in self.plugin_set.reserve:
            st = self.plugin(name).reserve(state, pod, node_name)
            if not is_success(st):
                return st
        return None

    def run_unreserve_plugins(self, state, pod, node_name) -> None:
        for name in self.plugin_set.unreserve:
            self.plugin(name).unreserve(state, pod, node_name)

    def run_permit_plugins(self, state, pod, node_name) -> Optional[Status]:
        waits: Dict[str, float] = {}
        for name in self.plugin_set.permit:
            st, timeout = self.plugin(name).permit(state, pod, node_name)
            if st is not None and st.code == Code.WAIT:
                waits[name] = timeout
            elif not is_success(st):
                return st
        if waits:
            wp = WaitingPod(pod, waits)
            with self._waiting_lock:
                self.waiting_pods[pod.metadata.uid] = wp
            return Status(Code.WAIT)
        return None

    def wait_on_permit(self, pod) -> Optional[Status]:
        with self._waiting_lock:
            wp = self.waiting_pods.get(pod.metadata.uid)
        if wp is None:
            return None
        try:
            return wp.wait(max(0.0, wp.deadline - time.monotonic()))
        finally:
            with self._waiting_lock:
                self.waiting_pods.pop(pod.metadata.uid, None)

    def get_waiting_pod(self, uid: str) -> Optional[WaitingPod]:
        with self._waiting_lock:
            return self.waiting_pods.get(uid)

    def iterate_waiting_pods(self):
        with self._waiting_lock:
            return list(self.waiting_pods.values())

    def run_pre_bind_plugins(self, state, pod, node_name) -> Optional[Status]:
        for name in self.plugin_set.pre_bind:
            st = self.plugin(name).pre_bind(state, pod, node_name)
            if not is_success(st):
                return st
        return None

    def run_bind_plugins(self, state, pod, node_name) -> Optional[Status]:
        for name in self.plugin_set.bind:
            st = self.plugin(name).bind(state, pod, node_name)
            if st is not None and st.code == Code.SKIP:
                continue
            return st
        return None

    def run_post_bind_plugins(self, state, pod, node_name) -> None:
        for name in self.plugin_set.post_bind:
            self.plugin(name).post_bind(state, pod, node_name)

    def has_filter_plugin(self, name: str) -> bool:
        return name in self.plugin_set.filter
