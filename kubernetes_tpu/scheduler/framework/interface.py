"""Plugin API: extension points, Status codes, CycleState.

Mirrors reference pkg/scheduler/framework/v1alpha1/interface.go: the 11
extension points (QueueSort, PreFilter, Filter, PreScore, Score+Normalize,
Reserve, Permit, PreBind, Bind, PostBind, Unreserve) and the Status code
lattice (interface.go:54-99). Plugins are plain Python classes; the device
lattice implements the Filter/Score semantics of the north-star plugins in
bulk, while these interfaces serve the host path.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

MAX_NODE_SCORE = 100
MIN_NODE_SCORE = 0


class Code:
    SUCCESS = 0
    ERROR = 1
    UNSCHEDULABLE = 2
    UNSCHEDULABLE_AND_UNRESOLVABLE = 3
    WAIT = 4
    SKIP = 5


class Status:
    """Plugin verdict. None is treated as Success (reference convention)."""

    def __init__(self, code: int = Code.SUCCESS, message: str = ""):
        self.code = code
        self.message = message

    @classmethod
    def success(cls) -> Optional["Status"]:
        return None

    @classmethod
    def unschedulable(cls, msg: str = "") -> "Status":
        return cls(Code.UNSCHEDULABLE, msg)

    @classmethod
    def unresolvable(cls, msg: str = "") -> "Status":
        return cls(Code.UNSCHEDULABLE_AND_UNRESOLVABLE, msg)

    @classmethod
    def error(cls, msg: str = "") -> "Status":
        return cls(Code.ERROR, msg)

    @classmethod
    def wait(cls, msg: str = "") -> "Status":
        return cls(Code.WAIT, msg)


def is_success(s: Optional[Status]) -> bool:
    return s is None or s.code == Code.SUCCESS


def is_unschedulable(s: Optional[Status]) -> bool:
    return s is not None and s.code in (
        Code.UNSCHEDULABLE,
        Code.UNSCHEDULABLE_AND_UNRESOLVABLE,
    )


class CycleState:
    """Per-scheduling-cycle key/value store passed through all plugins
    (cycle_state.go:44). Clone() supports preemption what-if simulation."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._data: Dict[str, Any] = {}
        self.skip_filter_plugins: Optional[set] = None

    def read(self, key: str) -> Any:
        with self._lock:
            if key not in self._data:
                raise KeyError(key)
            return self._data[key]

    def write(self, key: str, value: Any) -> None:
        with self._lock:
            self._data[key] = value

    def delete(self, key: str) -> None:
        with self._lock:
            self._data.pop(key, None)

    def clone(self) -> "CycleState":
        c = CycleState()
        with self._lock:
            for k, v in self._data.items():
                c._data[k] = v.clone() if hasattr(v, "clone") else v
        return c


class Plugin:
    name: str = "Plugin"


class QueueSortPlugin(Plugin):
    def less(self, pod_info1, pod_info2) -> bool:
        raise NotImplementedError


class PreFilterPlugin(Plugin):
    def pre_filter(self, state: CycleState, pod) -> Optional[Status]:
        raise NotImplementedError

    # PreFilterExtensions (AddPod/RemovePod) for preemption simulation
    def add_pod(self, state: CycleState, pod_to_schedule, pod_to_add, node_info) -> Optional[Status]:
        return None

    def remove_pod(self, state: CycleState, pod_to_schedule, pod_to_remove, node_info) -> Optional[Status]:
        return None

    def has_extensions(self) -> bool:
        return False


class FilterPlugin(Plugin):
    def filter(self, state: CycleState, pod, node_info) -> Optional[Status]:
        raise NotImplementedError


class PreScorePlugin(Plugin):
    def pre_score(self, state: CycleState, pod, nodes) -> Optional[Status]:
        raise NotImplementedError


class ScorePlugin(Plugin):
    def score(self, state: CycleState, pod, node_name: str) -> Tuple[int, Optional[Status]]:
        raise NotImplementedError

    def normalize_scores(self, state: CycleState, pod, scores: List[Tuple[str, float]]) -> Optional[Status]:
        """In-place normalization; default = none."""
        return None


class PostFilterPlugin(Plugin):
    def post_filter(self, state: CycleState, pod, filtered_node_status) -> Optional[Status]:
        raise NotImplementedError


class ReservePlugin(Plugin):
    def reserve(self, state: CycleState, pod, node_name: str) -> Optional[Status]:
        raise NotImplementedError


class UnreservePlugin(Plugin):
    def unreserve(self, state: CycleState, pod, node_name: str) -> None:
        raise NotImplementedError


class PermitPlugin(Plugin):
    def permit(self, state: CycleState, pod, node_name: str) -> Tuple[Optional[Status], float]:
        """Returns (status, timeout_seconds). Wait status parks the pod."""
        raise NotImplementedError


class PreBindPlugin(Plugin):
    def pre_bind(self, state: CycleState, pod, node_name: str) -> Optional[Status]:
        raise NotImplementedError


class BindPlugin(Plugin):
    def bind(self, state: CycleState, pod, node_name: str) -> Optional[Status]:
        raise NotImplementedError


class PostBindPlugin(Plugin):
    def post_bind(self, state: CycleState, pod, node_name: str) -> None:
        raise NotImplementedError
