"""Plugin registry + default profile wiring.

Mirrors framework/plugins/registry.go:46-77 (in-tree registry) and
algorithmprovider/registry.go:61-131 (default plugin set & weights: all
score weights 1 except NodePreferAvoidPods=10000). Out-of-tree plugins merge
by name, exactly like the reference's OutOfTreeRegistry option.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from . import plugins as p


@dataclass
class PluginSet:
    """Per-extension-point plugin names (+ weight for score)."""

    queue_sort: List[str] = field(default_factory=lambda: ["PrioritySort"])
    pre_filter: List[str] = field(default_factory=list)
    filter: List[str] = field(default_factory=list)
    pre_score: List[str] = field(default_factory=list)
    score: List[Tuple[str, float]] = field(default_factory=list)
    reserve: List[str] = field(default_factory=list)
    permit: List[str] = field(default_factory=list)
    pre_bind: List[str] = field(default_factory=list)
    bind: List[str] = field(default_factory=lambda: ["DefaultBinder"])
    post_bind: List[str] = field(default_factory=list)
    unreserve: List[str] = field(default_factory=list)


def default_plugin_set() -> PluginSet:
    """Default algorithm provider (algorithmprovider/registry.go:61-131).

    Filter order matches the reference: NodeUnschedulable → Fit → NodeName →
    NodePorts → NodeAffinity → VolumeRestrictions → TaintToleration →
    volume limits → VolumeBinding → VolumeZone → spread → InterPodAffinity.
    """
    return PluginSet(
        pre_filter=[
            "NodeResourcesFit",
            "NodePorts",
            "PodTopologySpread",
            "InterPodAffinity",
        ],
        filter=[
            "NodeUnschedulable",
            "NodeResourcesFit",
            "NodeName",
            "NodePorts",
            "NodeAffinity",
            "VolumeRestrictions",
            "TaintToleration",
            "NodeVolumeLimits",
            "EBSLimits",
            "GCEPDLimits",
            "AzureDiskLimits",
            "VolumeBinding",
            "VolumeZone",
            "PodTopologySpread",
            "InterPodAffinity",
        ],
        pre_score=["PodTopologySpread", "InterPodAffinity"],
        score=[
            ("NodeResourcesBalancedAllocation", 1.0),
            ("ImageLocality", 1.0),
            ("InterPodAffinity", 1.0),
            ("NodeResourcesLeastAllocated", 1.0),
            ("NodeAffinity", 1.0),
            ("NodePreferAvoidPods", 10000.0),
            ("DefaultPodTopologySpread", 1.0),
            ("TaintToleration", 1.0),
            ("PodTopologySpread", 1.0),
        ],
    )


class Registry(dict):
    """name -> factory(context) -> plugin instance. Context carries the
    snapshot getter / API server the way FrameworkHandle does."""

    def merge(self, other: "Registry") -> "Registry":
        for k, v in other.items():
            self[k] = v
        return self


def default_registry() -> Registry:
    r = Registry()
    r["NodeResourcesFit"] = lambda ctx: p.NodeResourcesFit(
        ctx.get("ignored_extended_resources")
    )
    r["NodeResourcesLeastAllocated"] = lambda ctx: p.NodeResourcesLeastAllocated()
    r["NodeResourcesMostAllocated"] = lambda ctx: p.NodeResourcesMostAllocated()
    r["NodeResourcesBalancedAllocation"] = lambda ctx: p.NodeResourcesBalancedAllocation()
    r["RequestedToCapacityRatio"] = lambda ctx: p.RequestedToCapacityRatio(
        ctx.get("rtc_shape")
    )
    r["NodeAffinity"] = lambda ctx: p.NodeAffinityPlugin()
    r["TaintToleration"] = lambda ctx: p.TaintTolerationPlugin()
    r["PodTopologySpread"] = lambda ctx: p.PodTopologySpreadPlugin(
        ctx.get("snapshot_getter")
    )
    r["InterPodAffinity"] = lambda ctx: p.InterPodAffinityPlugin(
        ctx.get("snapshot_getter"),
        hard_pod_affinity_weight=ctx.get("hard_pod_affinity_weight", 1.0),
    )
    r["NodeName"] = lambda ctx: p.NodeName()
    r["NodePorts"] = lambda ctx: p.NodePorts()
    r["NodeUnschedulable"] = lambda ctx: p.NodeUnschedulable()
    r["ImageLocality"] = lambda ctx: p.ImageLocality()
    r["NodePreferAvoidPods"] = lambda ctx: p.NodePreferAvoidPods()
    r["PrioritySort"] = lambda ctx: p.PrioritySort()
    r["DefaultBinder"] = lambda ctx: p.DefaultBinder(ctx.get("server"))
    r["DefaultPodTopologySpread"] = lambda ctx: p.SelectorSpread(
        ctx.get("selectors_for_pod")
    )
    r["VolumeBinding"] = lambda ctx: p.VolumeBinding(ctx.get("volume_binder"))
    r["VolumeRestrictions"] = lambda ctx: p.VolumeRestrictions()
    r["VolumeZone"] = lambda ctx: p.VolumeZone(ctx.get("volume_binder"))
    r["NodeVolumeLimits"] = lambda ctx: p.NodeVolumeLimits(
        ctx.get("volume_binder"), ctx.get("csinode_getter")
    )
    r["EBSLimits"] = lambda ctx: p.EBSLimits(ctx.get("volume_binder"))
    r["GCEPDLimits"] = lambda ctx: p.GCEPDLimits(ctx.get("volume_binder"))
    r["AzureDiskLimits"] = lambda ctx: p.AzureDiskLimits(ctx.get("volume_binder"))
    r["CinderLimits"] = lambda ctx: p.CinderLimits(ctx.get("volume_binder"))
    r["NodeLabel"] = lambda ctx: p.NodeLabel(**ctx.get("node_label_args", {}))
    r["ServiceAffinity"] = lambda ctx: p.ServiceAffinity(
        ctx.get("services_lister"),
        ctx.get("snapshot_getter"),
        **ctx.get("service_affinity_args", {}),
    )
    r["NodeResourceLimits"] = lambda ctx: p.NodeResourceLimits()
    r["Coscheduling"] = lambda ctx: p.Coscheduling(
        ctx.get("framework_getter"),
        permit_timeout=ctx.get("coscheduling_permit_timeout", 30.0),
    )
    return r


def coscheduling_plugin_set() -> PluginSet:
    """Default set + gang scheduling: Coscheduling takes over QueueSort
    (gang-adjacent pop order) and parks members in Permit until quorum."""
    ps = default_plugin_set()
    ps.queue_sort = ["Coscheduling"]
    ps.permit = ["Coscheduling"]
    ps.unreserve = ["Coscheduling"]
    ps.post_bind = ["Coscheduling"]
    return ps
