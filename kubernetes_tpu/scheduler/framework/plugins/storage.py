"""Storage-related filter plugins.

Reference: framework/plugins/volumebinding/volume_binding.go,
volumerestrictions/volume_restrictions.go, volumezone/volume_zone.go,
nodevolumelimits/{csi.go,non_csi.go}. These all run host-side after the
device mask narrows candidates (the reference's extender-style post-filter,
generic_scheduler.go:421) — volume state is API-shaped and churny, a poor
fit for the HBM-resident snapshot.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ....api import objects as v1
from ....controller.volume_scheduling import (
    REGION_LABELS,
    ZONE_LABELS,
    ClaimNotFound,
    VolumeBinder,
)
from ..interface import Code, CycleState, FilterPlugin, Status

ERR_REASON_BIND_CONFLICT = "node(s) didn't find available persistent volumes to bind"
ERR_REASON_NODE_CONFLICT = "node(s) had volume node affinity conflict"


class VolumeBinding(FilterPlugin):
    """volume_binding.go: delegate to the shared VolumeBinder's Find."""

    name = "VolumeBinding"

    def __init__(self, binder: Optional[VolumeBinder]):
        self.binder = binder

    @staticmethod
    def _pod_has_pvcs(pod: v1.Pod) -> bool:
        return any(vol.persistent_volume_claim for vol in pod.spec.volumes)

    def filter(self, state: CycleState, pod, node_info) -> Optional[Status]:
        if self.binder is None or not self._pod_has_pvcs(pod):
            return None
        try:
            unbound_ok, bound_ok, reasons = self.binder.find_pod_volumes(
                pod, node_info.node
            )
        except ClaimNotFound as e:
            # missing PVC can't be fixed by preemption
            return Status(Code.UNSCHEDULABLE_AND_UNRESOLVABLE, str(e))
        if unbound_ok and bound_ok:
            return None
        return Status.unschedulable(
            "; ".join(reasons) or ERR_REASON_BIND_CONFLICT
        )


def _attachable_volumes(
    pod: v1.Pod, binder: Optional[VolumeBinder], source: str
) -> Set[str]:
    """Unique volume ids of `source` kind used by a pod (direct + via PVC)."""
    out: Set[str] = set()
    has_pvc = False
    for vol in pod.spec.volumes:
        src = getattr(vol, source, None)
        if src is not None:
            out.add(_vol_id(src))
        elif vol.persistent_volume_claim:
            has_pvc = True
    if has_pvc and binder is not None:
        try:
            for claim in binder.pod_claims(pod):
                pv_name = claim.spec.volume_name
                if not pv_name:
                    continue
                pv = binder._pv(pv_name)
                if pv is None:
                    continue
                psrc = getattr(pv.spec, source, None)
                if psrc is not None:
                    out.add(_vol_id(psrc))
        except ClaimNotFound:
            pass
    return out


def _vol_id(src) -> str:
    for attr in ("pd_name", "volume_id", "disk_name", "iqn", "image"):
        val = getattr(src, attr, None)
        if val:
            return f"{type(src).__name__}:{val}"
    return f"{type(src).__name__}:?"


class VolumeRestrictions(FilterPlugin):
    """volume_restrictions.go isVolumeConflict: a GCE-PD/ISCSI/RBD volume
    already on the node conflicts unless both mounts are read-only; the same
    EBS volume on one node always conflicts (EBS has no read-only
    exemption)."""

    name = "VolumeRestrictions"

    _SOURCES = ("gce_persistent_disk", "aws_elastic_block_store", "iscsi", "rbd")

    def filter(self, state: CycleState, pod, node_info) -> Optional[Status]:
        new_vols = []
        for vol in pod.spec.volumes:
            for sname in self._SOURCES:
                src = getattr(vol, sname, None)
                if src is not None:
                    new_vols.append((sname, src))
        if not new_vols:
            return None
        for existing in node_info.pods:
            for evol in existing.spec.volumes:
                for sname, src in new_vols:
                    esrc = getattr(evol, sname, None)
                    if esrc is None:
                        continue
                    if _vol_id(esrc) != _vol_id(src):
                        continue
                    if sname != "aws_elastic_block_store" and (
                        src.read_only and esrc.read_only
                    ):
                        continue
                    return Status.unschedulable("node(s) had a volume conflict")
        return None


class VolumeZone(FilterPlugin):
    """volume_zone.go: a bound PV carrying zone/region labels restricts the
    pod to nodes whose labels match."""

    name = "VolumeZone"

    def __init__(self, binder: Optional[VolumeBinder]):
        self.binder = binder

    def filter(self, state: CycleState, pod, node_info) -> Optional[Status]:
        if self.binder is None:
            return None
        node_lbls = node_info.node.metadata.labels
        try:
            claims = self.binder.pod_claims(pod)
        except ClaimNotFound as e:
            return Status(Code.UNSCHEDULABLE_AND_UNRESOLVABLE, str(e))
        for claim in claims:
            if not claim.spec.volume_name:
                continue
            pv = self.binder._pv(claim.spec.volume_name)
            if pv is None:
                continue
            for keyset in (ZONE_LABELS, REGION_LABELS):
                pv_val = next(
                    (
                        pv.metadata.labels[k]
                        for k in keyset
                        if k in pv.metadata.labels
                    ),
                    None,
                )
                if pv_val is None:
                    continue
                node_val = next(
                    (node_lbls[k] for k in keyset if k in node_lbls), None
                )
                # PV zone labels may hold a __ separated set (volume helpers)
                if node_val is None or node_val not in pv_val.split("__"):
                    return Status.unschedulable(
                        "node(s) had no available volume zone"
                    )
        return None


# -- attachable-volume count limits (nodevolumelimits) ----------------------

DEFAULT_LIMITS = {
    "aws_elastic_block_store": 39,  # non_csi.go DefaultMaxEBSVolumes
    "gce_persistent_disk": 16,
    "azure_disk": 16,
    "cinder": 256,
}


class _NonCSILimits(FilterPlugin):
    source = ""  # volume source attr this instance counts
    limit_key = ""  # node allocatable resource name override

    def __init__(self, binder: Optional[VolumeBinder] = None, limit: Optional[int] = None):
        self.binder = binder
        self.limit = limit if limit is not None else DEFAULT_LIMITS[self.source]

    def filter(self, state: CycleState, pod, node_info) -> Optional[Status]:
        new = _attachable_volumes(pod, self.binder, self.source)
        if not new:
            return None
        used: Set[str] = set()
        for existing in node_info.pods:
            used |= _attachable_volumes(existing, self.binder, self.source)
        if len(used | new) > self.limit:
            return Status.unschedulable(
                "node(s) exceed max volume count"
            )
        return None


class EBSLimits(_NonCSILimits):
    name = "EBSLimits"
    source = "aws_elastic_block_store"


class GCEPDLimits(_NonCSILimits):
    name = "GCEPDLimits"
    source = "gce_persistent_disk"


class AzureDiskLimits(_NonCSILimits):
    name = "AzureDiskLimits"
    source = "azure_disk"


class CinderLimits(_NonCSILimits):
    name = "CinderLimits"
    source = "cinder"


class NodeVolumeLimits(FilterPlugin):
    """csi.go: per-CSI-driver attachable limits from the node's CSINode."""

    name = "NodeVolumeLimits"

    def __init__(self, binder: Optional[VolumeBinder], csinode_getter=None):
        self.binder = binder
        self._csinode = csinode_getter  # name -> CSINode | None

    def _pod_csi_volumes(self, pod) -> Dict[str, Set[str]]:
        """driver -> volume handles used by pod (via bound PVs)."""
        out: Dict[str, Set[str]] = {}
        if self.binder is None:
            return out
        try:
            claims = self.binder.pod_claims(pod)
        except ClaimNotFound:
            return out
        for claim in claims:
            if not claim.spec.volume_name:
                continue
            pv = self.binder._pv(claim.spec.volume_name)
            if pv is None or pv.spec.csi is None:
                continue
            out.setdefault(pv.spec.csi.driver, set()).add(
                pv.spec.csi.volume_handle
            )
        return out

    def filter(self, state: CycleState, pod, node_info) -> Optional[Status]:
        new = self._pod_csi_volumes(pod)
        if not new or self._csinode is None:
            return None
        csinode = self._csinode(node_info.name)
        if csinode is None:
            return None
        limits = {
            d.name: d.allocatable_count
            for d in csinode.drivers
            if d.allocatable_count is not None
        }
        if not limits:
            return None
        used: Dict[str, Set[str]] = {}
        for existing in node_info.pods:
            for driver, handles in self._pod_csi_volumes(existing).items():
                used.setdefault(driver, set()).update(handles)
        for driver, handles in new.items():
            limit = limits.get(driver)
            if limit is None:
                continue
            if len(used.get(driver, set()) | handles) > limit:
                return Status.unschedulable(
                    "node(s) exceed max volume count"
                )
        return None
