"""Gang (co-)scheduling on the QueueSort + Permit extension points.

The reference ships no in-tree coscheduling plugin — its extension points
were designed so one could be built out-of-tree (Permit's WAIT verdict and
the waitingPodsMap, framework/v1alpha1/interface.go:211-499,
waiting_pods_map.go). This plugin is that build, adapted to the batched TPU
cycle: a burst of gang members is typically placed by ONE wave-kernel batch,
so the whole gang reaches Permit within a cycle and the quorum release is a
single in-memory cascade — no per-pod polling.

Gang contract:
  * membership: label ``scheduling.k8s.io/group-name`` = gang id
    (namespace-scoped);
  * quorum: annotation ``scheduling.k8s.io/min-member`` (int, defaults to 1);
  * all-or-nothing: members WAIT in Permit until `min-member` of them hold
    reservations; any member's unreserve (bind failure, permit timeout)
    rejects every waiting member so their resources release together.

QueueSort keeps gang members adjacent (priority desc, then gang id, then
FIFO), so the batch former pops whole gangs into one device batch.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Set, Tuple

from ..interface import (
    PermitPlugin,
    PostBindPlugin,
    QueueSortPlugin,
    Status,
    UnreservePlugin,
)

GROUP_LABEL = "scheduling.k8s.io/group-name"
MIN_MEMBER_ANNOTATION = "scheduling.k8s.io/min-member"


def gang_key(pod) -> Optional[str]:
    name = pod.metadata.labels.get(GROUP_LABEL)
    if not name:
        return None
    return f"{pod.metadata.namespace}/{name}"


def min_member(pod) -> int:
    try:
        return max(1, int(pod.metadata.annotations.get(MIN_MEMBER_ANNOTATION, "1")))
    except ValueError:
        return 1


class _GangState:
    __slots__ = ("reserved", "released", "first_seen")

    def __init__(self) -> None:
        self.reserved: Set[str] = set()  # pod uids holding a reservation
        self.released = False  # quorum reached, members flow through
        self.first_seen = time.monotonic()


class Coscheduling(QueueSortPlugin, PermitPlugin, UnreservePlugin, PostBindPlugin):
    name = "Coscheduling"

    def __init__(self, framework_getter=None, permit_timeout: float = 30.0):
        # framework_getter breaks the construction cycle: the framework owns
        # the plugin instances AND the waitingPodsMap the cascade signals
        self._fw = framework_getter
        self.permit_timeout = permit_timeout
        self._lock = threading.Lock()
        self._gangs: Dict[str, _GangState] = {}

    # -- QueueSort ----------------------------------------------------------

    def less(self, pi1, pi2) -> bool:
        """priority desc, then gang id (members adjacent), then FIFO."""
        p1, p2 = pi1.pod.priority, pi2.pod.priority
        if p1 != p2:
            return p1 > p2
        g1 = gang_key(pi1.pod) or ""
        g2 = gang_key(pi2.pod) or ""
        if g1 != g2:
            return g1 < g2
        return pi1.timestamp < pi2.timestamp

    # -- Permit -------------------------------------------------------------

    def permit(self, state, pod, node_name) -> Tuple[Optional[Status], float]:
        key = gang_key(pod)
        if key is None:
            return None, 0.0
        quorum = min_member(pod)
        with self._lock:
            st = self._gangs.setdefault(key, _GangState())
            st.reserved.add(pod.metadata.uid)
            if st.released or len(st.reserved) >= quorum:
                st.released = True
                to_allow = list(st.reserved)
            else:
                return Status.wait(), self.permit_timeout
        # quorum reached by THIS pod: release every parked member
        self._cascade(to_allow, allow=True)
        return None, 0.0

    # -- Unreserve ----------------------------------------------------------

    def unreserve(self, state, pod, node_name) -> None:
        key = gang_key(pod)
        if key is None:
            return
        with self._lock:
            st = self._gangs.get(key)
            if st is None:
                return
            st.reserved.discard(pod.metadata.uid)
            # all-or-nothing: a lost reservation before release voids the
            # gang attempt; reject parked members so their resources free
            # together instead of idling until the permit timeout
            reject = list(st.reserved) if not st.released else []
            if not st.reserved:
                self._gangs.pop(key, None)
        if reject:
            self._cascade(reject, allow=False, msg=f"gang {key} lost a member")

    # -- helpers ------------------------------------------------------------

    def _cascade(self, uids, allow: bool, msg: str = "") -> None:
        fw = self._fw() if self._fw else None
        if fw is None:
            return
        for uid in uids:
            wp = fw.get_waiting_pod(uid)
            if wp is None:
                continue
            if allow:
                wp.allow(self.name)
            else:
                wp.reject(msg)

    def handle_scheduling_failure(self, pod) -> None:
        """A member hard-failed its scheduling cycle: quorum cannot arrive
        this round, so reject the parked siblings NOW instead of letting 49
        reservations idle-block cluster capacity until the permit timeout
        (the community plugin does this from PostFilter; our scheduler calls
        permit plugins' failure hook from _handle_failure)."""
        key = gang_key(pod)
        if key is None:
            return
        with self._lock:
            st = self._gangs.get(key)
            if st is None or st.released:
                return
            reject = list(st.reserved)
        if reject:
            self._cascade(
                reject, allow=False, msg=f"gang {key}: member failed scheduling"
            )

    # -- PostBind -----------------------------------------------------------

    def post_bind(self, state, pod, node_name) -> None:
        """Drop a bound member's bookkeeping; reclaim the gang record once
        every released member has bound."""
        key = gang_key(pod)
        if key is None:
            return
        with self._lock:
            st = self._gangs.get(key)
            if st is not None and st.released:
                st.reserved.discard(pod.metadata.uid)
                if not st.reserved:
                    self._gangs.pop(key, None)
