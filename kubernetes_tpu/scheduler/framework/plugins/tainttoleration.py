"""TaintToleration: filter untolerated NoSchedule/NoExecute; score counts
intolerable PreferNoSchedule taints, inverted-normalized.

Reference: framework/plugins/tainttoleration/taint_toleration.go:55-77
(Filter ⇒ UnschedulableAndUnresolvable), :129-167 (Score)."""

from __future__ import annotations

from typing import Optional

from ....api.objects import (
    TAINT_NO_EXECUTE,
    TAINT_NO_SCHEDULE,
    TAINT_PREFER_NO_SCHEDULE,
    find_untolerated_taint,
    tolerations_tolerate_taint,
)
from ..interface import CycleState, FilterPlugin, ScorePlugin, Status


class TaintTolerationPlugin(FilterPlugin, ScorePlugin):
    name = "TaintToleration"

    def filter(self, state: CycleState, pod, node_info) -> Optional[Status]:
        taint = find_untolerated_taint(
            node_info.node.spec.taints,
            pod.spec.tolerations,
            effects=(TAINT_NO_SCHEDULE, TAINT_NO_EXECUTE),
        )
        if taint is not None:
            return Status.unresolvable(
                f"node(s) had taint {{{taint.key}: {taint.value}}}"
            )
        return None

    def score(self, state, pod, node_name, snapshot=None):
        ni = snapshot.get(node_name)
        cnt = sum(
            1
            for t in ni.node.spec.taints
            if t.effect == TAINT_PREFER_NO_SCHEDULE
            and not tolerations_tolerate_taint(pod.spec.tolerations, t)
        )
        return float(cnt), None

    def normalize_scores(self, state, pod, scores):
        mx = max((s for _, s in scores), default=0.0)
        for i, (n, s) in enumerate(scores):
            scores[i] = (n, (mx - s) / mx * 100.0 if mx > 0 else 100.0)
        return None
