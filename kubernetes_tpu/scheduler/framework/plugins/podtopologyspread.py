"""PodTopologySpread: skew-bounded spreading over topology domains.

Reference: framework/plugins/podtopologyspread/ (filtering.go:43-121 PreFilter
match counts + min-match tracking; :285-333 Filter skew check;
scoring.go:165-250 soft-constraint scoring).

Semantics (shared exactly with the device kernel, ops/lattice.py spread_one):
  * domain counts include only nodes matching the incoming pod's
    nodeSelector/affinity (PreFilter eligibility);
  * a node must carry every constraint's topology key or it is unschedulable;
  * skew = matchNum(node's domain) + selfMatch(1 if pod matches its own
    selector) − min(matchNum over eligible domains); hard constraints fail
    when skew > maxSkew; soft constraints contribute the domain count as an
    inverted-normalized score.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from ....api import objects as v1
from ..interface import CycleState, FilterPlugin, PreFilterPlugin, ScorePlugin, Status
from .helpers import node_labels, pod_matches_node_selector

_STATE_KEY = "PreFilterPodTopologySpread"


class _SpreadState:
    def __init__(self):
        # (constraint idx) -> {topology value: match count}
        self.counts: Dict[int, Dict[str, int]] = {}
        self.self_match: Dict[int, bool] = {}

    def clone(self):
        c = _SpreadState()
        c.counts = {k: dict(v) for k, v in self.counts.items()}
        c.self_match = dict(self.self_match)
        return c


def _matches(pod: v1.Pod, constraint: v1.TopologySpreadConstraint, target: v1.Pod) -> bool:
    if target.metadata.namespace != pod.metadata.namespace:
        return False
    if constraint.label_selector is None:
        return False
    return constraint.label_selector.matches(target.metadata.labels)


class PodTopologySpreadPlugin(PreFilterPlugin, FilterPlugin, ScorePlugin):
    name = "PodTopologySpread"

    def __init__(self, snapshot_getter=None):
        self._snapshot = snapshot_getter  # callable -> Snapshot

    def _constraints(self, pod):
        return list(pod.spec.topology_spread_constraints)

    def has_extensions(self) -> bool:
        return True

    def pre_filter(self, state: CycleState, pod) -> Optional[Status]:
        s = _SpreadState()
        cons = self._constraints(pod)
        snapshot = self._snapshot() if self._snapshot else None
        if snapshot is not None:
            for ci, con in enumerate(cons):
                s.counts[ci] = {}
                s.self_match[ci] = (
                    con.label_selector is not None
                    and con.label_selector.matches(pod.metadata.labels)
                )
            for ni in snapshot.node_info_list:
                if ni.node is None or not pod_matches_node_selector(pod, ni.node):
                    continue
                labels = node_labels(ni.node)
                for ci, con in enumerate(cons):
                    val = labels.get(con.topology_key)
                    if val is None:
                        continue
                    cnt = sum(1 for p in ni.pods if _matches(pod, con, p))
                    s.counts[ci][val] = s.counts[ci].get(val, 0) + cnt
        state.write(_STATE_KEY, s)
        return None

    def add_pod(self, state, pod_to_schedule, pod_to_add, node_info):
        self._update(state, pod_to_schedule, pod_to_add, node_info, +1)
        return None

    def remove_pod(self, state, pod_to_schedule, pod_to_remove, node_info):
        self._update(state, pod_to_schedule, pod_to_remove, node_info, -1)
        return None

    def _update(self, state, pod, other, node_info, delta):
        try:
            s: _SpreadState = state.read(_STATE_KEY)
        except KeyError:
            return
        if node_info.node is None or not pod_matches_node_selector(pod, node_info.node):
            return
        labels = node_labels(node_info.node)
        for ci, con in enumerate(self._constraints(pod)):
            val = labels.get(con.topology_key)
            if val is None or not _matches(pod, con, other):
                continue
            s.counts[ci][val] = s.counts[ci].get(val, 0) + delta

    def filter(self, state: CycleState, pod, node_info) -> Optional[Status]:
        cons = self._constraints(pod)
        if not cons:
            return None
        try:
            s: _SpreadState = state.read(_STATE_KEY)
        except KeyError:
            return None
        labels = node_labels(node_info.node)
        for ci, con in enumerate(cons):
            if con.when_unsatisfiable != v1.DO_NOT_SCHEDULE:
                continue
            val = labels.get(con.topology_key)
            if val is None:
                return Status.unschedulable(
                    f"node missing topology key {con.topology_key}"
                )
            counts = s.counts.get(ci, {})
            match_num = counts.get(val, 0)
            min_match = min(counts.values()) if counts else 0
            self_num = 1 if s.self_match.get(ci) else 0
            if match_num + self_num - min_match > con.max_skew:
                return Status.unschedulable("max topology spread skew violated")
        return None

    def score(self, state, pod, node_name, snapshot=None):
        cons = self._constraints(pod)
        soft = [
            (ci, con)
            for ci, con in enumerate(cons)
            if con.when_unsatisfiable == v1.SCHEDULE_ANYWAY
        ]
        if not soft:
            return 0.0, None
        try:
            s: _SpreadState = state.read(_STATE_KEY)
        except KeyError:
            return 0.0, None
        ni = snapshot.get(node_name)
        labels = node_labels(ni.node)
        total = 0.0
        for ci, con in soft:
            val = labels.get(con.topology_key)
            if val is not None:
                total += s.counts.get(ci, {}).get(val, 0)
        return total, None

    def normalize_scores(self, state, pod, scores):
        mx = max((s for _, s in scores), default=0.0)
        for i, (n, s) in enumerate(scores):
            scores[i] = (n, (mx - s) / mx * 100.0 if mx > 0 else 100.0)
        return None
