"""NodeAffinity: required-term filter + preferred-term score.

Reference: framework/plugins/nodeaffinity/node_affinity.go:54 (Filter via
PodMatchesNodeSelectorAndAffinityTerms), :66-105 (Score = Σ weights of
matched preferred terms, max-normalized by the framework)."""

from __future__ import annotations

from typing import Optional

from ..interface import CycleState, FilterPlugin, ScorePlugin, Status
from .helpers import node_matches_term, pod_matches_node_selector


class NodeAffinityPlugin(FilterPlugin, ScorePlugin):
    name = "NodeAffinity"

    def filter(self, state: CycleState, pod, node_info) -> Optional[Status]:
        if node_info.node is None:
            return Status.error("node not found")
        if not pod_matches_node_selector(pod, node_info.node):
            return Status.unresolvable("node(s) didn't match node selector")
        return None

    def score(self, state, pod, node_name, snapshot=None):
        ni = snapshot.get(node_name)
        aff = pod.spec.affinity.node_affinity if pod.spec.affinity else None
        total = 0.0
        if aff:
            for pt in aff.preferred:
                if pt.weight != 0 and node_matches_term(ni.node, pt.preference):
                    total += pt.weight
        return total, None

    def normalize_scores(self, state, pod, scores):
        mx = max((s for _, s in scores), default=0.0)
        if mx > 0:
            for i, (n, s) in enumerate(scores):
                scores[i] = (n, s / mx * 100.0)
        return None
