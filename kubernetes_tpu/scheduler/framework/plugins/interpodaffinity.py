"""InterPodAffinity: required/preferred (anti-)affinity over topology domains.

Reference: framework/plugins/interpodaffinity/ (filtering.go:51-58,212,256 —
the three topologyToMatchedTermCount maps built by scanning all nodes' pods;
scoring.go:81-178,287-310 — ±weight accumulation over incoming AND existing
pods' terms, max-|score| normalization).

Semantics shared with the device kernel (ops/lattice.py):
  * incoming required affinity term satisfied on node n iff its topology
    domain has ≥1 matching existing pod, OR no pod anywhere matches and the
    pod matches its own selector (first-pod carve-out) and n has the key;
  * incoming required anti-affinity violated iff the domain has ≥1 match;
  * existing pods' required anti-affinity violated iff an existing pod in the
    same domain carries a term matching the incoming pod;
  * score: +w per matching existing pod in domain for preferred affinity
    (incoming and existing), −w for preferred anti-affinity, and existing
    pods' REQUIRED affinity terms contribute hard_pod_affinity_weight.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from ....api import objects as v1
from ..interface import CycleState, FilterPlugin, PreFilterPlugin, ScorePlugin, Status
from .helpers import node_labels, pod_matches_term, term_namespaces

_STATE_KEY = "PreFilterInterPodAffinity"
DEFAULT_HARD_POD_AFFINITY_WEIGHT = 1.0


class _AffinityState:
    def __init__(self):
        # per incoming required affinity term i: {topo value: count}
        self.aff_counts: Dict[int, Dict[str, int]] = {}
        self.aff_total: Dict[int, int] = {}
        self.aff_self: Dict[int, bool] = {}
        # per incoming required anti-affinity term i
        self.anti_counts: Dict[int, Dict[str, int]] = {}
        # existing pods' required anti-affinity terms matching incoming pod:
        # {(topology_key): {topo value: count}}
        self.existing_anti: Dict[str, Dict[str, int]] = {}

    def clone(self):
        c = _AffinityState()
        c.aff_counts = {k: dict(v) for k, v in self.aff_counts.items()}
        c.aff_total = dict(self.aff_total)
        c.aff_self = dict(self.aff_self)
        c.anti_counts = {k: dict(v) for k, v in self.anti_counts.items()}
        c.existing_anti = {k: dict(v) for k, v in self.existing_anti.items()}
        return c


def _incoming_terms(pod: v1.Pod):
    aff = pod.spec.affinity
    req_aff = list(aff.pod_affinity.required) if aff and aff.pod_affinity else []
    req_anti = (
        list(aff.pod_anti_affinity.required) if aff and aff.pod_anti_affinity else []
    )
    return req_aff, req_anti


def _existing_anti_terms(p: v1.Pod):
    a = p.spec.affinity
    if a and a.pod_anti_affinity:
        return a.pod_anti_affinity.required
    return ()


class InterPodAffinityPlugin(PreFilterPlugin, FilterPlugin, ScorePlugin):
    name = "InterPodAffinity"

    def __init__(self, snapshot_getter=None, hard_pod_affinity_weight: float = DEFAULT_HARD_POD_AFFINITY_WEIGHT):
        self._snapshot = snapshot_getter
        self.hard_weight = hard_pod_affinity_weight

    def has_extensions(self) -> bool:
        return True

    def pre_filter(self, state: CycleState, pod) -> Optional[Status]:
        s = _AffinityState()
        req_aff, req_anti = _incoming_terms(pod)
        for i, term in enumerate(req_aff):
            s.aff_counts[i] = {}
            s.aff_total[i] = 0
            s.aff_self[i] = pod_matches_term(pod, pod, term)
        for i in range(len(req_anti)):
            s.anti_counts[i] = {}
        snapshot = self._snapshot() if self._snapshot else None
        if snapshot is not None:
            for ni in snapshot.node_info_list:
                if ni.node is None:
                    continue
                labels = node_labels(ni.node)
                for p in ni.pods:
                    for i, term in enumerate(req_aff):
                        if pod_matches_term(p, pod, term):
                            val = labels.get(term.topology_key)
                            s.aff_total[i] += 1
                            if val is not None:
                                s.aff_counts[i][val] = s.aff_counts[i].get(val, 0) + 1
                    for i, term in enumerate(req_anti):
                        if pod_matches_term(p, pod, term):
                            val = labels.get(term.topology_key)
                            if val is not None:
                                s.anti_counts[i][val] = s.anti_counts[i].get(val, 0) + 1
                # existing pods' anti-affinity terms that match the incoming pod
                for p in ni.pods_with_affinity:
                    for term in _existing_anti_terms(p):
                        if pod_matches_term(pod, p, term):
                            val = labels.get(term.topology_key)
                            if val is not None:
                                d = s.existing_anti.setdefault(term.topology_key, {})
                                d[val] = d.get(val, 0) + 1
        state.write(_STATE_KEY, s)
        return None

    def add_pod(self, state, pod_to_schedule, pod_to_add, node_info):
        self._update(state, pod_to_schedule, pod_to_add, node_info, +1)
        return None

    def remove_pod(self, state, pod_to_schedule, pod_to_remove, node_info):
        self._update(state, pod_to_schedule, pod_to_remove, node_info, -1)
        return None

    def _update(self, state, pod, other, node_info, delta):
        try:
            s: _AffinityState = state.read(_STATE_KEY)
        except KeyError:
            return
        if node_info.node is None:
            return
        labels = node_labels(node_info.node)
        req_aff, req_anti = _incoming_terms(pod)
        for i, term in enumerate(req_aff):
            if pod_matches_term(other, pod, term):
                val = labels.get(term.topology_key)
                s.aff_total[i] = s.aff_total.get(i, 0) + delta
                if val is not None:
                    s.aff_counts[i][val] = s.aff_counts[i].get(val, 0) + delta
        for i, term in enumerate(req_anti):
            if pod_matches_term(other, pod, term):
                val = labels.get(term.topology_key)
                if val is not None:
                    s.anti_counts[i][val] = s.anti_counts[i].get(val, 0) + delta
        for term in _existing_anti_terms(other):
            if pod_matches_term(pod, other, term):
                val = labels.get(term.topology_key)
                if val is not None:
                    d = s.existing_anti.setdefault(term.topology_key, {})
                    d[val] = d.get(val, 0) + delta

    def filter(self, state: CycleState, pod, node_info) -> Optional[Status]:
        try:
            s: _AffinityState = state.read(_STATE_KEY)
        except KeyError:
            return None
        labels = node_labels(node_info.node)
        req_aff, req_anti = _incoming_terms(pod)
        for i, term in enumerate(req_aff):
            val = labels.get(term.topology_key)
            cnt = s.aff_counts.get(i, {}).get(val, 0) if val is not None else 0
            if cnt > 0:
                continue
            if s.aff_total.get(i, 0) == 0 and s.aff_self.get(i) and val is not None:
                continue  # first-pod carve-out
            return Status.unschedulable("pod affinity not satisfied")
        for i, term in enumerate(req_anti):
            val = labels.get(term.topology_key)
            if val is not None and s.anti_counts.get(i, {}).get(val, 0) > 0:
                return Status.unschedulable("pod anti-affinity violated")
        for topo_key, domains in s.existing_anti.items():
            val = labels.get(topo_key)
            if val is not None and domains.get(val, 0) > 0:
                return Status.unschedulable(
                    "existing pods' anti-affinity rules violated"
                )
        return None

    # -- scoring -----------------------------------------------------------

    def score(self, state, pod, node_name, snapshot=None):
        """O(pods-on-relevant-nodes) walk mirroring the kernel's eterm +
        preferred-term accumulation (scoring.go:81-178)."""
        ni = snapshot.get(node_name)
        labels = node_labels(ni.node)
        total = 0.0
        aff = pod.spec.affinity
        pref_aff = list(aff.pod_affinity.preferred) if aff and aff.pod_affinity else []
        pref_anti = (
            list(aff.pod_anti_affinity.preferred)
            if aff and aff.pod_anti_affinity
            else []
        )
        # incoming pod's preferred terms vs all existing pods in same domain
        for other_ni in snapshot.node_info_list:
            if other_ni.node is None:
                continue
            olabels = node_labels(other_ni.node)
            for wt in pref_aff:
                val = labels.get(wt.term.topology_key)
                if val is not None and olabels.get(wt.term.topology_key) == val:
                    total += wt.weight * sum(
                        1 for p in other_ni.pods if pod_matches_term(p, pod, wt.term)
                    )
            for wt in pref_anti:
                val = labels.get(wt.term.topology_key)
                if val is not None and olabels.get(wt.term.topology_key) == val:
                    total -= wt.weight * sum(
                        1 for p in other_ni.pods if pod_matches_term(p, pod, wt.term)
                    )
            # existing pods' terms vs incoming pod
            for p in other_ni.pods_with_affinity:
                a = p.spec.affinity
                if a and a.pod_affinity:
                    for term in a.pod_affinity.required:
                        if self.hard_weight > 0 and pod_matches_term(pod, p, term):
                            val = labels.get(term.topology_key)
                            if val is not None and olabels.get(term.topology_key) == val:
                                total += self.hard_weight
                    for wt in a.pod_affinity.preferred:
                        if pod_matches_term(pod, p, wt.term):
                            val = labels.get(wt.term.topology_key)
                            if val is not None and olabels.get(wt.term.topology_key) == val:
                                total += wt.weight
                if a and a.pod_anti_affinity:
                    for wt in a.pod_anti_affinity.preferred:
                        if pod_matches_term(pod, p, wt.term):
                            val = labels.get(wt.term.topology_key)
                            if val is not None and olabels.get(wt.term.topology_key) == val:
                                total -= wt.weight
        return total, None

    def normalize_scores(self, state, pod, scores):
        mx = max((abs(s) for _, s in scores), default=0.0)
        for i, (n, s) in enumerate(scores):
            scores[i] = (n, s / mx * 100.0 if mx > 0 else 0.0)
        return None
