"""Policy-configured / legacy plugins: NodeLabel, ServiceAffinity,
NodeResourceLimits.

Reference: framework/plugins/nodelabel/node_label.go (policy-args label
presence filter + score), serviceaffinity/service_affinity.go (same-service
pods pinned to nodes agreeing on configured label keys), and
noderesources/resource_limits.go (prefer nodes satisfying the pod's
resource limits).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ....api import objects as v1
from ....api.resources import CPU, MEMORY, parse_quantity
from ..interface import (
    CycleState,
    FilterPlugin,
    PreFilterPlugin,
    PreScorePlugin,
    ScorePlugin,
    Status,
)
from .helpers import node_labels, services_matching_pod


class NodeLabel(FilterPlugin, ScorePlugin):
    """node_label.go:31 — filter on configured present/absent label keys,
    score on preferred presence/absence."""

    name = "NodeLabel"

    def __init__(
        self,
        present_labels: Optional[List[str]] = None,
        absent_labels: Optional[List[str]] = None,
        present_labels_preference: Optional[List[str]] = None,
        absent_labels_preference: Optional[List[str]] = None,
    ):
        self.present = present_labels or []
        self.absent = absent_labels or []
        self.present_pref = present_labels_preference or []
        self.absent_pref = absent_labels_preference or []

    def filter(self, state: CycleState, pod, node_info) -> Optional[Status]:
        labels = node_labels(node_info.node)
        for k in self.present:
            if k not in labels:
                return Status.unschedulable(
                    "node(s) didn't have the requested labels"
                )
        for k in self.absent:
            if k in labels:
                return Status.unschedulable(
                    "node(s) had the excluded labels"
                )
        return None

    def score(self, state, pod, node_name, snapshot=None):
        labels = node_labels(snapshot.get(node_name).node)
        total = len(self.present_pref) + len(self.absent_pref)
        if total == 0:
            return 0.0, None
        hits = sum(1 for k in self.present_pref if k in labels) + sum(
            1 for k in self.absent_pref if k not in labels
        )
        return hits * 100.0 / total, None


_SA_STATE_KEY = "PreFilterServiceAffinity"


class ServiceAffinity(PreFilterPlugin, FilterPlugin, ScorePlugin):
    """service_affinity.go — pods of one Service agree on the node values of
    the configured affinity label keys; score spreads by anti-affinity keys."""

    name = "ServiceAffinity"

    def __init__(
        self,
        services_lister=None,  # () -> List[v1.Service]
        snapshot_getter=None,  # () -> Snapshot
        affinity_labels: Optional[List[str]] = None,
        anti_affinity_labels_preference: Optional[List[str]] = None,
    ):
        self._services = services_lister
        self._snapshot = snapshot_getter
        self.affinity_labels = affinity_labels or []
        self.anti_pref = anti_affinity_labels_preference or []

    def _service_selectors(self, pod: v1.Pod) -> List[Dict[str, str]]:
        if self._services is None:
            return []
        return services_matching_pod(self._services(), pod)

    def _matching_pods_nodes(self, pod: v1.Pod) -> List[str]:
        """Node names hosting other pods matched by the same services."""
        snap = self._snapshot() if self._snapshot else None
        if snap is None:
            return []
        sels = self._service_selectors(pod)
        if not sels:
            return []
        nodes = []
        for ni in snap.node_info_list:
            for other in ni.pods:
                if other.metadata.namespace != pod.metadata.namespace:
                    continue
                if any(
                    all(
                        other.metadata.labels.get(k) == vv
                        for k, vv in sel.items()
                    )
                    for sel in sels
                ):
                    nodes.append(ni.name)
                    break
        return nodes

    def pre_filter(self, state: CycleState, pod) -> Optional[Status]:
        if not self.affinity_labels:
            return None
        snap = self._snapshot() if self._snapshot else None
        constraints: Dict[str, str] = {}
        if snap is not None:
            for node_name in self._matching_pods_nodes(pod):
                ni = snap.get(node_name)
                if ni is None:
                    continue
                labels = node_labels(ni.node)
                for k in self.affinity_labels:
                    if k in labels:
                        constraints.setdefault(k, labels[k])
        state.write(_SA_STATE_KEY, constraints)
        return None

    def filter(self, state: CycleState, pod, node_info) -> Optional[Status]:
        if not self.affinity_labels:
            return None
        try:
            constraints: Dict[str, str] = state.read(_SA_STATE_KEY)
        except KeyError:
            constraints = {}
        labels = node_labels(node_info.node)
        for k, want in constraints.items():
            if labels.get(k) != want:
                return Status.unschedulable(
                    "node(s) didn't match service affinity"
                )
        return None

    def score(self, state, pod, node_name, snapshot=None):
        if not self.anti_pref:
            return 0.0, None
        ni = snapshot.get(node_name)
        labels = node_labels(ni.node)
        busy = self._matching_pods_nodes(pod)
        if not busy:
            return 100.0, None
        # fewer same-service pods sharing this node's label values → higher
        count = 0
        for other_name in busy:
            other = snapshot.get(other_name)
            if other is None:
                continue
            olabels = node_labels(other.node)
            if all(
                labels.get(k) == olabels.get(k) for k in self.anti_pref
            ):
                count += 1
        return max(0.0, 100.0 - count * 10.0), None


_RL_STATE_KEY = "PreScoreNodeResourceLimits"


class NodeResourceLimits(PreScorePlugin, ScorePlugin):
    """resource_limits.go:40 — one point per resource (cpu, memory) whose
    pod-level limit the node can satisfy."""

    name = "NodeResourceLimits"

    def pre_score(self, state: CycleState, pod, nodes) -> Optional[Status]:
        cpu = 0.0
        mem = 0.0
        for c in pod.spec.containers:
            cpu += parse_quantity(c.limits.get(CPU, 0)) if c.limits else 0.0
            mem += parse_quantity(c.limits.get(MEMORY, 0)) if c.limits else 0.0
        state.write(_RL_STATE_KEY, (cpu, mem))
        return None

    def score(self, state, pod, node_name, snapshot=None):
        try:
            cpu, mem = state.read(_RL_STATE_KEY)
        except KeyError:
            cpu, mem = 0.0, 0.0
        alloc = snapshot.get(node_name).allocatable
        score = 0
        if cpu > 0 and alloc.get(CPU, 0) >= cpu:
            score += 1
        if mem > 0 and alloc.get(MEMORY, 0) >= mem:
            score += 1
        return float(score), None
