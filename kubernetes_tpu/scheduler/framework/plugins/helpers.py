"""Shared matching helpers used by several plugins.

The semantics here are the single source of truth shared with the device
encoder/kernels: node labels include a defaulted kubernetes.io/hostname
pseudo-label (the encoder does the same, ops/encoding.py _write_node_row),
and node-selector matching mirrors v1helper.MatchNodeSelectorTerms as used
by reference nodeaffinity/node_affinity.go:54.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

from ....api import objects as v1
from ....api.selectors import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_GT,
    OP_IN,
    OP_LT,
    OP_NOT_IN,
)


def services_matching_pod(services, pod: v1.Pod):
    """Selectors (raw dicts) of Services selecting the pod — the shared core
    of SelectorSpread's getSelectors and ServiceAffinity
    (default_pod_topology_spread.go:43, service_affinity.go)."""
    out = []
    for svc in services:
        if svc.metadata.namespace != pod.metadata.namespace:
            continue
        sel = svc.spec.selector
        if sel and all(
            pod.metadata.labels.get(k) == vv for k, vv in sel.items()
        ):
            out.append(sel)
    return out


def node_labels(node: v1.Node) -> Dict[str, str]:
    labels = dict(node.metadata.labels)
    labels.setdefault("kubernetes.io/hostname", node.metadata.name)
    return labels


def _req_matches(labels: Dict[str, str], r: v1.NodeSelectorRequirement) -> bool:
    has = r.key in labels
    if r.operator == OP_IN:
        return has and labels[r.key] in r.values
    if r.operator == OP_NOT_IN:
        return not (has and labels[r.key] in r.values)
    if r.operator == OP_EXISTS:
        return has
    if r.operator == OP_DOES_NOT_EXIST:
        return not has
    if r.operator in (OP_GT, OP_LT):
        if not has:
            return False
        try:
            lv, rv = int(labels[r.key]), int(r.values[0])
        except (ValueError, IndexError):
            return False
        return lv > rv if r.operator == OP_GT else lv < rv
    return False


def node_matches_term(node: v1.Node, term: v1.NodeSelectorTerm) -> bool:
    """Empty term (no expressions, no fields) matches nothing."""
    if not term.match_expressions and not term.match_fields:
        return False
    labels = node_labels(node)
    for r in term.match_expressions:
        if not _req_matches(labels, r):
            return False
    for mf in term.match_fields:
        if mf.key != "metadata.name":
            return False
        if mf.operator == OP_IN:
            if node.metadata.name not in mf.values:
                return False
        elif mf.operator == OP_NOT_IN:
            if node.metadata.name in mf.values:
                return False
        else:
            return False
    return True


def pod_matches_node_selector(pod: v1.Pod, node: v1.Node) -> bool:
    """nodeSelector AND (OR over required nodeSelectorTerms) —
    PodMatchesNodeSelectorAndAffinityTerms."""
    labels = node_labels(node)
    for k, val in pod.spec.node_selector.items():
        if labels.get(k) != val:
            return False
    aff = pod.spec.affinity.node_affinity if pod.spec.affinity else None
    if aff and aff.required and aff.required.terms:
        if not any(node_matches_term(node, t) for t in aff.required.terms):
            return False
    return True


def term_namespaces(pod: v1.Pod, term: v1.PodAffinityTerm) -> frozenset:
    return frozenset(term.namespaces) if term.namespaces else frozenset(
        {pod.metadata.namespace}
    )


def pod_matches_term(
    target: v1.Pod, source_pod: v1.Pod, term: v1.PodAffinityTerm
) -> bool:
    """Does `target` match `term` (owned by source_pod, for ns defaulting)?"""
    if target.metadata.namespace not in term_namespaces(source_pod, term):
        return False
    if term.label_selector is None:
        return False
    return term.label_selector.matches(target.metadata.labels)
