"""Small plugins: NodeName, NodePorts, NodeUnschedulable, ImageLocality,
NodePreferAvoidPods, PrioritySort, DefaultBinder, SelectorSpread.

References: nodename/node_name.go:59, nodeports/node_ports.go:36,
nodeunschedulable/node_unschedulable.go:37, imagelocality/image_locality.go:47,
nodepreferavoidpods/node_prefer_avoid_pods.go:39, queuesort/priority_sort.go:42,
defaultbinder/default_binder.go:50, defaultpodtopologyspread/ (SelectorSpread).
"""

from __future__ import annotations

from typing import Optional

from ....api import objects as v1
from ....api.objects import (
    Binding,
    Taint,
    TAINT_NODE_UNSCHEDULABLE,
    TAINT_NO_SCHEDULE,
    pod_host_ports,
    tolerations_tolerate_taint,
)
from ....api.selectors import selector_from_match_labels
from ....client.apiserver import LeaderFenced
from ....runtime.consensus import DegradedWrites
from ..interface import (
    BindPlugin,
    CycleState,
    FilterPlugin,
    PreFilterPlugin,
    QueueSortPlugin,
    ScorePlugin,
    Status,
)

IMG_MIN_THRESHOLD = 23 * 1024 * 1024
IMG_MAX_THRESHOLD = 1000 * 1024 * 1024


class NodeName(FilterPlugin):
    name = "NodeName"

    def filter(self, state, pod, node_info) -> Optional[Status]:
        if pod.spec.node_name and pod.spec.node_name != node_info.name:
            return Status.unresolvable("node didn't match the requested hostname")
        return None


class NodePorts(PreFilterPlugin, FilterPlugin):
    name = "NodePorts"
    _STATE_KEY = "PreFilterNodePorts"

    def pre_filter(self, state, pod) -> Optional[Status]:
        state.write(self._STATE_KEY, pod_host_ports(pod))
        return None

    def filter(self, state, pod, node_info) -> Optional[Status]:
        try:
            want = state.read(self._STATE_KEY)
        except KeyError:
            want = pod_host_ports(pod)
        for hp in want:
            if node_info.used_ports.get(hp, 0) > 0:
                return Status.unschedulable("node didn't have free ports")
            # wildcard-IP overlap: 0.0.0.0 conflicts with any IP on same
            # (proto, port) and vice versa
            ip, proto, port = hp
            for (uip, uproto, uport), c in node_info.used_ports.items():
                if c > 0 and uproto == proto and uport == port and (
                    ip == "0.0.0.0" or uip == "0.0.0.0" or uip == ip
                ):
                    return Status.unschedulable("node didn't have free ports")
        return None


class NodeUnschedulable(FilterPlugin):
    name = "NodeUnschedulable"

    def filter(self, state, pod, node_info) -> Optional[Status]:
        if node_info.node.spec.unschedulable and not tolerations_tolerate_taint(
            pod.spec.tolerations,
            Taint(TAINT_NODE_UNSCHEDULABLE, "", TAINT_NO_SCHEDULE),
        ):
            return Status.unresolvable("node(s) were unschedulable")
        return None


class ImageLocality(ScorePlugin):
    name = "ImageLocality"

    def score(self, state, pod, node_name, snapshot=None):
        ni = snapshot.get(node_name)
        total_nodes = max(len(snapshot.node_info_list), 1)
        node_images = {}
        for img in ni.node.status.images:
            for nm in img.names:
                node_images[nm] = img.size_bytes
        total = 0.0
        for c in pod.spec.containers:
            if c.image and c.image in node_images:
                spread = (
                    sum(
                        1
                        for other in snapshot.node_info_list
                        if any(
                            c.image in im.names for im in other.node.status.images
                        )
                    )
                    / total_nodes
                )
                total += node_images[c.image] * spread
        score = (total - IMG_MIN_THRESHOLD) / (IMG_MAX_THRESHOLD - IMG_MIN_THRESHOLD) * 100.0
        return max(0.0, min(100.0, score)), None


class NodePreferAvoidPods(ScorePlugin):
    name = "NodePreferAvoidPods"

    def score(self, state, pod, node_name, snapshot=None):
        ni = snapshot.get(node_name)
        ann = ni.node.metadata.annotations.get(
            "scheduler.alpha.kubernetes.io/preferAvoidPods", ""
        )
        refs = {r.strip() for r in ann.split(",") if r.strip()}
        ctrl = next(
            (f"{r.kind}/{r.name}" for r in pod.metadata.owner_references if r.controller),
            None,
        )
        return (0.0 if ctrl and ctrl in refs else 100.0), None


class PrioritySort(QueueSortPlugin):
    """priority desc, then FIFO timestamp (priority_sort.go:42-46)."""

    name = "PrioritySort"

    def less(self, pi1, pi2) -> bool:
        p1, p2 = pi1.pod.priority, pi2.pod.priority
        if p1 != p2:
            return p1 > p2
        return pi1.timestamp < pi2.timestamp


class DefaultBinder(BindPlugin):
    name = "DefaultBinder"

    def __init__(self, server=None):
        # the scheduler injects its _FencedBindSurface here (the fence-
        # attaching seam); a raw server appears only in fence-less direct
        # framework construction (tests, non-HA embedders)
        self._server = server

    def bind(self, state, pod, node_name) -> Optional[Status]:
        if self._server is None:
            return Status.error("no API server")
        try:
            self._server.bind_pod(  # graftlint: fence-exempt(the injected surface IS the fenced seam — _FencedBindSurface routes into _bind_pods_fenced)
                Binding(
                    pod_name=pod.metadata.name,
                    pod_namespace=pod.metadata.namespace,
                    pod_uid=pod.metadata.uid,
                    target_node=node_name,
                )
            )
        except (DegradedWrites, LeaderFenced):
            # typed outcomes the binding cycle handles itself: park the
            # placement (degraded ride-through) / drop it (zombie fence).
            # Folding either into a generic error Status would turn a
            # retryable outage into a failed pod — or a fence rejection
            # into a requeue that races the new leader.
            raise
        except Exception as e:  # Conflict / NotFound
            return Status.error(str(e))
        return None


class SelectorSpread(ScorePlugin):
    """DefaultPodTopologySpread: fewer same-controller pods → higher score,
    zone-weighted 2/3 (default_pod_topology_spread.go:43,118).

    Selectors come from Services/RCs/RSs/StatefulSets matching the pod; here
    they are derived from a lister callable injected at construction."""

    name = "DefaultPodTopologySpread"
    ZONE_WEIGHT = 2.0 / 3.0
    ZONE_KEY = "topology.kubernetes.io/zone"

    def __init__(self, selectors_for_pod=None):
        # callable(pod) -> list[LabelSelector]; defaults to owner-based
        self._selectors = selectors_for_pod

    def _pod_selectors(self, pod):
        if self._selectors is not None:
            return self._selectors(pod)
        if pod.metadata.labels:
            return [selector_from_match_labels(pod.metadata.labels)]
        return []

    def _count(self, pod, selectors, ni) -> int:
        cnt = 0
        for p in ni.pods:
            if p.metadata.namespace != pod.metadata.namespace:
                continue
            if any(sel.matches(p.metadata.labels) for sel in selectors):
                cnt += 1
        return cnt

    def score(self, state, pod, node_name, snapshot=None):
        selectors = self._pod_selectors(pod)
        if not selectors:
            return 0.0, None
        ni = snapshot.get(node_name)
        return float(self._count(pod, selectors, ni)), None

    def normalize_scores(self, state, pod, scores):
        # raw = node match counts; invert & zone-weight like
        # CalculateSpreadPriority's finalization
        mx = max((s for _, s in scores), default=0.0)
        node_score = {
            n: ((mx - s) / mx * 100.0 if mx > 0 else 100.0) for n, s in scores
        }
        scores[:] = [(n, node_score[n]) for n, _ in scores]
        return None
