"""NodeResources plugins: Fit filter + the four scoring strategies.

Reference: framework/plugins/noderesources/{fit,least_allocated,
most_allocated,balanced_allocation,requested_to_capacity_ratio}.go.
Score formulas normalized to 0..100 (MAX_NODE_SCORE) like the originals.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ....api.objects import compute_pod_resource_request
from ....api.resources import CPU, MEMORY, PODS, ResourceList
from ..interface import (
    CycleState,
    FilterPlugin,
    PreFilterPlugin,
    ScorePlugin,
    Status,
)

_FIT_STATE_KEY = "PreFilterNodeResourcesFit"


class NodeResourcesFit(PreFilterPlugin, FilterPlugin):
    """fit.go:119 (PreFilter computes pod request once), fit.go:177-250
    (Filter: insufficient if podRequest > allocatable - requested).

    `ignored_resources` carries extender managedResources flagged
    ignoredByScheduler (fit.go IgnoredResources): the extender owns
    accounting for those, so the in-tree fit check must skip them."""

    name = "NodeResourcesFit"

    def __init__(self, ignored_resources=None):
        self.ignored = frozenset(ignored_resources or ())

    def pre_filter(self, state: CycleState, pod) -> Optional[Status]:
        req = compute_pod_resource_request(pod)
        for name in self.ignored:
            req.pop(name, None)
        state.write(_FIT_STATE_KEY, req)
        return None

    def has_extensions(self) -> bool:
        return True

    def add_pod(self, state, pod_to_schedule, pod_to_add, node_info):
        return None  # request of pod being scheduled is unaffected

    def remove_pod(self, state, pod_to_schedule, pod_to_remove, node_info):
        return None

    def filter(self, state: CycleState, pod, node_info) -> Optional[Status]:
        try:
            req: ResourceList = state.read(_FIT_STATE_KEY)
        except KeyError:
            req = compute_pod_resource_request(pod)
            for name in self.ignored:
                req.pop(name, None)
        alloc = node_info.allocatable
        used = node_info.requested
        # pods-count check (fit.go:205)
        if len(node_info.pods) + 1 > alloc.get(PODS, 110):
            return Status.unschedulable("Too many pods")
        for name, want in req.items():
            if want == 0:
                continue
            if want > alloc.get(name, 0) - used.get(name, 0):
                return Status.unschedulable(f"Insufficient {name}")
        return None


def _fractions(pod, node_info) -> Tuple[float, float]:
    """cpu/mem utilization fractions including the incoming pod's non-zero
    request (least_allocated.go:77-99 semantics)."""
    req = compute_pod_resource_request(pod, non_zero=True)
    alloc = node_info.allocatable
    used = node_info.non_zero_requested
    out = []
    for name in (CPU, MEMORY):
        cap = max(alloc.get(name, 0), 1)
        u = used.get(name, 0) + req.get(name, 0)
        out.append(min(u / cap, 1.0))
    return out[0], out[1]


class NodeResourcesLeastAllocated(ScorePlugin):
    """(cap-req)*100/cap averaged over cpu+memory (least_allocated.go:45)."""

    name = "NodeResourcesLeastAllocated"

    def score(self, state, pod, node_name, snapshot=None):
        ni = snapshot.get(node_name)
        cpu_f, mem_f = _fractions(pod, ni)
        return ((1.0 - cpu_f) * 100.0 + (1.0 - mem_f) * 100.0) / 2.0, None


class NodeResourcesMostAllocated(ScorePlugin):
    """req*100/cap averaged (most_allocated.go:75-102)."""

    name = "NodeResourcesMostAllocated"

    def score(self, state, pod, node_name, snapshot=None):
        ni = snapshot.get(node_name)
        cpu_f, mem_f = _fractions(pod, ni)
        return (cpu_f * 100.0 + mem_f * 100.0) / 2.0, None


class NodeResourcesBalancedAllocation(ScorePlugin):
    """(1 - |cpuFrac - memFrac|) * 100 (balanced_allocation.go:41)."""

    name = "NodeResourcesBalancedAllocation"

    def score(self, state, pod, node_name, snapshot=None):
        ni = snapshot.get(node_name)
        cpu_f, mem_f = _fractions(pod, ni)
        return (1.0 - abs(cpu_f - mem_f)) * 100.0, None


class RequestedToCapacityRatio(ScorePlugin):
    """Piecewise-linear function of utilization
    (requested_to_capacity_ratio.go:33). Default shape {0%:0, 100%:10}
    scaled to 0..100; custom shape points configurable."""

    name = "RequestedToCapacityRatio"

    def __init__(self, shape: Optional[List[Tuple[float, float]]] = None):
        # (utilization %, score 0..10) points, sorted by utilization
        self.shape = sorted(shape or [(0.0, 0.0), (100.0, 10.0)])

    def _interp(self, util: float) -> float:
        pts = self.shape
        if util <= pts[0][0]:
            return pts[0][1]
        for (x0, y0), (x1, y1) in zip(pts, pts[1:]):
            if util <= x1:
                if x1 == x0:
                    return y1
                return y0 + (y1 - y0) * (util - x0) / (x1 - x0)
        return pts[-1][1]

    def score(self, state, pod, node_name, snapshot=None):
        ni = snapshot.get(node_name)
        cpu_f, mem_f = _fractions(pod, ni)
        util = (cpu_f + mem_f) / 2.0 * 100.0
        return self._interp(util) * 10.0, None
