"""Host-side (in-tree) plugins.

One class per registered plugin name of the reference
(framework/plugins/registry.go:46-77). These are the oracle the device
kernels are differentially tested against, the fallback path for pods that
overflow the static device encoding, and the evaluation engine for
preemption what-ifs.
"""

from .helpers import node_labels, pod_matches_node_selector  # noqa: F401
from .noderesources import (  # noqa: F401
    NodeResourcesFit,
    NodeResourcesLeastAllocated,
    NodeResourcesMostAllocated,
    NodeResourcesBalancedAllocation,
    RequestedToCapacityRatio,
)
from .nodeaffinity import NodeAffinityPlugin  # noqa: F401
from .tainttoleration import TaintTolerationPlugin  # noqa: F401
from .podtopologyspread import PodTopologySpreadPlugin  # noqa: F401
from .interpodaffinity import InterPodAffinityPlugin  # noqa: F401
from .misc import (  # noqa: F401
    NodeName,
    NodePorts,
    NodeUnschedulable,
    ImageLocality,
    NodePreferAvoidPods,
    PrioritySort,
    DefaultBinder,
    SelectorSpread,
)
from .storage import (  # noqa: F401
    AzureDiskLimits,
    CinderLimits,
    EBSLimits,
    GCEPDLimits,
    NodeVolumeLimits,
    VolumeBinding,
    VolumeRestrictions,
    VolumeZone,
)
from .extended import (  # noqa: F401
    NodeLabel,
    NodeResourceLimits,
    ServiceAffinity,
)
from .coscheduling import Coscheduling  # noqa: F401
