"""HTTP scheduler extenders: out-of-process filter/prioritize/bind/preempt.

Reference: pkg/scheduler/core/extender.go (HTTPExtender:91, Filter:334,
Prioritize, Bind:404, ProcessPreemption:214) and the v1 extender API types
(pkg/scheduler/apis/extender/v1). JSON over HTTP POST, one verb per
capability; an extender advertises interest via managed resources and can be
`ignorable` (failures don't fail the pod).

Extender-interested pods run the host scheduling path: the device lattice
narrows nothing for an out-of-process veto, mirroring how the reference
serializes extender calls after its in-process filters
(generic_scheduler.go:421,502).
"""

from __future__ import annotations

import json
import threading
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..api import objects as v1


@dataclass
class ExtenderManagedResource:
    name: str = ""
    ignored_by_scheduler: bool = False


@dataclass
class ExtenderConfig:
    """KubeSchedulerConfiguration.extenders entry (apis/config/types.go
    Extender / legacy Policy ExtenderConfig)."""

    url_prefix: str = ""
    filter_verb: str = ""
    prioritize_verb: str = ""
    bind_verb: str = ""
    preempt_verb: str = ""
    weight: float = 1.0
    http_timeout: float = 30.0
    node_cache_capable: bool = False
    managed_resources: List[ExtenderManagedResource] = field(default_factory=list)
    ignorable: bool = False


class ExtenderError(Exception):
    pass


def _pod_dict(pod: v1.Pod) -> dict:
    return {
        "metadata": {
            "name": pod.metadata.name,
            "namespace": pod.metadata.namespace,
            "uid": pod.metadata.uid,
            "labels": dict(pod.metadata.labels),
        },
        "spec": {
            "nodeName": pod.spec.node_name,
            "schedulerName": pod.spec.scheduler_name,
            "containers": [
                {"name": c.name, "resources": {"requests": dict(c.requests)}}
                for c in pod.spec.containers
            ],
        },
    }


class HTTPExtender:
    """One configured extender endpoint (extender.go:91 NewHTTPExtender)."""

    def __init__(self, cfg: ExtenderConfig):
        self.cfg = cfg

    # -- capability probes ---------------------------------------------------

    @property
    def name(self) -> str:
        return self.cfg.url_prefix

    def is_ignorable(self) -> bool:
        return self.cfg.ignorable

    def is_binder(self) -> bool:
        return bool(self.cfg.bind_verb)

    def supports_preemption(self) -> bool:
        return bool(self.cfg.preempt_verb)

    def is_interested(self, pod: v1.Pod) -> bool:
        """IsInterested (extender.go:441): no managed resources => all pods;
        otherwise pods requesting one of them."""
        if not self.cfg.managed_resources:
            return True
        managed = {m.name for m in self.cfg.managed_resources}
        for c in list(pod.spec.containers) + list(pod.spec.init_containers):
            if any(r in managed for r in c.requests):
                return True
        return False

    # -- transport -----------------------------------------------------------

    def _post(self, verb: str, payload: dict) -> dict:
        url = f"{self.cfg.url_prefix.rstrip('/')}/{verb}"
        data = json.dumps(payload).encode()
        req = urllib.request.Request(
            url, data=data, headers={"Content-Type": "application/json"}
        )
        with urllib.request.urlopen(req, timeout=self.cfg.http_timeout) as resp:
            return json.loads(resp.read().decode() or "{}")

    # -- verbs ---------------------------------------------------------------

    def _node_args(self, node_names: Sequence[str]) -> dict:
        """nodeCacheCapable extenders receive names only; others get node
        objects (ExtenderArgs.Nodes vs NodeNames, extender.go:334)."""
        if self.cfg.node_cache_capable:
            return {"nodenames": list(node_names)}
        return {
            "nodes": {
                "items": [{"metadata": {"name": n}} for n in node_names]
            }
        }

    def filter(
        self, pod: v1.Pod, node_names: Sequence[str]
    ) -> Tuple[List[str], Dict[str, str]]:
        """(feasible node names, failed node -> reason). Raises on transport
        error (caller applies `ignorable`)."""
        if not self.cfg.filter_verb:
            return list(node_names), {}
        payload = {"pod": _pod_dict(pod)}
        payload.update(self._node_args(node_names))
        result = self._post(self.cfg.filter_verb, payload)
        if result.get("error"):
            raise ExtenderError(result["error"])
        feasible = result.get("nodenames")
        if feasible is None and result.get("nodes") is not None:
            feasible = [
                item["metadata"]["name"]
                for item in result["nodes"].get("items", [])
            ]
        if feasible is None:
            feasible = list(node_names)
        failed = result.get("failedNodes") or {}
        return list(feasible), dict(failed)

    def prioritize(
        self, pod: v1.Pod, node_names: Sequence[str]
    ) -> Dict[str, float]:
        """node -> weighted score (Prioritize + weight, extender.go:372)."""
        if not self.cfg.prioritize_verb:
            return {}
        payload = {"pod": _pod_dict(pod)}
        payload.update(self._node_args(node_names))
        result = self._post(self.cfg.prioritize_verb, payload)
        out: Dict[str, float] = {}
        for entry in result or []:
            out[entry["host"]] = entry["score"] * self.cfg.weight
        return out

    def bind(self, pod: v1.Pod, node_name: str) -> None:
        result = self._post(
            self.cfg.bind_verb,
            {
                "podName": pod.metadata.name,
                "podNamespace": pod.metadata.namespace,
                "podUID": pod.metadata.uid,
                "node": node_name,
            },
        )
        if result.get("error"):
            raise ExtenderError(result["error"])

    def process_preemption(
        self,
        pod: v1.Pod,
        victims_by_node: Dict[str, List[v1.Pod]],
    ) -> Dict[str, List[str]]:
        """node -> victim pod names the extender accepts
        (ProcessPreemption, extender.go:214)."""
        if not self.cfg.preempt_verb:
            return {
                node: [p.metadata.name for p in victims]
                for node, victims in victims_by_node.items()
            }
        result = self._post(
            self.cfg.preempt_verb,
            {
                "pod": _pod_dict(pod),
                "nodeNameToVictims": {
                    node: {"pods": [_pod_dict(p) for p in victims]}
                    for node, victims in victims_by_node.items()
                },
            },
        )
        out: Dict[str, List[str]] = {}
        for node, victims in (result.get("nodeNameToVictims") or {}).items():
            out[node] = [
                p["metadata"]["name"] for p in victims.get("pods", [])
            ]
        return out


def build_extenders(configs: Sequence[ExtenderConfig]) -> List[HTTPExtender]:
    return [HTTPExtender(c) for c in configs]
