"""Snapshot anti-entropy: background audit + repair of the HBM device state.

The data plane trusts two incremental protocols: the encoder's host masters
(per-pod add/remove deltas over numpy aggregates) and the device snapshot
(row scatters + kernel-committed occupancy). PRs 1 and 3 gave the API store
a detect → quarantine → repair → resume discipline; this module gives the
same to the device state, because a single silently-drifted row mis-places
every pod that scores that node until the process restarts.

Each audit pass (period `period_s`, under the cache lock, only while the
wave pipeline is quiescent — an in-flight batch legitimately holds device
commits the masters haven't replayed yet; that gate is SEMANTIC only:
mechanically the audit's row gather runs under a generation pin
(`SnapshotEncoder.pin_generation`), which a concurrent donating wave
launch cannot invalidate — it advances through a copy while the pinned
generation keeps serving the gather):

  1. **settle** — flush pending deltas so any remaining diff is drift, not
     an expected in-flight update;
  2. **master self-check** — re-encode the sampled rows' pod aggregates
     from the per-pod entries (`SnapshotEncoder.expected_row_aggregates`)
     and repair masters that drifted (an incremental-encoder bug or a
     half-applied update);
  3. **device diff** — fetch the sampled rows of every row-major device
     field in one transfer and compare column-wise against the masters
     (per-row checksums keyed by the cache generation: a row whose
     generation moved since the last pass gets a fresh baseline);
  4. **repair** — drifted rows are marked dirty and re-scattered by an
     immediate flush (targeted repair), then re-fetched to confirm;
  5. **escalate** — a row still wrong after its re-scatter, or
     `rebuild_after` consecutive drifting passes, forces a full snapshot
     rebuild (`invalidate_device` + flush) — device memory is a
     rebuildable cache (SURVEY.md §5).

Rows flagged by failure paths (`SnapshotEncoder.suspect_rows`, e.g. the
bulk-assume per-pod fallback) are audited first, every pass.

Counters/gauges (rendered by /metrics and the SIGUSR2 debugger dump):
  snapshot_drift_rows_total{column}   drifted row-columns detected
  snapshot_repaired_rows_total        rows repaired by targeted re-scatter
  snapshot_rebuilds_total             full-rebuild escalations
  snapshot_audit_passes_total         completed audit passes
  snapshot_audit_drift_rows           rows drifted in the LAST pass (gauge)
  snapshot_audit_consecutive_drift    consecutive drifting passes (gauge)

The generation-lifecycle series (`snapshot_generation_*`, emitted by
ops/encoding.py: current id, pinned readers, retiring count, retired /
copy-on-pin / retire-stall counters, retirement-latency histogram) render
through the same `snapshot_` dump prefix, so a stuck reader pin is
observable in the SIGUSR2 dump, never a silent HBM leak.
"""

from __future__ import annotations

import contextlib
import logging
import threading
from typing import Callable, Dict, List, Optional

import numpy as np

from ..utils.metrics import metrics

logger = logging.getLogger("kubernetes_tpu.scheduler.antientropy")

COUNTER_DRIFT_ROWS = "snapshot_drift_rows_total"  # label: column
COUNTER_REPAIRED = "snapshot_repaired_rows_total"
COUNTER_REBUILDS = "snapshot_rebuilds_total"
COUNTER_PASSES = "snapshot_audit_passes_total"
GAUGE_LAST_DRIFT = "snapshot_audit_drift_rows"
GAUGE_CONSECUTIVE = "snapshot_audit_consecutive_drift"


class SnapshotAntiEntropy:
    """Periodic auditor for one SnapshotEncoder. `lock` (the scheduler
    cache's RLock) serializes against every other encoder writer;
    `quiesced` must return False while kernel-committed device state is
    legitimately ahead of the host masters (in-flight wave batches)."""

    def __init__(
        self,
        encoder: "SnapshotEncoder",
        lock=None,
        quiesced: Optional[Callable[[], bool]] = None,
        period_s: float = 5.0,
        sample_rows: int = 64,
        rebuild_after: int = 3,
    ):
        self.encoder = encoder
        self.lock = lock if lock is not None else contextlib.nullcontext()
        self.quiesced = quiesced
        self.period_s = period_s
        self.sample_rows = max(1, sample_rows)
        self.rebuild_after = max(1, rebuild_after)
        self._cursor = 0  # round-robin over live rows across passes
        self._consecutive_drift = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        def loop():
            while not self._stop.wait(self.period_s):
                try:
                    self.audit_once()
                except Exception:
                    # an audit failure must never take the process down —
                    # it is a diagnostic/repair loop, not a dependency
                    logger.exception("anti-entropy audit pass failed")
        self._thread = threading.Thread(
            target=loop, daemon=True, name="snapshot-antientropy"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    # -- one pass ------------------------------------------------------------

    def _pick_rows(self, enc) -> List[int]:
        """Suspect rows first, then a round-robin window over the live
        rows so every row is audited within n/sample passes. The suspect
        set is NOT drained here — audit_once clears it only after the
        pass completes, so a mid-pass device error (fetch/flush raising)
        can't silently discard failure-flagged rows."""
        rows: List[int] = sorted(
            r for r in enc.suspect_rows if r < len(enc.row_names)
        )
        live = [
            r for r, name in enumerate(enc.row_names) if name is not None
        ]
        if live:
            start = self._cursor % len(live)
            take = min(self.sample_rows, len(live))
            window = [live[(start + i) % len(live)] for i in range(take)]
            self._cursor = (start + take) % len(live)
            rows.extend(r for r in window if r not in rows)
        return rows

    def audit_once(self) -> Dict[str, object]:
        """One audit/repair pass; returns a report dict (tests + SIGUSR2).

        Every device write in this pass goes through
        ``flush(donate=False)`` — the alias-free ``_scatter_rows_safe``
        program — so the auditor can never donate (and thereby corrupt)
        the live snapshot it is repairing. No donation site remains in
        this body, so no alias-safe marker is needed (the stale-pragma
        audit retired the one that used to sit here)."""
        enc = self.encoder
        # the retire-stall watchdog otherwise only runs on new lease
        # traffic: sweep it from this periodic pass (before any skip
        # path) so a leaked reader pin on an idle encoder still surfaces
        # in /metrics instead of silently holding its HBM generation
        enc.check_retire_stalls()
        report: Dict[str, object] = {
            "rows_audited": 0,
            "master_repaired": [],
            "device_drift": {},
            "rebuilt": False,
            "skipped": None,
        }
        with self.lock:
            if self.quiesced is not None and not self.quiesced():
                report["skipped"] = "pipeline busy"
                return report
            if enc._device is None:
                report["skipped"] = "no device snapshot"
                return report
            generation = enc.generation
            # settle pending deltas: after this flush, any device/master
            # difference is drift by definition. donate=False throughout
            # the audit: repair/settle scatters use the alias-free program
            # so the auditor can never corrupt the state it is fixing (the
            # donating in-place variant has been observed writing garbage
            # when deserialized from a persistent compilation cache).
            if enc.has_pending_updates:
                enc.flush(donate=False)
            rows = self._pick_rows(enc)
            if not rows:
                report["skipped"] = "no live rows"
                return report
            report["rows_audited"] = len(rows)
            report["generation"] = generation

            # 2) master self-check against entry-derived expectations
            for r in rows:
                bad = enc.verify_row_aggregates(r, repair=True)
                if bad:
                    report["master_repaired"].append((r, bad))
                    for col in bad:
                        metrics.inc(COUNTER_DRIFT_ROWS, {"column": col})
            if report["master_repaired"]:
                logger.warning(
                    "anti-entropy: master aggregates drifted on rows %s "
                    "(repaired from pod entries)",
                    report["master_repaired"],
                )

            # 3) device diff, column-wise
            drifted = self._device_diff(enc, rows, report["device_drift"])

            # 4) targeted repair: dirty rows (master repairs + device
            # drift) re-scatter in one flush, then confirm
            if drifted:
                for r in drifted:
                    enc._dirty_rows.add(r)
            if enc.has_pending_updates:
                enc.flush(donate=False)
            still_bad: List[int] = []
            if drifted:
                # the confirm re-fetch must not double-bump the drift
                # counters (same rows, same pass), and only rows whose
                # re-scatter actually STUCK count as repaired
                still_bad = self._device_diff(
                    enc, sorted(drifted), {}, count=False
                )
                repaired = len(drifted) - len(still_bad)
                if repaired:
                    metrics.inc(COUNTER_REPAIRED, by=float(repaired))

            # 5) escalation: re-scatter didn't stick, or drift keeps
            # coming back pass after pass
            any_drift = bool(drifted or report["master_repaired"])
            self._consecutive_drift = (
                self._consecutive_drift + 1 if any_drift else 0
            )
            if still_bad or self._consecutive_drift >= self.rebuild_after:
                logger.error(
                    "anti-entropy: escalating to full snapshot rebuild "
                    "(unrepaired rows=%s, consecutive drifting passes=%d)",
                    still_bad,
                    self._consecutive_drift,
                )
                enc.invalidate_device()
                enc.flush(donate=False)
                metrics.inc(COUNTER_REBUILDS)
                report["rebuilt"] = True
                self._consecutive_drift = 0

            # pass complete: every suspect row was audited (or is a stale
            # index past the row table) — safe to drain now. The lock is
            # held for the whole pass, so nothing was flagged concurrently.
            enc.suspect_rows.clear()
            metrics.inc(COUNTER_PASSES)
            metrics.set_gauge(GAUGE_LAST_DRIFT, float(len(drifted)))
            metrics.set_gauge(
                GAUGE_CONSECUTIVE, float(self._consecutive_drift)
            )
        return report

    @staticmethod
    def _device_diff(
        enc, rows: List[int], out: Dict[str, List[int]], count: bool = True
    ) -> set:
        """Compare fetched device rows against the masters column-wise;
        fills `out` (field -> drifted row list), returns the drifted row
        set and bumps the per-column drift counters (`count=False` for
        the post-repair confirm fetch, which re-reads the same rows)."""
        drifted: set = set()
        fetched = enc.fetch_device_rows(rows)
        if fetched is None:
            return drifted
        idx = np.asarray(rows, np.int64)
        for field, dev in fetched.items():
            master = enc._master_of(field)[idx]
            dev = np.asarray(dev)
            if dev.shape != master.shape:
                # capacity grew between fetch and compare (impossible
                # under the lock, but cheap to guard)
                continue
            eq = (
                np.isclose(dev, master)
                if dev.dtype.kind == "f"
                else dev == master
            )
            bad = np.nonzero(~eq.reshape(len(rows), -1).all(axis=1))[0]
            if bad.size:
                bad_rows = [rows[int(b)] for b in bad]
                out[field] = bad_rows
                drifted.update(bad_rows)
                if count:
                    metrics.inc(
                        COUNTER_DRIFT_ROWS,
                        {"column": field},
                        by=float(bad.size),
                    )
                logger.warning(
                    "anti-entropy: device column %r drifted from masters "
                    "on rows %s",
                    field,
                    bad_rows,
                )
        return drifted


def dataplane_health_lines() -> List[str]:
    """Data-plane self-defense state — audit drift/rebuild counters,
    kernel-guard trips, device-loss events — rendered for the SIGUSR2
    debugger dump. Empty when none of those components has run yet."""
    lines: List[str] = []
    for prefix in (
        "snapshot_",
        "kernel_guard_",
        "scheduler_device_",
        "scheduler_mesh_",
        "scheduler_wave_",
    ):
        for name, labels, value in metrics.snapshot_gauges(prefix):
            annotation = ""
            if name == "scheduler_device_down":
                annotation = (
                    "DOWN (host-path fallback)" if value else "serving"
                )
            lines.append(
                metrics.format_series_line(name, labels, value, annotation)
            )
        for name, labels, value in metrics.snapshot_counters(prefix):
            lines.append(metrics.format_series_line(name, labels, value))
    return lines
