"""Scheduler HA: the warm-standby / promotion metric surface.

The mechanics live where the state lives — standby bring-up, the standby
snapshot-refresh loop, the promotion-time adoption pass and the fenced
bind funnel are Scheduler methods (scheduler.py), the lease fencing token
is minted by the LeaderElector (client/leaderelection.py) and enforced by
the store (client/apiserver.py). This module is the one home for the
``scheduler_ha_*`` series names and the SIGUSR2 dump section, so the
metrics contract (graftlint pass 3) and the debugger read one surface.

Role model: a process is either a WARM STANDBY (informers tailing the
shared watch cache, HBM snapshot + compiled kernels kept warm, scheduling
loops NOT running) or the LEADER (everything running, binds fenced on the
leadership grant). ``scheduler_ha_role{identity}`` is 0/1 accordingly;
promotion flips it and counts adoption outcomes per pod.
"""

from __future__ import annotations

from typing import List

from ..utils.metrics import metrics

# 0 = warm standby, 1 = leader; labeled by lease identity so two
# replicas sharing a process (chaos suites) publish distinct series
GAUGE_ROLE = "scheduler_ha_role"  # {identity}
# seconds since the standby's device snapshot last matched the host
# masters (refreshed by the standby tick; ~0 in steady state)
GAUGE_STANDBY_SNAPSHOT_AGE = "scheduler_ha_standby_snapshot_age_seconds"  # {identity}
# standby ticks that actually scattered pending deltas into HBM
COUNTER_STANDBY_FLUSHES = "scheduler_ha_standby_flushes_total"
# standby -> leader transitions in this process
COUNTER_PROMOTIONS = "scheduler_ha_promotions_total"
# promotion-time adoption pass outcomes, per queued pod read back from
# the store: bound (dead leader's bind landed -> finish), pending (never
# landed -> this leader places it, fenced), gone (deleted mid-flight)
COUNTER_ADOPTIONS = "scheduler_ha_adoptions_total"  # {outcome}
# binds rejected by the leadership fence (we are a zombie ex-leader; the
# placement is forgotten, never retried), labeled by the transport that
# enforced it: path=local (in-process store bind lock) or path=rest (the
# /binding route's X-Leadership-Fence validation)
COUNTER_FENCED_BINDS = "scheduler_ha_fenced_binds_total"  # {path}
# kernel pre-compile passes completed while standing by
COUNTER_STANDBY_WARMUPS = "scheduler_ha_standby_warmups_total"


def ha_health_lines() -> List[str]:
    """Scheduler-HA + leader-election state for the SIGUSR2 dump: role and
    standby snapshot freshness per identity, promotion/adoption/fence
    counters, and the elector's acquisition/release/degraded-skip
    counters — a failed or slow handoff is diagnosable from one signal.
    Empty when no HA-aware scheduler has published state yet."""
    lines: List[str] = []
    for snap in (
        metrics.snapshot_gauges("scheduler_ha_"),
        metrics.snapshot_counters("scheduler_ha_"),
        metrics.snapshot_gauges("leader_election_"),
        metrics.snapshot_counters("leader_election_"),
    ):
        for name, labels, value in snap:
            annotation = ""
            if name == GAUGE_ROLE:
                annotation = "LEADER" if value else "warm standby"
            lines.append(
                metrics.format_series_line(name, labels, value, annotation)
            )
    return lines
