"""Host generic scheduler: the fallback/oracle scheduling algorithm.

Mirrors reference pkg/scheduler/core/generic_scheduler.go — Schedule(:150):
snapshot → PreFilter → findNodesThatFitPod(:414) with adaptive node sampling
numFeasibleNodesToFind(:390: 50−n/125 %, floor 5%, min 100) → PreScore →
prioritizeNodes(:626) → selectHost(:235, reservoir max). The device lattice
replaces this wholesale for encodable pods; this path serves overflow pods,
preemption what-ifs and differential tests. The reference's 16-goroutine
ParallelizeUntil fan-out is a plain loop here — the bulk path is on device.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..api import objects as v1
from .cache.nodeinfo import NodeInfo, Snapshot
from .framework.interface import Code, CycleState, Status, is_success
from .framework.runtime import Framework

MIN_FEASIBLE_NODES_TO_FIND = 100
MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND = 5


@dataclass
class ScheduleResult:
    suggested_host: str = ""
    evaluated_nodes: int = 0
    feasible_nodes: int = 0


@dataclass
class FitError(Exception):
    pod: v1.Pod = None
    num_all_nodes: int = 0
    filtered_nodes_statuses: Dict[str, Status] = field(default_factory=dict)

    def __str__(self) -> str:
        reasons: Dict[str, int] = {}
        for st in self.filtered_nodes_statuses.values():
            reasons[st.message] = reasons.get(st.message, 0) + 1
        parts = [f"{cnt} {msg}" for msg, cnt in sorted(reasons.items())]
        return (
            f"0/{self.num_all_nodes} nodes are available: {', '.join(parts)}."
        )


def num_feasible_nodes_to_find(
    num_all_nodes: int, percentage_of_nodes_to_score: int = 0
) -> int:
    """generic_scheduler.go:390-410."""
    if (
        num_all_nodes < MIN_FEASIBLE_NODES_TO_FIND
        or percentage_of_nodes_to_score >= 100
    ):
        return num_all_nodes
    adaptive = percentage_of_nodes_to_score
    if adaptive <= 0:
        adaptive = int(50 - num_all_nodes / 125)
        if adaptive < MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND:
            adaptive = MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND
    num = num_all_nodes * adaptive // 100
    return max(num, MIN_FEASIBLE_NODES_TO_FIND)


class GenericScheduler:
    def __init__(
        self,
        framework: Framework,
        percentage_of_nodes_to_score: int = 0,
        rng: Optional[random.Random] = None,
        extenders: Optional[list] = None,
    ):
        self.framework = framework
        self.percentage = percentage_of_nodes_to_score
        self._next_start_index = 0  # round-robin start (generic_scheduler.go:429)
        self._rng = rng or random.Random(0)
        self.extenders = extenders or []

    # -- public -------------------------------------------------------------

    def schedule(
        self,
        pod: v1.Pod,
        snapshot: Snapshot,
        state: Optional[CycleState] = None,
        nominated_pods_for_node=None,
    ) -> ScheduleResult:
        """Raises FitError when no node fits (Schedule, :150)."""
        state = state or CycleState()
        st = self.framework.run_pre_filter_plugins(state, pod)
        if not is_success(st):
            raise FitError(pod=pod, num_all_nodes=len(snapshot), filtered_nodes_statuses={"*prefilter*": st})
        feasible, statuses, evaluated = self.find_nodes_that_fit(
            pod, snapshot, state, nominated_pods_for_node
        )
        feasible = self._find_nodes_that_pass_extenders(pod, feasible, statuses)
        if not feasible:
            raise FitError(
                pod=pod,
                num_all_nodes=len(snapshot),
                filtered_nodes_statuses=statuses,
            )
        if len(feasible) == 1:
            return ScheduleResult(feasible[0].name, evaluated, 1)
        self.framework.run_pre_score_plugins(state, pod, feasible)
        names = [ni.name for ni in feasible]
        totals = self.framework.run_score_plugins(state, pod, names, snapshot)
        for ext in self.extenders:
            if not ext.cfg.prioritize_verb or not ext.is_interested(pod):
                continue
            try:
                for node, score in ext.prioritize(pod, names).items():
                    if node in totals:
                        # extender scores are 0..10 (MaxExtenderPriority);
                        # rescale to the 0..100 in-tree plugin range
                        # (prioritizeNodes, generic_scheduler.go:694)
                        totals[node] += score * (100.0 / 10.0)
            except Exception:
                # prioritize failures never fail the pod (the reference only
                # logs them, generic_scheduler.go:676)
                continue
        host = self.select_host(totals)
        return ScheduleResult(host, evaluated, len(feasible))

    def _find_nodes_that_pass_extenders(
        self, pod: v1.Pod, feasible: List[NodeInfo], statuses: Dict[str, Status]
    ) -> List[NodeInfo]:
        """findNodesThatPassExtenders (generic_scheduler.go:502)."""
        for ext in self.extenders:
            if not feasible:
                break
            if not ext.cfg.filter_verb or not ext.is_interested(pod):
                continue
            names = [ni.name for ni in feasible]
            try:
                passed, failed = ext.filter(pod, names)
            except Exception:
                if ext.is_ignorable():
                    continue
                # transport failure of a required extender is a cycle ERROR
                # (retry with backoff), NOT unschedulable — a FitError here
                # would wrongly trigger preemption against healthy nodes
                raise
            for node, reason in failed.items():
                statuses[node] = Status.unschedulable(f"extender: {reason}")
            keep = set(passed)
            feasible = [ni for ni in feasible if ni.name in keep]
        return feasible

    def find_nodes_that_fit(
        self,
        pod: v1.Pod,
        snapshot: Snapshot,
        state: CycleState,
        nominated_pods_for_node=None,
    ) -> Tuple[List[NodeInfo], Dict[str, Status], int]:
        """findNodesThatPassFilters (:429): adaptive sampling + round-robin
        start index; per-node double-pass with nominated pods (:570)."""
        all_nodes = snapshot.node_info_list
        num_to_find = num_feasible_nodes_to_find(len(all_nodes), self.percentage)
        feasible: List[NodeInfo] = []
        statuses: Dict[str, Status] = {}
        evaluated = 0
        n = len(all_nodes)
        for i in range(n):
            ni = all_nodes[(self._next_start_index + i) % n]
            evaluated += 1
            st = self._pod_passes_filters_on_node(
                state, pod, ni, nominated_pods_for_node
            )
            if is_success(st):
                feasible.append(ni)
                if len(feasible) >= num_to_find:
                    break
            else:
                statuses[ni.name] = st
        self._next_start_index = (self._next_start_index + evaluated) % max(n, 1)
        return feasible, statuses, evaluated

    def _pod_passes_filters_on_node(
        self, state: CycleState, pod: v1.Pod, ni: NodeInfo, nominated_pods_for_node
    ) -> Optional[Status]:
        """podPassesFiltersOnNode (:570): when higher-priority nominated pods
        exist for the node, filter twice — once assuming they are placed
        (resource safety), once without (affinity safety)."""
        nominated = (
            nominated_pods_for_node(ni.name) if nominated_pods_for_node else []
        )
        # exclude the pod being scheduled itself (addNominatedPods skips
        # same-UID pods) and lower-priority nominees
        nominated = [
            p
            for p in nominated
            if p.priority >= pod.priority and p.metadata.uid != pod.metadata.uid
        ]
        if nominated:
            ni2 = ni.clone()
            state2 = state.clone()
            for np_ in nominated:
                ni2.add_pod(np_)
                self.framework.run_pre_filter_extension_add_pod(state2, pod, np_, ni2)
            st = self.framework.run_filter_plugins(state2, pod, ni2)
            if not is_success(st):
                return st
        return self.framework.run_filter_plugins(state, pod, ni)

    def select_host(self, totals: Dict[str, float]) -> str:
        """reservoir-sample among max scorers (selectHost, :235)."""
        best = None
        count = 0
        for name, score in totals.items():
            if best is None or score > totals[best]:
                best, count = name, 1
            elif score == totals[best]:
                count += 1
                if self._rng.randrange(count) == 0:
                    best = name
        if best is None:
            raise ValueError("empty priority list")
        return best
