"""Hollow nodes: the kubemark scale rig, built on the real kubelet.

Reference: pkg/kubemark/hollow_kubelet.go — a REAL kubelet wired to a fake
container runtime/mounter so a 5k-node control plane runs on a few
machines. Round 1 shipped a separate hollow implementation; this now
delegates to kubelet.NodeAgentPool so hollow and real nodes share one sync
code path (kubelet/kubelet.py), differing only in the PodRuntime injected
(kubelet/runtime.py FakeRuntime).

HollowCluster keeps its original surface (add_node / start / stop /
kill_node) for the perf harness and tests.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ..api import objects as v1
from ..kubelet.kubelet import (
    NODE_LEASE_NS,  # noqa: F401 — re-exported for nodelifecycle
    NodeAgentPool,
    make_node_object,
)

_ip_lock = threading.Lock()
_ip_by_seed: Dict[str, str] = {}


def _fake_pod_ip(seed: str) -> str:
    """Deterministic, collision-free fake IP per seed (uid/node name): a
    process-wide counter mapped into 10.0.0.0/8 — collision-free up to ~16M
    allocations, stable for the process lifetime (unlike hash(), which is
    PYTHONHASHSEED-randomized and birthday-collides at kubemark scale)."""
    with _ip_lock:
        ip = _ip_by_seed.get(seed)
        if ip is None:
            n = len(_ip_by_seed)
            ip = f"10.{(n // (254 * 256)) % 256}.{(n // 254) % 256}.{n % 254 + 1}"
            _ip_by_seed[seed] = ip
        return ip


def make_hollow_node(
    name: str,
    cpu: str = "4",
    memory: str = "32Gi",
    pods: int = 110,
    labels: Optional[dict] = None,
) -> v1.Node:
    return make_node_object(name, cpu=cpu, memory=memory, pods=pods, labels=labels)


class HollowNode:
    """Back-compat handle for one hollow node."""

    def __init__(self, node: v1.Node):
        self.node = node
        self.name = node.metadata.name


class HollowCluster(NodeAgentPool):
    def __init__(
        self,
        server,
        num_nodes: int = 0,
        name_prefix: str = "hollow-node",
        heartbeat_interval: float = 10.0,
        housekeeping_interval: float = 0.5,  # NodeAgentPool's default
        node_template=make_hollow_node,
    ):
        super().__init__(
            server,
            heartbeat_interval=heartbeat_interval,
            housekeeping_interval=housekeeping_interval,
        )
        self.nodes: Dict[str, HollowNode] = {}
        self._template = node_template
        for i in range(num_nodes):
            self.add_node(f"{name_prefix}-{i}")

    def add_node(self, name: str, template=None, **kw) -> HollowNode:
        node = (template or self._template)(name, **kw)
        self.server.create("nodes", node)
        try:
            from ..client.leaderelection import Lease
            import time

            self.server.create(
                "leases",
                Lease(
                    metadata=v1.ObjectMeta(name=name, namespace=NODE_LEASE_NS),
                    holder_identity=name,
                    lease_duration_seconds=40.0,
                    renew_time=time.time(),
                ),
            )
        except Exception:
            pass
        super().add_node(name, register=False)
        hn = HollowNode(node)
        self.nodes[name] = hn
        return hn

    def kill_node(self, name: str) -> None:
        """Stop heartbeating a node (the node 'dies'); nodelifecycle should
        detect and evict."""
        self.nodes.pop(name, None)
        self.remove_node(name)

    def provisioner_for(self, node_template):
        """(provision, deprovision) hooks for an autoscaler NodeGroup: a
        scale-up creates the Node object AND starts a hollow kubelet for
        it (heartbeats, leases, pod sync — a full fleet member), and a
        scale-down tears the kubelet back down after the node object is
        deleted. node_template: name -> v1.Node (the group's
        `make_node`, so the nodegroup label rides along)."""

        def provision(name: str):
            return self.add_node(name, template=node_template)

        def deprovision(name: str):
            self.kill_node(name)

        return provision, deprovision
