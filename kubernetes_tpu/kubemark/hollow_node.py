"""Hollow kubelet: register, heartbeat, ack pods.

Reference: pkg/kubemark/hollow_kubelet.go — the kubelet's API interactions
without a container runtime: (1) register a Node with capacity, (2) post
NodeStatus Ready heartbeats + renew the per-node Lease
(pkg/kubelet/nodelease), (3) watch for pods bound to this node and drive
their status to Running (the fake runtime "starts" instantly).

One HollowCluster multiplexes many hollow nodes onto a few threads so a
5k-node cluster is cheap (the reference runs one process per hollow node;
in-process we can share the watch stream).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..api import objects as v1
from ..client.apiserver import Conflict, NotFound
from ..client.leaderelection import Lease

NODE_LEASE_NS = "kube-node-lease"

_ip_lock = threading.Lock()
_ip_by_seed: Dict[str, str] = {}


def _fake_pod_ip(seed: str) -> str:
    """Deterministic, collision-free fake IP per seed (uid/node name): a
    process-wide counter mapped into 10.0.0.0/8 — collision-free up to ~16M
    allocations, stable for the process lifetime (unlike hash(), which is
    PYTHONHASHSEED-randomized and birthday-collides at kubemark scale)."""
    with _ip_lock:
        ip = _ip_by_seed.get(seed)
        if ip is None:
            n = len(_ip_by_seed)
            ip = f"10.{(n // (254 * 256)) % 256}.{(n // 254) % 256}.{n % 254 + 1}"
            _ip_by_seed[seed] = ip
        return ip


def make_hollow_node(
    name: str,
    cpu: str = "4",
    memory: str = "32Gi",
    pods: int = 110,
    labels: Optional[dict] = None,
) -> v1.Node:
    return v1.Node(
        metadata=v1.ObjectMeta(name=name, namespace="", labels=labels or {}),
        spec=v1.NodeSpec(),
        status=v1.NodeStatus(
            capacity={"cpu": cpu, "memory": memory, "pods": pods},
            allocatable={"cpu": cpu, "memory": memory, "pods": pods},
            conditions=[
                v1.NodeCondition(type=v1.NODE_READY, status="True")
            ],
        ),
    )


class HollowNode:
    """One hollow node's state (registration handled by HollowCluster)."""

    def __init__(self, node: v1.Node):
        self.node = node
        self.name = node.metadata.name


class HollowCluster:
    def __init__(
        self,
        server,
        num_nodes: int = 0,
        name_prefix: str = "hollow-node",
        heartbeat_interval: float = 10.0,
        node_template=make_hollow_node,
    ):
        self.server = server
        self.heartbeat_interval = heartbeat_interval
        self.nodes: Dict[str, HollowNode] = {}
        self._template = node_template
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        for i in range(num_nodes):
            self.add_node(f"{name_prefix}-{i}")

    # -- registration --------------------------------------------------------

    def add_node(self, name: str, **kw) -> HollowNode:
        node = self._template(name, **kw)
        self.server.create("nodes", node)
        try:
            self.server.create(
                "leases",
                Lease(
                    metadata=v1.ObjectMeta(
                        name=name, namespace=NODE_LEASE_NS
                    ),
                    holder_identity=name,
                    lease_duration_seconds=40.0,
                    renew_time=time.time(),
                ),
            )
        except Exception:
            pass
        hn = HollowNode(node)
        self.nodes[name] = hn
        return hn

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        t = threading.Thread(
            target=self._heartbeat_loop, name="hollow-heartbeat", daemon=True
        )
        t.start()
        self._threads.append(t)
        t2 = threading.Thread(
            target=self._pod_ack_loop, name="hollow-pod-ack", daemon=True
        )
        t2.start()
        self._threads.append(t2)

    def stop(self) -> None:
        self._stop.set()

    # -- heartbeats (kubelet nodestatus + nodelease) -------------------------

    def _heartbeat_loop(self) -> None:
        while not self._stop.is_set():
            now = time.time()
            for name in list(self.nodes):
                if self._stop.is_set():
                    return
                try:
                    def renew(lease):
                        lease.renew_time = now
                        return lease

                    self.server.guaranteed_update(
                        "leases", NODE_LEASE_NS, name, renew
                    )
                except NotFound:
                    pass
                except Conflict:
                    pass
            # full NodeStatus heartbeat is lease-relieved (nodelease KEP):
            # only bump conditions once per interval on a sample of nodes
            self._stop.wait(self.heartbeat_interval)

    # -- pod acknowledgment (the fake runtime) -------------------------------

    def _pod_ack_loop(self) -> None:
        pods, rv = self.server.list("pods")
        for pod in pods:
            self._maybe_ack(pod)
        watcher = self.server.watch("pods", from_version=rv)
        while not self._stop.is_set():
            ev = watcher.get(timeout=0.5)
            if ev is None:
                continue
            if ev.type in ("ADDED", "MODIFIED"):
                self._maybe_ack(ev.object)
        watcher.stop()

    def _maybe_ack(self, pod: v1.Pod) -> None:
        if not pod.spec.node_name or pod.spec.node_name not in self.nodes:
            return
        if pod.status.phase == v1.POD_RUNNING:
            return

        def mutate(p):
            if p.status.phase == v1.POD_RUNNING or not p.spec.node_name:
                return None
            p.status.phase = v1.POD_RUNNING
            p.status.start_time = time.time()
            # fake sandbox IP (the real kubelet reports the CNI-assigned IP;
            # endpoints controller needs one to publish an address)
            p.status.pod_ip = _fake_pod_ip(p.metadata.uid)
            p.status.host_ip = _fake_pod_ip(p.spec.node_name)
            return p

        try:
            self.server.guaranteed_update(
                "pods", pod.metadata.namespace, pod.metadata.name, mutate
            )
        except NotFound:
            pass

    # -- failure injection (chaosmonkey-style) -------------------------------

    def kill_node(self, name: str) -> None:
        """Stop heartbeating a node (the node 'dies'); nodelifecycle should
        detect and evict."""
        self.nodes.pop(name, None)
