"""kubemark: hollow nodes for control-plane scale testing.

Reference: pkg/kubemark (hollow_kubelet.go:95,111-118 — a real kubelet
against fake runtime/mounter) + cmd/kubemark/hollow-node.go. Hollow nodes
register as real Nodes, heartbeat status + lease, and acknowledge bound
pods as Running without running anything — how a 5000-node control plane is
exercised on one machine.
"""

from .hollow_node import HollowCluster, HollowNode  # noqa: F401
