"""Write-ahead log + snapshot persistence for the API store.

The reference's durability story is etcd (staging/src/k8s.io/apiserver/pkg/
storage/etcd3/store.go): every write is raft-logged before acknowledgment
and state survives any component crash. This collapses that into a
single-node WAL with the same crash-only contract: a mutation is
acknowledged only after its record is on disk; recovery = load latest
snapshot + replay the tail. Compaction writes a full snapshot and truncates
the log (etcd's snapshot/compact cycle).

Record format: one JSON line per mutation
  {"rv": N, "verb": "create|update|delete", "kind": resource, "obj": {...}}
Commit-index control records (runtime/consensus.py epoch transitions) share
the stream so replay sees durability state in log order:
  {"rv": N, "verb": "commit", "kind": "-", "obj": null,
   "commit": C, "term": T, "event": "degraded|restored"}
They carry the rv at which they were logged (so snapshot compaction
retires them naturally) but apply no object change; recovery tracks the
highest commit index seen (recover_full) and skips them during replay.
Snapshot format: {"rv": N, "objects": {resource: [obj, ...]}}
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..api import serialization

SNAPSHOT_SUFFIX = ".snapshot.json"
LOG_SUFFIX = ".wal"

_DEBUG = bool(os.environ.get("KTPU_WAL_DEBUG"))


def _trace(path: str, msg: str) -> None:
    if not _DEBUG:
        return
    import time as _t

    with open(path + ".trace", "a", encoding="utf-8") as f:
        f.write(f"{_t.monotonic():.6f} [{threading.get_ident()}] {msg}\n")


class WriteAheadLog:
    def __init__(
        self,
        path: str,
        compact_every: int = 50_000,
        fsync: bool = True,
    ):
        """`path` is a prefix: <path>.wal + <path>.snapshot.json.

        fsync=True (the DEFAULT, matching etcd: acknowledged means on
        media) fsyncs every append before the mutation is acknowledged.
        fsync=False trades media-durability for throughput — the write is
        still flushed to the OS, so it survives process crashes but not
        machine crashes (etcd's --unsafe-no-fsync testing mode); benchmarks
        and tests may opt out explicitly."""
        self.path = path
        self.log_path = path + LOG_SUFFIX
        self.snap_path = path + SNAPSHOT_SUFFIX
        self.compact_every = compact_every
        self.fsync = fsync
        self._lock = threading.Lock()
        self._since_compact = 0
        os.makedirs(os.path.dirname(os.path.abspath(self.log_path)), exist_ok=True)
        self._f = None
        self._native = None  # (lib, handle) when the C++ sink is in use
        self._closed = False
        self._open_sink()

    def _open_sink(self) -> None:
        """Prefer the native group-commit sink (kubernetes_tpu/native):
        appends become enqueue+wait tickets and a batch of N records costs
        ONE fsync (etcd's wal.Save group commit). Python file IO otherwise."""
        from ..native import load_walsink

        lib = load_walsink()
        if lib is not None:
            h = lib.wal_open(self.log_path.encode(), 1 if self.fsync else 0)
            if h:
                self._native = (lib, h)
                return
        self._f = open(self.log_path, "a", encoding="utf-8")

    def _close_sink(self) -> None:
        if self._native is not None:
            lib, h = self._native
            lib.wal_close(h)
            self._native = None
        if self._f is not None:
            self._f.close()
            self._f = None

    @property
    def native(self) -> bool:
        return self._native is not None

    def fsync_count(self) -> int:
        """Committer fsyncs so far (native sink only; stats/tests)."""
        if self._native is None:
            return -1
        lib, h = self._native
        return int(lib.wal_fsync_count(h))

    # -- write path ----------------------------------------------------------

    @staticmethod
    def _record(rv: int, verb: str, kind: str, obj: Any) -> str:
        rec = {
            "rv": rv,
            "verb": verb,
            "kind": kind,
            "obj": serialization.encode(obj) if obj is not None else None,
        }
        return json.dumps(rec, default=str) + "\n"

    def append(self, rv: int, verb: str, kind: str, obj: Any) -> None:
        self.append_batch([(rv, verb, kind, obj)])

    def append_batch(self, records: List[Tuple[int, str, str, Any]]) -> None:
        """Durably append records IN ORDER; acknowledged once ALL are on
        disk. With the native sink the whole batch (plus any concurrent
        appenders') shares one fsync."""
        self._append_lines([self._record(*r) for r in records])

    def append_commit(self, rv: int, commit: int, term: int, event: str) -> None:
        """Durably log a commit-index epoch transition (consensus mode:
        entering/leaving degraded read-only). Same fsync contract as a
        mutation record — the epoch boundary must survive a crash."""
        rec = {
            "rv": rv,
            "verb": "commit",
            "kind": "-",
            "obj": None,
            "commit": commit,
            "term": term,
            "event": event,
        }
        self._append_lines([json.dumps(rec) + "\n"])

    def _append_lines(self, lines: List[str]) -> None:
        if not lines:
            return
        with self._lock:
            if self._native is not None:
                lib, h = self._native
                ticket = 0
                for line in lines:
                    data = line.encode()
                    ticket = lib.wal_enqueue(h, data, len(data))
                if lib.wal_wait(h, ticket) != 0:
                    # fail-stop like the Python path's OSError: the record
                    # is NOT durable, the mutation must not be acknowledged
                    raise OSError("WAL sink write/fsync failed")
            else:
                for line in lines:
                    self._f.write(line)
                self._f.flush()
                if self.fsync:
                    os.fsync(self._f.fileno())
            self._since_compact += len(lines)
            if _DEBUG:
                rvs = [json.loads(line).get("rv") for line in lines]
                _trace(self.path, f"append acked rvs={rvs} native={self._native is not None}")

    def due(self) -> bool:
        with self._lock:
            return self._since_compact >= self.compact_every

    def write_snapshot(self, rv: int, objects: Dict[str, List[Any]]) -> None:
        """Publish a snapshot at `rv` and drop log records it covers.
        Serialization happens OUTSIDE the wal lock (and the caller runs this
        off the store's mutation path — see APIServer._compact_async);
        appends racing the compaction are preserved by rewriting, not
        truncating, the log tail."""
        snap = {
            "rv": rv,
            "objects": {
                kind: [serialization.encode(o) for o in objs]
                for kind, objs in objects.items()
            },
        }
        if _DEBUG:
            _trace(self.path, f"compact start rv={rv} nobjs={sum(len(v) for v in objects.values())}")
        tmp = self.snap_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(snap, f, default=str)
            f.flush()
            os.fsync(f.fileno())
        with self._lock:
            if self._closed:
                return  # shut down mid-compaction: don't resurrect the sink
            os.replace(tmp, self.snap_path)  # atomic publish
            _trace(self.path, f"snapshot published rv={rv}")
            # rewrite the log keeping only records newer than the snapshot
            # (the sink is closed around the rewrite and reopened after —
            # appends are excluded by the wal lock for the duration)
            self._close_sink()
            keep: List[str] = []
            with open(self.log_path, encoding="utf-8") as f:
                for line in f:
                    line = line.rstrip("\n")
                    if not line:
                        continue
                    try:
                        if json.loads(line)["rv"] > rv:
                            keep.append(line)
                    except json.JSONDecodeError:
                        continue
            # ATOMIC rotation (tmp + replace): a concurrent recover() must
            # never observe a truncated in-place rewrite — it sees either
            # the old full log or the rewritten tail, both consistent with
            # the published snapshot
            log_tmp = self.log_path + ".tmp"
            with open(log_tmp, "w", encoding="utf-8") as f:
                for line in keep:
                    f.write(line + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(log_tmp, self.log_path)
            self._open_sink()
            self._since_compact = len(keep)
            if _DEBUG:
                _trace(self.path, f"log rewritten keep={len(keep)} rvs={[json.loads(l)['rv'] for l in keep[:40]]}")

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._close_sink()

    # -- recovery ------------------------------------------------------------

    @staticmethod
    def recover(path: str) -> Tuple[int, Dict[str, Dict[str, Any]]]:
        """Load snapshot + replay log tail. Returns (rv, {kind: {key: obj}})."""
        rv, objects, _commit = WriteAheadLog.recover_full(path)
        return rv, objects

    @staticmethod
    def recover_full(
        path: str,
    ) -> Tuple[int, Dict[str, Dict[str, Any]], int]:
        """Load snapshot + replay log tail. Returns
        (rv, {kind: {key: obj}}, commit_index) — commit_index is the
        highest consensus commit index recorded in the log (0 when the
        store never ran in consensus mode; the consistency checker ranks
        surviving replicas by it). Tolerates a torn final record (crash
        mid-append), like etcd's WAL CRC-truncate on recovery.

        Crash-point consistency: the compactor publishes the snapshot
        (atomic replace) BEFORE rewriting the log, so every on-disk state a
        crash can leave behind recovers fully. A LIVE writer compacting
        concurrently (tests; split-brain probes) can still interleave our
        two reads — stale snapshot paired with an already-rewritten log
        tail, silently losing the records in between. Detected by
        re-reading the snapshot rv after the log and retrying unless it
        still equals the rv of the snapshot we actually loaded (comparing
        against the REPLAYED rv is not enough: tail records replayed past
        the new snapshot's rv would mask the staleness — found by a
        14/25-pod recovery under a compacting writer). etcd forbids the
        scenario outright via flock."""
        for _ in range(10):
            rv, objects, snap_rv, commit = WriteAheadLog._recover_once(path)
            if _DEBUG:
                _trace(path, f"recover pass snap_rv={snap_rv} rv={rv} nobjs={sum(len(v) for v in objects.values())}")
            snap_path = path + SNAPSHOT_SUFFIX
            try:
                with open(snap_path, encoding="utf-8") as f:
                    current_rv = json.load(f)["rv"]
            except FileNotFoundError:
                current_rv = 0
            except (json.JSONDecodeError, OSError):
                continue  # snapshot replaced mid-read: retry
            if current_rv == snap_rv:
                # no snapshot was published between our two reads, so the
                # log tail we replayed is consistent with the snapshot we
                # loaded (a pending rewrite of THIS snapshot's log only
                # drops records the snapshot already covers)
                return rv, objects, commit
        return rv, objects, commit

    @staticmethod
    def _recover_once(
        path: str,
    ) -> Tuple[int, Dict[str, Dict[str, Any]], int, int]:
        """Returns (rv, objects, snap_rv, commit_index) — snap_rv is the
        rv of the snapshot file as loaded (0 if none), for the caller's
        staleness re-check; commit_index is the highest consensus commit
        recorded in the log tail (0 if none)."""
        rv = 0
        snap_rv = 0
        commit = 0
        objects: Dict[str, Dict[str, Any]] = {}
        snap_path = path + SNAPSHOT_SUFFIX
        log_path = path + LOG_SUFFIX
        if os.path.exists(snap_path):
            with open(snap_path, encoding="utf-8") as f:
                snap = json.load(f)
            rv = snap_rv = snap["rv"]
            for kind, objs in snap["objects"].items():
                d = objects.setdefault(kind, {})
                for data in objs:
                    obj = serialization.decode(kind, data)
                    d[obj.metadata.key] = obj
        if os.path.exists(log_path):
            with open(log_path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        break  # torn tail record: truncate here
                    verb = rec.get("verb")
                    if verb == "commit":
                        # consensus epoch record: no object change; it may
                        # share a data record's rv, so handle BEFORE the
                        # rv-dedup skip below
                        commit = max(commit, int(rec.get("commit", 0)))
                        continue
                    if rec["rv"] <= rv:
                        continue  # already in snapshot
                    rv = rec["rv"]
                    kind = rec["kind"]
                    d = objects.setdefault(kind, {})
                    if verb == "delete":
                        obj = serialization.decode(kind, rec["obj"])
                        d.pop(obj.metadata.key, None)
                    else:
                        obj = serialization.decode(kind, rec["obj"])
                        d[obj.metadata.key] = obj
        return rv, objects, snap_rv, commit
