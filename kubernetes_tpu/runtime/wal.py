"""Write-ahead log + snapshot persistence for the API store.

The reference's durability story is etcd (staging/src/k8s.io/apiserver/pkg/
storage/etcd3/store.go): every write is raft-logged before acknowledgment
and state survives any component crash. This collapses that into a
single-node WAL with the same crash-only contract: a mutation is
acknowledged only after its record is on disk; recovery = load latest
snapshot + replay the tail. Compaction writes a full snapshot and truncates
the log (etcd's snapshot/compact cycle).

Record format v2: one CRC32-framed JSON line per mutation
  K2 <crc32-hex8> {"rv": N, "verb": "create|update|delete", "kind": ..., "obj": {...}}
The CRC covers the JSON payload bytes (etcd frames WAL records the same
way), so recovery can tell a torn tail (crash mid-append: the damage is
the LAST thing in the log) from mid-log corruption (a flipped bit with
valid acked records after it — a medium fault, not a crash). The reader
version-sniffs per line: a line starting with `{` is a legacy v1 record
(plain JSON, no CRC) and stays recoverable forever.
Commit-index control records (runtime/consensus.py epoch transitions) share
the stream so replay sees durability state in log order:
  {"rv": N, "verb": "commit", "kind": "-", "obj": null,
   "commit": C, "term": T, "event": "degraded|restored"}
They carry the rv at which they were logged (so snapshot compaction
retires them naturally) but apply no object change; recovery tracks the
highest commit index seen (recover_full) and skips them during replay.
Snapshot format: {"rv": N, "objects": {resource: [obj, ...]}}

Disk fail-stop: a write or fsync error on the sink POISONS it permanently
(the fsyncgate lesson: after a failed fsync the kernel may have dropped
the dirty pages, so a retried fsync that "succeeds" proves nothing —
PostgreSQL shipped that bug for 20 years). Every subsequent append raises
SinkFailed without touching the file; the store is expected to go
degraded read-only and let a disk-healthy replica take over. The ONE
recoverable case is ENOSPC on the data write itself (before fsync): the
log is repaired back to the last acked record boundary and DiskFull is
raised — retryable once space frees, because no dirty-page state was
lost.
"""

from __future__ import annotations

import dataclasses
import errno
import json
import logging
import os
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..api import serialization
from ..utils.metrics import metrics

logger = logging.getLogger("kubernetes_tpu.wal")

SNAPSHOT_SUFFIX = ".snapshot.json"
LOG_SUFFIX = ".wal"

# v2 frame: "K2 " + 8 hex chars of crc32(payload) + " " + payload
FRAME_PREFIX = "K2 "

# recovery classification + repair (the four disk failure modes)
COUNTER_TORN_TAIL = "wal_torn_tail_truncations_total"
COUNTER_MIDLOG = "wal_midlog_corruptions_total"
COUNTER_RETRIES_EXHAUSTED = "wal_recover_retries_exhausted_total"
COUNTER_TMP_SWEEPS = "wal_orphan_tmp_sweeps_total"
# sink fail-stop + pressure
COUNTER_SINK_FAILURES = "wal_sink_failures_total"
COUNTER_ENOSPC = "wal_enospc_errors_total"
GAUGE_SINK_FAILED = "wal_sink_failed"
GAUGE_CORRUPT = "wal_recovered_corrupt"
# slow-disk watchdog: a dying disk's fsyncs stretch long before they fail
HIST_FSYNC = "wal_fsync_duration_seconds"
COUNTER_FSYNC_STALLS = "wal_fsync_stalls_total"
GAUGE_FSYNC_STALLED = "wal_fsync_stalled"
# disk-space probe (store-level family: the gate acts on it)
GAUGE_FREE_BYTES = "store_disk_free_bytes"

_DEBUG = bool(os.environ.get("KTPU_WAL_DEBUG"))


def _trace(path: str, msg: str) -> None:
    if not _DEBUG:
        return
    import time as _t

    with open(path + ".trace", "a", encoding="utf-8") as f:
        f.write(f"{_t.monotonic():.6f} [{threading.get_ident()}] {msg}\n")


class SinkFailed(OSError):
    """The WAL sink hit a write/fsync error and is permanently poisoned
    (fail-stop). The record was NOT made durable; the mutation must not be
    acknowledged. Not retryable in this process — recovery is failover to
    a disk-healthy replica."""


class DiskFull(OSError):
    """ENOSPC on the data write, caught BEFORE fsync: the log was repaired
    to the last acked record boundary and the sink stays usable.
    Retryable once disk space frees."""


def frame_record(payload: str) -> str:
    """CRC32-frame one JSON payload into a v2 WAL line."""
    crc = zlib.crc32(payload.encode("utf-8")) & 0xFFFFFFFF
    return f"{FRAME_PREFIX}{crc:08x} {payload}\n"


def parse_wal_line(line: str) -> Optional[dict]:
    """Parse one WAL line (either framing version) or None if damaged.

    v2 (`K2 <crc8> <json>`): the CRC must match the payload bytes — a
    bit-flip inside a string value still parses as JSON, only the CRC
    catches it. v1 (starts with `{`): plain JSON, best-effort. Anything
    else is damage."""
    if line.startswith(FRAME_PREFIX):
        body = line[len(FRAME_PREFIX):]
        if len(body) < 10 or body[8] != " ":
            return None
        try:
            want = int(body[:8], 16)
        except ValueError:
            return None
        payload = body[9:]
        if zlib.crc32(payload.encode("utf-8")) & 0xFFFFFFFF != want:
            return None
        try:
            rec = json.loads(payload)
        except json.JSONDecodeError:
            return None
        return rec if isinstance(rec, dict) else None
    if line.startswith("{"):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            return None
        return rec if isinstance(rec, dict) else None
    return None


@dataclasses.dataclass
class RecoveryReport:
    """What recovery found, beyond the recovered state itself. `corrupt`
    means mid-log damage with valid acked records after it: the returned
    state is the longest valid prefix and the replica must resync from a
    healthy peer before serving it as authoritative."""

    rv: int = 0
    objects: Dict[str, Dict[str, Any]] = dataclasses.field(default_factory=dict)
    commit: int = 0
    snap_rv: int = 0
    torn_tail: bool = False
    corrupt: bool = False
    bad_records: int = 0
    retries_exhausted: bool = False


class DiskSpaceProbe:
    """Low-watermark free-space probe with hysteresis: pressure enters at
    `low_bytes` free and clears at `high_bytes` (default 2x low), so the
    store goes read-only BEFORE appends start failing with ENOSPC and
    doesn't flap at the boundary. `statvfs` and `clock` are injectable
    for deterministic fault tests (testing/diskfaults.py)."""

    def __init__(
        self,
        path: str,
        low_bytes: int = 32 << 20,
        high_bytes: Optional[int] = None,
        statvfs: Callable = os.statvfs,
        min_interval_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.dir = os.path.dirname(os.path.abspath(path)) or "."
        self.low_bytes = low_bytes
        self.high_bytes = high_bytes if high_bytes is not None else low_bytes * 2
        self._statvfs = statvfs
        self._clock = clock
        self._min_interval_s = min_interval_s
        self._last_check: Optional[float] = None
        self.under_pressure = False

    def free_bytes(self) -> int:
        st = self._statvfs(self.dir)
        return int(st.f_bavail) * int(st.f_frsize)

    def check(self) -> Optional[bool]:
        """Returns True on entering pressure, False on recovering, None on
        no transition (including rate-limited skips and probe errors)."""
        now = self._clock()
        if (
            self._last_check is not None
            and now - self._last_check < self._min_interval_s
        ):
            return None
        self._last_check = now
        try:
            free = self.free_bytes()
        except OSError:
            return None
        metrics.set_gauge(GAUGE_FREE_BYTES, float(free))
        if not self.under_pressure and free < self.low_bytes:
            self.under_pressure = True
            return True
        if self.under_pressure and free >= self.high_bytes:
            self.under_pressure = False
            return False
        return None


class WriteAheadLog:
    # an fsync (or native group-commit wait) slower than this trips the
    # stall watchdog: a dying disk stretches fsyncs long before erroring
    FSYNC_STALL_S = 1.0

    def __init__(
        self,
        path: str,
        compact_every: int = 50_000,
        fsync: bool = True,
        native: bool = True,
    ):
        """`path` is a prefix: <path>.wal + <path>.snapshot.json.

        fsync=True (the DEFAULT, matching etcd: acknowledged means on
        media) fsyncs every append before the mutation is acknowledged.
        fsync=False trades media-durability for throughput — the write is
        still flushed to the OS, so it survives process crashes but not
        machine crashes (etcd's --unsafe-no-fsync testing mode); benchmarks
        and tests may opt out explicitly.

        native=False forces the pure-Python sink even when the C++
        group-commit sink is buildable — fault injection patches the
        Python sink seams (_sink_write/_sink_fsync)."""
        self.path = path
        self.log_path = path + LOG_SUFFIX
        self.snap_path = path + SNAPSHOT_SUFFIX
        self.compact_every = compact_every
        self.fsync = fsync
        self.allow_native = native
        self._lock = threading.Lock()
        self._since_compact = 0
        os.makedirs(os.path.dirname(os.path.abspath(self.log_path)), exist_ok=True)
        self._f = None
        self._native = None  # (lib, handle) when the C++ sink is in use
        self._closed = False
        self._failed: Optional[str] = None
        self._good_offset = 0
        # fired (once) when the sink poisons, with the reason — the store
        # flips its write gate to disk-failed read-only here. Called with
        # the wal lock held: callbacks must be cheap flag flips and must
        # never call back into the WAL.
        self._on_disk_failed: List[Callable[[str], None]] = []
        self.swept_tmp_files = self._sweep_tmp_files()
        self.repaired = self._repair_log()
        self._open_sink()

    # -- sink lifecycle ------------------------------------------------------

    def _open_sink(self) -> None:
        """Prefer the native group-commit sink (kubernetes_tpu/native):
        appends become enqueue+wait tickets and a batch of N records costs
        ONE fsync (etcd's wal.Save group commit). Python file IO otherwise."""
        if self.allow_native:
            from ..native import load_walsink

            lib = load_walsink()
            if lib is not None:
                h = lib.wal_open(self.log_path.encode(), 1 if self.fsync else 0)
                if h:
                    self._native = (lib, h)
                    return
        self._f = open(self.log_path, "a", encoding="utf-8")
        self._good_offset = self._f.seek(0, os.SEEK_END)

    def _close_sink(self) -> None:
        if self._native is not None:
            lib, h = self._native
            lib.wal_close(h)
            self._native = None
        if self._f is not None:
            self._f.close()
            self._f = None

    @property
    def native(self) -> bool:
        return self._native is not None

    @property
    def failed(self) -> Optional[str]:
        """The poison reason, or None while the sink is healthy."""
        return self._failed

    def on_disk_failed(self, cb: Callable[[str], None]) -> None:
        """Register a fail-stop listener (store write-gate wiring)."""
        self._on_disk_failed.append(cb)

    def _poison_locked(self, why: str) -> None:
        """Fail-stop: mark the sink permanently dead. Never reopened, never
        retried — a failed fsync means the kernel may have already dropped
        the dirty pages, so any retry that 'succeeds' is a lie."""
        if self._failed is not None:
            return
        self._failed = why
        metrics.inc(COUNTER_SINK_FAILURES)
        metrics.set_gauge(GAUGE_SINK_FAILED, 1.0)
        logger.error(
            "WAL sink FAILED (fail-stop, not retryable): %s — store must go "
            "read-only and yield to a disk-healthy replica",
            why,
        )
        try:
            self._close_sink()
        except OSError:
            pass
        for cb in list(self._on_disk_failed):
            try:
                cb(why)
            except Exception:
                logger.exception("disk-failed callback raised")

    def fsync_count(self) -> int:
        """Committer fsyncs so far (native sink only; stats/tests)."""
        if self._native is None:
            return -1
        lib, h = self._native
        return int(lib.wal_fsync_count(h))

    # -- startup repair ------------------------------------------------------

    def _sweep_tmp_files(self) -> int:
        """Remove snapshot/log `.tmp` leftovers from a crash mid-compaction.
        Both are pre-publish staging files (os.replace is the publish), so
        an orphan is never part of recoverable state — just disk leak."""
        swept = 0
        for p in (self.snap_path + ".tmp", self.log_path + ".tmp"):
            try:
                os.unlink(p)
            except FileNotFoundError:
                continue
            except OSError:
                logger.exception("orphan tmp sweep failed for %s", p)
                continue
            swept += 1
            logger.warning("swept orphaned compaction tmp file %s", p)
        if swept:
            metrics.inc(COUNTER_TMP_SWEEPS, by=float(swept))
        return swept

    def _repair_log(self) -> Optional[str]:
        """Physically truncate the log at the first damaged record before
        appending to it. Without this, new appends land AFTER the damage
        and a torn tail mutates into mid-log corruption on the next
        recovery. Returns "torn"/"corrupt"/None. The dropped suffix of a
        corrupt log was already refused by recovery (longest-valid-prefix
        contract) — truncating makes the file agree with the served state
        so replication resync can heal by re-appending from the prefix."""
        try:
            with open(self.log_path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return None
        except OSError:
            return None
        offset = 0
        bad_at: Optional[int] = None
        valid_after_bad = False
        for raw in data.splitlines(keepends=True):
            line = raw.decode("utf-8", errors="replace").strip()
            end = offset + len(raw)
            if line:
                ok = parse_wal_line(line) is not None
                # a parseable final line missing its newline was never
                # acked (the \n is fsynced with the payload): torn
                if ok and not raw.endswith(b"\n"):
                    ok = False
                if not ok and bad_at is None:
                    bad_at = offset
                elif ok and bad_at is not None:
                    valid_after_bad = True
            offset = end
        if bad_at is None:
            return None
        kind = "corrupt" if valid_after_bad else "torn"
        if valid_after_bad:
            metrics.inc(COUNTER_MIDLOG)
            logger.error(
                "WAL %s: mid-log corruption at byte %d with valid records "
                "after it — truncating to the valid prefix; this replica "
                "must resync from a healthy peer before leading",
                self.log_path,
                bad_at,
            )
        else:
            metrics.inc(COUNTER_TORN_TAIL)
            logger.warning(
                "WAL %s: torn tail at byte %d (crash mid-append) — truncated",
                self.log_path,
                bad_at,
            )
        try:
            with open(self.log_path, "rb+") as f:
                f.truncate(bad_at)
        except OSError:
            logger.exception("WAL tail repair failed for %s", self.log_path)
            return kind
        return kind

    # -- write path ----------------------------------------------------------

    @staticmethod
    def _record(rv: int, verb: str, kind: str, obj: Any) -> str:
        rec = {
            "rv": rv,
            "verb": verb,
            "kind": kind,
            "obj": serialization.encode(obj) if obj is not None else None,
        }
        return frame_record(json.dumps(rec, default=str))

    def append(self, rv: int, verb: str, kind: str, obj: Any) -> None:
        self.append_batch([(rv, verb, kind, obj)])

    def append_batch(self, records: List[Tuple[int, str, str, Any]]) -> None:
        """Durably append records IN ORDER; acknowledged once ALL are on
        disk. With the native sink the whole batch (plus any concurrent
        appenders') shares one fsync."""
        self._append_lines([self._record(*r) for r in records])

    def append_commit(self, rv: int, commit: int, term: int, event: str) -> None:
        """Durably log a commit-index epoch transition (consensus mode:
        entering/leaving degraded read-only). Same fsync contract as a
        mutation record — the epoch boundary must survive a crash."""
        rec = {
            "rv": rv,
            "verb": "commit",
            "kind": "-",
            "obj": None,
            "commit": commit,
            "term": term,
            "event": event,
        }
        self._append_lines([frame_record(json.dumps(rec))])

    def _sink_write(self, data: str) -> None:
        """Python-sink write seam (patched by testing/diskfaults.py)."""
        self._f.write(data)
        self._f.flush()

    def _sink_fsync(self) -> None:
        """Python-sink fsync seam (patched by testing/diskfaults.py)."""
        os.fsync(self._f.fileno())

    def _append_lines(self, lines: List[str]) -> None:
        if not lines:
            return
        with self._lock:
            if self._failed is not None:
                raise SinkFailed(f"WAL sink poisoned (fail-stop): {self._failed}")
            t0 = time.monotonic()
            if self._native is not None:
                lib, h = self._native
                ticket = 0
                for line in lines:
                    data = line.encode()
                    ticket = lib.wal_enqueue(h, data, len(data))
                if lib.wal_wait(h, ticket) != 0:
                    # the record is NOT durable, the mutation must not be
                    # acknowledged — and the sink can't say whether the
                    # failure was the write or the fsync, so fail-stop
                    self._poison_locked("native sink write/fsync failed")
                    raise SinkFailed("WAL sink write/fsync failed")
                self._observe_fsync_locked(time.monotonic() - t0)
            else:
                try:
                    self._sink_write("".join(lines))
                except OSError as e:
                    if e.errno == errno.ENOSPC:
                        self._repair_enospc_locked(e)  # raises
                    self._poison_locked(f"write failed: {e}")
                    raise SinkFailed(f"WAL write failed: {e}") from e
                if self.fsync:
                    try:
                        self._sink_fsync()
                    except OSError as e:
                        # fsyncgate: the pages this fsync failed on may be
                        # gone from the page cache — even an ENOSPC here
                        # poisons, because retrying can't prove durability
                        self._poison_locked(f"fsync failed: {e}")
                        raise SinkFailed(f"WAL fsync failed: {e}") from e
                self._observe_fsync_locked(time.monotonic() - t0)
                self._good_offset = self._f.tell()
            self._since_compact += len(lines)
            if _DEBUG:
                rvs = [(parse_wal_line(line.rstrip("\n")) or {}).get("rv") for line in lines]
                _trace(self.path, f"append acked rvs={rvs} native={self._native is not None}")

    def _repair_enospc_locked(self, cause: OSError) -> None:
        """ENOSPC before fsync is the one recoverable sink error: nothing
        durable was promised yet, so roll the file back to the last acked
        record boundary and raise DiskFull (retryable once space frees).
        If even the repair fails, fall through to fail-stop."""
        metrics.inc(COUNTER_ENOSPC)
        try:
            try:
                self._f.close()  # discard buffered partial data
            except OSError:
                pass
            self._f = open(self.log_path, "a", encoding="utf-8")
            self._f.truncate(self._good_offset)
        except OSError as e:
            self._poison_locked(f"ENOSPC repair failed: {e}")
            raise SinkFailed(f"WAL ENOSPC and repair failed: {e}") from cause
        logger.warning(
            "WAL append hit ENOSPC; log repaired to last acked record "
            "(offset %d) — store should enter disk-pressure read-only",
            self._good_offset,
        )
        raise DiskFull(
            errno.ENOSPC,
            "WAL append failed: no space left on device "
            "(log repaired to last acked record; retry after space frees)",
        ) from cause

    def _observe_fsync_locked(self, dt: float) -> None:
        if not self.fsync:
            return
        metrics.observe(HIST_FSYNC, dt)
        stalled = dt >= self.FSYNC_STALL_S
        if stalled:
            metrics.inc(COUNTER_FSYNC_STALLS)
            logger.warning(
                "WAL fsync stalled: %.3fs (threshold %.1fs) — disk may be dying",
                dt,
                self.FSYNC_STALL_S,
            )
        metrics.set_gauge(GAUGE_FSYNC_STALLED, 1.0 if stalled else 0.0)

    def due(self) -> bool:
        with self._lock:
            return self._since_compact >= self.compact_every

    def write_snapshot(self, rv: int, objects: Dict[str, List[Any]]) -> None:
        """Publish a snapshot at `rv` and drop log records it covers.
        Serialization happens OUTSIDE the wal lock (and the caller runs this
        off the store's mutation path — see APIServer._compact_async);
        appends racing the compaction are preserved by rewriting, not
        truncating, the log tail. I/O errors propagate to the caller (which
        counts and backs off) — with the sink reopened first, so a failed
        compaction never wedges the append path."""
        snap = {
            "rv": rv,
            "objects": {
                kind: [serialization.encode(o) for o in objs]
                for kind, objs in objects.items()
            },
        }
        if _DEBUG:
            _trace(self.path, f"compact start rv={rv} nobjs={sum(len(v) for v in objects.values())}")
        tmp = self.snap_path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(snap, f, default=str)
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        with self._lock:
            if self._closed:
                return  # shut down mid-compaction: don't resurrect the sink
            if self._failed is not None:
                return  # poisoned sink: no log rewrite, no reopen
            os.replace(tmp, self.snap_path)  # atomic publish
            _trace(self.path, f"snapshot published rv={rv}")
            # rewrite the log keeping only records newer than the snapshot
            # (the sink is closed around the rewrite and reopened after —
            # appends are excluded by the wal lock for the duration)
            self._close_sink()
            log_tmp = self.log_path + ".tmp"
            try:
                keep: List[str] = []
                with open(self.log_path, encoding="utf-8") as f:
                    for line in f:
                        line = line.rstrip("\n")
                        if not line:
                            continue
                        rec = parse_wal_line(line)
                        if rec is not None and rec.get("rv", 0) > rv:
                            keep.append(line)
                # ATOMIC rotation (tmp + replace): a concurrent recover()
                # must never observe a truncated in-place rewrite — it sees
                # either the old full log or the rewritten tail, both
                # consistent with the published snapshot
                with open(log_tmp, "w", encoding="utf-8") as f:
                    for line in keep:
                        f.write(line + "\n")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(log_tmp, self.log_path)
                self._since_compact = len(keep)
            except OSError:
                try:
                    os.unlink(log_tmp)
                except OSError:
                    pass
                raise
            finally:
                # ALWAYS reopen (or poison trying): an exception above used
                # to leave the sink closed forever — every later append
                # died and compaction was wedged for the process lifetime
                try:
                    self._open_sink()
                except OSError as e:
                    self._poison_locked(f"sink reopen after compaction failed: {e}")
            if _DEBUG:
                _trace(self.path, f"log rewritten keep={len(keep)}")

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._close_sink()

    # -- recovery ------------------------------------------------------------

    @staticmethod
    def recover(path: str) -> Tuple[int, Dict[str, Dict[str, Any]]]:
        """Load snapshot + replay log tail. Returns (rv, {kind: {key: obj}})."""
        report = WriteAheadLog.recover_report(path)
        return report.rv, report.objects

    @staticmethod
    def recover_full(
        path: str,
    ) -> Tuple[int, Dict[str, Dict[str, Any]], int]:
        """Load snapshot + replay log tail. Returns
        (rv, {kind: {key: obj}}, commit_index) — commit_index is the
        highest consensus commit index recorded in the log (0 when the
        store never ran in consensus mode; the consistency checker ranks
        surviving replicas by it)."""
        report = WriteAheadLog.recover_report(path)
        return report.rv, report.objects, report.commit

    @staticmethod
    def recover_report(path: str) -> RecoveryReport:
        """Full recovery with damage classification (RecoveryReport).

        Tolerates a torn final record (crash mid-append), like etcd's WAL
        CRC-truncate on recovery; REFUSES to replay past mid-log
        corruption — the returned state is snapshot + longest valid prefix
        and `corrupt` is set so the caller resyncs from a healthy peer
        instead of silently serving a log with acked records missing.

        Crash-point consistency: the compactor publishes the snapshot
        (atomic replace) BEFORE rewriting the log, so every on-disk state a
        crash can leave behind recovers fully. A LIVE writer compacting
        concurrently (tests; split-brain probes) can still interleave our
        two reads — stale snapshot paired with an already-rewritten log
        tail, silently losing the records in between. Detected by
        re-reading the snapshot rv after the log and retrying unless it
        still equals the rv of the snapshot we actually loaded (comparing
        against the REPLAYED rv is not enough: tail records replayed past
        the new snapshot's rv would mask the staleness — found by a
        14/25-pod recovery under a compacting writer). etcd forbids the
        scenario outright via flock."""
        report = RecoveryReport()
        for _ in range(10):
            report = WriteAheadLog._recover_once(path)
            if _DEBUG:
                _trace(path, f"recover pass snap_rv={report.snap_rv} rv={report.rv}")
            snap_path = path + SNAPSHOT_SUFFIX
            try:
                with open(snap_path, encoding="utf-8") as f:
                    current_rv = json.load(f)["rv"]
            except FileNotFoundError:
                current_rv = 0
            except (json.JSONDecodeError, OSError):
                continue  # snapshot replaced mid-read: retry
            if current_rv == report.snap_rv:
                # no snapshot was published between our two reads, so the
                # log tail we replayed is consistent with the snapshot we
                # loaded (a pending rewrite of THIS snapshot's log only
                # drops records the snapshot already covers)
                WriteAheadLog._count_damage(path, report)
                return report
        # a live writer compacted under us 10 times in a row (or the
        # snapshot is unreadable): the state below may pair a stale
        # snapshot with a newer log tail — say so instead of returning it
        # as if it were clean (satellite: this used to fall through silent)
        report.retries_exhausted = True
        metrics.inc(COUNTER_RETRIES_EXHAUSTED)
        logger.error(
            "WAL recovery of %s exhausted its 10 staleness retries — the "
            "returned state may pair a stale snapshot with a newer log "
            "tail; re-run recovery once the writer is quiesced",
            path,
        )
        WriteAheadLog._count_damage(path, report)
        return report

    @staticmethod
    def _count_damage(path: str, report: RecoveryReport) -> None:
        if report.corrupt:
            metrics.inc(COUNTER_MIDLOG)
            metrics.set_gauge(GAUGE_CORRUPT, 1.0)
            logger.error(
                "WAL %s: mid-log corruption (%d bad record(s) with valid "
                "acked records after) — recovered the longest valid prefix "
                "(rv=%d); REFUSING to serve the post-damage suffix, resync "
                "from a healthy peer",
                path,
                report.bad_records,
                report.rv,
            )
        elif report.torn_tail:
            metrics.inc(COUNTER_TORN_TAIL)
            logger.warning(
                "WAL %s: torn tail (%d damaged trailing record(s), crash "
                "mid-append) — truncated at the last acked record (rv=%d)",
                path,
                report.bad_records,
                report.rv,
            )

    @staticmethod
    def _recover_once(path: str) -> RecoveryReport:
        report = RecoveryReport()
        objects = report.objects
        snap_path = path + SNAPSHOT_SUFFIX
        log_path = path + LOG_SUFFIX
        if os.path.exists(snap_path):
            with open(snap_path, encoding="utf-8") as f:
                snap = json.load(f)
            report.rv = report.snap_rv = snap["rv"]
            for kind, objs in snap["objects"].items():
                d = objects.setdefault(kind, {})
                for data in objs:
                    obj = serialization.decode(kind, data)
                    d[obj.metadata.key] = obj
        if os.path.exists(log_path):
            bad_seen = False
            with open(log_path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    rec = parse_wal_line(line)
                    if rec is None:
                        # damaged record: stop replaying, keep scanning to
                        # classify (torn tail vs mid-log corruption)
                        report.bad_records += 1
                        bad_seen = True
                        continue
                    if bad_seen:
                        # a valid acked record AFTER damage: this is not a
                        # crash artifact, it is medium corruption — never
                        # replay past it (the rv sequence has a hole)
                        report.corrupt = True
                        continue
                    verb = rec.get("verb")
                    if verb == "commit":
                        # consensus epoch record: no object change; it may
                        # share a data record's rv, so handle BEFORE the
                        # rv-dedup skip below
                        report.commit = max(report.commit, int(rec.get("commit", 0)))
                        continue
                    if rec["rv"] <= report.rv:
                        continue  # already in snapshot
                    report.rv = rec["rv"]
                    kind = rec["kind"]
                    d = objects.setdefault(kind, {})
                    if verb == "delete":
                        obj = serialization.decode(kind, rec["obj"])
                        d.pop(obj.metadata.key, None)
                    else:
                        obj = serialization.decode(kind, rec["obj"])
                        d[obj.metadata.key] = obj
            if bad_seen and not report.corrupt:
                report.torn_tail = True
        return report
