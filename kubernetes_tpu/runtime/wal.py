"""Write-ahead log + snapshot persistence for the API store.

The reference's durability story is etcd (staging/src/k8s.io/apiserver/pkg/
storage/etcd3/store.go): every write is raft-logged before acknowledgment
and state survives any component crash. This collapses that into a
single-node WAL with the same crash-only contract: a mutation is
acknowledged only after its record is on disk; recovery = load latest
snapshot + replay the tail. Compaction writes a full snapshot and truncates
the log (etcd's snapshot/compact cycle).

Record format: one JSON line per mutation
  {"rv": N, "verb": "create|update|delete", "kind": resource, "obj": {...}}
Snapshot format: {"rv": N, "objects": {resource: [obj, ...]}}
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..api import serialization

SNAPSHOT_SUFFIX = ".snapshot.json"
LOG_SUFFIX = ".wal"


class WriteAheadLog:
    def __init__(
        self,
        path: str,
        compact_every: int = 50_000,
        fsync: bool = True,
    ):
        """`path` is a prefix: <path>.wal + <path>.snapshot.json.

        fsync=True (the DEFAULT, matching etcd: acknowledged means on
        media) fsyncs every append before the mutation is acknowledged.
        fsync=False trades media-durability for throughput — the write is
        still flushed to the OS, so it survives process crashes but not
        machine crashes (etcd's --unsafe-no-fsync testing mode); benchmarks
        and tests may opt out explicitly."""
        self.path = path
        self.log_path = path + LOG_SUFFIX
        self.snap_path = path + SNAPSHOT_SUFFIX
        self.compact_every = compact_every
        self.fsync = fsync
        self._lock = threading.Lock()
        self._since_compact = 0
        os.makedirs(os.path.dirname(os.path.abspath(self.log_path)), exist_ok=True)
        self._f = open(self.log_path, "a", encoding="utf-8")

    # -- write path ----------------------------------------------------------

    def append(self, rv: int, verb: str, kind: str, obj: Any) -> None:
        rec = {
            "rv": rv,
            "verb": verb,
            "kind": kind,
            "obj": serialization.encode(obj) if obj is not None else None,
        }
        line = json.dumps(rec, default=str)
        with self._lock:
            self._f.write(line + "\n")
            self._f.flush()
            if self.fsync:
                os.fsync(self._f.fileno())
            self._since_compact += 1

    def due(self) -> bool:
        with self._lock:
            return self._since_compact >= self.compact_every

    def write_snapshot(self, rv: int, objects: Dict[str, List[Any]]) -> None:
        """Publish a snapshot at `rv` and drop log records it covers.
        Serialization happens OUTSIDE the wal lock (and the caller runs this
        off the store's mutation path — see APIServer._compact_async);
        appends racing the compaction are preserved by rewriting, not
        truncating, the log tail."""
        snap = {
            "rv": rv,
            "objects": {
                kind: [serialization.encode(o) for o in objs]
                for kind, objs in objects.items()
            },
        }
        tmp = self.snap_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(snap, f, default=str)
            f.flush()
            os.fsync(f.fileno())
        with self._lock:
            os.replace(tmp, self.snap_path)  # atomic publish
            # rewrite the log keeping only records newer than the snapshot
            self._f.close()
            keep: List[str] = []
            with open(self.log_path, encoding="utf-8") as f:
                for line in f:
                    line = line.rstrip("\n")
                    if not line:
                        continue
                    try:
                        if json.loads(line)["rv"] > rv:
                            keep.append(line)
                    except json.JSONDecodeError:
                        continue
            self._f = open(self.log_path, "w", encoding="utf-8")
            for line in keep:
                self._f.write(line + "\n")
            self._f.flush()
            self._since_compact = len(keep)

    def close(self) -> None:
        with self._lock:
            self._f.close()

    # -- recovery ------------------------------------------------------------

    @staticmethod
    def recover(path: str) -> Tuple[int, Dict[str, Dict[str, Any]]]:
        """Load snapshot + replay log tail. Returns (rv, {kind: {key: obj}}).
        Tolerates a torn final record (crash mid-append), like etcd's WAL
        CRC-truncate on recovery."""
        rv = 0
        objects: Dict[str, Dict[str, Any]] = {}
        snap_path = path + SNAPSHOT_SUFFIX
        log_path = path + LOG_SUFFIX
        if os.path.exists(snap_path):
            with open(snap_path, encoding="utf-8") as f:
                snap = json.load(f)
            rv = snap["rv"]
            for kind, objs in snap["objects"].items():
                d = objects.setdefault(kind, {})
                for data in objs:
                    obj = serialization.decode(kind, data)
                    d[obj.metadata.key] = obj
        if os.path.exists(log_path):
            with open(log_path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        break  # torn tail record: truncate here
                    if rec["rv"] <= rv:
                        continue  # already in snapshot
                    rv = rec["rv"]
                    kind = rec["kind"]
                    verb = rec["verb"]
                    d = objects.setdefault(kind, {})
                    if verb == "delete":
                        obj = serialization.decode(kind, rec["obj"])
                        d.pop(obj.metadata.key, None)
                    else:
                        obj = serialization.decode(kind, rec["obj"])
                        d[obj.metadata.key] = obj
        return rv, objects
