"""Raft-lite commit-index consensus for the API store.

The reference's durability rides etcd raft: a write is acknowledged to the
client only once a MAJORITY of the raft group has it durably logged
(etcd raft's commit index; surfaced through storage.Interface at
staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go). The previous
build's replication layer fanned records out and *hoped*: on a quorum miss
it logged "proceeding availability-first" and returned success, so an
acknowledged write could sit only on the primary and vanish at failover —
exactly the writes for which the quorum-gated election's leader-
completeness argument stops holding.

This module is the missing piece: a real **commit index** over the
existing WAL + replication fan-out.

  * **commit index**: the largest rv held durably by a majority of the
    replica set (self included). The leader advances it from follower
    acks (each follower acks only after its own durable apply) and
    piggybacks it on every ``recs``/``hb`` frame so followers learn it
    too. It is monotonic: once committed, always committed.
  * **quorum-gated acks**: ``ship()`` blocks until the commit index
    covers the shipped records or a bounded window expires. Quorum met →
    the write is acknowledged, and by construction a majority holds it.
  * **degraded read-only mode**: on quorum miss the store does NOT lie.
    The in-flight write fails with :class:`QuorumLost` (retryable; HTTP
    503 + Retry-After through apiserver/rest.py) and the store enters an
    explicit degraded mode — subsequent writes fail fast with
    :class:`DegradedWrites` while reads and watches keep serving. The
    WAL records the epoch transition. When follower acks catch the
    commit index up to the leader's tip (a quorum again holds every
    appended record), the leader re-opens writes and logs the
    ``restored`` epoch.
  * **provably lossless failover**: election votes on
    ``(term, commit_index, last_rv)`` — rv order is log-prefix order, so
    the winner holds every committed (= client-acknowledged) write.
    scripts/consistency_check.py replays a chaos run's client-visible
    acks against surviving replica state and fails on any loss.
  * **commit-index resync**: a reconnecting follower's hello carries its
    rv; when the leader's record buffer still covers that suffix it
    replays just the tail (``catchup`` frame) instead of shipping a full
    snapshot.

Kept deliberately raft-*lite*: there is one log (the store's rv sequence),
terms come from the existing promotion/fencing protocol, and membership is
static per process lifetime. What is NOT cut is the safety core: no
acknowledgment without majority durability, no commit-index regression,
no write acceptance without a quorum connected.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils.metrics import metrics

logger = logging.getLogger("kubernetes_tpu.runtime.consensus")

# quorum_state gauge values (utils/metrics.py: apiserver_quorum_state)
HEALTHY = 1.0
DEGRADED = 0.0

# metrics series names (PERFORMANCE.md "Durability" section): the SIGUSR2
# debugger dump (scheduler/cache/debugger.py) renders every gauge under
# this prefix, so a wedged cluster is diagnosable without logs.
GAUGE_COMMIT_INDEX = "apiserver_commit_index"
GAUGE_QUORUM_STATE = "apiserver_quorum_state"
GAUGE_FOLLOWER_LAG = "apiserver_replication_follower_lag"
GAUGE_REPLICA_TIP = "apiserver_replication_tip_rv"
COUNTER_DEGRADED_ENTRIES = "apiserver_degraded_entries_total"
COUNTER_DEGRADED_REJECTS = "apiserver_writes_rejected_degraded_total"
COUNTER_CATCHUP_RESYNCS = "apiserver_replication_catchup_resyncs_total"
COUNTER_SNAPSHOT_RESYNCS = "apiserver_replication_snapshot_resyncs_total"


class DegradedWrites(RuntimeError):
    """Write rejected: the store is in degraded read-only mode because a
    quorum of the replica set is not caught up. Retryable — surfaced as
    HTTP 503 + Retry-After by apiserver/rest.py; reads and watches keep
    serving. Distinct from NotPrimary (a fenced store never re-opens)."""

    retry_after_s = 1.0


class QuorumLost(DegradedWrites):
    """THIS write missed quorum inside the ack window. Its outcome is
    unknown (the record is durable locally and streamed to followers; it
    may yet commit) — the one honest answer is "not acknowledged, retry".
    Raising it also flips the store into degraded read-only mode."""


class DiskFailed(DegradedWrites):
    """Write rejected: this replica's WAL sink hit a write/fsync error and
    is fail-stopped (runtime/wal.py SinkFailed — the fsyncgate discipline:
    a failed fsync is never retried). Permanent for THIS process; the
    503 + Retry-After is still honest because a leader with a failed disk
    releases its lease and a disk-healthy replica promotes, so retries
    land somewhere writable."""


class DiskPressure(DegradedWrites):
    """Write rejected: the WAL volume is under disk pressure (low-watermark
    probe tripped, or an append hit ENOSPC and was rolled back). Lifts
    automatically when free space recovers — compaction is attempted as
    reclaim — so this IS plainly retryable."""


class RecordBuffer:
    """Bounded in-memory tail of the leader's replicated log, for
    commit-index resync: a reconnecting follower at rv R gets the
    ``(R, tip]`` suffix replayed instead of a full snapshot whenever the
    buffer still covers R+1. Entries are wire-encoded records
    ``[rv, verb, kind, data]`` in strict rv order."""

    def __init__(self, maxlen: int = 50_000):
        self.maxlen = maxlen
        self._recs: List[list] = []
        self._lock = threading.Lock()

    def extend(self, recs: List[list]) -> None:
        with self._lock:
            self._recs.extend(recs)
            if len(self._recs) > self.maxlen:
                del self._recs[: len(self._recs) - self.maxlen]

    def since(self, rv: int) -> Optional[List[list]]:
        """Records with rv' > rv, or None when the suffix is no longer
        fully buffered (caller must fall back to a snapshot)."""
        with self._lock:
            if not self._recs:
                return None if rv < 0 else []
            if self._recs[0][0] > rv + 1:
                return None  # gap: the tail was evicted past rv
            return [r for r in self._recs if r[0] > rv]

    def __len__(self) -> int:
        with self._lock:
            return len(self._recs)


class ConsensusCoordinator:
    """Leader-side commit-index authority for one replica set.

    Owns: per-follower match indices, the monotonic commit index, the
    healthy/degraded epoch state, the WAL epoch records, and the metrics
    gauges. The ReplicationListener feeds it (local appends, follower
    acks/drops) and blocks on :meth:`wait_commit`; the APIServer's write
    gate (runtime/store.py) consults :meth:`check_writable` before any
    mutation is applied."""

    def __init__(
        self,
        cluster_size: int,
        term: int = 1,
        window_s: float = 0.75,
        buffer_len: int = 50_000,
    ):
        if cluster_size < 1:
            raise ValueError(f"cluster_size must be >= 1, got {cluster_size}")
        self.cluster_size = cluster_size
        self.term = term
        self.window_s = window_s
        self.buffer = RecordBuffer(buffer_len)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._match: Dict[int, int] = {}  # follower id -> durably acked rv
        self._tip = 0  # leader's own last durable rv
        self._commit = 0  # monotonic commit index
        self._degraded = False
        self._degraded_since: Optional[float] = None
        self._wal = None  # epoch-transition records land here
        self._on_reopen: List[Callable[[], None]] = []
        self._publish_locked()

    # -- wiring ---------------------------------------------------------------

    def attach_wal(self, wal) -> None:
        self._wal = wal

    def on_reopen(self, cb: Callable[[], None]) -> None:
        """Register a callback fired (off-lock) when degraded mode lifts."""
        self._on_reopen.append(cb)

    # -- quorum math ----------------------------------------------------------

    @property
    def majority(self) -> int:
        """Replicas (self included) that must hold a record durably."""
        return self.cluster_size // 2 + 1

    def _commit_candidate_locked(self) -> int:
        """Largest rv held by a majority: k-th largest of the match vector
        padded with zeros for unseen members (raft's matchIndex median)."""
        held = sorted([self._tip] + list(self._match.values()), reverse=True)
        held += [0] * max(0, self.cluster_size - len(held))
        return held[self.majority - 1]

    # -- leader-side events ---------------------------------------------------

    def local_append(self, rv: int, recs: Optional[List[list]] = None) -> None:
        """The leader durably appended up to rv (WAL fsync done); buffer
        the wire records for commit-index resync of reconnectors."""
        if recs:
            self.buffer.extend(recs)
        with self._cond:
            if rv > self._tip:
                self._tip = rv
            reopened = self._advance_locked()
        if reopened:
            self._after_reopen()

    def follower_ack(self, follower_id: int, rv: int) -> None:
        """A follower durably holds up to rv. Advances the commit index;
        lifts degraded mode when a quorum has caught the tip."""
        with self._cond:
            if rv > self._match.get(follower_id, 0):
                self._match[follower_id] = rv
            reopened = self._advance_locked()
        if reopened:
            self._after_reopen()

    def forget(self, follower_id: int) -> None:
        """Follower link died: its future acks can no longer advance the
        quorum. The commit index never regresses (committed is forever)."""
        with self._cond:
            self._match.pop(follower_id, None)
            self._publish_locked()
        # retire the departed link's lag series: a stale gauge would read
        # as a live in-sync replica in the SIGUSR2 dump
        metrics.remove_gauge(
            GAUGE_FOLLOWER_LAG, labels={"follower": str(follower_id)}
        )

    def _advance_locked(self) -> bool:
        """Recompute the commit index under the lock. Returns True when
        degraded mode just lifted — the caller runs _after_reopen() OFF
        the lock (the epoch WAL append and callbacks must not nest it)."""
        cand = self._commit_candidate_locked()
        if cand > self._commit:
            self._commit = cand
            self._cond.notify_all()
        reopened = False
        if self._degraded and self._commit >= self._tip:
            # a quorum again holds EVERY appended record: re-open writes
            self._degraded = False
            self._degraded_since = None
            reopened = True
        self._publish_locked()
        return reopened

    def _after_reopen(self) -> None:
        self._log_epoch("restored")
        logger.warning(
            "write quorum restored at commit_index=%d (tip=%d): "
            "leaving degraded read-only mode", self.commit_index, self.tip,
        )
        for cb in list(self._on_reopen):
            try:
                cb()
            except Exception:
                logger.exception("consensus reopen callback failed")

    # -- ship-path gate -------------------------------------------------------

    def wait_commit(self, rv: int, window_s: Optional[float] = None) -> bool:
        """Block until commit_index >= rv or the window expires. True =
        committed (the caller may acknowledge the write)."""
        deadline = time.monotonic() + (
            self.window_s if window_s is None else window_s
        )
        with self._cond:
            while self._commit < rv:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True

    def quorum_miss(self, rv: int) -> Optional[QuorumLost]:
        """The write at rv missed its window: enter degraded read-only
        mode (idempotent) and return the exception the write path must
        raise — the client is NOT acknowledged. Returns None when an ack
        raced the window expiry and the commit index already covers rv —
        the write IS committed and must be acknowledged normally;
        entering degraded mode then would wedge a healthy store
        read-only forever (nothing would ever lift it: rejected writes
        don't append, and caught-up followers send no further acks)."""
        with self._cond:
            if self._commit >= rv:
                return None
            entered = not self._degraded
            if entered:
                self._degraded = True
                self._degraded_since = time.monotonic()
                metrics.inc(COUNTER_DEGRADED_ENTRIES)
            self._publish_locked()
            commit, needed = self._commit, self.majority
        if entered:
            self._log_epoch("degraded")
            logger.error(
                "write quorum NOT met for rv=%d (commit_index=%d, need %d/%d "
                "replicas): entering degraded READ-ONLY mode until a quorum "
                "catches up; the in-flight write is NOT acknowledged",
                rv, commit, needed, self.cluster_size,
            )
        return QuorumLost(
            f"write quorum lost: rv {rv} not committed "
            f"(commit_index={commit}, majority={needed}/{self.cluster_size}); "
            "store is degraded read-only — retry after quorum recovery"
        )

    def check_writable(self) -> None:
        """Degraded-mode gate, consulted by the store BEFORE applying any
        mutation (runtime/store.py WriteGate): fail fast instead of
        burning an ack window per rejected write."""
        if self._degraded:
            metrics.inc(COUNTER_DEGRADED_REJECTS)
            with self._lock:
                commit, tip = self._commit, self._tip
            raise DegradedWrites(
                f"store degraded read-only: write quorum lost "
                f"(commit_index={commit}, tip={tip}); reads and watches "
                "still serve — retry later"
            )

    # -- introspection --------------------------------------------------------

    @property
    def commit_index(self) -> int:
        with self._lock:
            return self._commit

    @property
    def degraded(self) -> bool:
        with self._lock:
            return self._degraded

    @property
    def tip(self) -> int:
        with self._lock:
            return self._tip

    def acked_quorum_size(self, rv: int) -> int:
        """Replicas (self included) known to durably hold rv — the test
        hook behind "an ack implies commit_index >= rv on a majority"."""
        with self._lock:
            n = 1 if self._tip >= rv else 0
            return n + sum(1 for v in self._match.values() if v >= rv)

    def state(self) -> Dict[str, Any]:
        """Structured dump for the SIGUSR2 debugger and tests."""
        with self._lock:
            return {
                "term": self.term,
                "cluster_size": self.cluster_size,
                "majority": self.majority,
                "tip": self._tip,
                "commit_index": self._commit,
                "quorum_state": "degraded" if self._degraded else "healthy",
                "degraded_for_s": (
                    round(time.monotonic() - self._degraded_since, 3)
                    if self._degraded_since is not None
                    else 0.0
                ),
                "follower_match": dict(self._match),
                "follower_lag": {
                    fid: self._tip - rv for fid, rv in self._match.items()
                },
                "buffered_records": len(self.buffer),
            }

    # -- internals ------------------------------------------------------------

    def _publish_locked(self) -> None:
        # scalars only: this runs on every local append AND every
        # follower ack (the write hot path). The per-follower lag series
        # is O(followers) metrics-lock traffic and is refreshed from the
        # heartbeat loop instead (publish_follower_lags).
        metrics.set_gauge(GAUGE_COMMIT_INDEX, float(self._commit))
        metrics.set_gauge(GAUGE_REPLICA_TIP, float(self._tip))
        metrics.set_gauge(
            GAUGE_QUORUM_STATE, DEGRADED if self._degraded else HEALTHY
        )

    def publish_follower_lags(self) -> None:
        """Refresh the per-follower lag gauges — called once per
        heartbeat beat (runtime/replication.py), OFF the write path."""
        with self._lock:
            lags = {fid: max(self._tip - rv, 0) for fid, rv in self._match.items()}
        for fid, lag in lags.items():
            metrics.set_gauge(
                GAUGE_FOLLOWER_LAG, float(lag), labels={"follower": str(fid)}
            )

    def _log_epoch(self, event: str) -> None:
        """Durable epoch-transition record: recovery (and the consistency
        checker) can see exactly when acks stopped being quorum-backed."""
        wal = self._wal
        if wal is None:
            return
        with self._lock:
            tip, commit = self._tip, self._commit
        try:
            wal.append_commit(tip, commit, self.term, event)
        except OSError:
            logger.exception("failed to log %s epoch transition", event)


def vote_key(status: Dict[str, Any]) -> Tuple[int, int, int, int]:
    """Election ordering over (term, commit_index, last_rv): term first
    (raft's up-to-date check), then rv (log length; rv order is log-
    prefix order), then the candidate's HELD commit (its commit claim
    capped at its rv), then id as the deterministic tiebreak.

    rv deliberately outranks the commit claim: a lagging follower can
    LEARN a high commit index from a heartbeat without HOLDING the
    committed records (commit rides every hb frame), and ranking that
    claim above log length would elect it over the follower that
    actually has them — losing acknowledged writes. Raft's ballot is
    (term, lastLogIndex) for exactly this reason. The commit index still
    gates the election, as a floor: a candidate whose rv is below any
    learned commit index refuses to promote at all (the known_commit
    check in Follower._run_election) — it KNOWS acknowledged writes
    exist that it does not hold."""
    rv = int(status.get("rv", 0))
    return (
        int(status.get("term", 0)),
        rv,
        min(int(status.get("commit", 0)), rv),
        int(status.get("id", -1)),
    )


def log_key(status: Dict[str, Any]) -> Tuple[int, int, int]:
    """vote_key without the node-id tiebreak: the voter-side up-to-date
    check (raft §5.4.1). A voter grants to any candidate whose log is AT
    LEAST as up-to-date as its own — including exact ties, or two equally
    caught-up candidates would each self-vote and refuse the other
    forever (the id tiebreak belongs to ranking, not to grant
    eligibility; dueling ties resolve by jittered election timing)."""
    return vote_key(status)[:3]
