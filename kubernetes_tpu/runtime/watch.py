"""Watch interface: typed change events over a queue.

Equivalent of apimachinery's watch.Interface
(staging/src/k8s.io/apimachinery/pkg/watch/watch.go): a result channel of
{Added, Modified, Deleted, Bookmark} events plus Stop. BOOKMARK events
carry only a resourceVersion (no object state change): the watch cache
(apiserver/cacher.py) emits them periodically so idle watchers' resume
positions keep advancing and a reconnect stays inside the replay window.
The raw store never emits them — only the cacher fan-out does.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass
from typing import Any, Iterator, Optional

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
BOOKMARK = "BOOKMARK"


@dataclass
class Event:
    type: str
    object: Any
    resource_version: int = 0
    # fan-out enqueue timestamp (time.monotonic), stamped by the watch
    # cache's dispatch loop; lets consumers measure delivery latency
    # without a side channel. 0.0 for events from the raw store.
    ts: float = 0.0


class Watcher:
    """A single watch stream; the store pushes events, the consumer iterates."""

    def __init__(self, maxsize: int = 100000):
        self._q: "queue.Queue[Optional[Event]]" = queue.Queue(maxsize=maxsize)
        self._stopped = threading.Event()

    def push(self, ev: Event) -> None:
        if not self._stopped.is_set():
            self._q.put(ev)

    def stop(self) -> None:
        if not self._stopped.is_set():
            self._stopped.set()
            self._q.put(None)

    @property
    def stopped(self) -> bool:
        return self._stopped.is_set()

    def get(self, timeout: Optional[float] = None) -> Optional[Event]:
        """Next event, or None if stopped / timed out."""
        try:
            ev = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        return ev

    def __iter__(self) -> Iterator[Event]:
        while True:
            ev = self._q.get()
            if ev is None:
                return
            yield ev
