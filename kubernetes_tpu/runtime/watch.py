"""Watch interface: typed change events over a queue.

Equivalent of apimachinery's watch.Interface
(staging/src/k8s.io/apimachinery/pkg/watch/watch.go): a result channel of
{Added, Modified, Deleted, Bookmark} events plus Stop. BOOKMARK events
carry only a resourceVersion (no object state change): the watch cache
(apiserver/cacher.py) emits them periodically so idle watchers' resume
positions keep advancing and a reconnect stays inside the replay window.
The raw store never emits them — only the cacher fan-out does.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass
from typing import Any, Iterator, Optional

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
BOOKMARK = "BOOKMARK"


@dataclass
class Event:
    type: str
    object: Any
    resource_version: int = 0
    # fan-out enqueue timestamp (time.monotonic), stamped by the watch
    # cache's dispatch loop; lets consumers measure delivery latency
    # without a side channel. 0.0 for events from the raw store.
    ts: float = 0.0


COUNTER_OVERFLOW = "watch_queue_overflow_total"


class Watcher:
    """A single watch stream; the store pushes events, the consumer iterates.

    push() and stop() are NON-BLOCKING by contract: both run on single-
    threaded dispatch paths (the store's write-path ``_notify`` fan-out,
    the watch cache's per-kind dispatch thread), where one blocking
    ``queue.put`` against a full queue wedges every watcher behind the
    loop — the CacheWatcher variant of this bug stalled the cacher
    dispatch thread on the stop() sentinel put until PR 6 overrode it.
    The discipline now lives in the base class: a consumer whose queue
    fills (maxsize events of backlog — dead, not slow) is terminated and
    counted, and stop() drops its wake-up sentinel on the floor when the
    queue is full, so iteration ends via the stopped-flag poll instead.
    """

    def __init__(self, maxsize: int = 100000):
        self._q: "queue.Queue[Optional[Event]]" = queue.Queue(maxsize=maxsize)
        self._stopped = threading.Event()

    def push(self, ev: Event) -> None:
        if self._stopped.is_set():
            return
        try:
            self._q.put_nowait(ev)
        except queue.Full:
            # a consumer maxsize events behind is gone; terminating it is
            # the only option that doesn't block the dispatch thread
            from ..utils.metrics import metrics

            metrics.inc(COUNTER_OVERFLOW)
            self.stop()

    def stop(self) -> None:
        if not self._stopped.is_set():
            self._stopped.set()
            try:
                self._q.put_nowait(None)
            except queue.Full:
                pass  # sentinel-free termination: __iter__/get poll stopped

    @property
    def stopped(self) -> bool:
        return self._stopped.is_set()

    def get(self, timeout: Optional[float] = None) -> Optional[Event]:
        """Next event, or None if stopped / timed out."""
        try:
            ev = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        return ev

    def __iter__(self) -> Iterator[Event]:
        # sentinel-free termination: a dropped sentinel (full queue at
        # stop time) must still end the iteration once the queue drains
        while True:
            try:
                ev = self._q.get(timeout=0.2)
            except queue.Empty:
                if self._stopped.is_set():
                    return
                continue
            if ev is None:
                return
            yield ev
