"""Runtime primitives: watch events and thread-safe stores (apimachinery-lite)."""

from .watch import Event, ADDED, MODIFIED, DELETED, Watcher  # noqa: F401
from .store import ThreadSafeStore, Indexer  # noqa: F401
