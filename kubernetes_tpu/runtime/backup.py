"""Fenced online backup / disaster restore for the API-server store.

Backup is an ONLINE consistent image: one lock acquisition captures the
full object state at a single resourceVersion, plus the consensus commit
index and the replication term (``APIServer.backup_state``). Nothing
stops serving while it runs — the lock hold is the same order as a big
LIST.

Restore is FENCED. A restored cluster is a new epoch: clients, schedulers
and ex-leaders from before the disaster may still be running with state
(and fencing tokens) minted against the old one. Restoring bytes alone
would let them write — the classic split-brain-after-restore. So restore:

  * bumps every lease's ``lease_transitions`` and clears its holder, so
    every pre-restore ``BindFence`` is STRUCTURALLY rejected by the
    store's fence check (identity and transition count both mismatch) —
    no grace periods, no wall clocks;
  * bumps the replication term past the backup's, durably (an
    ``append_commit`` record), so a zombie ex-primary that reconnects is
    fenced by the raft higher-term rule before it can ship a frame.

The image format is versioned JSON (``ktpu-backup-v1``) written with
tmp + fsync + atomic rename — a torn backup file is impossible, only an
old-or-new one.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict

from ..api import serialization
from ..utils.metrics import metrics
from .wal import LOG_SUFFIX, SNAPSHOT_SUFFIX, WriteAheadLog, parse_wal_line

logger = logging.getLogger("kubernetes_tpu.runtime.backup")

BACKUP_FORMAT = "ktpu-backup-v1"

COUNTER_BACKUPS = "store_backups_total"
COUNTER_RESTORES = "store_restores_total"
# leases fenced (holder cleared + transitions bumped) during restores —
# equals the number of pre-restore BindFence tokens structurally voided
COUNTER_RESTORE_FENCED = "store_restore_fenced_leases_total"

__all__ = [
    "BACKUP_FORMAT",
    "backup_from_server",
    "backup_from_wal",
    "load_backup",
    "write_backup",
    "restore_into",
]


def write_backup(image: Dict[str, Any], path: str) -> str:
    """Durably write a backup image: tmp + fsync + atomic rename, the
    same crash discipline as the WAL's snapshot publish."""
    if image.get("format") != BACKUP_FORMAT:
        raise ValueError(
            f"not a {BACKUP_FORMAT} image: format={image.get('format')!r}"
        )
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(image, f, default=str)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    metrics.inc(COUNTER_BACKUPS)
    logger.info(
        "backup written: %s (rv=%d commit=%d term=%d, %d kinds)",
        path, image["rv"], image["commit"], image["term"],
        len(image["objects"]),
    )
    return path


def backup_from_server(server, path: str) -> Dict[str, Any]:
    """Online backup of a LIVE server (one-lock-consistent image)."""
    image = server.backup_state()
    write_backup(image, path)
    return image


def backup_from_wal(wal_path: str, path: str) -> Dict[str, Any]:
    """Offline backup from a (stopped) server's WAL directory — the
    disaster case where no live server exists to snapshot. Recovery
    semantics are identical to a crash restart: torn tails truncate,
    mid-log corruption stops replay at the longest valid prefix (and is
    surfaced in the image so the operator knows the backup may miss
    acked writes)."""
    report = WriteAheadLog.recover_report(wal_path)
    term = _max_logged_term(wal_path)
    image = {
        "format": BACKUP_FORMAT,
        "rv": report.rv,
        "commit": report.commit or report.rv,
        "term": term,
        "objects": {
            kind: [serialization.encode(o) for o in store.values()]
            for kind, store in report.objects.items()
        },
    }
    if report.corrupt:
        image["source_corrupt"] = True
        logger.error(
            "offline backup of %s: source WAL was mid-log corrupt — the "
            "image holds the longest valid prefix (rv=%d) and may be "
            "missing acknowledged writes", wal_path, report.rv,
        )
    write_backup(image, path)
    return image


def _max_logged_term(wal_path: str) -> int:
    """Highest replication term recorded in the log's commit records
    (1 when the store never ran in consensus mode)."""
    term = 1
    try:
        with open(wal_path + LOG_SUFFIX, encoding="utf-8") as f:
            for line in f:
                rec = parse_wal_line(line.rstrip("\n"))
                if rec is not None and rec.get("verb") == "commit":
                    term = max(term, int(rec.get("term", 1)))
    except OSError:
        pass
    return term


def load_backup(path: str) -> Dict[str, Any]:
    with open(path, encoding="utf-8") as f:
        image = json.load(f)
    if image.get("format") != BACKUP_FORMAT:
        raise ValueError(
            f"{path}: not a {BACKUP_FORMAT} image "
            f"(format={image.get('format')!r})"
        )
    return image


def restore_into(
    image: Dict[str, Any], wal_path: str, force: bool = False
) -> Dict[str, Any]:
    """Materialize a backup image as a FRESH fenced WAL at ``wal_path``.

    Refuses to clobber an existing log unless ``force`` (restoring over
    live state is the operator's most expensive typo). Returns a summary
    dict: {rv, term, fenced_leases, objects}.

    Fencing: every lease in the image has its holder cleared and its
    transition count bumped, and the replication term is bumped past the
    image's — see the module docstring for why both are load-bearing.
    """
    log_path = wal_path + LOG_SUFFIX
    if not force and os.path.exists(log_path) and os.path.getsize(log_path):
        raise FileExistsError(
            f"{log_path} exists and is non-empty; pass force=True to "
            "overwrite it with the restored image"
        )

    rv = int(image["rv"])
    old_term = int(image.get("term", 1))
    new_term = old_term + 1

    objects: Dict[str, list] = {}
    fenced = 0
    for kind, docs in image["objects"].items():
        decoded = []
        for data in docs:
            obj = serialization.decode(kind, data)
            if kind == "leases":
                # void every pre-restore BindFence: wrong holder AND
                # wrong transition count — structural rejection, no
                # reliance on lease expiry wall-clocks
                obj.holder_identity = ""
                obj.lease_transitions = int(obj.lease_transitions) + 1
                obj.renew_time = 0.0
                fenced += 1
            decoded.append(obj)
        objects[kind] = decoded

    if force:
        for suffix in (LOG_SUFFIX, SNAPSHOT_SUFFIX):
            try:
                os.unlink(wal_path + suffix)
            except FileNotFoundError:
                pass

    wal = WriteAheadLog(wal_path)
    try:
        wal.write_snapshot(rv, objects)
        # durable epoch bump: a recovering replica learns the post-
        # restore term from this record, and any zombie ex-primary at
        # old_term is fenced by the higher-term rule on first contact
        wal.append_commit(rv, rv, new_term, "restore")  # graftlint: walseam-exempt(restore target: nothing serves from this WAL yet, a failed restore must abort loudly and propagate)
    finally:
        wal.close()

    metrics.inc(COUNTER_RESTORES)
    metrics.inc(COUNTER_RESTORE_FENCED, by=float(fenced))
    logger.warning(
        "restored %s from backup image: rv=%d term %d->%d, %d leases "
        "fenced (all pre-restore bind tokens are now invalid)",
        wal_path, rv, old_term, new_term, fenced,
    )
    return {
        "rv": rv,
        "term": new_term,
        "fenced_leases": fenced,
        "objects": sum(len(v) for v in objects.values()),
    }
