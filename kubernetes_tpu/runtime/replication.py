"""API-store replication: quorum WAL shipping + quorum-gated failover.

The reference's HA story for the API store is etcd raft behind
storage.Interface (staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go:1,
watch fan-out storage/cacher/cacher.go:448): writes replicate to a quorum
before acknowledgment and a new leader takes over on lease expiry. This
build keeps the single-writer store (client/apiserver.py) and adds the
raft-lite subset that matters at this scale:

  * **log shipping, parallel fan-out, quorum-acked**: every acknowledged
    mutation is streamed to ALL followers concurrently under ONE shared
    deadline; the client sees success once a MAJORITY of the replica set
    (primary included) holds the record durable. A slow follower past the
    quorum is left connected to catch up; a follower that would stall the
    quorum itself is ejected with an explicit frame so it knows it is
    stale and must not self-promote.
  * **terms**: each promotion bumps a monotonically increasing term. A
    handshake carrying a higher term FENCES the lower-term node: a deposed
    primary that learns of a successor steps down to read-only (raft's
    "higher term wins").
  * **quorum-gated election**: followers know the replica-set peer list.
    On primary-lease expiry a follower first VERIFIES the primary is
    actually unreachable (a merely-slow link re-tails instead of
    promoting), then polls its peers; it promotes only when it can reach
    a strict majority of the replica set AND holds the highest (rv, id)
    among reachable candidates. rv order is log-prefix order (records
    apply strictly in rv sequence), so the max-rv survivor provably holds
    every quorum-acked write — raft's leader-completeness argument in
    miniature. A minority partition can never elect: split-brain is
    structurally excluded.

Wire protocol: newline-delimited JSON frames over TCP.
  follower -> primary  {"hello": {"rv": N, "term": T}}
  primary  -> follower {"snap": {"rv": N, "term": T, "objects": {...}}}
                       {"recs": [[rv, verb, kind, obj|null], ...], "term": T}
                       {"hb": rv, "term": T}
                       {"ejected": T}   (you are out of the sync set)
  follower -> primary  {"ack": rv}
Election endpoint (per follower): {"status": 1} ->
  {"rv": N, "term": T, "synced": 0|1, "promoted": 0|1, "id": I}
A primary receiving a hello with term > its own replies {"fence": T} and
steps its store down; a follower seeing a snap/recs term < its own drops
the connection (stale primary).
"""

from __future__ import annotations

import json
import logging
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..api import serialization

# ONE NotPrimary type for the whole tree (advisor r4): the store raises it
# on fenced writes; re-exported here for callers importing from runtime.
from ..client.apiserver import NotPrimary  # noqa: F401  (re-export)

logger = logging.getLogger("kubernetes_tpu.runtime.replication")


def _send(f, frame: dict) -> None:
    f.write((json.dumps(frame, default=str) + "\n").encode())
    f.flush()


def _recv(f) -> Optional[dict]:
    line = f.readline()
    if not line:
        return None
    return json.loads(line)


class _FollowerConn:
    """Primary-side state for one connected follower."""

    def __init__(self, sock: socket.socket, rfile, wfile):
        self.sock = sock
        self.rfile = rfile
        self.wfile = wfile
        self.lock = threading.Lock()  # serialize frames on this link
        self.acked_rv = 0
        self.ack_cond = threading.Condition(self.lock)


class ReplicationListener:
    """Primary-side replication endpoint. Attach to an APIServer via
    `attach(server)`: every logged mutation is shipped to all connected
    followers in parallel and acknowledged once a quorum holds it.

    cluster_size: total replica count INCLUDING this primary. When set,
    ship() returns as soon as majority-minus-self followers acked (the
    primary's own WAL append is the +1); laggards stay connected and
    catch up from the TCP stream. When None (legacy two-node mode),
    every live follower must ack — still under one shared deadline.

    ack_timeout_s bounds how long the write path can stall: on deadline,
    followers that would have blocked the required quorum are ejected
    (with an explicit "ejected" frame — an ejected follower must never
    self-promote; it is missing acknowledged writes)."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        term: int = 1,
        heartbeat_s: float = 0.2,
        ack_timeout_s: float = 0.75,
        cluster_size: Optional[int] = None,
    ):
        self.term = term
        self.heartbeat_s = heartbeat_s
        self.ack_timeout_s = ack_timeout_s
        self.cluster_size = cluster_size
        self.server: Optional[Any] = None  # APIServer, set by attach()
        self._followers: List[_FollowerConn] = []
        self._lock = threading.Lock()
        # shared ack signal: ship() blocks here and re-checks the quorum on
        # every ack from ANY follower (per-conn waits would serialize — a
        # dead first conn would burn the whole deadline even with quorum
        # already met elsewhere)
        self._ack_cond = threading.Condition()
        self._stopped = threading.Event()
        self._sock = socket.create_server((host, port))
        self.address: Tuple[str, int] = self._sock.getsockname()[:2]
        threading.Thread(
            target=self._accept_loop, daemon=True, name="repl-accept"
        ).start()
        threading.Thread(
            target=self._heartbeat_loop, daemon=True, name="repl-heartbeat"
        ).start()

    # -- wiring ---------------------------------------------------------------

    def attach(self, server) -> None:
        """Install on the store: server.replicator = self."""
        self.server = server
        server.replicator = self

    @property
    def _needed_acks(self) -> Optional[int]:
        """Follower acks required for commit (None = all live followers).
        Majority of cluster_size includes the primary: N//2 followers."""
        if self.cluster_size is None:
            return None
        return self.cluster_size // 2

    # -- accept / handshake ---------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                sock, _addr = self._sock.accept()
            except OSError:
                return  # closed
            threading.Thread(
                target=self._serve_follower,
                args=(sock,),
                daemon=True,
                name="repl-follower",
            ).start()

    def _serve_follower(self, sock: socket.socket) -> None:
        rfile = sock.makefile("rb")
        wfile = sock.makefile("wb")
        try:
            hello = _recv(rfile)
            if hello is None:
                sock.close()
                return
            if "ping" in hello:
                # liveness probe (see Follower._primary_reachable): a bare
                # TCP connect is answered by the kernel's listen backlog
                # even when this process is wedged — only an application
                # reply proves the primary is actually serving
                _send(wfile, {"pong": self.term})
                sock.close()
                return
            if "hello" not in hello:
                sock.close()
                return
            peer_term = int(hello["hello"].get("term", 0))
            if peer_term > self.term:
                # a successor exists: fence ourselves (raft higher-term rule)
                _send(wfile, {"fence": peer_term})
                self._step_down(peer_term)
                sock.close()
                return
            conn = _FollowerConn(sock, rfile, wfile)
            # consistent snapshot: the follower may be arbitrarily behind
            # (or empty); ship full state under the store lock so no
            # mutation lands between snapshot and the live stream
            srv = self.server
            if srv is None:
                sock.close()
                return
            with srv._lock:
                snap = {
                    "rv": srv._rv,
                    "term": self.term,
                    "objects": {
                        kind: [serialization.encode(o) for o in store.values()]
                        for kind, store in srv._objects.items()
                    },
                }
                _send(wfile, {"snap": snap})
                with self._lock:
                    self._followers.append(conn)
        except (OSError, ValueError, json.JSONDecodeError):
            sock.close()
            return
        # ack reader: runs for the life of the connection. A recv timeout
        # is NOT a dead follower — ship() may briefly set a socket timeout
        # for its bounded send; an idle link simply has nothing to say —
        # only EOF/hard errors drop the connection.
        try:
            while not self._stopped.is_set():
                try:
                    frame = _recv(rfile)
                except TimeoutError:
                    continue
                if frame is None:
                    break
                if "ack" in frame:
                    with conn.ack_cond:
                        conn.acked_rv = int(frame["ack"])
                        conn.ack_cond.notify_all()
                    with self._ack_cond:
                        self._ack_cond.notify_all()
        except (OSError, ValueError):
            pass
        self._drop(conn)

    def _drop(self, conn: _FollowerConn, eject: bool = False) -> None:
        with self._lock:
            if conn in self._followers:
                self._followers.remove(conn)
            else:
                eject = False  # already gone; don't re-notify
        if eject:
            # explicit stale notice (advisor r4): without it the dropped
            # follower sees only silence, its lease lapses, and it promotes
            # at a stale rv with term+1 — fencing the healthy primary and
            # losing every write acked after the ejection. With the frame
            # it KNOWS it is out of the sync set and must re-sync instead.
            try:
                conn.sock.settimeout(0.5)
                with conn.lock:
                    _send(conn.wfile, {"ejected": self.term})
            except OSError:
                pass
        try:
            conn.sock.close()
        except OSError:
            pass

    def _step_down(self, new_term: int) -> None:
        logger.warning(
            "fenced by higher term %d (was %d): stepping down", new_term, self.term
        )
        srv = self.server
        if srv is not None:
            srv.read_only = True

    # -- shipping -------------------------------------------------------------

    def ship(self, records: List[Tuple[int, str, str, Any]]) -> None:
        """Replicate records (already WAL-durable locally) to every
        follower in parallel; returns once the required quorum acked.
        One shared deadline bounds the total stall at ack_timeout_s no
        matter how many followers are half-dead (r4 weak #7: the serial
        loop stalled ack_timeout PER follower)."""
        if not records:
            return
        recs = [
            [rv, verb, kind, serialization.encode(obj) if obj is not None else None]
            for rv, verb, kind, obj in records
        ]
        last_rv = records[-1][0]
        with self._lock:
            followers = list(self._followers)
        if not followers:
            return
        deadline = time.monotonic() + self.ack_timeout_s
        # send phase: fan the frame out to every link first (sends fill
        # kernel socket buffers and return; a wedged link raises/times out
        # without consuming the shared ack budget of the others)
        live: List[_FollowerConn] = []
        for conn in followers:
            try:
                # bound the SEND only, and restore blocking mode right
                # after: a persistent socket timeout would poison the ack
                # reader's blocking recv on the same socket (any write-idle
                # gap > ack_timeout would look like a dead follower)
                with conn.lock:
                    conn.sock.settimeout(self.ack_timeout_s)
                    try:
                        _send(conn.wfile, {"recs": recs, "term": self.term})
                    finally:
                        conn.sock.settimeout(None)
                live.append(conn)
            except OSError:
                logger.warning("dropping follower (send failed)")
                self._drop(conn, eject=False)
        # wait phase: ONE shared deadline and ONE shared condition across
        # ALL links; quorum satisfaction by any subset returns immediately
        needed = self._needed_acks
        with self._ack_cond:
            while True:
                n_acked = sum(1 for c in live if c.acked_rv >= last_rv)
                if needed is not None and n_acked >= needed:
                    break
                if n_acked == len(live):
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._ack_cond.wait(remaining)
        acked = [c for c in live if c.acked_rv >= last_rv]
        laggards = [c for c in live if c.acked_rv < last_rv]
        if needed is not None:
            if len(acked) < needed:
                # quorum miss: the laggards may hold the ONLY follower
                # copies of earlier writes — ejecting them here would turn
                # the next primary death into a permanent outage (every
                # replica parked un-promotable). Keep them connected; the
                # stream is buffered and their acks can catch up. Dead
                # links clean up via send/heartbeat failures (plain drop →
                # the follower reconnects and full-resyncs).
                logger.error(
                    "write quorum NOT met (%d/%d follower acks): proceeding "
                    "availability-first; durability degraded until followers "
                    "catch up",
                    len(acked),
                    needed,
                )
            # quorum met: laggards also keep their connection and catch up
            return
        for conn in laggards:
            # legacy all-ack mode: a follower that can't keep up inside
            # ack_timeout is ejected from the sync set with an explicit
            # stale notice (etcd's analogue: a dying member stalls the
            # quorum round until the leader drops it)
            logger.warning("ejecting follower (ack timeout)")
            self._drop(conn, eject=True)

    def _heartbeat_loop(self) -> None:
        while not self._stopped.wait(self.heartbeat_s):
            srv = self.server
            rv = srv._rv if srv is not None else 0
            with self._lock:
                followers = list(self._followers)
            for conn in followers:
                try:
                    with conn.lock:
                        _send(conn.wfile, {"hb": rv, "term": self.term})
                except OSError:
                    self._drop(conn)

    @property
    def follower_count(self) -> int:
        with self._lock:
            return len(self._followers)

    def close(self) -> None:
        self._stopped.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            for conn in self._followers:
                try:
                    conn.sock.close()
                except OSError:
                    pass
            self._followers.clear()


class Follower:
    """Standby replica: tails a primary's replication stream into an
    in-memory state (and optionally its own WAL), promotes on lease expiry
    — gated by sync state, primary reachability, and (when a peer list is
    configured) a majority election.

    on_promote(server) is called with the LIVE APIServer built from the
    replica when this follower wins the failover.

    peers/cluster_size/node_id (optional, all-or-nothing): the election
    configuration. `peers` lists the OTHER followers' election endpoints;
    cluster_size is the TOTAL replica count including the primary. The
    follower serves its own election endpoint at `election_address`."""

    def __init__(
        self,
        primary_addr: Tuple[str, int],
        lease_s: float = 1.0,
        wal=None,
        on_promote: Optional[Callable[[Any], None]] = None,
        peers: Optional[List[Tuple[str, int]]] = None,
        cluster_size: Optional[int] = None,
        node_id: int = 0,
    ):
        self.primary_addr = primary_addr
        self.lease_s = lease_s
        self.wal = wal
        self.on_promote = on_promote
        self.peers = list(peers) if peers else []
        self.cluster_size = cluster_size
        self.node_id = node_id
        self.term = 0
        self.rv = 0
        self.objects: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._compacting = threading.Event()
        self._last_seen: Optional[float] = None  # None until first frame
        self._promoted: Optional[Any] = None
        self._synced = threading.Event()  # snapshot applied at least once
        self._ejected = threading.Event()  # primary declared us stale
        self._election_sock: Optional[socket.socket] = None
        self.election_address: Optional[Tuple[str, int]] = None
        if peers is not None or cluster_size is not None:
            self._election_sock = socket.create_server(("127.0.0.1", 0))
            self.election_address = self._election_sock.getsockname()[:2]
            threading.Thread(
                target=self._election_loop, daemon=True, name="repl-election"
            ).start()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="repl-tail"
        )
        self._monitor = threading.Thread(
            target=self._lease_loop, daemon=True, name="repl-lease"
        )

    def start(self) -> "Follower":
        self._thread.start()
        self._monitor.start()
        return self

    def wait_synced(self, timeout: float = 10.0) -> bool:
        return self._synced.wait(timeout)

    @property
    def ejected(self) -> bool:
        return self._ejected.is_set()

    # -- tail -----------------------------------------------------------------

    def _run(self) -> None:
        """Reconnect loop: an initial connection failure (primary briefly
        not listening, transient refusal) RETRIES instead of arming the
        failover timer — a follower that has never synced has nothing to
        promote (advisor r4 high: promoting an empty replica would bring
        up a blank control plane over real durable state)."""
        backoff = 0.05
        while not self._stopped.is_set():
            try:
                sock = socket.create_connection(self.primary_addr, timeout=5.0)
            except OSError:
                if self._promoted is not None:
                    return
                self._stopped.wait(backoff)
                backoff = min(backoff * 2, 2.0)
                continue
            backoff = 0.05
            self._tail_one(sock)
            self._stopped.wait(0.05)

    def _tail_one(self, sock: socket.socket) -> None:
        rfile = sock.makefile("rb")
        wfile = sock.makefile("wb")
        try:
            _send(wfile, {"hello": {"rv": self.rv, "term": self.term}})
            while not self._stopped.is_set():
                frame = _recv(rfile)
                if frame is None:
                    break
                self._last_seen = time.monotonic()
                if "snap" in frame:
                    self._apply_snapshot(frame["snap"])
                    self._synced.set()
                    self._ejected.clear()  # full snapshot: stale no more
                elif "recs" in frame:
                    if int(frame.get("term", 0)) < self.term:
                        break  # stale primary
                    self._apply_records(frame["recs"])
                    _send(wfile, {"ack": self.rv})
                elif "ejected" in frame:
                    # we were dropped from the sync set for lagging: we are
                    # MISSING acknowledged writes. Promotion from here would
                    # lose them (advisor r4 medium) — block promotion until
                    # the next connect re-handshakes for a FULL snapshot
                    # (which clears the block: fresh state is promotable).
                    logger.warning(
                        "ejected from sync set at rv=%d: will not promote "
                        "until re-synced", self.rv
                    )
                    self._synced.clear()
                    self._ejected.set()
                    break
                elif "fence" in frame:
                    break
                # heartbeats only refresh _last_seen
        except (OSError, ValueError, json.JSONDecodeError):
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _apply_snapshot(self, snap: dict) -> None:
        with self._lock:
            self.rv = snap["rv"]
            self.term = int(snap.get("term", self.term))
            self.objects = {
                kind: {
                    (o := serialization.decode(kind, data)).metadata.key: o
                    for data in objs
                }
                for kind, objs in snap["objects"].items()
            }
        if self.wal is not None:
            # persist the handshake snapshot too: recovery from this WAL
            # must rebuild the FULL replicated state, not just the records
            # streamed after the connection (review r4)
            self.wal.write_snapshot(*self._snapshot_state())

    def _snapshot_state(self):
        """(rv, {kind: [DEEP-COPIED objects]}) under the lock: a promotion
        racing a snapshot write mutates the live objects (the promoted
        APIServer shares self.objects), so the write must encode copies —
        the same rule as APIServer._compact_async."""
        import copy as _copy

        with self._lock:
            return self.rv, {
                kind: [_copy.deepcopy(o) for o in d.values()]
                for kind, d in self.objects.items()
            }

    def _maybe_compact(self) -> None:
        """Follower-side WAL compaction, OFF the replication tail thread:
        inline it would stall the ack past the primary's ship timeout and
        starve heartbeats into a spurious self-promotion."""
        if self.wal is None or not self.wal.due() or self._compacting.is_set():
            return
        self._compacting.set()

        def run():
            try:
                self.wal.write_snapshot(*self._snapshot_state())
            except Exception:
                logger.exception("follower WAL compaction failed")
            finally:
                self._compacting.clear()

        threading.Thread(target=run, daemon=True, name="repl-compact").start()

    def _apply_records(self, recs: List) -> None:
        wal_batch = []
        with self._lock:
            for rv, verb, kind, data in recs:
                if rv <= self.rv:
                    continue
                self.rv = rv
                d = self.objects.setdefault(kind, {})
                obj = serialization.decode(kind, data) if data is not None else None
                if verb == "delete":
                    if obj is not None:
                        d.pop(obj.metadata.key, None)
                elif obj is not None:
                    d[obj.metadata.key] = obj
                wal_batch.append((rv, verb, kind, obj))
        if self.wal is not None and wal_batch:
            # replica durability: promotion after OUR crash recovers from
            # this WAL exactly like a primary restart; compaction is the
            # follower's own job (the primary's doesn't cross the wire)
            self.wal.append_batch(wal_batch)
            self._maybe_compact()

    # -- election endpoint ----------------------------------------------------

    def _election_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                sock, _addr = self._election_sock.accept()
            except OSError:
                return
            try:
                sock.settimeout(2.0)
                rfile = sock.makefile("rb")
                wfile = sock.makefile("wb")
                frame = _recv(rfile)
                if frame and "status" in frame:
                    _send(
                        wfile,
                        {
                            "rv": self.rv,
                            "term": self.term,
                            "synced": int(self._synced.is_set()),
                            "promoted": int(self._promoted is not None),
                            "id": self.node_id,
                        },
                    )
            except (OSError, ValueError, json.JSONDecodeError):
                pass
            finally:
                try:
                    sock.close()
                except OSError:
                    pass

    def _poll_peer(self, addr: Tuple[str, int]) -> Optional[dict]:
        try:
            sock = socket.create_connection(addr, timeout=0.5)
            try:
                sock.settimeout(0.5)
                wfile = sock.makefile("wb")
                rfile = sock.makefile("rb")
                _send(wfile, {"status": 1})
                return _recv(rfile)
            finally:
                sock.close()
        except (OSError, ValueError, json.JSONDecodeError):
            return None

    # -- failover -------------------------------------------------------------

    def _primary_reachable(self) -> bool:
        """A lease can lapse because the primary died OR because this link
        (or this process) stalled. Before any promotion, distinguish: if
        the primary still ANSWERS, it is alive — re-tail instead of
        splitting the brain (advisor r4 medium). The probe requires an
        application-level pong: a bare TCP connect is completed by the
        kernel's listen backlog even when the primary process is wedged,
        which would defer failover forever for a hung-but-listening
        primary."""
        try:
            sock = socket.create_connection(self.primary_addr, timeout=0.5)
            try:
                sock.settimeout(0.5)
                wfile = sock.makefile("wb")
                rfile = sock.makefile("rb")
                _send(wfile, {"ping": 1})
                reply = _recv(rfile)
                return bool(reply) and "pong" in reply
            finally:
                sock.close()
        except (OSError, ValueError, json.JSONDecodeError):
            return False

    def _lease_loop(self) -> None:
        while not self._stopped.wait(self.lease_s / 4):
            if self._ejected.is_set():
                continue  # stale replica: no promotion until re-synced
            if not self._synced.is_set() or self.rv <= 0:
                continue  # nothing real to promote yet (advisor r4 high)
            last = self._last_seen
            if last is None or time.monotonic() - last <= self.lease_s:
                continue
            if self._primary_reachable():
                # primary alive, our tail is what lapsed: treat the probe
                # as a heartbeat; the reconnect loop re-tails
                self._last_seen = time.monotonic()
                continue
            if not self._election_allows_promotion():
                continue  # no quorum / a better candidate exists: retry
            self.promote()
            return

    def _election_allows_promotion(self) -> bool:
        """Quorum gate: with no peer config, legacy two-node behavior
        (the sole follower promotes). With peers, require a strict
        majority of cluster_size reachable AND no reachable candidate
        ahead of us in (rv, id) order — rv order is log-prefix order, so
        the winner provably holds every quorum-acked write."""
        if not self.peers and self.cluster_size is None:
            return True
        statuses = [s for s in (self._poll_peer(a) for a in self.peers) if s]
        if any(s.get("promoted") for s in statuses):
            logger.warning("election: a peer already promoted; standing down")
            return False
        n = self.cluster_size or (len(self.peers) + 2)  # peers + self + primary
        votes = 1 + len(statuses)
        if votes * 2 <= n:
            logger.warning(
                "election: no quorum (%d/%d reachable): refusing to promote "
                "(minority partition must not serve writes)", votes, n
            )
            return False
        me = (self.rv, self.node_id)
        for s in statuses:
            if s.get("synced") and (
                int(s.get("rv", 0)), int(s.get("id", -1))
            ) > me:
                logger.info(
                    "election: peer id=%s rv=%s outranks us; deferring",
                    s.get("id"), s.get("rv"),
                )
                return False
        return True

    def promote(self, force: bool = False):
        """Become primary: term+1, build a live APIServer from the replica.
        Idempotent; returns the promoted server. Refuses (returns None)
        when this replica has never synced or was ejected from the sync
        set — promoting it would serve empty/stale state over real durable
        writes — unless force=True (operator override)."""
        with self._lock:
            if self._promoted is not None:
                return self._promoted
            if not force and (
                not self._synced.is_set() or self.rv <= 0 or self._ejected.is_set()
            ):
                logger.error(
                    "refusing promotion: synced=%s rv=%d ejected=%s (use "
                    "force=True to override)",
                    self._synced.is_set(), self.rv, self._ejected.is_set(),
                )
                return None
            from ..client.apiserver import APIServer

            self._stopped.set()
            self.term += 1
            srv = APIServer(wal=self.wal)
            srv._rv = self.rv
            srv._objects = self.objects
            self._promoted = srv
            logger.warning(
                "follower promoted to primary at rv=%d term=%d", self.rv, self.term
            )
        # best-effort fence of the old primary: it may be merely STALLED
        # (lease lapsed without dying) — a hello at our higher term makes
        # it step down read-only instead of splitting the brain. A dead
        # primary simply refuses the connection.
        try:
            sock = socket.create_connection(self.primary_addr, timeout=1.0)
            try:
                wfile = sock.makefile("wb")
                _send(wfile, {"hello": {"rv": self.rv, "term": self.term}})
            finally:
                sock.close()
        except OSError:
            pass
        if self.on_promote is not None:
            try:
                self.on_promote(srv)
            except Exception:
                logger.exception("on_promote callback failed")
        return srv

    @property
    def promoted(self):
        return self._promoted

    def stop(self) -> None:
        self._stopped.set()
        if self._election_sock is not None:
            try:
                self._election_sock.close()
            except OSError:
                pass
