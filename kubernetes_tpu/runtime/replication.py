"""API-store replication: synchronous WAL shipping + lease failover.

The reference's HA story for the API store is etcd raft behind
storage.Interface (staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go:1,
watch fan-out storage/cacher/cacher.go:448): writes replicate to a quorum
before acknowledgment and a new leader takes over on lease expiry. This
build keeps the single-writer store (client/apiserver.py) and adds the
etcd-raft-lite subset that matters at this scale:

  * **log shipping, synchronous**: every acknowledged mutation is streamed
    to connected followers and acked back BEFORE the client sees success —
    kill -9 the primary at any point and no acknowledged write is lost.
  * **terms**: each promotion bumps a monotonically increasing term. A
    handshake carrying a higher term FENCES the lower-term node: a deposed
    primary that learns of a successor steps down to read-only (raft's
    "higher term wins", minus the election — there is one designated
    follower per link).
  * **lease failover**: the primary heartbeats over the replication link;
    a follower whose lease expires promotes itself — it already holds the
    full replicated state, so promotion is: bump term, build a live
    APIServer from the replica, start serving.

Wire protocol: newline-delimited JSON frames over TCP.
  follower -> primary  {"hello": {"rv": N, "term": T}}
  primary  -> follower {"snap": {"rv": N, "term": T, "objects": {...}}}
                       {"recs": [[rv, verb, kind, obj|null], ...], "term": T}
                       {"hb": rv, "term": T}
  follower -> primary  {"ack": rv}
A primary receiving a hello with term > its own replies {"fence": T} and
steps its store down; a follower seeing a snap/recs term < its own drops
the connection (stale primary).
"""

from __future__ import annotations

import json
import logging
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..api import serialization

logger = logging.getLogger("kubernetes_tpu.runtime.replication")


class NotPrimary(RuntimeError):
    """Write rejected: this store has been fenced by a higher term."""


def _send(f, frame: dict) -> None:
    f.write((json.dumps(frame, default=str) + "\n").encode())
    f.flush()


def _recv(f) -> Optional[dict]:
    line = f.readline()
    if not line:
        return None
    return json.loads(line)


class _FollowerConn:
    """Primary-side state for one connected follower."""

    def __init__(self, sock: socket.socket, rfile, wfile):
        self.sock = sock
        self.rfile = rfile
        self.wfile = wfile
        self.lock = threading.Lock()  # serialize frames on this link
        self.acked_rv = 0
        self.ack_cond = threading.Condition(self.lock)


class ReplicationListener:
    """Primary-side replication endpoint. Attach to an APIServer via
    `attach(server)`: every logged mutation is shipped synchronously to all
    connected followers (ack'd before the store acknowledges the client).

    ack_timeout_s bounds how long a dead follower can stall the write path:
    on timeout the follower is dropped (availability over sync replication
    to a corpse — etcd similarly ejects a partitioned member from the
    quorum's critical path once a new quorum forms)."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        term: int = 1,
        heartbeat_s: float = 0.2,
        ack_timeout_s: float = 0.75,
    ):
        self.term = term
        self.heartbeat_s = heartbeat_s
        self.ack_timeout_s = ack_timeout_s
        self.server: Optional[Any] = None  # APIServer, set by attach()
        self._followers: List[_FollowerConn] = []
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._sock = socket.create_server((host, port))
        self.address: Tuple[str, int] = self._sock.getsockname()[:2]
        threading.Thread(
            target=self._accept_loop, daemon=True, name="repl-accept"
        ).start()
        threading.Thread(
            target=self._heartbeat_loop, daemon=True, name="repl-heartbeat"
        ).start()

    # -- wiring ---------------------------------------------------------------

    def attach(self, server) -> None:
        """Install on the store: server.replicator = self."""
        self.server = server
        server.replicator = self

    # -- accept / handshake ---------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                sock, _addr = self._sock.accept()
            except OSError:
                return  # closed
            threading.Thread(
                target=self._serve_follower,
                args=(sock,),
                daemon=True,
                name="repl-follower",
            ).start()

    def _serve_follower(self, sock: socket.socket) -> None:
        rfile = sock.makefile("rb")
        wfile = sock.makefile("wb")
        try:
            hello = _recv(rfile)
            if hello is None or "hello" not in hello:
                sock.close()
                return
            peer_term = int(hello["hello"].get("term", 0))
            if peer_term > self.term:
                # a successor exists: fence ourselves (raft higher-term rule)
                _send(wfile, {"fence": peer_term})
                self._step_down(peer_term)
                sock.close()
                return
            conn = _FollowerConn(sock, rfile, wfile)
            # consistent snapshot: the follower may be arbitrarily behind
            # (or empty); ship full state under the store lock so no
            # mutation lands between snapshot and the live stream
            srv = self.server
            if srv is None:
                sock.close()
                return
            with srv._lock:
                snap = {
                    "rv": srv._rv,
                    "term": self.term,
                    "objects": {
                        kind: [serialization.encode(o) for o in store.values()]
                        for kind, store in srv._objects.items()
                    },
                }
                _send(wfile, {"snap": snap})
                with self._lock:
                    self._followers.append(conn)
        except (OSError, ValueError, json.JSONDecodeError):
            sock.close()
            return
        # ack reader: runs for the life of the connection
        try:
            while not self._stopped.is_set():
                frame = _recv(rfile)
                if frame is None:
                    break
                if "ack" in frame:
                    with conn.ack_cond:
                        conn.acked_rv = int(frame["ack"])
                        conn.ack_cond.notify_all()
        except (OSError, ValueError):
            pass
        self._drop(conn)

    def _drop(self, conn: _FollowerConn) -> None:
        with self._lock:
            if conn in self._followers:
                self._followers.remove(conn)
        try:
            conn.sock.close()
        except OSError:
            pass

    def _step_down(self, new_term: int) -> None:
        logger.warning(
            "fenced by higher term %d (was %d): stepping down", new_term, self.term
        )
        srv = self.server
        if srv is not None:
            srv.read_only = True

    # -- shipping -------------------------------------------------------------

    def ship(self, records: List[Tuple[int, str, str, Any]]) -> None:
        """Synchronously replicate records (already WAL-durable locally) to
        every follower; returns once each live follower acked (dead ones
        are dropped after ack_timeout_s)."""
        if not records:
            return
        recs = [
            [rv, verb, kind, serialization.encode(obj) if obj is not None else None]
            for rv, verb, kind, obj in records
        ]
        last_rv = records[-1][0]
        with self._lock:
            followers = list(self._followers)
        for conn in followers:
            try:
                with conn.ack_cond:
                    _send(conn.wfile, {"recs": recs, "term": self.term})
                    deadline = time.monotonic() + self.ack_timeout_s
                    while conn.acked_rv < last_rv:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            raise OSError("follower ack timeout")
                        conn.ack_cond.wait(remaining)
            except OSError:
                # a half-dead follower can stall this write path once, for
                # at most ack_timeout_s, before being ejected from the sync
                # set (etcd's analogue: a dying member stalls the quorum
                # round until the leader drops it). Reads sharing the store
                # lock stall with it — the bounded, one-time price of the
                # no-acked-write-lost guarantee.
                logger.warning("dropping follower (ship failed/timed out)")
                self._drop(conn)

    def _heartbeat_loop(self) -> None:
        while not self._stopped.wait(self.heartbeat_s):
            srv = self.server
            rv = srv._rv if srv is not None else 0
            with self._lock:
                followers = list(self._followers)
            for conn in followers:
                try:
                    with conn.lock:
                        _send(conn.wfile, {"hb": rv, "term": self.term})
                except OSError:
                    self._drop(conn)

    @property
    def follower_count(self) -> int:
        with self._lock:
            return len(self._followers)

    def close(self) -> None:
        self._stopped.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            for conn in self._followers:
                try:
                    conn.sock.close()
                except OSError:
                    pass
            self._followers.clear()


class Follower:
    """Standby replica: tails a primary's replication stream into an
    in-memory state (and optionally its own WAL), promotes on lease expiry.

    on_promote(server) is called with the LIVE APIServer built from the
    replica when the primary's lease lapses (or promote() is called)."""

    def __init__(
        self,
        primary_addr: Tuple[str, int],
        lease_s: float = 1.0,
        wal=None,
        on_promote: Optional[Callable[[Any], None]] = None,
    ):
        self.primary_addr = primary_addr
        self.lease_s = lease_s
        self.wal = wal
        self.on_promote = on_promote
        self.term = 0
        self.rv = 0
        self.objects: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._compacting = threading.Event()
        self._last_seen = time.monotonic()
        self._promoted: Optional[Any] = None
        self._synced = threading.Event()  # snapshot applied
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="repl-tail"
        )
        self._monitor = threading.Thread(
            target=self._lease_loop, daemon=True, name="repl-lease"
        )

    def start(self) -> "Follower":
        self._thread.start()
        self._monitor.start()
        return self

    def wait_synced(self, timeout: float = 10.0) -> bool:
        return self._synced.wait(timeout)

    # -- tail -----------------------------------------------------------------

    def _run(self) -> None:
        try:
            sock = socket.create_connection(self.primary_addr, timeout=5.0)
        except OSError:
            self._last_seen = 0.0  # unreachable from the start: lease lapses
            return
        rfile = sock.makefile("rb")
        wfile = sock.makefile("wb")
        try:
            _send(wfile, {"hello": {"rv": self.rv, "term": self.term}})
            while not self._stopped.is_set():
                frame = _recv(rfile)
                if frame is None:
                    break
                self._last_seen = time.monotonic()
                if "snap" in frame:
                    self._apply_snapshot(frame["snap"])
                    self._synced.set()
                elif "recs" in frame:
                    if int(frame.get("term", 0)) < self.term:
                        break  # stale primary
                    self._apply_records(frame["recs"])
                    _send(wfile, {"ack": self.rv})
                elif "fence" in frame:
                    break
                # heartbeats only refresh _last_seen
        except (OSError, ValueError, json.JSONDecodeError):
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _apply_snapshot(self, snap: dict) -> None:
        with self._lock:
            self.rv = snap["rv"]
            self.term = int(snap.get("term", self.term))
            self.objects = {
                kind: {
                    (o := serialization.decode(kind, data)).metadata.key: o
                    for data in objs
                }
                for kind, objs in snap["objects"].items()
            }
        if self.wal is not None:
            # persist the handshake snapshot too: recovery from this WAL
            # must rebuild the FULL replicated state, not just the records
            # streamed after the connection (review r4)
            self.wal.write_snapshot(*self._snapshot_state())

    def _snapshot_state(self):
        """(rv, {kind: [DEEP-COPIED objects]}) under the lock: a promotion
        racing a snapshot write mutates the live objects (the promoted
        APIServer shares self.objects), so the write must encode copies —
        the same rule as APIServer._compact_async."""
        import copy as _copy

        with self._lock:
            return self.rv, {
                kind: [_copy.deepcopy(o) for o in d.values()]
                for kind, d in self.objects.items()
            }

    def _maybe_compact(self) -> None:
        """Follower-side WAL compaction, OFF the replication tail thread:
        inline it would stall the ack past the primary's ship timeout and
        starve heartbeats into a spurious self-promotion."""
        if self.wal is None or not self.wal.due() or self._compacting.is_set():
            return
        self._compacting.set()

        def run():
            try:
                self.wal.write_snapshot(*self._snapshot_state())
            except Exception:
                logger.exception("follower WAL compaction failed")
            finally:
                self._compacting.clear()

        threading.Thread(target=run, daemon=True, name="repl-compact").start()

    def _apply_records(self, recs: List) -> None:
        wal_batch = []
        with self._lock:
            for rv, verb, kind, data in recs:
                if rv <= self.rv:
                    continue
                self.rv = rv
                d = self.objects.setdefault(kind, {})
                obj = serialization.decode(kind, data) if data is not None else None
                if verb == "delete":
                    if obj is not None:
                        d.pop(obj.metadata.key, None)
                elif obj is not None:
                    d[obj.metadata.key] = obj
                wal_batch.append((rv, verb, kind, obj))
        if self.wal is not None and wal_batch:
            # replica durability: promotion after OUR crash recovers from
            # this WAL exactly like a primary restart; compaction is the
            # follower's own job (the primary's doesn't cross the wire)
            self.wal.append_batch(wal_batch)
            self._maybe_compact()

    # -- failover -------------------------------------------------------------

    def _lease_loop(self) -> None:
        while not self._stopped.wait(self.lease_s / 4):
            if time.monotonic() - self._last_seen > self.lease_s:
                self.promote()
                return

    def promote(self):
        """Become primary: term+1, build a live APIServer from the replica.
        Idempotent; returns the promoted server."""
        with self._lock:
            if self._promoted is not None:
                return self._promoted
            from ..client.apiserver import APIServer

            self._stopped.set()
            self.term += 1
            srv = APIServer(wal=self.wal)
            srv._rv = self.rv
            srv._objects = self.objects
            self._promoted = srv
            logger.warning(
                "follower promoted to primary at rv=%d term=%d", self.rv, self.term
            )
        # best-effort fence of the old primary: it may be merely STALLED
        # (lease lapsed without dying) — a hello at our higher term makes
        # it step down read-only instead of splitting the brain. A dead
        # primary simply refuses the connection.
        try:
            sock = socket.create_connection(self.primary_addr, timeout=1.0)
            try:
                wfile = sock.makefile("wb")
                _send(wfile, {"hello": {"rv": self.rv, "term": self.term}})
            finally:
                sock.close()
        except OSError:
            pass
        if self.on_promote is not None:
            try:
                self.on_promote(srv)
            except Exception:
                logger.exception("on_promote callback failed")
        return srv

    @property
    def promoted(self):
        return self._promoted

    def stop(self) -> None:
        self._stopped.set()
