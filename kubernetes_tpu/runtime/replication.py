"""API-store replication: commit-index-gated WAL shipping + lossless failover.

The reference's HA story for the API store is etcd raft behind
storage.Interface (staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go:1,
watch fan-out storage/cacher/cacher.go:448): writes replicate to a quorum
before acknowledgment and a new leader takes over on lease expiry. This
build keeps the single-writer store (client/apiserver.py) and adds the
raft-lite subset that matters at this scale:

  * **log shipping, parallel fan-out, commit-index-acked**: every
    mutation is streamed to ALL followers concurrently; the client sees
    success iff the commit index (runtime/consensus.py) reaches the
    record — i.e. a MAJORITY of the replica set (primary included) holds
    it durably appended. **Every acknowledged write replicates to a
    quorum before acknowledgment — true by construction**: on a quorum
    miss the write path raises instead of acking, and the store enters
    degraded READ-ONLY mode (writes 503-retryable, reads/watches keep
    serving) until follower acks catch the commit index back up to the
    leader's tip, at which point writes re-open and the WAL records the
    epoch transition. There is no availability-first fallback.
  * **terms**: each promotion bumps a monotonically increasing term. A
    handshake carrying a higher term FENCES the lower-term node: a deposed
    primary that learns of a successor steps down to read-only (raft's
    "higher term wins").
  * **vote-granted election on (term, commit_index, rv)**: followers know
    the replica-set peer list and learn the commit index from every
    recs/hb frame. On primary-lease expiry a follower first VERIFIES the
    primary is actually unreachable (a merely-slow link re-tails instead
    of promoting), then runs a raft-style election round at a FRESH term:
    each voter grants at most ONE candidate per term (so two same-term
    majorities — split brain — are structurally impossible), refuses
    candidates whose (term, rv, commit) log is behind its own (§5.4.1
    up-to-date check), and refuses everyone while its own primary lease
    is still fresh (leader stickiness). A candidate promotes only on a
    strict GRANT majority of cluster_size; rv order is log-prefix order,
    so the winner provably holds every committed — that is, every
    client-acknowledged — write: raft's leader-completeness argument in
    miniature. A minority partition can never elect.
  * **commit-index resync**: a reconnecting follower's hello carries its
    rv; when the leader still buffers that log suffix (and the terms
    match, so the follower's log is a prefix of the leader's) it replays
    just the tail in a ``catchup`` frame instead of a full snapshot.

Wire protocol: newline-delimited JSON frames over TCP.
  follower -> primary  {"hello": {"rv": N, "term": T, "uid": U}}
                       (uid = stable replica identity: a reconnect evicts
                        the same replica's superseded half-open link so
                        one node never holds two commit-quorum slots)
  primary  -> follower {"snap": {"rv": N, "term": T, "commit": C,
                                 "objects": {...}}}
                       {"catchup": {"from": N, "rv": N', "term": T,
                                    "commit": C, "recs": [...]}}
                       {"recs": [[rv, verb, kind, obj|null], ...],
                        "term": T, "commit": C}
                       {"hb": rv, "term": T, "commit": C}
                       {"ejected": T}   (you are out of the sync set)
  follower -> primary  {"ack": rv}     (rv is DURABLY applied; sent after
                                        snap/catchup handshakes too)
Election endpoint (per follower):
  {"status": 1} ->
      {"rv": N, "term": T, "commit": C, "synced": 0|1, "promoted": 0|1,
       "id": I}
  {"vote": {"term": T', "id": I, "key": [t, rv, commit]}} ->
      same status + {"granted": 0|1}   (single grant per term, log
                                        up-to-date check, lease-fresh
                                        stickiness)
A primary receiving a hello with term > its own replies {"fence": T} and
steps its store down; a follower seeing a snap/recs term < its own drops
the connection (stale primary).
"""

from __future__ import annotations

import json
import logging
import queue
import random
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..api import serialization
from ..utils.metrics import metrics
from .consensus import (
    COUNTER_CATCHUP_RESYNCS,
    COUNTER_SNAPSHOT_RESYNCS,
    ConsensusCoordinator,
    DegradedWrites,  # noqa: F401  (re-export: the write-path 503 surface)
    QuorumLost,  # noqa: F401  (re-export)
    log_key,
)

# ONE NotPrimary type for the whole tree (advisor r4): the store raises it
# on fenced writes; re-exported here for callers importing from runtime.
from ..client.apiserver import NotPrimary  # noqa: F401  (re-export)

logger = logging.getLogger("kubernetes_tpu.runtime.replication")

# a DiskCorrupt replica (mid-log WAL corruption at recovery) finished a
# full snap/catchup resync from the leader and is promotable again
COUNTER_CORRUPT_HEALS = "store_disk_corrupt_heals_total"
GAUGE_DISK_CORRUPT = "store_disk_corrupt"


def _send(f, frame: dict) -> None:
    f.write((json.dumps(frame, default=str) + "\n").encode())
    f.flush()


def _recv(f) -> Optional[dict]:
    line = f.readline()
    if not line:
        return None
    return json.loads(line)


class _FollowerConn:
    """Primary-side state for one connected follower.

    Outbound frames go through a bounded queue drained by a dedicated
    writer thread (etcd's per-peer stream goroutine). This is load-
    bearing, not a convenience: the previous design bounded ship()'s
    send with a temporary socket timeout, and settimeout() flips the fd's
    blocking mode UNDER the ack reader concurrently parked in recv on
    the SAME socket — a reader that began its read inside the toggle
    window died on a spurious BlockingIOError and took a perfectly
    healthy follower's acks (and its commit-index contribution) with it.
    With a writer thread, nobody ever changes the socket's mode: sends
    block only their own thread, a wedged link shows up as a FULL queue
    (bounded memory, explicit drop), and the reader's recv is untouched."""

    _next_fid = 0
    _fid_lock = threading.Lock()
    QUEUE_MAX = 4096  # frames; a link this far behind is wedged, not slow

    def __init__(self, sock: socket.socket, rfile, wfile):
        self.sock = sock
        self.rfile = rfile
        self.wfile = wfile
        self.acked_rv = 0
        self.ack_cond = threading.Condition()
        self.uid: Optional[str] = None  # replica identity from the hello
        # heartbeat-side stall detection state (see _heartbeat_loop)
        self.hb_seq_mark = 0
        self.hb_stalled_since: Optional[float] = None
        self.outq: "queue.Queue[Optional[dict]]" = queue.Queue(self.QUEUE_MAX)
        # flush tracking: seq of frames enqueued vs actually written to
        # the socket — legacy-mode ship() waits for the flush so a
        # concurrent close() can't silently discard a frame it already
        # counted as delivered (consensus mode needs no such wait: its
        # commit gate only trusts real follower acks)
        self.sent_cond = threading.Condition()
        self.enq_seq = 0
        self.sent_seq = 0
        with _FollowerConn._fid_lock:
            # link identity for the consensus match table: a RECONNECT is a
            # new link with empty known-durable state, never a resumed one
            _FollowerConn._next_fid += 1
            self.fid = _FollowerConn._next_fid

    def start_writer(self, on_error: Callable[["_FollowerConn"], None]) -> None:
        def run() -> None:
            while True:
                frame = self.outq.get()
                if frame is None:
                    return  # poison pill from _drop
                try:
                    _send(self.wfile, frame)
                except OSError:
                    on_error(self)
                    return
                with self.sent_cond:
                    self.sent_seq += 1
                    self.sent_cond.notify_all()

        threading.Thread(
            target=run, daemon=True, name=f"repl-writer-{self.fid}"
        ).start()

    def send_async(self, frame: dict) -> int:
        """Enqueue without blocking. Returns the frame's flush seq
        (truthy), or 0 when the queue is full (wedged link)."""
        try:
            with self.sent_cond:
                self.outq.put_nowait(frame)
                self.enq_seq += 1
                return self.enq_seq
        except queue.Full:
            return 0

    def wait_flushed(self, seq: int, deadline: float) -> bool:
        """Block until the writer has written frame `seq` (or deadline)."""
        with self.sent_cond:
            while self.sent_seq < seq:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self.sent_cond.wait(remaining)
            return True

    def close_writer(self) -> None:
        try:
            self.outq.put_nowait(None)
        except queue.Full:
            pass  # writer will exit on the closed socket's OSError


class ReplicationListener:
    """Primary-side replication endpoint. Attach to an APIServer via
    `attach(server)`: every logged mutation is shipped to all connected
    followers in parallel and acknowledged once a quorum holds it.

    cluster_size: total replica count INCLUDING this primary. When set,
    a ConsensusCoordinator (runtime/consensus.py) gates every ship() on
    the commit index: the write acks iff a majority holds it durably
    within ack_timeout_s, else the write raises QuorumLost and the store
    enters degraded read-only mode until followers catch up. Laggards
    past the quorum stay connected and catch up from the TCP stream.
    When None (legacy two-node mode), every live follower must ack under
    one shared deadline, and a follower that would stall the quorum is
    ejected (with an explicit "ejected" frame — an ejected follower must
    never self-promote; it is missing acknowledged writes)."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        term: int = 1,
        heartbeat_s: float = 0.2,
        ack_timeout_s: float = 0.75,
        cluster_size: Optional[int] = None,
    ):
        self.term = term
        self.heartbeat_s = heartbeat_s
        self.ack_timeout_s = ack_timeout_s
        self.cluster_size = cluster_size
        self.consensus: Optional[ConsensusCoordinator] = (
            ConsensusCoordinator(cluster_size, term=term, window_s=ack_timeout_s)
            if cluster_size is not None
            else None
        )
        self.server: Optional[Any] = None  # APIServer, set by attach()
        self._followers: List[_FollowerConn] = []
        self._lock = threading.Lock()
        # shared ack signal: ship() blocks here and re-checks the quorum on
        # every ack from ANY follower (per-conn waits would serialize — a
        # dead first conn would burn the whole deadline even with quorum
        # already met elsewhere)
        self._ack_cond = threading.Condition()
        self._stopped = threading.Event()
        self._sock = socket.create_server((host, port))
        self.address: Tuple[str, int] = self._sock.getsockname()[:2]
        threading.Thread(
            target=self._accept_loop, daemon=True, name="repl-accept"
        ).start()
        threading.Thread(
            target=self._heartbeat_loop, daemon=True, name="repl-heartbeat"
        ).start()

    # -- wiring ---------------------------------------------------------------

    def attach(self, server) -> None:
        """Install on the store: server.replicator = self. In consensus
        mode also arm the store's degraded-mode write gate and point the
        coordinator's epoch records at the store's WAL — the local rv may
        already be ahead of 0 (recovered store), seed the tip from it."""
        self.server = server
        server.replicator = self
        if self.consensus is not None:
            self.consensus.attach_wal(server._wal)
            self.consensus.local_append(server._rv)
            gate = getattr(server, "write_gate", None)
            if gate is not None:
                gate.attach_consensus(self.consensus)

    # -- accept / handshake ---------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                sock, _addr = self._sock.accept()
            except OSError:
                return  # closed
            threading.Thread(
                target=self._serve_follower,
                args=(sock,),
                daemon=True,
                name="repl-follower",
            ).start()

    def _serve_follower(self, sock: socket.socket) -> None:
        rfile = sock.makefile("rb")
        wfile = sock.makefile("wb")
        try:
            hello = _recv(rfile)
            if hello is None:
                sock.close()
                return
            if "ping" in hello:
                # liveness probe (see Follower._primary_reachable): a bare
                # TCP connect is answered by the kernel's listen backlog
                # even when this process is wedged — only an application
                # reply proves the primary is actually serving
                _send(wfile, {"pong": self.term})
                sock.close()
                return
            if "hello" not in hello:
                sock.close()
                return
            peer_term = int(hello["hello"].get("term", 0))
            peer_rv = int(hello["hello"].get("rv", 0))
            peer_uid = hello["hello"].get("uid")
            if peer_uid:
                # a reconnect supersedes the same replica's old link: a
                # half-open previous connection would otherwise keep its
                # consensus match entry alive alongside the new one —
                # double-counting ONE physical replica toward the commit
                # majority (phantom quorum at cluster_size >= 5)
                with self._lock:
                    stale = [
                        c for c in self._followers if c.uid == peer_uid
                    ]
                for c in stale:
                    logger.info("dropping superseded link for replica %s", peer_uid)
                    self._drop(c)
            if peer_term > self.term:
                # a successor exists: fence ourselves (raft higher-term rule)
                _send(wfile, {"fence": peer_term})
                self._step_down(peer_term)
                sock.close()
                return
            conn = _FollowerConn(sock, rfile, wfile)
            conn.uid = peer_uid
            # consistent handshake under the store lock so no mutation
            # lands between the state transfer and the live stream. The
            # follower may be arbitrarily behind (or empty) -> full
            # snapshot; a SAME-TERM reconnector whose log suffix the
            # leader still buffers gets just the tail replayed from its
            # rv (commit-index resync) — same-term is the prefix proof:
            # this leader shipped every record the follower holds.
            srv = self.server
            if srv is None:
                sock.close()
                return
            cons = self.consensus
            # the writer thread owns the socket's send side from here on:
            # the state-transfer frame below is ENQUEUED, never sent
            # inline — an inline snapshot send to a peer that stopped
            # reading would block on a full kernel buffer while HOLDING
            # srv._lock, wedging the entire API server behind one bad
            # reconnector (the failure class the writer threads exist
            # for; the heartbeat stall detector reaps such a link)
            conn.start_writer(lambda c: self._drop(c))
            with srv._lock:
                commit = cons.commit_index if cons is not None else srv._rv
                delta = None
                if cons is not None and peer_term == self.term:
                    if peer_rv == srv._rv:
                        delta = []
                    elif peer_rv < srv._rv:
                        tail = cons.buffer.since(peer_rv)
                        if (
                            tail
                            and tail[0][0] == peer_rv + 1
                            and tail[-1][0] == srv._rv
                        ):
                            delta = tail
                if delta is not None:
                    conn.send_async(
                        {
                            "catchup": {
                                "from": peer_rv,
                                "rv": srv._rv,
                                "term": self.term,
                                "commit": commit,
                                "recs": delta,
                            }
                        }
                    )
                    metrics.inc(COUNTER_CATCHUP_RESYNCS)
                else:
                    snap = {
                        "rv": srv._rv,
                        "term": self.term,
                        "commit": commit,
                        "objects": {
                            kind: [
                                serialization.encode(o) for o in store.values()
                            ]
                            for kind, store in srv._objects.items()
                        },
                    }
                    conn.send_async({"snap": snap})
                    metrics.inc(COUNTER_SNAPSHOT_RESYNCS)
                # registering under srv._lock keeps stream continuity:
                # every mutation after the state-transfer cut enqueues
                # behind it (ship() runs under this same lock), so the
                # follower sees snapshot-then-records in exact rv order
                with self._lock:
                    self._followers.append(conn)
        except (OSError, ValueError, json.JSONDecodeError):
            sock.close()
            return
        # ack reader: runs for the life of the connection, and its recv is
        # never perturbed — all sends go through the conn's writer thread,
        # so nothing ever toggles this socket's blocking mode. An idle
        # link simply has nothing to say; only EOF/hard errors drop it.
        try:
            while not self._stopped.is_set():
                frame = _recv(rfile)
                if frame is None:
                    break
                if "ack" in frame:
                    rv = int(frame["ack"])
                    with conn.ack_cond:
                        conn.acked_rv = rv
                        conn.ack_cond.notify_all()
                    if self.consensus is not None:
                        # the follower's ack means DURABLY applied: it
                        # advances the commit index (and may lift
                        # degraded mode when the quorum catches the tip)
                        self.consensus.follower_ack(conn.fid, rv)
                    with self._ack_cond:
                        self._ack_cond.notify_all()
        except (OSError, ValueError):
            pass
        self._drop(conn)

    def _drop(self, conn: _FollowerConn, eject: bool = False) -> None:
        with self._lock:
            if conn in self._followers:
                self._followers.remove(conn)
            else:
                eject = False  # already gone; don't re-notify
        if self.consensus is not None:
            # a dead link's acks can no longer back the quorum (the commit
            # index itself never regresses: committed is forever)
            self.consensus.forget(conn.fid)
        if eject:
            # explicit stale notice (advisor r4): without it the dropped
            # follower sees only silence, its lease lapses, and it promotes
            # at a stale rv with term+1 — fencing the healthy primary and
            # losing every write acked after the ejection. With the frame
            # it KNOWS it is out of the sync set and must re-sync instead.
            # Sent through the writer queue like every frame (no
            # interleaving with an in-flight send); wait for the writer to
            # actually flush it before the close cuts the link — an
            # already-wedged link just loses a best-effort notice.
            seq = conn.send_async({"ejected": self.term})
            if seq:
                conn.wait_flushed(seq, time.monotonic() + 0.5)
        conn.close_writer()
        try:
            # shutdown, not just close: the rfile/wfile makefile handles
            # hold _io_refs, so close() alone never closes the fd — the
            # peer would see no FIN (it blocks in recv forever instead of
            # reconnecting) and our own ack reader would block forever too.
            # shutdown() tears the TCP stream down regardless of refs.
            conn.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            conn.sock.close()
        except OSError:
            pass

    def _step_down(self, new_term: int) -> None:
        logger.warning(
            "fenced by higher term %d (was %d): stepping down", new_term, self.term
        )
        srv = self.server
        if srv is not None:
            srv.read_only = True

    # -- shipping -------------------------------------------------------------

    def ship(self, records: List[Tuple[int, str, str, Any]]) -> None:
        """Replicate records (already WAL-durable locally) to every
        follower in parallel; returns once committed. One shared deadline
        bounds the total stall at ack_timeout_s no matter how many
        followers are half-dead (r4 weak #7: the serial loop stalled
        ack_timeout PER follower).

        Consensus mode (cluster_size set): returns iff the commit index
        reached the last record — a majority of the replica set holds
        every record durably. On a window miss it raises QuorumLost (the
        caller must NOT acknowledge the write) and the store enters
        degraded read-only mode until followers catch up; the laggards
        stay connected — they may hold the only follower copies of
        earlier writes, and their buffered stream is exactly what lifts
        degraded mode.

        Legacy mode (cluster_size None): every live follower must ack;
        one that cannot inside the deadline is ejected with an explicit
        stale notice (etcd's analogue: a dying member stalls the quorum
        round until the leader drops it)."""
        if not records:
            return
        recs = [
            [rv, verb, kind, serialization.encode(obj) if obj is not None else None]
            for rv, verb, kind, obj in records
        ]
        last_rv = records[-1][0]
        cons = self.consensus
        if cons is not None:
            # the local WAL append already happened (store._log_batch
            # orders durability before shipping): count self, buffer the
            # tail for commit-index resync of reconnectors
            cons.local_append(last_rv, recs)
        with self._lock:
            followers = list(self._followers)
        if not followers and cons is None:
            if self._stopped.is_set():
                # closed mid-burst (primary shutdown / simulated crash):
                # the follower set was just torn down, so "no followers"
                # here is NOT solo mode — acking would record a write no
                # surviving replica ever saw
                raise NotPrimary(
                    "replication listener closed mid-write: not acknowledged"
                )
            return  # legacy solo mode: nothing to wait for
        deadline = time.monotonic() + self.ack_timeout_s
        # send phase: enqueue on every link's writer (never blocks the
        # write path; each writer thread drains its own socket). A FULL
        # queue means the link is wedged beyond QUEUE_MAX frames of
        # backlog — drop it explicitly instead of buffering unboundedly.
        live: List[_FollowerConn] = []
        seqs: Dict[_FollowerConn, int] = {}
        frame = {"recs": recs, "term": self.term}
        if cons is not None:
            frame["commit"] = cons.commit_index
        for conn in followers:
            seq = conn.send_async(frame)
            if seq:
                live.append(conn)
                seqs[conn] = seq
            else:
                logger.warning("dropping follower (outbound queue full)")
                self._drop(conn, eject=False)
        if cons is not None:
            # commit-index gate: ONE bounded wait; acks from ANY follower
            # advance it. Window miss -> degraded read-only + QuorumLost
            # (the in-flight write is NOT acknowledged to the client).
            # quorum_miss rechecks under its lock: an ack racing the
            # window expiry means the write IS committed — ack it.
            if cons.wait_commit(last_rv, max(deadline - time.monotonic(), 0.0)):
                return
            exc = cons.quorum_miss(last_rv)
            if exc is None:
                return  # committed in the race window after all
            raise exc
        # legacy flush phase: acking-on-deadline-expiry (below) only makes
        # sense if the frame actually LEFT this process — wait for each
        # writer to hand it to the kernel, under the same shared deadline
        for conn in live:
            conn.wait_flushed(seqs[conn], deadline)
        # legacy wait phase: ONE shared deadline and ONE shared condition
        # across ALL links; all-acked by any subset returns immediately
        with self._ack_cond:
            while True:
                n_acked = sum(1 for c in live if c.acked_rv >= last_rv)
                if n_acked == len(live):
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._ack_cond.wait(remaining)
        laggards = [c for c in live if c.acked_rv < last_rv]
        if laggards and self._stopped.is_set():
            # the listener was closed mid-write (primary shutting down /
            # simulated crash): the un-acked frame may never have reached
            # the follower — success here would acknowledge a write the
            # surviving replica can lose. Fail the call instead.
            raise NotPrimary(
                "replication listener closed mid-write: not acknowledged"
            )
        for conn in laggards:
            logger.warning("ejecting follower (ack timeout)")
            self._drop(conn, eject=True)

    def _heartbeat_loop(self) -> None:
        while not self._stopped.wait(self.heartbeat_s):
            srv = self.server
            rv = srv._rv if srv is not None else 0
            frame = {"hb": rv, "term": self.term}
            if self.consensus is not None:
                # piggyback the commit index so followers learn it even
                # on an idle stream (their election votes carry it), and
                # refresh the per-follower lag gauges off the write path
                frame["commit"] = self.consensus.commit_index
                self.consensus.publish_follower_lags()
            with self._lock:
                followers = list(self._followers)
            now = time.monotonic()
            stall_after = max(self.ack_timeout_s * 4, 2.0)
            for conn in followers:
                conn.send_async(frame)  # full queue: stall logic decides
                # dead-link detection (the inline-send era dropped on send
                # OSError; a writer thread sending into a half-open socket
                # "succeeds" into the kernel buffer for many minutes):
                # a non-empty queue whose writer makes NO progress across
                # consecutive beats is a wedged link — drop it so it stops
                # inflating follower_count and holding a match entry.
                if conn.outq.empty() or conn.sent_seq != conn.hb_seq_mark:
                    conn.hb_seq_mark = conn.sent_seq
                    conn.hb_stalled_since = None
                elif conn.hb_stalled_since is None:
                    conn.hb_stalled_since = now
                elif now - conn.hb_stalled_since > stall_after:
                    logger.warning("dropping follower (writer stalled)")
                    self._drop(conn)

    @property
    def follower_count(self) -> int:
        with self._lock:
            return len(self._followers)

    def close(self) -> None:
        self._stopped.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            for conn in self._followers:
                conn.close_writer()
                try:
                    conn.sock.shutdown(socket.SHUT_RDWR)  # see _drop
                except OSError:
                    pass
                try:
                    conn.sock.close()
                except OSError:
                    pass
            self._followers.clear()


class Follower:
    """Standby replica: tails a primary's replication stream into an
    in-memory state (and optionally its own WAL), promotes on lease expiry
    — gated by sync state, primary reachability, and (when a peer list is
    configured) a majority election.

    on_promote(server) is called with the LIVE APIServer built from the
    replica when this follower wins the failover.

    peers/cluster_size/node_id (optional, all-or-nothing): the election
    configuration. `peers` lists the OTHER followers' election endpoints;
    cluster_size is the TOTAL replica count including the primary. The
    follower serves its own election endpoint at `election_address`."""

    def __init__(
        self,
        primary_addr: Tuple[str, int],
        lease_s: float = 1.0,
        wal=None,
        on_promote: Optional[Callable[[Any], None]] = None,
        peers: Optional[List[Tuple[str, int]]] = None,
        cluster_size: Optional[int] = None,
        node_id: int = 0,
        heartbeat_s: float = 0.2,
        ack_timeout_s: float = 0.75,
        disk_corrupt: bool = False,
    ):
        self.primary_addr = primary_addr
        self.lease_s = lease_s
        self.wal = wal
        # disk_corrupt: this replica's WAL recovery found MID-LOG
        # corruption (wal.RecoveryReport.corrupt) — its state is an honest
        # prefix but may be missing acked writes, so it must not promote
        # until a snap/catchup resync from the leader has healed it.
        # disk_failed flips when OUR OWN wal appends start failing: the
        # replica keeps tailing (in-memory reads stay correct) but is
        # barred from promotion — a leader that cannot durably log is not
        # a leader.
        self.disk_corrupt = bool(disk_corrupt)
        self.disk_failed = False
        self.on_promote = on_promote
        self.peers = list(peers) if peers else []
        self.cluster_size = cluster_size
        self.node_id = node_id
        # replication timing this node will use if IT becomes the leader
        # (promote() must not silently revert a cluster tuned for slow
        # links back to defaults — that would flap every post-failover
        # write into QuorumLost)
        self.heartbeat_s = heartbeat_s
        self.ack_timeout_s = ack_timeout_s
        # stable replica identity across reconnects: lets the primary
        # evict this replica's superseded half-open link at re-handshake
        # (one physical replica must never hold two commit-quorum slots)
        self.replica_uid = f"{node_id}-{random.getrandbits(64):016x}"
        self.term = 0
        self.rv = 0
        # highest commit index learned from the leader (piggybacked on
        # snap/catchup/recs/hb frames): the election vote's durability
        # proof — a candidate behind on commit loses to one that holds it
        self.commit_index = 0
        self.objects: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        # read-path observers (apiserver/frontend.FollowerReadStore):
        # notified off-lock after records apply / the commit index moves /
        # a snapshot resets state — how a follower-attached watch cache
        # learns of changes without polling. Commit waits park on the
        # condition (notified from _learn_commit).
        self._observers: List[Any] = []
        self._commit_cond = threading.Condition()
        self._stopped = threading.Event()
        self._compacting = threading.Event()
        self._last_seen: Optional[float] = None  # None until first frame
        self._promoted: Optional[Any] = None
        self._synced = threading.Event()  # snapshot applied at least once
        self._ejected = threading.Event()  # primary declared us stale
        # the ReplicationListener this node runs AFTER winning a
        # consensus-mode election (promote() wires it so the new leader's
        # acks stay quorum-gated); peers learn its address via _my_status
        self._promoted_listener: Optional[ReplicationListener] = None
        self._cur_sock: Optional[socket.socket] = None  # live tail socket
        # single-vote-per-term election state (raft §5.2): at most ONE
        # candidate per term ever collects this node's grant, so two
        # leaders in one term are structurally impossible
        self._vote_lock = threading.Lock()
        self._voted_term = 0
        self._voted_for: Optional[int] = None
        self._next_vote_term = 0
        self._election_sock: Optional[socket.socket] = None
        self.election_address: Optional[Tuple[str, int]] = None
        if peers is not None or cluster_size is not None:
            self._election_sock = socket.create_server(("127.0.0.1", 0))
            self.election_address = self._election_sock.getsockname()[:2]
            threading.Thread(
                target=self._election_loop, daemon=True, name="repl-election"
            ).start()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="repl-tail"
        )
        self._monitor = threading.Thread(
            target=self._lease_loop, daemon=True, name="repl-lease"
        )

    def start(self) -> "Follower":
        self._thread.start()
        self._monitor.start()
        return self

    def wait_synced(self, timeout: float = 10.0) -> bool:
        return self._synced.wait(timeout)

    def register_observer(self, obs: Any) -> None:
        """Attach a read-path observer. Duck interface (all optional):
        ``on_records(recs)`` with recs = [(rv, verb, kind, obj-copy)]
        after a batch durably applies, ``on_commit(commit_index)`` when
        the learned commit index advances, ``on_snapshot()`` after a full
        state transfer replaced the replica state (the observer's
        incremental view is invalid — resync from list)."""
        self._observers.append(obs)

    def _observe(self, method: str, *args) -> None:
        for obs in self._observers:
            fn = getattr(obs, method, None)
            if fn is None:
                continue
            try:
                fn(*args)
            except Exception:
                logger.exception("follower observer %s failed", method)

    def list_kind(self, kind: str) -> Tuple[List[Any], int]:
        """(deep-copied objects of kind, replica rv) under the replica
        lock: the follower-read seed list (FollowerReadStore.list)."""
        import copy as _copy

        with self._lock:
            d = self.objects.get(kind, {})
            return [_copy.deepcopy(o) for o in d.values()], self.rv

    def wait_commit(self, rv: int, timeout: float = 5.0) -> bool:
        """Block until the learned commit index covers rv (or timeout).
        The follower-read freshness gate: a consistent read demanding rv
        R is served only once a quorum durably holds R."""
        deadline = time.monotonic() + timeout
        with self._commit_cond:
            while self.commit_index < rv:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stopped.is_set():
                    return self.commit_index >= rv
                self._commit_cond.wait(remaining)
        return True

    @property
    def ejected(self) -> bool:
        return self._ejected.is_set()

    # -- tail -----------------------------------------------------------------

    def _run(self) -> None:
        """Reconnect loop: an initial connection failure (primary briefly
        not listening, transient refusal) RETRIES instead of arming the
        failover timer — a follower that has never synced has nothing to
        promote (advisor r4 high: promoting an empty replica would bring
        up a blank control plane over real durable state)."""
        backoff = 0.05
        while not self._stopped.is_set():
            try:
                sock = socket.create_connection(self.primary_addr, timeout=5.0)
            except OSError:
                if self._promoted is not None:
                    return
                self._stopped.wait(backoff)
                backoff = min(backoff * 2, 2.0)
                continue
            backoff = 0.05
            self._cur_sock = sock
            self._tail_one(sock)
            self._cur_sock = None
            self._stopped.wait(0.05)

    def _tail_one(self, sock: socket.socket) -> None:
        # create_connection's 5s CONNECT timeout would otherwise persist
        # onto every recv: an idle-but-healthy stream (heartbeat interval
        # at or above it) would churn through spurious disconnect/resync
        # cycles — and a cycle landing mid-ship fails a healthy write.
        sock.settimeout(None)
        rfile = sock.makefile("rb")
        wfile = sock.makefile("wb")
        try:
            _send(
                wfile,
                {
                    "hello": {
                        "rv": self.rv,
                        "term": self.term,
                        "uid": self.replica_uid,
                    }
                },
            )
            while not self._stopped.is_set():
                frame = _recv(rfile)
                if frame is None:
                    break
                self._last_seen = time.monotonic()
                self._learn_commit(frame)
                if "snap" in frame:
                    self._apply_snapshot(frame["snap"])
                    self._synced.set()
                    self._ejected.clear()  # full snapshot: stale no more
                    self._mark_disk_healed("snapshot")
                    # ack the handshake state: the leader's commit index
                    # needs to know we durably hold it (a reconnect during
                    # degraded mode lifts it through exactly this ack)
                    _send(wfile, {"ack": self.rv})
                elif "catchup" in frame:
                    # commit-index resync: the leader replayed just our
                    # missing log suffix — applying it makes us exactly as
                    # synced (and as promotable) as a full snapshot would
                    cu = frame["catchup"]
                    if int(cu.get("term", 0)) < self.term:
                        break  # stale primary
                    self.term = int(cu.get("term", self.term))
                    self._apply_records(cu.get("recs", []))
                    self._synced.set()
                    self._ejected.clear()
                    # a corrupt replica's hello carried its valid-prefix
                    # rv; this catchup re-appended the missing suffix to
                    # the (already-truncated) WAL — the log is whole again
                    self._mark_disk_healed("catchup")
                    _send(wfile, {"ack": self.rv})
                elif "recs" in frame:
                    if int(frame.get("term", 0)) < self.term:
                        break  # stale primary
                    self._apply_records(frame["recs"])
                    _send(wfile, {"ack": self.rv})
                elif "ejected" in frame:
                    # we were dropped from the sync set for lagging: we are
                    # MISSING acknowledged writes. Promotion from here would
                    # lose them (advisor r4 medium) — block promotion until
                    # the next connect re-handshakes for a FULL snapshot
                    # (which clears the block: fresh state is promotable).
                    logger.warning(
                        "ejected from sync set at rv=%d: will not promote "
                        "until re-synced", self.rv
                    )
                    self._synced.clear()
                    self._ejected.set()
                    break
                elif "fence" in frame:
                    break
                # heartbeats only refresh _last_seen
        except (OSError, ValueError, json.JSONDecodeError):
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _mark_disk_healed(self, how: str) -> None:
        """A full resync (snap, or catchup onto the repaired valid-prefix
        WAL) replaced/completed our state from the leader: the DiskCorrupt
        promotion bar lifts."""
        if not self.disk_corrupt:
            return
        self.disk_corrupt = False
        metrics.inc(COUNTER_CORRUPT_HEALS)
        metrics.set_gauge(GAUGE_DISK_CORRUPT, 0.0)
        logger.warning(
            "disk-corrupt replica healed via %s resync at rv=%d: "
            "promotable again", how, self.rv,
        )

    def _learn_commit(self, frame: dict) -> None:
        """Track the leader's piggybacked commit index (recs/hb carry it
        top-level; snap/catchup inside their payload). Monotonic."""
        c = frame.get("commit", 0)
        for key in ("snap", "catchup"):
            if key in frame:
                c = max(c, frame[key].get("commit", 0) or 0)
        if c and int(c) > self.commit_index:
            self.commit_index = int(c)
            with self._commit_cond:
                self._commit_cond.notify_all()
            self._observe("on_commit", self.commit_index)

    def _apply_snapshot(self, snap: dict) -> None:
        with self._lock:
            self.rv = snap["rv"]
            self.term = int(snap.get("term", self.term))
            self.objects = {
                kind: {
                    (o := serialization.decode(kind, data)).metadata.key: o
                    for data in objs
                }
                for kind, objs in snap["objects"].items()
            }
        if self.wal is not None:
            # persist the handshake snapshot too: recovery from this WAL
            # must rebuild the FULL replicated state, not just the records
            # streamed after the connection (review r4)
            self.wal.write_snapshot(*self._snapshot_state())
        # a full state transfer invalidates any incremental read-path
        # view built from the record stream: observers resync from list
        self._observe("on_snapshot")

    def _snapshot_state(self):
        """(rv, {kind: [DEEP-COPIED objects]}) under the lock: a promotion
        racing a snapshot write mutates the live objects (the promoted
        APIServer shares self.objects), so the write must encode copies —
        the same rule as APIServer._compact_async."""
        import copy as _copy

        with self._lock:
            return self.rv, {
                kind: [_copy.deepcopy(o) for o in d.values()]
                for kind, d in self.objects.items()
            }

    def _maybe_compact(self) -> None:
        """Follower-side WAL compaction, OFF the replication tail thread:
        inline it would stall the ack past the primary's ship timeout and
        starve heartbeats into a spurious self-promotion."""
        if self.wal is None or not self.wal.due() or self._compacting.is_set():
            return
        self._compacting.set()

        def run():
            try:
                self.wal.write_snapshot(*self._snapshot_state())
            except Exception:
                logger.exception("follower WAL compaction failed")
            finally:
                self._compacting.clear()

        threading.Thread(target=run, daemon=True, name="repl-compact").start()

    def _apply_records(self, recs: List) -> None:
        wal_batch = []
        with self._lock:
            for rv, verb, kind, data in recs:
                if rv <= self.rv:
                    continue
                self.rv = rv
                d = self.objects.setdefault(kind, {})
                obj = serialization.decode(kind, data) if data is not None else None
                if verb == "delete":
                    if obj is not None:
                        d.pop(obj.metadata.key, None)
                elif obj is not None:
                    d[obj.metadata.key] = obj
                wal_batch.append((rv, verb, kind, obj))
        if self.wal is not None and wal_batch and not self.disk_failed:
            # replica durability: promotion after OUR crash recovers from
            # this WAL exactly like a primary restart; compaction is the
            # follower's own job (the primary's doesn't cross the wire)
            try:
                self.wal.append_batch(wal_batch)
                self._maybe_compact()
            except OSError as e:
                # OUR disk died, not the stream. Fail-stop the durability
                # side only: in-memory state stays correct (reads and watch
                # fan-out keep working) but this replica can never again
                # vouch for durability, so promotion is barred permanently
                # and we stop touching the WAL — appending to a failed sink
                # would just re-raise forever.
                self.disk_failed = True
                logger.error(
                    "follower WAL append failed (disk fail-stop): %s — "
                    "replica continues serving in-memory but is barred "
                    "from promotion", e,
                )
        if wal_batch and self._observers:
            # observers get COPIES: the stored objects are live replica
            # state (a promotion shares self.objects with the promoted
            # APIServer) and the read path hands its view to watch queues
            import copy as _copy

            self._observe(
                "on_records",
                [
                    (rv, verb, kind, _copy.deepcopy(obj))
                    for rv, verb, kind, obj in wal_batch
                ],
            )

    # -- election endpoint ----------------------------------------------------

    def _my_status(self) -> dict:
        status = {
            "rv": self.rv,
            "term": self.term,
            "commit": self.commit_index,
            "synced": int(self._synced.is_set()),
            "promoted": int(self._promoted is not None),
            "id": self.node_id,
        }
        listener = self._promoted_listener
        if listener is not None:
            # advertise the new leader's replication endpoint: peers that
            # find us promoted during their election rounds redirect their
            # tails here (and their acks are what open our write quorum)
            status["repl_addr"] = list(listener.address)
        return status

    def _grant_vote(self, vote_term: int, cand_id: int, cand_key) -> bool:
        """Voter side of the election (raft §5.2/§5.4.1): grant iff
          * the round's term is NEW (above our current term — a round at
            or below it is stale),
          * our primary lease is NOT fresh (leader stickiness: a node
            still hearing the primary must not help depose it),
          * we have not voted for a DIFFERENT candidate this term
            (single vote per term: two majorities cannot form), and
          * the candidate's log is at least as up-to-date as ours
            (log_key: term, rv, capped commit — so a grant-majority
            winner provably holds every committed write).
        """
        with self._vote_lock:
            if self._promoted is not None:
                return False
            if vote_term <= self.term:
                return False
            last = self._last_seen
            if last is not None and (time.monotonic() - last) <= self.lease_s:
                return False
            if vote_term < self._voted_term:
                return False
            if vote_term == self._voted_term and self._voted_for != cand_id:
                return False
            if tuple(cand_key) < log_key(self._my_status()):
                return False
            self._voted_term = vote_term
            self._voted_for = cand_id
            return True

    def _election_loop(self) -> None:
        # runs until stop() closes the socket — NOT gated on _stopped:
        # promote() sets _stopped (the tail must die) but the election
        # endpoint must keep answering, both to tell candidates a leader
        # exists and to advertise the new leader's repl_addr so every
        # surviving follower (not just the first to ask) can redirect
        while True:
            try:
                sock, _addr = self._election_sock.accept()
            except OSError:
                return
            try:
                sock.settimeout(2.0)
                rfile = sock.makefile("rb")
                wfile = sock.makefile("wb")
                frame = _recv(rfile)
                if frame and "status" in frame:
                    _send(wfile, self._my_status())
                elif frame and "vote" in frame:
                    v = frame["vote"]
                    granted = self._grant_vote(
                        int(v.get("term", 0)),
                        int(v.get("id", -1)),
                        tuple(v.get("key", (0, 0, 0))),
                    )
                    reply = self._my_status()
                    reply["granted"] = int(granted)
                    # let refused candidates fast-forward past terms this
                    # voter has already consumed, instead of crawling one
                    # term per election round
                    reply["voted_term"] = self._voted_term
                    _send(wfile, reply)
            except (OSError, ValueError, json.JSONDecodeError):
                pass
            finally:
                try:
                    sock.close()
                except OSError:
                    pass

    def _request_vote(
        self, addr: Tuple[str, int], vote_term: int, key
    ) -> Optional[dict]:
        return self._rpc(
            addr,
            {
                "vote": {
                    "term": vote_term,
                    "id": self.node_id,
                    "key": list(key),
                }
            },
        )

    # -- failover -------------------------------------------------------------

    def _primary_reachable(self) -> bool:
        """A lease can lapse because the primary died OR because this link
        (or this process) stalled. Before any promotion, distinguish: if
        the primary still ANSWERS, it is alive — re-tail instead of
        splitting the brain (advisor r4 medium). The probe requires an
        application-level pong: a bare TCP connect is completed by the
        kernel's listen backlog even when the primary process is wedged,
        which would defer failover forever for a hung-but-listening
        primary."""
        reply = self._rpc(self.primary_addr, {"ping": 1})
        return bool(reply) and "pong" in reply

    @staticmethod
    def _rpc(
        addr: Tuple[str, int], frame: dict, timeout: float = 0.5
    ) -> Optional[dict]:
        """One-shot request/reply over a fresh connection (election
        status/vote polls, liveness probes). None on any failure."""
        try:
            sock = socket.create_connection(addr, timeout=timeout)
            try:
                sock.settimeout(timeout)
                wfile = sock.makefile("wb")
                rfile = sock.makefile("rb")
                _send(wfile, frame)
                return _recv(rfile)
            finally:
                sock.close()
        except (OSError, ValueError, json.JSONDecodeError):
            return None

    def _poll_status(self, addr: Tuple[str, int]) -> Optional[dict]:
        return self._rpc(addr, {"status": 1})

    def _maybe_defect_to_new_leader(self) -> None:
        """Zombie-leader escape: a follower whose tail is still fed by a
        DEPOSED or degraded old primary keeps a fresh lease (heartbeats
        carry no proof of leadership) and would never run an election —
        parked on a zombie forever while the real leader runs a replica
        short. So even with a fresh lease, occasionally ask the peers: if
        any reachable peer is promoted at a HIGHER term and advertises
        its replication endpoint, redirect there and cut the current
        tail (the zombie, at its lower term, can never fence us back)."""
        for addr in self.peers:
            s = self._poll_status(addr)
            if (
                s
                and s.get("promoted")
                and int(s.get("term", 0)) > self.term
                and s.get("repl_addr")
            ):
                new_addr = (s["repl_addr"][0], int(s["repl_addr"][1]))
                logger.warning(
                    "defecting from zombie primary %s to promoted peer "
                    "id=%s term=%s at %s",
                    self.primary_addr, s.get("id"), s.get("term"), new_addr,
                )
                self.primary_addr = new_addr
                cur = self._cur_sock
                if cur is not None:
                    try:
                        cur.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                return

    def _lease_loop(self) -> None:
        # freshly-randomized tick per round (raft's randomized election
        # timeout): dueling candidates that split one round's votes MUST
        # desynchronize — a fixed per-node factor (or none) phase-locks
        # them into splitting every round forever, since the vote
        # fast-forward re-aligns their terms after each split
        ticks = 0
        while not self._stopped.wait(
            self.lease_s / 4 * random.uniform(0.5, 1.5)
        ):
            ticks += 1
            if self._ejected.is_set():
                continue  # stale replica: no promotion until re-synced
            if self.disk_corrupt or self.disk_failed:
                # a replica whose WAL was mid-log corrupt (until a resync
                # heals it) or whose disk fail-stopped (permanent) must
                # never become primary: its durability story is a lie
                continue
            if not self._synced.is_set() or self.rv <= 0:
                continue  # nothing real to promote yet (advisor r4 high)
            last = self._last_seen
            if last is None or time.monotonic() - last <= self.lease_s:
                # lease fresh — but the feeder may be a zombie: scan the
                # peers about once per lease period for a promoted
                # higher-term leader (no-op while partitioned from them)
                if self.peers and ticks % 4 == 0:
                    self._maybe_defect_to_new_leader()
                continue
            if self._primary_reachable():
                # primary alive, our tail is what lapsed: treat the probe
                # as a heartbeat; the reconnect loop re-tails
                self._last_seen = time.monotonic()
                continue
            won_term = self._run_election()
            if won_term is None:
                continue  # no grant majority this round: retry
            self.promote(term=won_term)
            return

    def _run_election(self) -> Optional[int]:
        """One election round (raft §5.2): pick a FRESH term, vote for
        ourselves, request votes from every peer, and win only on a
        strict GRANT majority of cluster_size. Voters grant at most one
        candidate per term and only candidates whose (term, rv, commit)
        log is at least as up-to-date as their own — so two leaders in
        one term are impossible (grant majorities intersect) and the
        winner provably holds every committed (client-acknowledged)
        write. Returns the won term, or None (stand down this round).

        A failed round never reuses its term (_next_vote_term): a peer's
        grant from a dead round can then never combine with a later
        round's grants into two same-term majorities."""
        if not self.peers and self.cluster_size is None:
            return self.term + 1  # legacy two-node: the sole follower
        self._next_vote_term = max(self._next_vote_term, self.term + 1)
        vote_term = self._next_vote_term
        self._next_vote_term += 1
        my_key = log_key(self._my_status())
        # self-vote under the same single-vote rule we apply to peers
        with self._vote_lock:
            if self._promoted is not None:
                return None
            if vote_term < self._voted_term or (
                vote_term == self._voted_term
                and self._voted_for != self.node_id
            ):
                return None
            self._voted_term = vote_term
            self._voted_for = self.node_id
        replies = [
            r
            for r in (
                self._request_vote(a, vote_term, my_key) for a in self.peers
            )
            if r
        ]
        for r in replies:
            if r.get("promoted"):
                # a leader already exists: stand down — and redirect our
                # tail to its replication endpoint when it advertises one
                # (our ack is likely the quorum slot that re-opens its
                # writes; without the redirect we would retry the DEAD old
                # primary's address forever)
                addr = r.get("repl_addr")
                if addr:
                    self.primary_addr = (addr[0], int(addr[1]))
                    logger.warning(
                        "election: peer id=%s already promoted; re-tailing "
                        "its replication endpoint %s", r.get("id"),
                        self.primary_addr,
                    )
                else:
                    logger.warning(
                        "election: a peer already promoted; standing down"
                    )
                return None
        n = self.cluster_size or (len(self.peers) + 2)  # peers + self + primary
        reachable = 1 + len(replies)
        if reachable * 2 <= n:
            logger.warning(
                "election: no quorum (%d/%d reachable): refusing to promote "
                "(minority partition must not serve writes)", reachable, n
            )
            return None
        # commit-index floor (belt-and-braces; the voters' up-to-date
        # check already enforces it): committed means CLIENT-ACKNOWLEDGED.
        # If anyone reachable learned a commit index above our rv,
        # acknowledged writes exist that we do not hold.
        known_commit = max(
            [self.commit_index] + [int(r.get("commit", 0)) for r in replies]
        )
        if self.rv < known_commit:
            logger.warning(
                "election: our rv=%d is below the known commit index %d "
                "(acknowledged writes we do not hold): refusing to promote",
                self.rv, known_commit,
            )
            return None
        grants = 1 + sum(1 for r in replies if r.get("granted"))
        if grants * 2 <= n:
            # fast-forward past terms the voters have already consumed so
            # the next round isn't refused as stale
            self._next_vote_term = max(
                [self._next_vote_term]
                + [int(r.get("voted_term", 0)) + 1 for r in replies]
            )
            logger.info(
                "election: %d/%d grants at term %d (need majority): "
                "standing down this round", grants, n, vote_term,
            )
            return None
        logger.warning(
            "election: WON term %d with %d/%d grants (rv=%d commit=%d)",
            vote_term, grants, n, self.rv, self.commit_index,
        )
        return vote_term

    def promote(self, force: bool = False, term: Optional[int] = None):
        """Become primary at `term` (an election-won term; defaults to
        term+1 for the legacy/operator paths), building a live APIServer
        from the replica. Idempotent; returns the promoted server.
        Refuses (returns None) when this replica has never synced, was
        ejected from the sync set, recovered a mid-log-corrupt WAL that
        hasn't been healed by a resync yet, or fail-stopped its disk —
        promoting any of those would serve wrong/stale state over real
        durable writes — unless force=True (operator override)."""
        with self._lock:
            if self._promoted is not None:
                return self._promoted
            if not force and (
                not self._synced.is_set() or self.rv <= 0
                or self._ejected.is_set()
                or self.disk_corrupt or self.disk_failed
            ):
                logger.error(
                    "refusing promotion: synced=%s rv=%d ejected=%s "
                    "disk_corrupt=%s disk_failed=%s (use force=True to "
                    "override)",
                    self._synced.is_set(), self.rv, self._ejected.is_set(),
                    self.disk_corrupt, self.disk_failed,
                )
                return None
            from ..client.apiserver import APIServer

            self._stopped.set()
            self.term = term if term is not None else self.term + 1
            srv = APIServer(wal=self.wal)
            srv._rv = self.rv
            srv._objects = self.objects
            if self.cluster_size is not None:
                # consensus mode: the new leader's ack contract is the
                # SAME as the old one's — no write acks until a majority
                # holds it. Bring up a replication endpoint at the won
                # term (advertised via _my_status "repl_addr"; surviving
                # followers redirect their tails to it from their next
                # election round) and gate the store on its commit index.
                # Until a quorum of followers reconnects, writes degrade
                # instead of silently acking unreplicated.
                listener = ReplicationListener(
                    term=self.term,
                    cluster_size=self.cluster_size,
                    heartbeat_s=self.heartbeat_s,
                    ack_timeout_s=self.ack_timeout_s,
                )
                listener.attach(srv)
                self._promoted_listener = listener
            self._promoted = srv
            logger.warning(
                "follower promoted to primary at rv=%d term=%d", self.rv, self.term
            )
        # best-effort fence of the old primary: it may be merely STALLED
        # (lease lapsed without dying) — a hello at our higher term makes
        # it step down read-only instead of splitting the brain. A dead
        # primary simply refuses the connection.
        try:
            sock = socket.create_connection(self.primary_addr, timeout=1.0)
            try:
                wfile = sock.makefile("wb")
                _send(wfile, {"hello": {"rv": self.rv, "term": self.term}})
            finally:
                sock.close()
        except OSError:
            pass
        if self.on_promote is not None:
            try:
                self.on_promote(srv)
            except Exception:
                logger.exception("on_promote callback failed")
        return srv

    @property
    def promoted(self):
        return self._promoted

    def stop(self) -> None:
        self._stopped.set()
        if self._promoted_listener is not None:
            self._promoted_listener.close()
        if self._election_sock is not None:
            try:
                self._election_sock.close()
            except OSError:
                pass
