"""Thread-safe keyed stores with indexers + the API store's write gate.

Equivalent of client-go tools/cache thread_safe_store.go / index.go: a
locked map keyed by namespace/name with pluggable index functions, used as
the informer-backed local cache every component reads instead of the API
server (reference pattern: Reflector -> DeltaFIFO -> Indexer).

WriteGate is the API store's write-admission authority: one place that
answers "may this store accept a mutation right now?" across the two
distinct refusal modes the HA stack produces — fenced (a higher-term
primary exists; permanent for this process, NotPrimary) and degraded
(write quorum lost; lifts when followers catch the commit index up,
DegradedWrites/503-retryable — see runtime/consensus.py). Reads and
watches are never gated.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

from ..utils.metrics import metrics

IndexFunc = Callable[[Any], List[str]]

COUNTER_DISK_REJECTS = "store_disk_writes_rejected_total"


class WriteGate:
    """Write-admission gate for the API store (client/apiserver.py).

    ``fenced`` is the raft higher-term-wins fence: set when a successor
    primary appears; this process never writes again. ``degraded``
    delegates to the attached ConsensusCoordinator's commit-index state:
    writes fail fast (retryable) while a quorum is not caught up, instead
    of burning a replication ack window per rejected write. The store
    calls :meth:`check_degraded` BEFORE applying any mutation."""

    def __init__(self):
        self.fenced = False
        self._consensus = None
        # disk fail-stop (permanent for the process: the WAL sink poisoned
        # itself on a write/fsync error) vs disk pressure (transient: low
        # free space / ENOSPC, lifts when space recovers)
        self.disk_failed = False
        self.disk_failed_reason = ""
        self.disk_pressure = False

    def attach_consensus(self, coordinator) -> None:
        """Arm the degraded-mode gate (runtime/replication.py attach())."""
        self._consensus = coordinator

    def set_disk_failed(self, reason: str) -> None:
        """Fail-stop: the WAL sink is dead; this store never writes again
        (mirrors the WAL's own poison — there is no clear path)."""
        self.disk_failed = True
        self.disk_failed_reason = reason

    def set_disk_pressure(self, value: bool) -> None:
        self.disk_pressure = bool(value)

    @property
    def disk_healthy(self) -> bool:
        """Leadership eligibility: a leader with a failed disk must release
        its lease (client/leaderelection.py disk_health wiring). Pressure
        does NOT disqualify — it lifts; a poisoned sink never does."""
        return not self.disk_failed

    @property
    def degraded(self) -> bool:
        c = self._consensus
        return bool(
            self.disk_failed
            or self.disk_pressure
            or (c is not None and c.degraded)
        )

    def check_degraded(self) -> None:
        """Raise the matching DegradedWrites subclass when writes must be
        refused: disk fail-stop, disk pressure, then quorum state."""
        if self.disk_failed:
            from .consensus import DiskFailed

            metrics.inc(COUNTER_DISK_REJECTS)
            raise DiskFailed(
                f"store disk failed (WAL sink fail-stop): {self.disk_failed_reason}"
            )
        if self.disk_pressure:
            from .consensus import DiskPressure

            metrics.inc(COUNTER_DISK_REJECTS)
            raise DiskPressure(
                "store under disk pressure: WAL volume low on space "
                "(read-only until space recovers)"
            )
        c = self._consensus
        if c is not None:
            c.check_writable()

    def describe(self) -> str:
        """One-line state for debug dumps (SIGUSR2 debugger)."""
        if self.fenced:
            return "fenced (higher-term primary exists)"
        if self.disk_failed:
            return f"disk-failed read-only ({self.disk_failed_reason})"
        if self.disk_pressure:
            return "disk-pressure read-only (low free space)"
        if self.degraded:
            return "degraded read-only (write quorum lost)"
        return "open"


def meta_namespace_key(obj: Any) -> str:
    return obj.metadata.key


class ThreadSafeStore:
    def __init__(self, key_func: Callable[[Any], str] = meta_namespace_key):
        self._lock = threading.RLock()
        self._items: Dict[str, Any] = {}
        self._key_func = key_func

    def add(self, obj: Any) -> None:
        with self._lock:
            self._items[self._key_func(obj)] = obj

    update = add

    def delete(self, obj: Any) -> None:
        with self._lock:
            self._items.pop(self._key_func(obj), None)

    def get(self, key: str) -> Optional[Any]:
        with self._lock:
            return self._items.get(key)

    def list(self) -> List[Any]:
        with self._lock:
            return list(self._items.values())

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._items.keys())

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


class Indexer(ThreadSafeStore):
    """Store + secondary indices (cache.Indexer)."""

    def __init__(
        self,
        key_func: Callable[[Any], str] = meta_namespace_key,
        indexers: Optional[Dict[str, IndexFunc]] = None,
    ):
        super().__init__(key_func)
        self._indexers = indexers or {}
        self._indices: Dict[str, Dict[str, set]] = {
            name: {} for name in self._indexers
        }

    def add(self, obj: Any) -> None:
        key = self._key_func(obj)
        with self._lock:
            old = self._items.get(key)
            if old is not None:
                self._remove_from_indices(old, key)
            self._items[key] = obj
            self._add_to_indices(obj, key)

    update = add

    def delete(self, obj: Any) -> None:
        key = self._key_func(obj)
        with self._lock:
            old = self._items.pop(key, None)
            if old is not None:
                self._remove_from_indices(old, key)

    def by_index(self, index_name: str, index_value: str) -> List[Any]:
        with self._lock:
            keys = self._indices.get(index_name, {}).get(index_value, set())
            return [self._items[k] for k in keys if k in self._items]

    def _add_to_indices(self, obj: Any, key: str) -> None:
        for name, fn in self._indexers.items():
            for val in fn(obj):
                self._indices[name].setdefault(val, set()).add(key)

    def _remove_from_indices(self, obj: Any, key: str) -> None:
        for name, fn in self._indexers.items():
            for val in fn(obj):
                s = self._indices[name].get(val)
                if s is not None:
                    s.discard(key)
                    if not s:
                        del self._indices[name][val]
