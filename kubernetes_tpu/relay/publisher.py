"""Relay publisher + fleet orchestration (the parent-process side).

``RelayPublisher`` sits next to a frontend's ``Cacher`` and bridges it
into shared memory: per kind, ONE cache watcher feeds ONE
:class:`~kubernetes_tpu.relay.ring.FrameRing`, each event's memoized
binary frame (apiserver/watchcodec.py) written exactly once. Every
relay worker process fans those bytes out to its own clients — the
frontend pays per FRAME, the workers pay per frame × their clients,
and no Python GIL is shared between the two.

``start_relay`` wires the full tier: it reserves one TCP port with
SO_REUSEPORT *without listening* (the kernel only shards accepts among
LISTENING sockets, so the parent's reservation socket receives nothing
— it just pins the port number), then spawns N worker processes
(`python -m kubernetes_tpu.relay.worker`) that bind the same port WITH
listen. Worker death sheds its accept share to the siblings instantly;
``RelayHandle.respawn_worker`` brings the count back, and the fresh
worker rebuilds the retained window from the ring floor so clients can
resume at rvs from before it existed.

The publisher's pump threads are graftlint dispatch roots (the same
never-block contract as the cacher's dispatch loop): bounded queue
gets, lock-free shared-memory writes, no sockets.
"""

from __future__ import annotations

import os
import selectors
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..apiserver import watchcodec
from ..runtime.watch import BOOKMARK
from ..utils.metrics import metrics
from .ring import FrameRing, RESYNC_TYPE

COUNTER_FRAMES = "relay_frames_published_total"        # {kind}
COUNTER_RING_EVICTIONS = "relay_ring_evictions_total"  # {kind}
COUNTER_RESYNCS = "relay_publisher_resyncs_total"      # {kind}
GAUGE_RING_FLOOR = "relay_ring_floor_rv"               # {kind}
GAUGE_RING_HEAD = "relay_ring_head_seq"                # {kind}
GAUGE_WORKERS = "relay_workers"
COUNTER_WORKER_RESTARTS = "relay_worker_restarts_total"

# ring sized for ~1 MiB of retained frames per kind by default in tests;
# the bench passes 4 MiB+ so the resume window spans whole churn storms
DEFAULT_RING_CAPACITY = 1 << 22

_PUMP_POLL_S = 0.5


class RelayPublisher:
    """One cache watcher -> one shared-memory ring, per kind."""

    def __init__(
        self,
        cacher,
        kinds: Sequence[str],
        ring_capacity: int = DEFAULT_RING_CAPACITY,
    ):
        self._cacher = cacher
        self._stop = threading.Event()
        self.rings: Dict[str, FrameRing] = {}
        self._threads: List[threading.Thread] = []
        for kind in kinds:
            ring = FrameRing.create(capacity=ring_capacity)
            self.rings[kind] = ring
            t = threading.Thread(
                target=self._pump,
                args=(kind, ring),
                name=f"relay-pub-{kind}",
                daemon=True,
            )
            self._threads.append(t)
            t.start()

    # graftlint dispatch root: nothing in here may block unboundedly —
    # the ring write path is lock-free shared memory and the watcher get
    # is bounded by _PUMP_POLL_S.
    def _pump(self, kind: str, ring: FrameRing) -> None:
        w, replay_left = self._subscribe(kind, ring, initial=True)
        evicted_base = ring.floor()[0]
        while not self._stop.is_set():
            ev = w.get(timeout=_PUMP_POLL_S)
            if ev is None:
                if w.stopped and not self._stop.is_set():
                    # the publisher fell behind its own cache fan-out
                    # (queue overflow): continuity is broken, so resync —
                    # re-subscribe at the current cache rv, raise the ring
                    # floor, and tell workers to shed their clients (they
                    # resume through the cacher window / relist on 410)
                    metrics.inc(COUNTER_RESYNCS, {"kind": kind})
                    w, replay_left = self._subscribe(kind, ring, initial=False)
                continue
            if replay_left:
                # skip the rv=0 state replay: the ring carries the LIVE
                # tail only; workers serve initial state via their own
                # upstream state-sync path. The replay's closing event is
                # a bookmark at the cache rv — the ring's base position.
                replay_left -= 1
                if replay_left == 0 and ev.type == BOOKMARK:
                    ring.set_initial_floor(ev.resource_version)
                    ring.publish(
                        ev.resource_version,
                        watchcodec.bookmark_frame(ev.resource_version),
                    )
                continue
            if ev.type == BOOKMARK:
                frame = watchcodec.bookmark_frame(ev.resource_version)
            else:
                frame = watchcodec.event_frame(ev)
            ring.publish(ev.resource_version, frame)
            metrics.inc(COUNTER_FRAMES, {"kind": kind})
            floor_seq, _cum, floor_rv = ring.floor()
            if floor_seq > evicted_base:
                metrics.inc(
                    COUNTER_RING_EVICTIONS, {"kind": kind},
                    by=floor_seq - evicted_base,
                )
                evicted_base = floor_seq
            metrics.set_gauge(GAUGE_RING_FLOOR, floor_rv, {"kind": kind})
            metrics.set_gauge(GAUGE_RING_HEAD, ring.head()[0], {"kind": kind})

    def _subscribe(self, kind: str, ring: FrameRing, initial: bool):
        """(watcher, replay_left). A non-initial subscribe is a RESYNC:
        the ring gets a control record telling workers to shed clients,
        and the floor jumps to the new subscription's base rv."""
        kc = self._cacher.cache_for(kind)
        w = kc.watch(0)
        if not initial:
            base = kc.current_rv
            ring.publish(base, RESYNC_TYPE + b"")
            ring.set_initial_floor(base)
        return w, getattr(w, "replay_count", 0)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2.0)
        for ring in self.rings.values():
            ring.close()

    def ring_names(self) -> Dict[str, str]:
        return {kind: ring.name for kind, ring in self.rings.items()}


class RelayHandle:
    """The running relay tier: publisher + reserved port + worker fleet."""

    def __init__(
        self,
        publisher: RelayPublisher,
        port: int,
        reserve_sock: socket.socket,
        workers: List[Tuple[subprocess.Popen, int]],
        spawn_args: List[str],
        tls: bool,
    ):
        self.publisher = publisher
        self.port = port
        self.tls = tls
        self._reserve = reserve_sock
        self.workers = workers  # [(Popen, stats_port)]
        self._spawn_args = spawn_args
        metrics.set_gauge(GAUGE_WORKERS, len(workers))

    # -- fleet management ----------------------------------------------------

    def kill_worker(self, idx: int, sig: int = 9) -> int:
        proc, _sp = self.workers[idx]
        os.kill(proc.pid, sig)
        proc.wait(timeout=10)
        return proc.pid

    def respawn_worker(self, idx: int) -> None:
        proc, stats_port = _spawn_worker(self._spawn_args)
        self.workers[idx] = (proc, stats_port)
        metrics.inc(COUNTER_WORKER_RESTARTS)
        metrics.set_gauge(GAUGE_WORKERS, len(self.workers))

    def worker_stats(self, timeout: float = 5.0) -> List[dict]:
        """Per-worker stats dicts (skips dead workers)."""
        import json
        import urllib.request

        out = []
        for proc, stats_port in self.workers:
            if proc.poll() is not None:
                continue
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{stats_port}/", timeout=timeout
                ) as resp:
                    out.append(json.loads(resp.read()))
            except OSError:
                continue
        return out

    def stop(self) -> None:
        for proc, _sp in self.workers:
            if proc.poll() is None:
                proc.terminate()
        for proc, _sp in self.workers:
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5)
        try:
            self._reserve.close()
        except OSError:
            pass
        self.publisher.stop()
        metrics.set_gauge(GAUGE_WORKERS, 0)


def _reuseport_socket(host: str, port: int) -> socket.socket:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
    s.bind((host, port))
    return s


def _spawn_worker(
    args: List[str], timeout: float = 60.0
) -> Tuple[subprocess.Popen, int]:
    """Start one relay worker and wait for its READY line."""
    proc = subprocess.Popen(
        args,
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
    )
    sel = selectors.DefaultSelector()
    sel.register(proc.stdout, selectors.EVENT_READ)
    deadline = time.monotonic() + timeout
    line = ""
    while time.monotonic() < deadline:
        if not sel.select(timeout=0.25):
            if proc.poll() is not None:
                break
            continue
        line = proc.stdout.readline()
        break
    sel.close()
    parts = line.split()
    if len(parts) < 4 or parts[0] != "READY":
        proc.kill()
        raise RuntimeError(f"relay worker failed to start: {line!r}")
    return proc, int(parts[3])


def start_relay(
    cacher,
    sync_url: str,
    kinds: Sequence[str] = ("pods",),
    n_workers: int = 2,
    host: str = "127.0.0.1",
    port: int = 0,
    tls_cert: Optional[str] = None,
    tls_key: Optional[str] = None,
    hollow_clients: int = 0,
    hollow_kind: str = "pods",
    ring_capacity: int = DEFAULT_RING_CAPACITY,
    max_pending_bytes: int = 4 << 20,
    bookmark_period_s: float = 2.0,
) -> RelayHandle:
    """Bring up the relay tier over an existing Cacher.

    ``sync_url`` is the REST base URL (the frontend this publisher lives
    in) that workers use for rv=0 state synchronization. ``hollow_clients``
    is split evenly across workers (kubemark-style in-process watchers
    for scale benches). Returns a :class:`RelayHandle`.
    """
    publisher = RelayPublisher(cacher, kinds, ring_capacity=ring_capacity)
    reserve = _reuseport_socket(host, port)
    bound_port = reserve.getsockname()[1]
    args = [
        sys.executable, "-m", "kubernetes_tpu.relay.worker",
        "--host", host,
        "--port", str(bound_port),
        "--sync-url", sync_url,
        "--max-pending-bytes", str(max_pending_bytes),
        "--bookmark-period", str(bookmark_period_s),
    ]
    for kind, name in publisher.ring_names().items():
        args += ["--ring", f"{kind}={name}"]
    if tls_cert and tls_key:
        args += ["--tls-cert", tls_cert, "--tls-key", tls_key]
    per_worker = hollow_clients // max(n_workers, 1) if hollow_clients else 0
    if per_worker:
        args += ["--hollow", str(per_worker), "--hollow-kind", hollow_kind]
    workers = []
    try:
        for _ in range(n_workers):
            workers.append(_spawn_worker(args))
    except Exception:
        for proc, _sp in workers:
            proc.kill()
        reserve.close()
        publisher.stop()
        raise
    return RelayHandle(
        publisher, bound_port, reserve, workers, args,
        tls=bool(tls_cert and tls_key),
    )


def relay_health_lines() -> List[str]:
    """Publisher/fleet counters for the SIGUSR2 serving-relay section."""
    lines: List[str] = []
    for snap in (
        metrics.snapshot_gauges("relay_"),
        metrics.snapshot_counters("relay_"),
    ):
        for name, labels, value in snap:
            lines.append(metrics.format_series_line(name, labels, value))
    return lines
