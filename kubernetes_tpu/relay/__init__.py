"""Million-client watch relay: shared-memory fan-out tier.

The serving tier's PR-14 ceiling was the frontend process itself: one
GIL, one ``wfile.write`` per client per frame, plaintext. This package
moves fan-out OUT of the frontend: a publisher writes each kind's
memoized binary watch frames exactly once into a shared-memory frame
ring (``ring``), and N SO_REUSEPORT worker processes (``worker``) fan
the same bytes out to their accepted clients with batched non-blocking
``sendmsg`` — cost scales with frames produced, not clients connected,
and TLS terminates at the worker so the hop is honest about crypto.

Orchestration (``publisher.start_relay``) reserves the shared port,
spawns the workers, and hands back a :class:`~.publisher.RelayHandle`
for chaos surgery (kill/respawn) and stats aggregation.
"""

from .publisher import (  # noqa: F401
    RelayHandle,
    RelayPublisher,
    relay_health_lines,
    start_relay,
)
from .ring import FrameRing, RingReader  # noqa: F401
