"""Relay worker: SO_REUSEPORT accept sharding + batched frame fan-out.

One worker process owns the clients it accepted and nothing else. The
kernel shards accepts across the N workers listening on the shared
port (SO_REUSEPORT); each worker polls the shared-memory frame rings
and fans a new frame out to every subscribed client with batched
non-blocking ``socket.sendmsg`` (scatter-gather writev) — one wire
chunk is built per frame per worker and SHARED across all client send
queues, so per-frame cost is O(clients) pointer appends plus the
syscalls, never O(clients) encodes.

Never-block discipline (graftlint dispatch root ``RelayWorker._dispatch``,
the same contract the cacher's dispatch thread lives under):

  * sends are non-blocking; a would-block registers the fd for
    writability and moves on,
  * a client whose pending buffer exceeds the bound is a SLOW CLIENT
    and is evicted on the spot (it reconnects and resumes through the
    cacher-window contract — or relists on 410),
  * accepts, TLS handshakes, HTTP parsing, and rv=0 state sync all live
    on intake threads, never in the dispatch loop.

Death is invisible to informers: the ring outlives the worker, a
replacement reader starts at the ring FLOOR and rebuilds the retained
window, and clients that reconnect mid-gap resume at their last
delivered rv (or 410 into a relist, exactly like the cacher window).
"""

from __future__ import annotations

import argparse
import json
import os
import selectors
import socket
import ssl
import struct
import sys
import threading
import time
from array import array
from collections import deque
from typing import Dict, List, Optional
from urllib.parse import parse_qs, urlsplit

from ..apiserver.watchcodec import WATCH_CONTENT_TYPE, bookmark_frame
from .ring import BOOKMARK_TYPE, FrameRing, PAD, RESYNC_TYPE, RingReader

_EVENT_TYPES = (b"A", b"M", b"D", b"J")
_FRAME_HDR = struct.Struct(">cI")

# sendmsg is capped at IOV_MAX buffers per call; stay far below it
_SENDMSG_BATCH = 64
_INTAKE_TIMEOUT_S = 15.0
_POLL_BUSY_S = 0.002
_POLL_IDLE_S = 0.02


def _chunk(frame: bytes) -> bytes:
    """HTTP/1.1 chunked wire form, built once per frame per worker."""
    return b"%x\r\n%s\r\n" % (len(frame), frame)


class _Client:
    __slots__ = (
        "sock", "fd", "kind", "resume_rv", "pending", "pending_bytes",
        "tls", "wregistered",
    )

    def __init__(self, sock, kind: str, resume_rv: int, tls: bool):
        self.sock = sock
        self.fd = sock.fileno()
        self.kind = kind
        self.resume_rv = resume_rv
        self.pending: deque = deque()
        self.pending_bytes = 0
        self.tls = tls
        self.wregistered = False

    def queue(self, wire: bytes) -> None:
        self.pending.append(wire)
        self.pending_bytes += len(wire)


class _KindState:
    __slots__ = (
        "kind", "ring", "reader", "history", "clients", "last_rv",
        "last_frame_t", "hollow_delivered", "hollow_rv",
    )

    def __init__(self, kind: str, ring: FrameRing, hollow: int):
        self.kind = kind
        self.ring = ring
        self.reader = RingReader(ring)  # from the floor: full window
        # (rv, ftype, wire) of retained frames for resume replay
        self.history: deque = deque()
        self.clients: List[_Client] = []
        self.last_rv = ring.floor_rv()
        self.last_frame_t = time.monotonic()
        # kubemark-style hollow watchers: per-client delivered counters
        # and rv cursors keep the per-client fan-out work REAL (one
        # filter + one bump per client per frame) without sockets
        self.hollow_delivered = array("Q", [0] * hollow) if hollow else None
        self.hollow_rv = (
            array("Q", [self.last_rv] * hollow) if hollow else None
        )


class RelayWorker:
    def __init__(
        self,
        host: str,
        port: int,
        rings: Dict[str, str],
        sync_url: str,
        tls_cert: Optional[str] = None,
        tls_key: Optional[str] = None,
        hollow: int = 0,
        hollow_kind: str = "pods",
        max_pending_bytes: int = 4 << 20,
        bookmark_period_s: float = 2.0,
    ):
        self.sync_url = sync_url
        self.max_pending_bytes = max_pending_bytes
        self.bookmark_period_s = bookmark_period_s
        self._stop = threading.Event()
        self._incoming: deque = deque()  # intake -> dispatch handoff
        self._sel = selectors.DefaultSelector()
        self._kinds: Dict[str, _KindState] = {}
        for kind, shm_name in rings.items():
            n_hollow = hollow if kind == hollow_kind else 0
            self._kinds[kind] = _KindState(
                kind, FrameRing.attach(shm_name), n_hollow
            )
        self._ssl_ctx = None
        if tls_cert and tls_key:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls_cert, tls_key)
            self._ssl_ctx = ctx
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        self._listener.bind((host, port))
        self._listener.listen(1024)
        self.port = self._listener.getsockname()[1]
        # counters (dispatch-thread writes, stats-thread reads: benign)
        self.frames_seen = 0
        self.real_delivered = 0
        self.hollow_delivered_total = 0
        self.evicted_slow = 0
        self.disconnects = 0
        self.shed = 0
        self.sync_streams = 0
        self.n_clients = 0

    # -- intake side (blocking is fine here: never on the dispatch path) -----

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return  # listener closed: shutting down
            t = threading.Thread(
                target=self._handle_intake, args=(conn,),
                name="relay-intake", daemon=True,
            )
            t.start()

    def _handle_intake(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(_INTAKE_TIMEOUT_S)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # cap the kernel send buffer: autotuning would grow it to
            # ~4 MiB per deaf client, hiding that much fan-out behind
            # the OS before max_pending_bytes could ever trip — the
            # per-client memory bound must be OURS, not the autotuner's
            conn.setsockopt(
                socket.SOL_SOCKET, socket.SO_SNDBUF,
                min(self.max_pending_bytes, 128 << 10),
            )
            if self._ssl_ctx is not None:
                conn = self._ssl_ctx.wrap_socket(conn, server_side=True)
            kind, from_rv = self._read_request(conn)
            st = self._kinds.get(kind)
            if st is None:
                self._reject(conn, 404, f"no relay ring for kind {kind}")
                return
            if from_rv and from_rv < st.ring.floor_rv():
                self._reject(
                    conn, 410,
                    f"resourceVersion {from_rv} is too old for the relay "
                    f"ring (floor {st.ring.floor_rv()})",
                )
                return
            conn.sendall(
                b"HTTP/1.1 200 OK\r\n"
                b"Content-Type: " + WATCH_CONTENT_TYPE.encode() + b"\r\n"
                b"Transfer-Encoding: chunked\r\n\r\n"
            )
            if not from_rv:
                # rv=0: replay current state from the frontend (one
                # upstream watch, forwarded verbatim up to its closing
                # bookmark), then ride the ring from the bookmark rv
                from_rv = self._state_sync(conn, kind)
            conn.settimeout(0)  # non-blocking from here: dispatch owns it
            self._incoming.append(
                _Client(conn, kind, from_rv, tls=self._ssl_ctx is not None)
            )
        except (OSError, ValueError, ssl.SSLError):
            try:
                conn.close()
            except OSError:
                pass

    def _read_request(self, conn) -> (str, int):
        f = conn.makefile("rb")
        try:
            reqline = f.readline(4096).decode("latin-1").strip()
            while True:
                line = f.readline(4096)
                if not line or line in (b"\r\n", b"\n"):
                    break
            parts = reqline.split()
            if len(parts) != 3 or parts[0] != "GET":
                raise ValueError(f"bad relay request: {reqline!r}")
            split = urlsplit(parts[1])
            q = parse_qs(split.query)
            if q.get("watch", ["0"])[-1] not in ("1", "true"):
                raise ValueError("relay serves watches only")
            kind = split.path.rstrip("/").rsplit("/", 1)[-1]
            from_rv = int(q.get("resourceVersion", ["0"])[-1] or 0)
            return kind, from_rv
        finally:
            f.close()

    def _reject(self, conn, status: int, body: str) -> None:
        reason = {404: "Not Found", 410: "Gone"}.get(status, "Bad Request")
        payload = body.encode()
        conn.sendall(
            b"HTTP/1.1 %d %s\r\nContent-Type: text/plain\r\n"
            b"Content-Length: %d\r\nConnection: close\r\n\r\n%s"
            % (status, reason.encode(), len(payload), payload)
        )
        conn.close()

    def _state_sync(self, conn, kind: str) -> int:
        """Forward the upstream rv=0 state replay (ADDED per object +
        the closing bookmark) verbatim, returning the bookmark rv."""
        import http.client

        self.sync_streams += 1
        sp = urlsplit(self.sync_url)
        if sp.scheme == "https":
            up = http.client.HTTPSConnection(
                sp.hostname, sp.port or 443, timeout=_INTAKE_TIMEOUT_S,
                context=ssl._create_unverified_context(),
            )
        else:
            up = http.client.HTTPConnection(
                sp.hostname, sp.port or 80, timeout=_INTAKE_TIMEOUT_S
            )
        try:
            up.request(
                "GET", f"/api/v1/{kind}?watch=1&resourceVersion=0",
                headers={"Accept": WATCH_CONTENT_TYPE},
            )
            resp = up.getresponse()
            if resp.status != 200:
                raise OSError(f"state sync failed: HTTP {resp.status}")
            while True:
                head = resp.read(_FRAME_HDR.size)
                if len(head) < _FRAME_HDR.size:
                    raise OSError("state sync stream truncated")
                code, length = _FRAME_HDR.unpack(head)
                payload = resp.read(length)
                if len(payload) < length:
                    raise OSError("state sync stream truncated")
                conn.sendall(_chunk(head + payload))
                if code == BOOKMARK_TYPE:
                    return struct.unpack(">Q", payload)[0]
        finally:
            up.close()

    # -- dispatch side (graftlint dispatch root: never block) ----------------

    def _dispatch(self) -> None:
        states = list(self._kinds.values())
        while not self._stop.is_set():
            now = time.monotonic()
            self._drain_incoming()
            progressed = False
            for st in states:
                frames, lapped = st.reader.read_new()
                if lapped:
                    # the dispatch loop itself fell a full ring behind:
                    # every client of the kind is gapped — shed them all
                    self._shed_kind(st)
                if frames:
                    progressed = True
                    st.last_frame_t = now
                    for _seq, rv, ftype, frame in frames:
                        self._fan_out(st, rv, ftype, frame)
            for st in states:
                if now - st.last_frame_t >= self.bookmark_period_s:
                    # ring idle (degraded primary / stalled publisher):
                    # per-stream heartbeats keep informer resume
                    # positions fresh from the worker alone
                    st.last_frame_t = now
                    if st.clients:
                        wire = _chunk(bookmark_frame(st.last_rv))
                        for c in st.clients:
                            c.queue(wire)
                # copy: a write failure inside _try_flush drops the
                # client from st.clients mid-iteration
                for c in list(st.clients):
                    if c.pending:
                        self._try_flush(st, c)
            self._sel.select(
                timeout=_POLL_BUSY_S if progressed else _POLL_IDLE_S
            )

    def _drain_incoming(self) -> None:
        while True:
            try:
                c = self._incoming.popleft()
            except IndexError:
                return
            st = self._kinds[c.kind]
            if c.resume_rv and c.resume_rv < st.ring.floor_rv():
                # floor advanced between intake and registration: the
                # stream is gapped before it started — close it; the
                # reconnect gets a clean 410 from intake
                self._drop(st, c, counted=False)
                continue
            # replay the retained window above the client's position,
            # then a bookmark advancing it to the kind's current rv
            for rv, ftype, wire in st.history:
                if ftype in _EVENT_TYPES and rv > c.resume_rv:
                    c.queue(wire)
            c.queue(_chunk(bookmark_frame(max(st.last_rv, c.resume_rv))))
            st.clients.append(c)
            self.n_clients += 1
            self._try_flush(st, c)

    def _fan_out(self, st: _KindState, rv: int, ftype: bytes,
                 frame: bytes) -> None:
        if ftype == RESYNC_TYPE:
            # publisher lost continuity: every client must resume
            # through the cacher window instead of trusting the ring
            self._shed_kind(st)
            return
        self.frames_seen += 1
        wire = _chunk(frame)
        st.history.append((rv, ftype, wire))
        floor_rv = st.ring.floor_rv()
        while st.history and st.history[0][0] < floor_rv:
            st.history.popleft()
        if ftype != BOOKMARK_TYPE:
            if rv > st.last_rv:
                st.last_rv = rv
        hd = st.hollow_delivered
        if hd is not None:
            # the hollow fleet's per-client work is real: one rv-filter
            # check + one counter bump per client per frame
            if ftype == BOOKMARK_TYPE:
                for i in range(len(hd)):
                    hd[i] += 1
                self.hollow_delivered_total += len(hd)
            else:
                hrv = st.hollow_rv
                n = 0
                for i in range(len(hd)):
                    if rv > hrv[i]:
                        hd[i] += 1
                        hrv[i] = rv
                        n += 1
                self.hollow_delivered_total += n
        if st.clients:
            slow = None
            for c in st.clients:
                c.queue(wire)
                if c.pending_bytes > self.max_pending_bytes:
                    if slow is None:
                        slow = []
                    slow.append(c)
            self.real_delivered += len(st.clients)
            if slow:
                for c in slow:
                    self.evicted_slow += 1
                    self._drop(st, c)

    def _try_flush(self, st: _KindState, c: _Client) -> None:
        sock = c.sock
        try:
            while c.pending:
                if c.tls:
                    n = sock.send(c.pending[0])
                else:
                    bufs = []
                    for i, b in enumerate(c.pending):
                        if i >= _SENDMSG_BATCH:
                            break
                        bufs.append(b)
                    n = sock.sendmsg(bufs)
                c.pending_bytes -= n
                while n:
                    head = c.pending[0]
                    if n >= len(head):
                        n -= len(head)
                        c.pending.popleft()
                    else:
                        c.pending[0] = head[n:]
                        n = 0
        except (BlockingIOError, ssl.SSLWantWriteError, ssl.SSLWantReadError):
            if not c.wregistered:
                try:
                    self._sel.register(c.fd, selectors.EVENT_WRITE, c)
                    c.wregistered = True
                except (KeyError, ValueError, OSError):
                    pass
            return
        except OSError:
            # abrupt disconnect: detected AT the write-failure site —
            # account for the stream immediately, never at the next tick
            self.disconnects += 1
            self._drop(st, c)
            return
        if c.wregistered:
            try:
                self._sel.unregister(c.fd)
            except (KeyError, ValueError, OSError):
                pass
            c.wregistered = False

    def _drop(self, st: _KindState, c: _Client, counted: bool = True) -> None:
        if c.wregistered:
            try:
                self._sel.unregister(c.fd)
            except (KeyError, ValueError, OSError):
                pass
            c.wregistered = False
        try:
            c.sock.close()
        except OSError:
            pass
        try:
            st.clients.remove(c)
            if counted:
                self.n_clients -= 1
        except ValueError:
            pass  # never registered (pre-registration close)

    def _shed_kind(self, st: _KindState) -> None:
        for c in list(st.clients):
            self.shed += 1
            self._drop(st, c)
        st.history.clear()
        st.last_rv = max(st.last_rv, st.ring.floor_rv())

    # -- lifecycle -----------------------------------------------------------

    def stats(self) -> dict:
        t = os.times()
        per_kind = {}
        n_hollow = 0
        for kind, st in self._kinds.items():
            hollow = len(st.hollow_delivered) if st.hollow_delivered else 0
            n_hollow += hollow
            per_kind[kind] = {
                "last_rv": st.last_rv,
                "floor_rv": st.ring.floor_rv(),
                "history": len(st.history),
                "clients": len(st.clients),
                "hollow": hollow,
                "lapped": st.reader.lapped_total,
            }
        return {
            "pid": os.getpid(),
            "port": self.port,
            "clients": self.n_clients,
            "hollow": n_hollow,
            "frames": self.frames_seen,
            "real_delivered": self.real_delivered,
            "hollow_delivered": self.hollow_delivered_total,
            "delivered": self.real_delivered + self.hollow_delivered_total,
            "evicted_slow": self.evicted_slow,
            "disconnects": self.disconnects,
            "shed": self.shed,
            "sync_streams": self.sync_streams,
            "cpu_s": t[0] + t[1],
            "kinds": per_kind,
        }

    def start_intake(self) -> None:
        threading.Thread(
            target=self._accept_loop, name="relay-accept", daemon=True
        ).start()

    def run(self) -> None:
        """Dispatch forever on the calling thread."""
        self.start_intake()
        try:
            self._dispatch()
        finally:
            self.close()

    def stop(self) -> None:
        self._stop.set()

    def close(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        for st in self._kinds.values():
            for c in list(st.clients):
                self._drop(st, c, counted=False)
            st.ring.close()  # attach-side close: never unlinks
        try:
            self._sel.close()
        except OSError:
            pass


def _serve_stats(worker: RelayWorker) -> int:
    """Tiny JSON stats endpoint (the netchaos child-process idiom)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802
            body = json.dumps(worker.stats()).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    srv.daemon_threads = True
    threading.Thread(
        target=srv.serve_forever, name="relay-stats", daemon=True
    ).start()
    return srv.server_address[1]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description="kubernetes_tpu relay worker")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--ring", action="append", default=[],
                    metavar="KIND=SHM_NAME", required=True)
    ap.add_argument("--sync-url", required=True)
    ap.add_argument("--tls-cert")
    ap.add_argument("--tls-key")
    ap.add_argument("--hollow", type=int, default=0)
    ap.add_argument("--hollow-kind", default="pods")
    ap.add_argument("--max-pending-bytes", type=int, default=4 << 20)
    ap.add_argument("--bookmark-period", type=float, default=2.0)
    args = ap.parse_args(argv)
    rings = dict(spec.split("=", 1) for spec in args.ring)
    worker = RelayWorker(
        args.host, args.port, rings, args.sync_url,
        tls_cert=args.tls_cert, tls_key=args.tls_key,
        hollow=args.hollow, hollow_kind=args.hollow_kind,
        max_pending_bytes=args.max_pending_bytes,
        bookmark_period_s=args.bookmark_period,
    )
    stats_port = _serve_stats(worker)
    print(f"READY relay-worker {worker.port} {stats_port} {os.getpid()}",
          flush=True)
    import signal

    signal.signal(signal.SIGTERM, lambda *_: worker.stop())
    worker.run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
