"""Seq-numbered shared-memory frame ring: the relay's fan-out bus.

The watch relay's economics depend on one fact: a watch frame is
immutable bytes encoded ONCE per event (apiserver/watchcodec.py
memoizes it on the Event object). This ring extends that sharing across
PROCESS boundaries: the frontend's publisher writes each kind's frames
exactly once into a `multiprocessing.shared_memory` segment, and every
relay worker process reads the same bytes with zero IPC round trips and
zero GIL sharing — fan-out cost scales with frames produced, not
clients connected.

Layout (all integers big-endian, one segment per kind):

    header (64 bytes, single writer):
        magic(4) version(4) capacity(8)
        head_seq(8) head_cum(8)            next record's seq / cum offset
        floor_seq(8) floor_cum(8)          oldest fully-retained record
        floor_rv(8)                        410 boundary (see below)
    record := seq+1(8) rv(8) type(1) length(4) payload(length)

Records are laid contiguously in a byte ring addressed by CUMULATIVE
offset (phys = cum % capacity, so positions are monotonic and a reader
can detect being lapped). A record never wraps: when the tail remaining
is too small the writer emits a PAD record ('P'), or — when even a
record header no longer fits — both sides skip to the boundary by the
same rule. `type` is the watch frame's own leading type byte ('A'/'M'/
'D'/'J' events, 'B' bookmarks), duplicated in the record header so
workers can branch without parsing payloads.

Concurrency model: ONE writer (the publisher), N reader processes, no
locks. Each record is a seqlock: the stored seq field is written as 0
(invalid) before the payload is touched and set to seq+1 only after the
payload is complete; a reader copies the payload and re-reads the seq —
any mismatch means the writer lapped it mid-copy and the reader resyncs
to the floor. Readers never block the writer and the writer NEVER
blocks on readers: a reader that stalls past the ring capacity simply
observes `lapped=True` and re-enters at the floor (its clients resume
through the cacher-window contract instead).

Floor / 410 contract (mirrors apiserver/cacher.py's window floor): the
ring retains a sliding window of recent frames; `floor_rv` is the
oldest resumable position. A client resuming at rv >= floor_rv replays
buffered frames with rv > its position; rv < floor_rv is Expired (410,
re-list). Evicting an EVENT record with resource version r advances
floor_rv to r+1 — exactly KindCache's `evicted.resource_version + 1`;
bookmark and pad evictions never advance it (a bookmark at rv r proves
nothing about events <= r still being needed: they were written, and
therefore evicted, before it).
"""

from __future__ import annotations

import struct
from collections import deque
from multiprocessing import shared_memory
from typing import List, Optional, Tuple

_MAGIC = 0x4B545259  # "KTRY"
_VERSION = 1

_HEADER = struct.Struct(">IIQQQQQQ")  # magic ver cap head_seq head_cum floor_seq floor_cum floor_rv
HEADER_SIZE = 64
_REC = struct.Struct(">QQcI")  # seq+1, rv, type, payload length
REC_HDR = _REC.size

PAD = b"P"
BOOKMARK_TYPE = b"B"
# control record: the publisher lost continuity (its own cache watcher
# overflowed) and re-subscribed — workers shed every client of the kind
# so they resume through the cacher-window contract instead of silently
# missing events. Never forwarded to clients.
RESYNC_TYPE = b"R"

_HEAD_SEQ_OFF = 16
_FLOOR_OFF = 32  # floor_seq floor_cum floor_rv
_Q = struct.Struct(">Q")
_QQ = struct.Struct(">QQ")
_QQQ = struct.Struct(">QQQ")


class RingLapped(RuntimeError):
    """A reader fell more than one ring capacity behind the writer."""


def _unregister_tracker(shm: shared_memory.SharedMemory) -> None:
    """Python 3.10's SharedMemory registers ATTACHES with the resource
    tracker, which then unlinks the segment when the attaching process
    exits — destroying the ring under the publisher. Readers must not
    own the segment's lifetime; only the creator unlinks."""
    try:  # pragma: no cover - interpreter-version dependent
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")  # type: ignore[attr-defined]
    except Exception:
        pass


class FrameRing:
    """Writer handle over one kind's shared-memory frame ring."""

    def __init__(self, shm: shared_memory.SharedMemory, capacity: int,
                 owner: bool):
        self._shm = shm
        self._buf = shm.buf
        self.capacity = capacity
        self._owner = owner
        # writer-only state (reconstructed on attach from the header)
        (_, _, _, self._head_seq, self._head_cum, self._floor_seq,
         self._floor_cum, self._floor_rv) = _HEADER.unpack(
            bytes(self._buf[: _HEADER.size])
        )
        # (seq, start_cum, end_cum, rv, type) of live records, oldest
        # first — the writer's own eviction bookkeeping (readers only
        # ever see the header floor fields)
        self._live: deque = deque()

    # -- construction --------------------------------------------------------

    @classmethod
    def create(cls, capacity: int = 1 << 22,
               name: Optional[str] = None) -> "FrameRing":
        shm = shared_memory.SharedMemory(
            create=True, size=HEADER_SIZE + capacity, name=name
        )
        shm.buf[: HEADER_SIZE] = b"\x00" * HEADER_SIZE
        shm.buf[: _HEADER.size] = _HEADER.pack(
            _MAGIC, _VERSION, capacity, 0, 0, 0, 0, 0
        )
        return cls(shm, capacity, owner=True)

    @classmethod
    def attach(cls, name: str) -> "FrameRing":
        shm = shared_memory.SharedMemory(name=name)
        _unregister_tracker(shm)
        magic, ver, cap = struct.unpack(">IIQ", bytes(shm.buf[:16]))
        if magic != _MAGIC or ver != _VERSION:
            shm.close()
            raise ValueError(f"not a frame ring: {name}")
        return cls(shm, cap, owner=False)

    @property
    def name(self) -> str:
        return self._shm.name

    def close(self) -> None:
        try:
            self._buf = None
            self._shm.close()
            if self._owner:
                self._shm.unlink()
        except (OSError, BufferError):
            pass

    # -- header access (torn-read safe: single u64 fields, re-validated
    #    through the per-record seqlock on the reader side) ------------------

    def _write_head(self) -> None:
        self._buf[_HEAD_SEQ_OFF:_HEAD_SEQ_OFF + 16] = _QQ.pack(
            self._head_seq, self._head_cum
        )

    def _write_floor(self) -> None:
        self._buf[_FLOOR_OFF:_FLOOR_OFF + 24] = _QQQ.pack(
            self._floor_seq, self._floor_cum, self._floor_rv
        )

    def head(self) -> Tuple[int, int]:
        """(head_seq, head_cum) — re-read until stable."""
        while True:
            a = bytes(self._buf[_HEAD_SEQ_OFF:_HEAD_SEQ_OFF + 16])
            b = bytes(self._buf[_HEAD_SEQ_OFF:_HEAD_SEQ_OFF + 16])
            if a == b:
                return _QQ.unpack(a)

    def floor(self) -> Tuple[int, int, int]:
        """(floor_seq, floor_cum, floor_rv) — re-read until stable."""
        while True:
            a = bytes(self._buf[_FLOOR_OFF:_FLOOR_OFF + 24])
            b = bytes(self._buf[_FLOOR_OFF:_FLOOR_OFF + 24])
            if a == b:
                return _QQQ.unpack(a)

    def floor_rv(self) -> int:
        return self.floor()[2]

    # -- writer --------------------------------------------------------------

    def set_initial_floor(self, rv: int) -> None:
        """Publisher start: nothing older than `rv` will ever be in the
        ring, so a resume below it must 410 (the cacher itself may still
        cover it — the worker's state-sync path handles rv=0)."""
        self._floor_rv = max(self._floor_rv, rv)
        self._write_floor()

    def _evict_one(self) -> None:
        seq, _start, end, rv, ftype = self._live.popleft()
        self._floor_seq = seq + 1
        self._floor_cum = end
        if ftype not in (PAD, BOOKMARK_TYPE, RESYNC_TYPE):
            # KindCache's exact floor rule: evicted event rv + 1
            self._floor_rv = max(self._floor_rv, rv + 1)
        # publish the new floor BEFORE the writer overwrites the bytes:
        # a lapped reader resyncing mid-publish must land on a floor
        # whose records are all still intact
        self._write_floor()

    def _make_room(self, need: int) -> None:
        while self._head_cum + need - self._floor_cum > self.capacity:
            if not self._live:
                raise ValueError(
                    f"frame of {need} bytes exceeds ring capacity "
                    f"{self.capacity}"
                )
            self._evict_one()

    def _write_record(self, rv: int, ftype: bytes, payload) -> None:
        start = self._head_cum
        phys = start % self.capacity
        n = len(payload)
        base = HEADER_SIZE + phys
        # seqlock: invalidate first, payload second, seq last
        self._buf[base:base + 8] = _Q.pack(0)
        self._buf[base + 8:base + REC_HDR] = _REC.pack(
            0, rv, ftype, n
        )[8:]
        if n:
            self._buf[base + REC_HDR:base + REC_HDR + n] = payload
        self._buf[base:base + 8] = _Q.pack(self._head_seq + 1)
        end = start + REC_HDR + n
        self._live.append((self._head_seq, start, end, rv, ftype))
        self._head_seq += 1
        self._head_cum = end
        self._write_head()

    def publish(self, rv: int, frame) -> int:
        """Append one watch frame (the full wire bytes from
        apiserver/watchcodec — type byte included). Never blocks: slow
        readers are lapped, never waited for. Returns the record seq."""
        n = len(frame)
        if REC_HDR + n > self.capacity // 2:
            raise ValueError(
                f"frame of {n} bytes too large for ring capacity "
                f"{self.capacity}"
            )
        ftype = bytes(frame[:1]) or PAD
        phys = self._head_cum % self.capacity
        rem = self.capacity - phys
        if rem < REC_HDR:
            # tail too small for even a header: both sides skip by rule
            self._make_room(rem)
            self._head_cum += rem
            self._write_head()
        elif rem < REC_HDR + n:
            # pad record so the real record starts at offset 0
            self._make_room(rem)
            self._write_record(0, PAD, b"\x00" * (rem - REC_HDR))
        seq = self._head_seq
        self._make_room(REC_HDR + n)
        self._write_record(rv, ftype, frame)
        return seq


class RingReader:
    """One reader cursor over a FrameRing (per worker, per kind).

    `read_new()` returns frames published since the cursor, detecting
    laps via the per-record seqlock. A fresh reader starts at the FLOOR
    (not the head): a relay worker replacing a SIGKILLed sibling must
    rebuild the full retained window so reconnecting clients can resume
    at rvs from before the worker existed."""

    _RESYNC_BOUND = 8

    def __init__(self, ring: FrameRing, from_floor: bool = True):
        self.ring = ring
        if from_floor:
            self.seq, self.cum = ring.floor()[:2]
        else:
            self.seq, self.cum = ring.head()
        self.lapped_total = 0

    def _resync(self) -> None:
        self.seq, self.cum, _rv = self.ring.floor()
        self.lapped_total += 1

    def read_new(
        self, max_frames: int = 4096
    ) -> Tuple[List[Tuple[int, int, bytes, bytes]], bool]:
        """([(seq, rv, type, frame)], lapped). `lapped=True` means the
        cursor fell out of the ring and was reset to the floor — frames
        were MISSED and the caller must treat every downstream consumer
        as gapped (close clients; they resume via the cacher window)."""
        ring = self.ring
        buf = ring._buf
        cap = ring.capacity
        out: List[Tuple[int, int, bytes, bytes]] = []
        lapped = False
        resyncs = 0
        while len(out) < max_frames:
            head_seq, head_cum = ring.head()
            if self.cum >= head_cum:
                break
            phys = self.cum % cap
            rem = cap - phys
            if rem < REC_HDR:
                self.cum += rem  # writer's implicit boundary skip
                continue
            base = HEADER_SIZE + phys
            stored, rv, ftype, n = _REC.unpack(
                bytes(buf[base:base + REC_HDR])
            )
            if stored != self.seq + 1 or REC_HDR + n > cap:
                # overwritten under us (or torn): fall back to the floor
                resyncs += 1
                lapped = True
                if resyncs > self._RESYNC_BOUND:
                    break
                self._resync()
                continue
            payload = bytes(buf[base + REC_HDR:base + REC_HDR + n])
            # seqlock validate: unchanged seq proves the copy is whole
            if bytes(buf[base:base + 8]) != _Q.pack(self.seq + 1):
                resyncs += 1
                lapped = True
                if resyncs > self._RESYNC_BOUND:
                    break
                self._resync()
                continue
            if ftype != PAD:
                out.append((self.seq, rv, ftype, payload))
            self.seq += 1
            self.cum += REC_HDR + n
        return out, lapped
