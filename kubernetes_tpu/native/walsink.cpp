// Group-commit WAL sink: the native IO runtime under runtime/wal.py.
//
// The reference's durability layer is etcd, whose raft log batches many
// proposals into one fsync (wal.Save group commit). The Python WAL fsyncs
// per record; this sink restores the etcd behavior: appenders ENQUEUE
// records (cheap, in rv order under the store lock) and WAIT for a
// durability ticket; a dedicated committer thread drains the queue, writes
// everything pending, fsyncs ONCE, and advances the durable generation.
// A 512-record bulk bind costs one fsync instead of 512.
//
// C ABI (ctypes-loaded from kubernetes_tpu/native/__init__.py):
//   wal_open(path, do_fsync) -> handle
//   wal_enqueue(h, data, len) -> ticket (uint64)
//   wal_wait(h, ticket) -> 0|-1    blocks until ticket durable (-1: IO err)
//   wal_flush(h) -> 0|-1           blocks until everything durable
//   wal_fsync_count(h) -> uint64   committer fsyncs so far (stats/tests)
//   wal_close(h)

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <unistd.h>

namespace {

struct WalSink {
  int fd = -1;
  bool do_fsync = true;
  std::mutex mu;
  std::condition_variable cv_work;   // committer wakes on new records
  std::condition_variable cv_done;   // waiters wake on durability advance
  std::vector<std::string> pending;  // records not yet written
  uint64_t enqueued = 0;             // tickets handed out
  uint64_t durable = 0;              // highest durable ticket
  uint64_t fsyncs = 0;
  bool failed = false;  // unrecoverable IO error; waiters unblock with -1
  bool closing = false;
  std::thread committer;

  void run() {
    std::unique_lock<std::mutex> lk(mu);
    for (;;) {
      cv_work.wait(lk, [&] { return !pending.empty() || closing; });
      if (pending.empty() && closing) return;
      std::vector<std::string> batch;
      batch.swap(pending);
      uint64_t batch_hi = enqueued;
      lk.unlock();
      // one writev-style pass + one fsync for the whole batch
      std::string buf;
      size_t total = 0;
      for (const auto& r : batch) total += r.size();
      buf.reserve(total);
      for (const auto& r : batch) buf.append(r);
      const char* p = buf.data();
      size_t left = buf.size();
      while (left > 0) {
        ssize_t n = ::write(fd, p, left);
        if (n < 0) {
          if (errno == EINTR) continue;
          break;  // disk error: mark failed below; waiters get -1
        }
        p += n;
        left -= static_cast<size_t>(n);
      }
      bool ok = (left == 0);
      if (ok && do_fsync) ok = (::fsync(fd) == 0);
      lk.lock();
      if (do_fsync) fsyncs++;
      if (ok) {
        durable = batch_hi;
      } else {
        failed = true;  // fail-stop: the Python layer raises OSError
      }
      cv_done.notify_all();
    }
  }
};

}  // namespace

extern "C" {

void* wal_open(const char* path, int do_fsync) {
  int fd = ::open(path, O_WRONLY | O_CREAT | O_APPEND, 0644);
  if (fd < 0) return nullptr;
  auto* s = new WalSink();
  s->fd = fd;
  s->do_fsync = do_fsync != 0;
  s->committer = std::thread([s] { s->run(); });
  return s;
}

uint64_t wal_enqueue(void* h, const char* data, uint64_t len) {
  auto* s = static_cast<WalSink*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  s->pending.emplace_back(data, static_cast<size_t>(len));
  uint64_t ticket = ++s->enqueued;
  s->cv_work.notify_one();
  return ticket;
}

int wal_wait(void* h, uint64_t ticket) {
  auto* s = static_cast<WalSink*>(h);
  std::unique_lock<std::mutex> lk(s->mu);
  s->cv_done.wait(lk, [&] {
    return s->durable >= ticket || s->failed || s->closing;
  });
  return (s->durable >= ticket) ? 0 : -1;
}

int wal_flush(void* h) {
  auto* s = static_cast<WalSink*>(h);
  std::unique_lock<std::mutex> lk(s->mu);
  uint64_t target = s->enqueued;
  s->cv_done.wait(lk, [&] {
    return s->durable >= target || s->failed || s->closing;
  });
  return (s->durable >= target) ? 0 : -1;
}

uint64_t wal_fsync_count(void* h) {
  auto* s = static_cast<WalSink*>(h);
  std::lock_guard<std::mutex> lk(s->mu);
  return s->fsyncs;
}

void wal_close(void* h) {
  auto* s = static_cast<WalSink*>(h);
  {
    std::lock_guard<std::mutex> lk(s->mu);
    s->closing = true;
    s->cv_work.notify_all();
  }
  s->committer.join();
  {
    std::lock_guard<std::mutex> lk(s->mu);
    s->cv_done.notify_all();  // release any stragglers
  }
  ::close(s->fd);
  delete s;
}

}  // extern "C"
