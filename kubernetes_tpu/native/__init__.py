"""Native runtime components (C++), loaded via ctypes.

The compute path is JAX/XLA; the IO runtime around it is native where the
reference's is process-native: the group-commit WAL sink replaces
per-record fsyncs with etcd-style batched commits (walsink.cpp). Builds
lazily with g++ into a content-hash-keyed cache; every consumer has a pure
Python fallback, so environments without a toolchain lose performance,
never correctness.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
import threading
from typing import Optional

logger = logging.getLogger("kubernetes_tpu.native")

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_LOCK = threading.Lock()
_CACHE: dict = {}


def _build(src_name: str) -> Optional[str]:
    """Compile one .cpp into a cached .so; returns the path or None."""
    src = os.path.join(_SRC_DIR, src_name)
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    out_dir = os.path.join(
        tempfile.gettempdir(), f"kubernetes_tpu_native_{os.getuid()}"
    )
    os.makedirs(out_dir, exist_ok=True)
    out = os.path.join(out_dir, f"{src_name.rsplit('.', 1)[0]}-{digest}.so")
    if os.path.exists(out):
        return out
    tmp = out + f".tmp{os.getpid()}"
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread", src, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)  # atomic: racing builders both succeed
        return out
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired, OSError) as e:
        detail = getattr(e, "stderr", b"") or b""
        logger.warning(
            "native build of %s failed (%s); using Python fallback",
            src_name,
            detail.decode(errors="replace")[-500:] or e,
        )
        return None


def load_walsink() -> Optional[ctypes.CDLL]:
    """The group-commit WAL sink library, or None (Python fallback)."""
    with _BUILD_LOCK:
        if "walsink" in _CACHE:
            return _CACHE["walsink"]
        lib = None
        so = _build("walsink.cpp")
        if so is not None:
            try:
                lib = ctypes.CDLL(so)
                lib.wal_open.restype = ctypes.c_void_p
                lib.wal_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
                lib.wal_enqueue.restype = ctypes.c_uint64
                lib.wal_enqueue.argtypes = [
                    ctypes.c_void_p,
                    ctypes.c_char_p,
                    ctypes.c_uint64,
                ]
                lib.wal_wait.restype = ctypes.c_int
                lib.wal_wait.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
                lib.wal_flush.restype = ctypes.c_int
                lib.wal_flush.argtypes = [ctypes.c_void_p]
                lib.wal_fsync_count.restype = ctypes.c_uint64
                lib.wal_fsync_count.argtypes = [ctypes.c_void_p]
                lib.wal_close.argtypes = [ctypes.c_void_p]
            except OSError as e:
                logger.warning("walsink load failed: %s", e)
                lib = None
        _CACHE["walsink"] = lib
        return lib
