"""API-boundary object validation (the high-value subset of
pkg/apis/core/validation/validation.go, ~16k LoC in the reference):
malformed objects are rejected AT WRITE TIME with a 400, never discovered
later as a scheduler-side encode exception (r4 verdict #6).

Covered: DNS-1123 name/namespace formats, label key/value syntax,
resource-quantity syntax (requests/limits/overhead/capacity/allocatable),
label-selector operator syntax, and spec immutability on update
(pod.spec.nodeName may be set once, never moved; container resources are
immutable). Everything else (the reference's long tail of per-field
rules) is intentionally out of scope at this stage.

Always-on: wired directly into APIServer.create/update after admission
mutators (the reference's strategy.Validate runs after admission too, so
defaulted fields are validated, not raw input).
"""

from __future__ import annotations

import re
from typing import Any, Optional

from .resources import parse_quantity

# DNS-1123 subdomain (RFC 1123): what object names must look like
_NAME_RE = re.compile(r"^[a-z0-9]([-a-z0-9.]{0,251}[a-z0-9])?$")
# label VALUE: empty, or 63 chars of alnum/-_. starting+ending alnum
_LABEL_VALUE_RE = re.compile(r"^([A-Za-z0-9]([-A-Za-z0-9_.]{0,61}[A-Za-z0-9])?)?$")
# label key NAME part (the bit after an optional dns-prefix/)
_LABEL_NAME_RE = re.compile(r"^[A-Za-z0-9]([-A-Za-z0-9_.]{0,61}[A-Za-z0-9])?$")
_SELECTOR_OPS = frozenset({"In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt"})


class ValidationError(ValueError):
    """Rejected at the API boundary; REST maps it to 400 BadRequest."""


def _bad(msg: str) -> None:
    raise ValidationError(msg)


def validate_name(name: str, what: str) -> None:
    if not name:
        _bad(f"{what}: name is required")
    if len(name) > 253 or not _NAME_RE.match(name):
        _bad(
            f"{what}: invalid name {name!r} (must be a DNS-1123 subdomain: "
            "lowercase alphanumerics, '-' and '.')"
        )


def validate_label_key(key: str, what: str) -> None:
    if not key:
        _bad(f"{what}: empty label key")
    prefix, slash, name = key.rpartition("/")
    if slash and (
        not prefix or len(prefix) > 253 or not _NAME_RE.match(prefix)
    ):
        _bad(f"{what}: invalid label key prefix {prefix!r}")
    if len(name) > 63 or not _LABEL_NAME_RE.match(name):
        _bad(f"{what}: invalid label key {key!r}")


def validate_labels(labels, what: str) -> None:
    for k, v in labels.items():
        validate_label_key(k, what)
        if len(str(v)) > 63 or not _LABEL_VALUE_RE.match(str(v)):
            _bad(f"{what}: invalid label value {v!r} for key {k!r}")


def validate_quantities(d, what: str) -> None:
    for name, q in d.items():
        try:
            v = parse_quantity(q)
        except Exception:
            _bad(f"{what}: invalid quantity {q!r} for {name!r}")
        else:
            if v < 0:
                _bad(f"{what}: negative quantity {q!r} for {name!r}")


def validate_selector(sel: Optional[Any], what: str) -> None:
    """LabelSelector: match_labels values + match_expressions operators
    (apimachinery LabelSelectorAsSelector rules)."""
    if sel is None:
        return
    ml = getattr(sel, "match_labels", None)
    if ml:
        # selectors store match_labels as a (key, value) tuple sequence
        # (api/selectors.py LabelSelector); plain dicts also accepted
        pairs = ml.items() if hasattr(ml, "items") else ml
        validate_labels(dict(pairs), f"{what}.matchLabels")
    for expr in getattr(sel, "match_expressions", ()) or ():
        op = getattr(expr, "operator", "")
        if op not in _SELECTOR_OPS:
            _bad(f"{what}: invalid selector operator {op!r}")
        values = getattr(expr, "values", ()) or ()
        if op in ("In", "NotIn") and not values:
            _bad(f"{what}: operator {op} requires values")
        if op in ("Exists", "DoesNotExist") and values:
            _bad(f"{what}: operator {op} must not carry values")
        validate_label_key(getattr(expr, "key", ""), what)


def _validate_pod(pod, what: str) -> None:
    for c in list(pod.spec.containers) + list(pod.spec.init_containers):
        validate_quantities(c.requests, f"{what}.resources.requests")
        validate_quantities(c.limits, f"{what}.resources.limits")
    if pod.spec.overhead:
        validate_quantities(pod.spec.overhead, f"{what}.overhead")
    validate_labels(pod.spec.node_selector, f"{what}.nodeSelector")
    aff = pod.spec.affinity
    if aff is not None:
        pa = getattr(aff, "pod_affinity", None)
        paa = getattr(aff, "pod_anti_affinity", None)
        for grp, gname in ((pa, "podAffinity"), (paa, "podAntiAffinity")):
            if grp is None:
                continue
            for term in getattr(grp, "required", ()) or ():
                validate_selector(term.label_selector, f"{what}.{gname}")
                if not term.topology_key:
                    _bad(f"{what}.{gname}: topologyKey is required")
            for w in getattr(grp, "preferred", ()) or ():
                term = getattr(w, "pod_affinity_term", None) or getattr(
                    w, "term", None
                )
                if term is not None:
                    validate_selector(term.label_selector, f"{what}.{gname}")
    for tsc in pod.spec.topology_spread_constraints:
        validate_selector(tsc.label_selector, f"{what}.topologySpread")
        if not tsc.topology_key:
            _bad(f"{what}.topologySpread: topologyKey is required")


def _validate_pod_update(new, old, what: str) -> None:
    # spec.nodeName is write-once (the bind); moving a running pod is not
    # a thing (validation.go ValidatePodUpdate: spec is immutable except
    # image/activeDeadlineSeconds/tolerations additions)
    if (
        old.spec.node_name
        and new.spec.node_name
        and new.spec.node_name != old.spec.node_name
    ):
        _bad(
            f"{what}: spec.nodeName is immutable "
            f"({old.spec.node_name!r} -> {new.spec.node_name!r})"
        )
    old_req = [c.requests for c in old.spec.containers]
    new_req = [c.requests for c in new.spec.containers]
    if len(old_req) == len(new_req) and old_req != new_req:
        _bad(f"{what}: container resource requests are immutable")


def _validate_node(node, what: str) -> None:
    validate_quantities(node.status.capacity, f"{what}.status.capacity")
    validate_quantities(node.status.allocatable, f"{what}.status.allocatable")


def _validate_workload(obj, what: str) -> None:
    sel = getattr(obj.spec, "selector", None)
    # workload selectors may be a plain dict (service-style) or a
    # LabelSelector object
    if isinstance(sel, dict):
        validate_labels(sel, f"{what}.selector")
    else:
        validate_selector(sel, f"{what}.selector")


def validate_object(
    verb: str, resource: str, obj: Any, old: Any = None
) -> None:
    """Entry point, called by APIServer.create/update after admission."""
    meta = getattr(obj, "metadata", None)
    if meta is None:
        return
    what = f"{resource}/{meta.name}"
    # events are machine-generated at high rate with dotted composite
    # names; skip the name gate there (the reference's event names are
    # similarly synthetic)
    if resource != "events":
        validate_name(meta.name, what)
        if meta.namespace:
            validate_name(meta.namespace, what + ".namespace")
        if meta.labels:
            validate_labels(meta.labels, what + ".labels")
    if resource == "pods":
        _validate_pod(obj, what)
        if verb == "update" and old is not None:
            _validate_pod_update(obj, old, what)
    elif resource == "nodes":
        _validate_node(obj, what)
    elif resource in (
        "services",
        "replicasets",
        "deployments",
        "daemonsets",
        "statefulsets",
        "jobs",
        "poddisruptionbudgets",
    ):
        _validate_workload(obj, what)
    elif resource in ("persistentvolumeclaims",):
        validate_quantities(
            getattr(obj.spec, "resources", {}) or {}, what + ".resources"
        )
    elif resource == "resourcequotas":
        validate_quantities(obj.spec.hard, what + ".hard")
