"""API-boundary object validation (the high-value subset of
pkg/apis/core/validation/validation.go, ~16k LoC in the reference):
malformed objects are rejected AT WRITE TIME with a 400, never discovered
later as a scheduler-side encode exception (r4 verdict #6).

Covered: DNS-1123 name/namespace formats, label key/value syntax,
resource-quantity syntax (requests/limits/overhead/capacity/allocatable),
label-selector operator syntax, and spec immutability on update
(pod.spec.nodeName may be set once, never moved; container resources are
immutable). Everything else (the reference's long tail of per-field
rules) is intentionally out of scope at this stage.

Always-on: wired directly into APIServer.create/update after admission
mutators (the reference's strategy.Validate runs after admission too, so
defaulted fields are validated, not raw input).
"""

from __future__ import annotations

import re
from typing import Any, Optional

from .resources import parse_quantity

# DNS-1123 subdomain (RFC 1123): what object names must look like
_NAME_RE = re.compile(r"^[a-z0-9]([-a-z0-9.]{0,251}[a-z0-9])?$")
# label VALUE: empty, or 63 chars of alnum/-_. starting+ending alnum
_LABEL_VALUE_RE = re.compile(r"^([A-Za-z0-9]([-A-Za-z0-9_.]{0,61}[A-Za-z0-9])?)?$")
# label key NAME part (the bit after an optional dns-prefix/)
_LABEL_NAME_RE = re.compile(r"^[A-Za-z0-9]([-A-Za-z0-9_.]{0,61}[A-Za-z0-9])?$")
_SELECTOR_OPS = frozenset({"In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt"})


class ValidationError(ValueError):
    """Rejected at the API boundary; REST maps it to 400 BadRequest."""


def _bad(msg: str) -> None:
    raise ValidationError(msg)


def _as_int(v, what: str) -> int:
    """Boundary-safe int coercion: wire decodes can leave numeric fields
    as strings, and a TypeError/ValueError here must surface as a 400,
    not a 500 (the module's contract)."""
    try:
        return int(v)
    except (TypeError, ValueError):
        _bad(f"{what}: invalid integer {v!r}")


def validate_name(name: str, what: str) -> None:
    if not name:
        _bad(f"{what}: name is required")
    if len(name) > 253 or not _NAME_RE.match(name):
        _bad(
            f"{what}: invalid name {name!r} (must be a DNS-1123 subdomain: "
            "lowercase alphanumerics, '-' and '.')"
        )


def validate_label_key(key: str, what: str) -> None:
    if not key:
        _bad(f"{what}: empty label key")
    prefix, slash, name = key.rpartition("/")
    if slash and (
        not prefix or len(prefix) > 253 or not _NAME_RE.match(prefix)
    ):
        _bad(f"{what}: invalid label key prefix {prefix!r}")
    if len(name) > 63 or not _LABEL_NAME_RE.match(name):
        _bad(f"{what}: invalid label key {key!r}")


def validate_labels(labels, what: str) -> None:
    for k, v in labels.items():
        validate_label_key(k, what)
        if len(str(v)) > 63 or not _LABEL_VALUE_RE.match(str(v)):
            _bad(f"{what}: invalid label value {v!r} for key {k!r}")


def validate_quantities(d, what: str) -> None:
    for name, q in d.items():
        try:
            v = parse_quantity(q)
        except Exception:
            _bad(f"{what}: invalid quantity {q!r} for {name!r}")
        else:
            if v < 0:
                _bad(f"{what}: negative quantity {q!r} for {name!r}")


def validate_selector(sel: Optional[Any], what: str) -> None:
    """LabelSelector: match_labels values + match_expressions operators
    (apimachinery LabelSelectorAsSelector rules)."""
    if sel is None:
        return
    ml = getattr(sel, "match_labels", None)
    if ml:
        # selectors store match_labels as a (key, value) tuple sequence
        # (api/selectors.py LabelSelector); plain dicts also accepted
        pairs = ml.items() if hasattr(ml, "items") else ml
        validate_labels(dict(pairs), f"{what}.matchLabels")
    for expr in getattr(sel, "match_expressions", ()) or ():
        op = getattr(expr, "operator", "")
        if op not in _SELECTOR_OPS:
            _bad(f"{what}: invalid selector operator {op!r}")
        values = getattr(expr, "values", ()) or ()
        if op in ("In", "NotIn") and not values:
            _bad(f"{what}: operator {op} requires values")
        if op in ("Exists", "DoesNotExist") and values:
            _bad(f"{what}: operator {op} must not carry values")
        validate_label_key(getattr(expr, "key", ""), what)


def _validate_pod(pod, what: str) -> None:
    _validate_pod_spec(pod.spec, what)


def _validate_pod_spec(spec, what: str) -> None:
    if not spec.containers:
        _bad(f"{what}: spec.containers must not be empty")
    seen = set()
    for c in list(spec.containers) + list(spec.init_containers):
        if c.name:
            if c.name in seen:
                _bad(f"{what}: duplicate container name {c.name!r}")
            seen.add(c.name)
        validate_quantities(c.requests, f"{what}.resources.requests")
        validate_quantities(c.limits, f"{what}.resources.limits")
    if spec.overhead:
        validate_quantities(spec.overhead, f"{what}.overhead")
    validate_labels(spec.node_selector, f"{what}.nodeSelector")
    aff = spec.affinity
    if aff is not None:
        pa = getattr(aff, "pod_affinity", None)
        paa = getattr(aff, "pod_anti_affinity", None)
        for grp, gname in ((pa, "podAffinity"), (paa, "podAntiAffinity")):
            if grp is None:
                continue
            for term in getattr(grp, "required", ()) or ():
                validate_selector(term.label_selector, f"{what}.{gname}")
                if not term.topology_key:
                    _bad(f"{what}.{gname}: topologyKey is required")
            for w in getattr(grp, "preferred", ()) or ():
                term = getattr(w, "pod_affinity_term", None) or getattr(
                    w, "term", None
                )
                if term is not None:
                    validate_selector(term.label_selector, f"{what}.{gname}")
    for tsc in spec.topology_spread_constraints:
        validate_selector(tsc.label_selector, f"{what}.topologySpread")
        if not tsc.topology_key:
            _bad(f"{what}.topologySpread: topologyKey is required")
        if _as_int(tsc.max_skew, f"{what}.topologySpread.maxSkew") < 1:
            _bad(f"{what}.topologySpread: maxSkew must be >= 1")


def _validate_pod_update(new, old, what: str) -> None:
    # spec.nodeName is write-once (the bind); moving a running pod is not
    # a thing (validation.go ValidatePodUpdate: spec is immutable except
    # image/activeDeadlineSeconds/tolerations additions)
    if (
        old.spec.node_name
        and new.spec.node_name
        and new.spec.node_name != old.spec.node_name
    ):
        _bad(
            f"{what}: spec.nodeName is immutable "
            f"({old.spec.node_name!r} -> {new.spec.node_name!r})"
        )
    old_req = [c.requests for c in old.spec.containers]
    new_req = [c.requests for c in new.spec.containers]
    if len(old_req) == len(new_req) and old_req != new_req:
        _bad(f"{what}: container resource requests are immutable")


def _validate_node(node, what: str) -> None:
    validate_quantities(node.status.capacity, f"{what}.status.capacity")
    validate_quantities(node.status.allocatable, f"{what}.status.allocatable")


def _validate_workload(obj, what: str) -> None:
    sel = getattr(obj.spec, "selector", None)
    # workload selectors may be a plain dict (service-style) or a
    # LabelSelector object
    if isinstance(sel, dict):
        validate_labels(sel, f"{what}.selector")
    else:
        validate_selector(sel, f"{what}.selector")
    # validate the pod TEMPLATE at workload write time (the reference's
    # ValidatePodTemplateSpec): an empty-containers template would pass
    # here only for its controller to fail EVERY pod create forever
    tmpl = getattr(obj.spec, "template", None)
    tmpl_spec = getattr(tmpl, "spec", None) if tmpl is not None else None
    if tmpl_spec is not None and hasattr(tmpl_spec, "containers"):
        _validate_pod_spec(tmpl_spec, f"{what}.template")


def _validate_workload_update(new, old, what: str) -> None:
    """spec.selector is immutable on workload updates (validation.go
    ValidateDeploymentUpdate / ValidateReplicaSetUpdate / apps
    ValidateStatefulSetUpdate): retargeting a live controller's selector
    silently orphans/adopts pods."""
    old_sel = getattr(old.spec, "selector", None)
    new_sel = getattr(new.spec, "selector", None)

    def norm(s):
        """Representation-independent canonical form: the same selector
        may arrive as a LabelSelector object (in-process), a plain
        matchLabels dict, or a wire-decoded {"matchLabels": ...} dict —
        an unchanged selector in a different shape must NOT read as a
        mutation. Order-insensitive throughout."""
        if s is None:
            return None
        if isinstance(s, dict):
            if "matchLabels" in s or "matchExpressions" in s or (
                "match_labels" in s or "match_expressions" in s
            ):
                ml = s.get("matchLabels", s.get("match_labels")) or {}
                me = s.get("matchExpressions", s.get("match_expressions")) or ()
                pairs = ml.items() if hasattr(ml, "items") else ml
                return (
                    tuple(sorted((str(k), str(v)) for k, v in pairs)),
                    tuple(
                        sorted(
                            (
                                str(e.get("key", "")),
                                str(e.get("operator", "")),
                                tuple(sorted(map(str, e.get("values") or ()))),
                            )
                            for e in me
                        )
                    ),
                )
            return (
                tuple(sorted((str(k), str(v)) for k, v in s.items())), ()
            )
        ml = getattr(s, "match_labels", None)
        pairs = ml.items() if hasattr(ml, "items") else (ml or ())
        me = getattr(s, "match_expressions", ()) or ()
        return (
            tuple(sorted((str(k), str(v)) for k, v in pairs)),
            tuple(
                sorted(
                    (
                        str(e.key),
                        str(e.operator),
                        tuple(sorted(map(str, e.values or ()))),
                    )
                    for e in me
                )
            ),
        )

    if old_sel is not None and norm(new_sel) != norm(old_sel):
        _bad(f"{what}: spec.selector is immutable")


def _validate_service(svc, what: str, old=None) -> None:
    for p in getattr(svc.spec, "ports", ()) or ():
        # ports are (protocol, port) tuples in this build's ServiceSpec
        port = p[1] if isinstance(p, (tuple, list)) and len(p) > 1 else getattr(p, "port", None)
        if port is not None and not (
            0 < _as_int(port, f"{what}.port") <= 65535
        ):
            _bad(f"{what}: port {port} out of range 1-65535")
    # clusterIP is allocate-once and may not be changed OR CLEARED
    # (validation.go ValidateServiceUpdate: a manifest re-apply without
    # the allocated IP must not wipe the VIP existing clients resolve)
    if old is not None:
        old_ip = getattr(old.spec, "cluster_ip", "")
        new_ip = getattr(svc.spec, "cluster_ip", "")
        if old_ip and new_ip != old_ip:
            _bad(
                f"{what}: spec.clusterIP is immutable "
                f"({old_ip!r} -> {new_ip!r})"
            )


def validate_object(
    verb: str, resource: str, obj: Any, old: Any = None
) -> None:
    """Entry point, called by APIServer.create/update after admission."""
    meta = getattr(obj, "metadata", None)
    if meta is None:
        return
    what = f"{resource}/{meta.name}"
    # events are machine-generated at high rate with dotted composite
    # names; skip the name gate there (the reference's event names are
    # similarly synthetic)
    if resource != "events":
        validate_name(meta.name, what)
        if meta.namespace:
            validate_name(meta.namespace, what + ".namespace")
        if meta.labels:
            validate_labels(meta.labels, what + ".labels")
    if resource == "pods":
        _validate_pod(obj, what)
        if verb == "update" and old is not None:
            _validate_pod_update(obj, old, what)
    elif resource == "nodes":
        _validate_node(obj, what)
    elif resource == "services":
        _validate_workload(obj, what)
        _validate_service(obj, what, old=old if verb == "update" else None)
    elif resource in (
        "replicasets",
        "deployments",
        "daemonsets",
        "statefulsets",
        "jobs",
        "poddisruptionbudgets",
    ):
        _validate_workload(obj, what)
        if verb == "update" and old is not None and resource != "poddisruptionbudgets":
            _validate_workload_update(obj, old, what)
    elif resource in ("persistentvolumeclaims",):
        validate_quantities(
            getattr(obj.spec, "resources", {}) or {}, what + ".resources"
        )
    elif resource == "cronjobs":
        # the jobTemplate's pod template must be valid at write time, or
        # the cronjob controller's per-tick job create fails forever
        jt = getattr(obj.spec, "job_template", None)
        jspec = getattr(jt, "spec", None) if jt is not None else None
        tmpl = getattr(jspec, "template", None) if jspec is not None else None
        tspec = getattr(tmpl, "spec", None) if tmpl is not None else None
        if tspec is not None and hasattr(tspec, "containers"):
            _validate_pod_spec(tspec, what + ".jobTemplate.template")
    elif resource == "resourcequotas":
        validate_quantities(obj.spec.hard, what + ".hard")
    elif resource == "priorityclasses":
        _validate_priority_class(obj, what)


# the reference's HighestUserDefinablePriority (scheduling/types.go): user
# classes stay below it; values above are reserved for the system-* tier
# (system-cluster-critical / system-node-critical). The system tier has
# its own ceiling (SystemCriticalPriority = 2e9): anything approaching
# int32 range would overflow the encoder's priority-band columns, and
# exactly 2^31-1 collides with the preempt kernel's empty-band sentinel.
HIGHEST_USER_DEFINABLE_PRIORITY = 1_000_000_000
HIGHEST_SYSTEM_PRIORITY = 2_000_000_000
_PREEMPTION_POLICIES = frozenset({"Never", "PreemptLowerPriority"})


def _validate_priority_class(pc: Any, what: str) -> None:
    """PriorityClass field validation (apis/scheduling/validation): the
    user-value cap and the preemptionPolicy enum are hard 400s — an
    unknown policy must never silently default to PreemptLowerPriority
    (admission copies the class's policy onto pods; a typo'd "never"
    would quietly make a tier preempting)."""
    value = _as_int(pc.value, what + ".value")
    if (
        value > HIGHEST_USER_DEFINABLE_PRIORITY
        and not pc.metadata.name.startswith("system-")
    ):
        _bad(
            f"{what}: value {value} exceeds the user-definable maximum "
            f"{HIGHEST_USER_DEFINABLE_PRIORITY} (reserved for system-* "
            "classes)"
        )
    if value > HIGHEST_SYSTEM_PRIORITY:
        _bad(
            f"{what}: value {value} exceeds the system maximum "
            f"{HIGHEST_SYSTEM_PRIORITY}"
        )
    policy = pc.preemption_policy
    if policy is not None and policy not in _PREEMPTION_POLICIES:
        _bad(
            f"{what}: unknown preemptionPolicy {policy!r} "
            f"(must be one of {sorted(_PREEMPTION_POLICIES)})"
        )


def validate_single_global_default(pc: Any, existing) -> None:
    """At most ONE PriorityClass may carry globalDefault: true — called
    by the store's create/update under its lock with every OTHER stored
    class, so two racing creates cannot both land a default. (The
    admission resolver picks `next(global_default)`; with two defaults
    the winner would be storage-order luck.)"""
    if not pc.global_default:
        return
    for cur in existing:
        if getattr(cur, "global_default", False):
            _bad(
                f"priorityclasses/{pc.metadata.name}: globalDefault is "
                f"already held by {cur.metadata.name!r}; only one "
                "PriorityClass may be the global default"
            )
