"""Binary (protobuf-wire-shaped) codec for the API object model.

The apimachinery protobuf serializer role (reference
staging/src/k8s.io/apimachinery/pkg/runtime/serializer/protobuf/protobuf.go):
a length-prefixed binary wire format negotiated via
``application/vnd.kubernetes.protobuf``, ~2-4x denser than JSON and
cheaper to scan. The envelope mirrors the reference's: the 4-byte magic
``k8s\\x00`` followed by an ``Unknown`` message carrying the TypeMeta and
the raw object bytes (protobuf.go's Unknown{TypeMeta, Raw}).

The body encoding is protobuf wire format (varint field headers, LEB128
varints, length-delimited submessages) over a schema derived
REFLECTIVELY from the dataclass model: field numbers are 1-based
dataclass field order. That makes this a self-consistent wire format —
both ends must share the object model, which holds everywhere in this
tree (the reference ships generated.pb.go for the same reason). Schema
evolution rule: append new dataclass fields, never reorder (the same
rule proto field numbers enforce).

Scalar mapping:
  bool/int     -> varint (zigzag, so negatives stay small)
  float        -> fixed64 little-endian double
  str          -> len-delimited UTF-8
  bytes        -> len-delimited
  dataclass    -> len-delimited submessage
  list/tuple   -> repeated field (one header per element)
  dict         -> repeated map-entry submessage {1: key, 2: value}
  Quantity/Any -> tagged scalar-union submessage {1: str, 2: varint,
                  3: double, 4: json-bytes} (JSON bytes carry anything
                  non-scalar, e.g. Unstructured content — the reference
                  likewise cannot protobuf-encode custom resources)

Like to_dict, encoding omits fields equal to their default (omitempty),
so wire size tracks the populated surface, not the schema width.
"""

from __future__ import annotations

import dataclasses
import json
import struct
import typing
from typing import Any, Dict, List, Optional, Tuple, Type, get_args, get_origin, get_type_hints

from . import objects as v1
from .serialization import KIND_TO_RESOURCE, RESOURCE_KINDS

MAGIC = b"k8s\x00"
CONTENT_TYPE = "application/vnd.kubernetes.protobuf"

_WIRE_VARINT = 0
_WIRE_FIXED64 = 1
_WIRE_LEN = 2


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63) if n < 0 else n << 1


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _put_varint(buf: bytearray, n: int) -> None:
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            buf.append(b | 0x80)
        else:
            buf.append(b)
            return


def _get_varint(data: bytes, i: int) -> Tuple[int, int]:
    shift = 0
    out = 0
    while True:
        b = data[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7
        if shift > 70:
            raise ValueError("varint overflow")


def _put_header(buf: bytearray, field: int, wire: int) -> None:
    _put_varint(buf, (field << 3) | wire)


# -- schema cache ------------------------------------------------------------

# class -> [(field_num, name, resolved_type)]; field numbers are 1-based
# dataclass declaration order (append-only evolution contract, see module
# docstring)
_SCHEMA: Dict[type, List[Tuple[int, str, Any]]] = {}
_DEFAULTS: Dict[type, Dict[str, Any]] = {}


def _resolve_optional(tp):
    if get_origin(tp) is typing.Union:
        args = [a for a in get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


# bare container hints (list, not List[X]) have no get_origin/get_args;
# normalize them to their Any-parameterized forms so the container
# branches fire
_BARE_HINTS = {
    list: List[Any],
    tuple: Tuple[Any, ...],
    dict: Dict[str, Any],
}


def _schema(cls: type) -> List[Tuple[int, str, Any]]:
    s = _SCHEMA.get(cls)
    if s is None:
        hints = get_type_hints(cls)
        s = _SCHEMA[cls] = [
            (i, f.name, _BARE_HINTS.get(hints[f.name], hints[f.name]))
            for i, f in enumerate(dataclasses.fields(cls), start=1)
        ]
        defaults = {}
        for f in dataclasses.fields(cls):
            if f.default is not dataclasses.MISSING:
                defaults[f.name] = f.default
            elif f.default_factory is not dataclasses.MISSING:  # type: ignore[misc]
                defaults[f.name] = f.default_factory()  # type: ignore[misc]
        _DEFAULTS[cls] = defaults
    return s


# -- encode ------------------------------------------------------------------


def _enc_union(buf: bytearray, field: int, val: Any) -> None:
    """Scalar-union / Any submessage: {1: str, 2: varint, 3: double,
    4: json bytes}. bool is NOT int here: JSON bytes keep its type."""
    sub = bytearray()
    if isinstance(val, str):
        _put_header(sub, 1, _WIRE_LEN)
        raw = val.encode()
        _put_varint(sub, len(raw))
        sub += raw
    elif isinstance(val, bool) or not isinstance(val, (int, float)):
        raw = json.dumps(val, default=str).encode()
        _put_header(sub, 4, _WIRE_LEN)
        _put_varint(sub, len(raw))
        sub += raw
    elif isinstance(val, int):
        _put_header(sub, 2, _WIRE_VARINT)
        _put_varint(sub, _zigzag(val))
    else:
        _put_header(sub, 3, _WIRE_FIXED64)
        sub += struct.pack("<d", val)
    _put_header(buf, field, _WIRE_LEN)
    _put_varint(buf, len(sub))
    buf += sub


def _enc_value(buf: bytearray, field: int, val: Any, tp: Any) -> None:
    tp = _resolve_optional(tp)
    origin = get_origin(tp)
    if dataclasses.is_dataclass(tp) and not origin:
        sub = _enc_message(val)
        _put_header(buf, field, _WIRE_LEN)
        _put_varint(buf, len(sub))
        buf += sub
        return
    if origin in (list, tuple):
        args = get_args(tp)
        if origin is tuple and args and Ellipsis not in args:
            # fixed-shape tuple (e.g. a (key, value) pair): ONE positional
            # submessage, field number = position — repeating the outer
            # field would flatten the pair structure
            sub = bytearray()
            for pos, (item, itp) in enumerate(zip(val, args), start=1):
                _enc_value(sub, pos, item, itp)
            _put_header(buf, field, _WIRE_LEN)
            _put_varint(buf, len(sub))
            buf += sub
            return
        item_tp = args[0] if args else Any
        for item in val:
            _enc_value(buf, field, item, item_tp)
        return
    if origin is dict:
        _kt, vt = get_args(tp) or (str, Any)
        for k in sorted(val):
            entry = bytearray()
            _enc_value(entry, 1, k, str)
            _enc_value(entry, 2, val[k], vt)
            _put_header(buf, field, _WIRE_LEN)
            _put_varint(buf, len(entry))
            buf += entry
        return
    if tp is str and isinstance(val, str):
        raw = val.encode()
        _put_header(buf, field, _WIRE_LEN)
        _put_varint(buf, len(raw))
        buf += raw
        return
    if tp is bytes and isinstance(val, bytes):
        _put_header(buf, field, _WIRE_LEN)
        _put_varint(buf, len(val))
        buf += val
        return
    if tp is bool or (tp is int and isinstance(val, (bool, int))):
        _put_header(buf, field, _WIRE_VARINT)
        _put_varint(buf, _zigzag(int(val)))
        return
    if tp is float and isinstance(val, (int, float)):
        _put_header(buf, field, _WIRE_FIXED64)
        buf += struct.pack("<d", float(val))
        return
    if isinstance(val, (set, frozenset)):
        # no set-typed fields exist in the wire model; fail loudly rather
        # than letting the union fallback stringify it irreversibly
        raise TypeError(f"set-typed field has no wire form: {val!r}")
    # Quantity (str|int|float union), Any, or a value whose runtime type
    # diverges from the hint: the tagged union keeps it lossless
    _enc_union(buf, field, val)


# explicit-empty sentinel for container fields whose default is NON-empty
# (e.g. CRDSpec.versions defaults ["v1"]): proto wire has no native form
# for "present but empty" repeated fields. A 1-byte payload of 0x00 is a
# field-0 header, which real submessages can never start with (field 0 is
# reserved), and k8s strings never contain NUL.
_EMPTY_SENTINEL = b"\x00"


def _enc_message(obj: Any) -> bytearray:
    cls = type(obj)
    buf = bytearray()
    defaults = _DEFAULTS.get(cls)
    if defaults is None:
        _schema(cls)
        defaults = _DEFAULTS[cls]
    for num, name, tp in _schema(cls):
        val = getattr(obj, name)
        if val is None:
            continue
        if name in defaults and val == defaults[name]:
            continue  # omitempty (value == default: decode restores it)
        if isinstance(val, (list, tuple, dict, str, bytes, frozenset)) and not val:
            # empty value. Skipping is only sound when decode's default
            # restores the same empty — i.e. the field HAS a default and
            # it is itself empty. A REQUIRED field (no default) must
            # always hit the wire or cls(**kwargs) fails at decode; a
            # non-empty default (namespace="default",
            # scheduler_name="default-scheduler") makes the emptiness
            # meaningful.
            if name in defaults and not defaults[name]:
                continue
            if isinstance(val, (str, bytes)):
                pass  # zero-length payload decodes back to ""/b""
            else:
                _put_header(buf, num, _WIRE_LEN)
                _put_varint(buf, len(_EMPTY_SENTINEL))
                buf += _EMPTY_SENTINEL
                continue
        _enc_value(buf, num, val, tp)
    return buf


# -- decode ------------------------------------------------------------------


def _dec_union(data: bytes) -> Any:
    i = 0
    val: Any = None
    while i < len(data):
        header, i = _get_varint(data, i)
        field, wire = header >> 3, header & 7
        if wire == _WIRE_LEN:
            ln, i = _get_varint(data, i)
            raw = data[i:i + ln]
            i += ln
            val = raw.decode() if field == 1 else json.loads(raw)
        elif wire == _WIRE_VARINT:
            n, i = _get_varint(data, i)
            val = _unzigzag(n)
        else:
            val = struct.unpack_from("<d", data, i)[0]
            i += 8
    return val


def _dec_value(wire: int, data: bytes, i: int, tp: Any) -> Tuple[Any, int]:
    tp = _resolve_optional(tp)
    origin = get_origin(tp)
    if wire == _WIRE_VARINT:
        n, i = _get_varint(data, i)
        v = _unzigzag(n)
        if tp is bool:
            return bool(v), i
        if tp is float:
            return float(v), i
        return v, i
    if wire == _WIRE_FIXED64:
        return struct.unpack_from("<d", data, i)[0], i + 8
    ln, i = _get_varint(data, i)
    raw = bytes(data[i:i + ln])
    i += ln
    return _dec_single_len(raw, tp)[0], i


def _dec_single_len(raw: bytes, tp: Any) -> Tuple[Any, int]:
    """Decode one length-delimited payload as type tp."""
    tp = _resolve_optional(tp)
    origin = get_origin(tp)
    if dataclasses.is_dataclass(tp) and not origin:
        return _dec_message(raw, tp), len(raw)
    if tp is str:
        return raw.decode(), len(raw)
    if tp is bytes:
        return raw, len(raw)
    if origin is tuple:
        args = get_args(tp)
        if args and Ellipsis not in args:
            # fixed-shape tuple: positional submessage
            out = []
            j = 0
            while j < len(raw):
                h, j = _get_varint(raw, j)
                pos, w = h >> 3, h & 7
                item, j = _dec_value(w, raw, j, args[pos - 1])
                out.append(item)
            return tuple(out), len(raw)
    if origin is dict or origin in (list, tuple) or tp in (Any, object) or origin is typing.Union:
        return _dec_union(raw), len(raw)
    # scalar-union carried payload
    return _dec_union(raw), len(raw)


def _dec_message(data: bytes, cls: type) -> Any:
    fields_by_num = {num: (name, tp) for num, name, tp in _schema(cls)}
    kwargs: Dict[str, Any] = {}
    i = 0
    while i < len(data):
        header, i = _get_varint(data, i)
        num, wire = header >> 3, header & 7
        ent = fields_by_num.get(num)
        if ent is None:
            # unknown field (newer writer): skip by wire type
            if wire == _WIRE_VARINT:
                _n, i = _get_varint(data, i)
            elif wire == _WIRE_FIXED64:
                i += 8
            else:
                ln, i = _get_varint(data, i)
                i += ln
            continue
        name, tp = ent
        rtp = _resolve_optional(tp)
        origin = get_origin(rtp)
        if origin in (list, tuple):
            targs = get_args(rtp)
            if origin is tuple and targs and Ellipsis not in targs:
                # fixed-shape tuple field: one positional submessage
                ln, i = _get_varint(data, i)
                raw = bytes(data[i:i + ln])
                i += ln
                kwargs[name] = _dec_single_len(raw, rtp)[0]
                continue
            (item_tp, *_r) = targs or (Any,)
            item_rtp = _resolve_optional(item_tp)
            if wire == _WIRE_VARINT:
                # int/bool list element rides the varint wire directly
                n, i = _get_varint(data, i)
                item: Any = _unzigzag(n)
                if item_rtp is bool:
                    item = bool(item)
            elif wire == _WIRE_FIXED64:
                item = struct.unpack_from("<d", data, i)[0]
                i += 8
            else:
                ln, i = _get_varint(data, i)
                raw = bytes(data[i:i + ln])
                i += ln
                if raw == _EMPTY_SENTINEL:
                    kwargs.setdefault(name, [])
                    continue
                item = _dec_single_len(raw, item_tp)[0]
            kwargs.setdefault(name, []).append(item)
        elif origin is dict:
            _kt, vt = get_args(rtp) or (str, Any)
            ln, i = _get_varint(data, i)
            raw = bytes(data[i:i + ln])
            i += ln
            if raw == _EMPTY_SENTINEL:
                kwargs.setdefault(name, {})
                continue
            k = val = None
            j = 0
            while j < len(raw):
                eh, j = _get_varint(raw, j)
                enum_, ew = eh >> 3, eh & 7
                if enum_ == 1:
                    k, j = _dec_value(ew, raw, j, str)
                else:
                    val, j = _dec_value(ew, raw, j, vt)
            kwargs.setdefault(name, {})[k] = val
        else:
            kwargs[name], i = _dec_value(wire, data, i, tp)
    # tuplify tuple-typed fields
    for num, name, tp in _schema(cls):
        rtp = _resolve_optional(tp)
        if get_origin(rtp) is tuple and name in kwargs:
            kwargs[name] = tuple(kwargs[name])
    return cls(**kwargs)


# -- envelope (protobuf.go Unknown) ------------------------------------------


def encode_obj(obj: Any, api_version: str = "v1") -> bytes:
    """Typed object -> magic + Unknown{typeMeta{apiVersion,kind}, raw}.

    Unstructured (custom resources) raises TypeError: CRs are JSON-only,
    as in the reference (protobuf is unsupported for CRDs there too)."""
    if isinstance(obj, v1.Unstructured):
        raise TypeError("custom resources have no binary encoding; use JSON")
    kind = type(obj).__name__
    body = _enc_message(obj)
    tm = bytearray()
    _enc_value(tm, 1, api_version, str)
    _enc_value(tm, 2, kind, str)
    env = bytearray()
    _put_header(env, 1, _WIRE_LEN)
    _put_varint(env, len(tm))
    env += tm
    _put_header(env, 2, _WIRE_LEN)
    _put_varint(env, len(body))
    env += body
    return MAGIC + bytes(env)


def decode_obj(data: bytes, cls: Optional[Type] = None) -> Any:
    """magic + Unknown -> typed object. cls overrides the kind lookup."""
    if not data.startswith(MAGIC):
        raise ValueError("missing k8s binary envelope magic")
    data = data[len(MAGIC):]
    i = 0
    kind = ""
    raw = b""
    while i < len(data):
        header, i = _get_varint(data, i)
        num = header >> 3
        ln, i = _get_varint(data, i)
        chunk = bytes(data[i:i + ln])
        i += ln
        if num == 1:
            j = 0
            while j < len(chunk):
                h2, j = _get_varint(chunk, j)
                ln2, j = _get_varint(chunk, j)
                s = chunk[j:j + ln2].decode()
                j += ln2
                if h2 >> 3 == 2:
                    kind = s
        elif num == 2:
            raw = chunk
    if cls is None:
        resource = KIND_TO_RESOURCE.get(kind)
        if resource is None:
            raise KeyError(f"unknown kind {kind!r} in binary envelope")
        cls = RESOURCE_KINDS[resource]
    return _dec_message(raw, cls)
