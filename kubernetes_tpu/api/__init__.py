"""API object model: resources, labels/selectors, Pod/Node types.

The moral equivalent of the reference's staging/src/k8s.io/api +
apimachinery's label/selector machinery, reduced to the typed surface the
control plane actually consumes, with TPU-friendly plain-data objects
(dataclasses, no codegen).
"""

from .resources import (  # noqa: F401
    Quantity,
    parse_quantity,
    ResourceList,
    MILLI_CPU,
    MEMORY,
    EPHEMERAL_STORAGE,
    PODS,
    DEFAULT_MILLI_CPU_REQUEST,
    DEFAULT_MEMORY_REQUEST,
)
from .selectors import (  # noqa: F401
    Requirement,
    LabelSelector,
    labels_match_selector,
    selector_from_match_labels,
)
from .objects import (  # noqa: F401
    ObjectMeta,
    OwnerReference,
    Taint,
    Toleration,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    NodeSelector,
    PreferredSchedulingTerm,
    NodeAffinity,
    PodAffinityTerm,
    WeightedPodAffinityTerm,
    PodAffinity,
    PodAntiAffinity,
    Affinity,
    TopologySpreadConstraint,
    ContainerPort,
    Container,
    PodSpec,
    PodCondition,
    PodStatus,
    Pod,
    NodeSpec,
    ContainerImage,
    NodeCondition,
    NodeStatus,
    Node,
    Binding,
)
