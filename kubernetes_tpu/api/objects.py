"""Core typed objects: Pod, Node, and the scheduling-relevant sub-structures.

Covers the slice of staging/src/k8s.io/api/core/v1/types.go the control plane
consumes: metadata, resources, taints/tolerations, node & pod affinity,
topology spread constraints, host ports, images, conditions. Plain mutable
dataclasses; Pod/Node deep-copy is a hand-rolled structural copy (every
mutable sub-object cloned, frozen ones shared — ~100x faster than
copy.deepcopy on the store's hot path; tests/test_api.py pins field
completeness); other kinds fall back to copy.deepcopy. Defaulting happens in
constructors; conversion layers are unnecessary (single internal version).
"""

from __future__ import annotations

import copy
import dataclasses
import itertools
import time
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from .resources import (
    CPU,
    DEFAULT_MILLI_CPU_REQUEST,
    DEFAULT_MEMORY_REQUEST,
    MEMORY,
    PODS,
    Quantity,
    ResourceList,
)
from .selectors import LabelSelector

_uid_counter = itertools.count(1)


def new_uid() -> str:
    return f"uid-{next(_uid_counter)}"


@dataclass
class OwnerReference:
    api_version: str = "v1"
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = False
    # finalizer gate: the GC may not delete the owner until this dependent
    # is gone (reference metav1.OwnerReference.BlockOwnerDeletion; enforced
    # at admission by OwnerReferencesPermissionEnforcement)
    block_owner_deletion: bool = False


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=new_uid)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    resource_version: int = 0
    generation: int = 0
    creation_timestamp: float = field(default_factory=time.time)
    deletion_timestamp: Optional[float] = None
    owner_references: List[OwnerReference] = field(default_factory=list)
    finalizers: List[str] = field(default_factory=list)

    @property
    def key(self) -> str:
        """namespace/name cache key (cache.MetaNamespaceKeyFunc)."""
        return f"{self.namespace}/{self.name}" if self.namespace else self.name


# ---------------------------------------------------------------------------
# Taints and tolerations (v1 types.go Taint/Toleration)
# ---------------------------------------------------------------------------

TAINT_NO_SCHEDULE = "NoSchedule"
TAINT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
TAINT_NO_EXECUTE = "NoExecute"

TOLERATION_OP_EXISTS = "Exists"
TOLERATION_OP_EQUAL = "Equal"

TAINT_NODE_UNSCHEDULABLE = "node.kubernetes.io/unschedulable"

# cluster-autoscaler contract annotations/labels (autoscaler/):
# a pod with no controller owner blocks scale-down of its node unless it
# carries the safe-to-evict annotation; nodes provisioned by the autoscaler
# carry the nodegroup label so scale-down knows which catalog entry (and
# min-size floor) they count against.
ANN_SAFE_TO_EVICT = "cluster-autoscaler.kubernetes.io/safe-to-evict"
LABEL_NODEGROUP = "autoscaler.kubernetes-tpu.io/nodegroup"


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = TAINT_NO_SCHEDULE


@dataclass(frozen=True)
class Toleration:
    key: str = ""  # empty key + Exists tolerates everything
    operator: str = TOLERATION_OP_EQUAL
    value: str = ""
    effect: str = ""  # empty effect matches all effects
    toleration_seconds: Optional[int] = None

    def tolerates(self, taint: Taint) -> bool:
        """v1/toleration.go ToleratesTaint."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator == TOLERATION_OP_EXISTS:
            return True
        return self.value == taint.value


def tolerations_tolerate_taint(
    tolerations: Sequence[Toleration], taint: Taint
) -> bool:
    return any(t.tolerates(taint) for t in tolerations)


def find_untolerated_taint(
    taints: Sequence[Taint],
    tolerations: Sequence[Toleration],
    effects: Sequence[str] = (TAINT_NO_SCHEDULE, TAINT_NO_EXECUTE),
) -> Optional[Taint]:
    """v1helper.FindMatchingUntoleratedTaint (filter path)."""
    for taint in taints:
        if taint.effect not in effects:
            continue
        if not tolerations_tolerate_taint(tolerations, taint):
            return taint
    return None


# ---------------------------------------------------------------------------
# Node affinity (v1 NodeSelector*)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class NodeSelectorRequirement:
    key: str
    operator: str  # In/NotIn/Exists/DoesNotExist/Gt/Lt
    values: Tuple[str, ...] = ()


@dataclass(frozen=True)
class NodeSelectorTerm:
    # AND of expressions; matchFields (metadata.name) folded into
    # match_fields for the single supported field.
    match_expressions: Tuple[NodeSelectorRequirement, ...] = ()
    match_fields: Tuple[NodeSelectorRequirement, ...] = ()


@dataclass(frozen=True)
class NodeSelector:
    # OR of terms (nodeSelectorTerms)
    terms: Tuple[NodeSelectorTerm, ...] = ()


@dataclass(frozen=True)
class PreferredSchedulingTerm:
    weight: int
    preference: NodeSelectorTerm


@dataclass(frozen=True)
class NodeAffinity:
    required: Optional[NodeSelector] = None
    preferred: Tuple[PreferredSchedulingTerm, ...] = ()


# ---------------------------------------------------------------------------
# Pod affinity (v1 PodAffinity*)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PodAffinityTerm:
    label_selector: Optional[LabelSelector] = None
    namespaces: Tuple[str, ...] = ()  # empty => pod's own namespace
    topology_key: str = ""


@dataclass(frozen=True)
class WeightedPodAffinityTerm:
    weight: int
    term: PodAffinityTerm


@dataclass(frozen=True)
class PodAffinity:
    required: Tuple[PodAffinityTerm, ...] = ()
    preferred: Tuple[WeightedPodAffinityTerm, ...] = ()


@dataclass(frozen=True)
class PodAntiAffinity:
    required: Tuple[PodAffinityTerm, ...] = ()
    preferred: Tuple[WeightedPodAffinityTerm, ...] = ()


@dataclass(frozen=True)
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAntiAffinity] = None


# ---------------------------------------------------------------------------
# Topology spread (v1 TopologySpreadConstraint)
# ---------------------------------------------------------------------------

DO_NOT_SCHEDULE = "DoNotSchedule"
SCHEDULE_ANYWAY = "ScheduleAnyway"


@dataclass(frozen=True)
class TopologySpreadConstraint:
    max_skew: int
    topology_key: str
    when_unsatisfiable: str  # DoNotSchedule | ScheduleAnyway
    label_selector: Optional[LabelSelector] = None


# ---------------------------------------------------------------------------
# Containers & pods
# ---------------------------------------------------------------------------


@dataclass
class ContainerPort:
    container_port: int
    host_port: int = 0
    protocol: str = "TCP"
    host_ip: str = ""


@dataclass
class Probe:
    """v1.Probe (types.go): the handler itself is the runtime's health
    check in this build — the kubelet asks the PodRuntime, the way the
    reference's prober execs/GETs into the container."""

    period_seconds: float = 10.0
    initial_delay_seconds: float = 0.0
    failure_threshold: int = 3
    success_threshold: int = 1


@dataclass
class ContainerStatus:
    name: str = ""
    ready: bool = False
    restart_count: int = 0
    state: str = "running"  # waiting | running | terminated


@dataclass(frozen=True)
class SecurityContext:
    """Container security context subset the admission gates act on
    (reference core/v1 SecurityContext)."""

    privileged: bool = False
    run_as_user: Optional[int] = None
    run_as_non_root: bool = False


@dataclass
class Container:
    name: str = ""
    image: str = ""
    requests: Dict[str, Quantity] = field(default_factory=dict)
    limits: Dict[str, Quantity] = field(default_factory=dict)
    ports: List[ContainerPort] = field(default_factory=list)
    liveness_probe: Optional[Probe] = None
    readiness_probe: Optional[Probe] = None
    image_pull_policy: str = ""  # "" = kubelet default (IfNotPresent)
    security_context: Optional[SecurityContext] = None
    # entrypoint (v1.Container Command/Args): consumed by ProcessRuntime,
    # which supervises a real host process per container
    command: List[str] = field(default_factory=list)
    args: List[str] = field(default_factory=list)


@dataclass
class PodSpec:
    node_name: str = ""
    scheduler_name: str = "default-scheduler"
    priority: Optional[int] = None
    priority_class_name: str = ""
    # PreemptLowerPriority | Never; None = inherit the class's policy
    # (filled by the Priority admission plugin, like spec.priority)
    preemption_policy: Optional[str] = None
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    overhead: Dict[str, Quantity] = field(default_factory=dict)
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: List[Toleration] = field(default_factory=list)
    topology_spread_constraints: List[TopologySpreadConstraint] = field(
        default_factory=list
    )
    host_network: bool = False
    restart_policy: str = "Always"
    termination_grace_period_seconds: int = 30
    volumes: List["Volume"] = field(default_factory=list)
    service_account_name: str = ""
    # bounded-duration pods (Jobs set this); the quota "Terminating" scope
    # selects on its presence (reference core/v1 ActiveDeadlineSeconds)
    active_deadline_seconds: Optional[int] = None
    # named RuntimeClass; the RuntimeClass admission plugin merges the
    # class's overhead/scheduling into the pod (node/v1 RuntimeClassName)
    runtime_class_name: str = ""


@dataclass(frozen=True)
class GCEPersistentDiskVolumeSource:
    pd_name: str = ""
    read_only: bool = False


@dataclass(frozen=True)
class AWSElasticBlockStoreVolumeSource:
    volume_id: str = ""
    read_only: bool = False


@dataclass(frozen=True)
class ISCSIVolumeSource:
    target_portal: str = ""
    iqn: str = ""
    lun: int = 0
    read_only: bool = False


@dataclass(frozen=True)
class RBDVolumeSource:
    monitors: Tuple[str, ...] = ()
    image: str = ""
    pool: str = "rbd"
    read_only: bool = False


@dataclass(frozen=True)
class AzureDiskVolumeSource:
    disk_name: str = ""
    data_disk_uri: str = ""
    read_only: bool = False


@dataclass(frozen=True)
class CinderVolumeSource:
    volume_id: str = ""
    read_only: bool = False


@dataclass(frozen=True)
class CSIVolumeSource:
    driver: str = ""
    volume_handle: str = ""
    read_only: bool = False


@dataclass
class Volume:
    name: str = ""
    # A tiny union: exactly one of these set.
    persistent_volume_claim: Optional[str] = None  # claim name
    host_path: Optional[str] = None
    empty_dir: bool = False
    config_map: Optional[str] = None
    secret: Optional[str] = None
    gce_persistent_disk: Optional[GCEPersistentDiskVolumeSource] = None
    aws_elastic_block_store: Optional[AWSElasticBlockStoreVolumeSource] = None
    iscsi: Optional[ISCSIVolumeSource] = None
    rbd: Optional[RBDVolumeSource] = None
    azure_disk: Optional[AzureDiskVolumeSource] = None
    cinder: Optional[CinderVolumeSource] = None


POD_PENDING = "Pending"
POD_RUNNING = "Running"
POD_SUCCEEDED = "Succeeded"
POD_FAILED = "Failed"

COND_POD_SCHEDULED = "PodScheduled"
COND_POD_READY = "Ready"


@dataclass
class PodCondition:
    type: str
    status: str  # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_transition_time: float = field(default_factory=time.time)


@dataclass
class PodStatus:
    phase: str = POD_PENDING
    conditions: List[PodCondition] = field(default_factory=list)
    nominated_node_name: str = ""
    reason: str = ""
    message: str = ""
    start_time: Optional[float] = None
    pod_ip: str = ""  # set by the node agent once the sandbox is up
    host_ip: str = ""
    container_statuses: List[ContainerStatus] = field(default_factory=list)


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)
    kind: str = "Pod"

    def deep_copy(self) -> "Pod":
        """Structural copy: clone every mutable container/dataclass, share
        frozen ones (selectors, affinity, taints, volume sources — immutable
        by construction). ~100x faster than copy.deepcopy's memo walk; the
        API store copies on every create/get/list/watch-event, so this is on
        the control plane's hottest path."""
        return Pod(
            metadata=_copy_meta(self.metadata),
            spec=_copy_pod_spec(self.spec),
            status=_copy_pod_status(self.status),
            kind=self.kind,
        )

    def __deepcopy__(self, memo) -> "Pod":
        return self.deep_copy()

    @property
    def priority(self) -> int:
        """pod priority with default 0 (podutil.GetPodPriority)."""
        return self.spec.priority if self.spec.priority is not None else 0


def assume_copy(pod: Pod, node_name: str) -> Pod:
    """Shell copy for the scheduler cache's assume protocol: a fresh Pod +
    PodSpec shell with node_name set, SHARING metadata, status, and every
    spec innard (containers, volumes, tolerations, affinity — all treated
    as read-only once queued; informer updates arrive as new objects and
    the confirmation swaps in the API server's own copy, cache.add_pod).
    ~10x cheaper than deep_copy on the bulk assume path, which the host
    bind stage's pods/s ceiling is made of. dataclasses.replace keeps the
    shell complete as PodSpec grows fields."""
    return Pod(
        metadata=pod.metadata,
        spec=dataclasses.replace(pod.spec, node_name=node_name),
        status=pod.status,
        kind=pod.kind,
    )


def event_copy(pod: Pod) -> Pod:
    """Watch-event snapshot of a stored pod: fresh Pod/meta/spec/status
    SHELLS so the event is isolated from the store's in-place shell
    mutators (bind_pods' node_name set, _bump's resource_version, delete's
    deletion_timestamp), while sharing every list/dict innard — the store
    replaces objects wholesale on update and never mutates innards in
    place. This is the batch-bind hot path's copy (one per MODIFIED
    event); cold paths keep full deep_copy."""
    return Pod(
        metadata=dataclasses.replace(pod.metadata),
        spec=dataclasses.replace(pod.spec),
        status=dataclasses.replace(pod.status),
        kind=pod.kind,
    )


def _copy_meta(m: ObjectMeta) -> ObjectMeta:
    return ObjectMeta(
        name=m.name,
        namespace=m.namespace,
        uid=m.uid,
        labels=dict(m.labels),
        annotations=dict(m.annotations),
        resource_version=m.resource_version,
        generation=m.generation,
        creation_timestamp=m.creation_timestamp,
        deletion_timestamp=m.deletion_timestamp,
        owner_references=[
            OwnerReference(
                r.api_version, r.kind, r.name, r.uid, r.controller,
                r.block_owner_deletion,
            )
            for r in m.owner_references
        ],
        finalizers=list(m.finalizers),
    )


def _copy_container(c: Container) -> Container:
    return Container(
        name=c.name,
        image=c.image,
        requests=dict(c.requests),
        limits=dict(c.limits),
        ports=[
            ContainerPort(p.container_port, p.host_port, p.protocol, p.host_ip)
            for p in c.ports
        ],
        liveness_probe=c.liveness_probe,  # Probe is treated as immutable
        readiness_probe=c.readiness_probe,
        image_pull_policy=c.image_pull_policy,
        security_context=c.security_context,  # frozen
        command=list(c.command),
        args=list(c.args),
    )


def _copy_volume(v: Volume) -> Volume:
    # sources are frozen dataclasses / scalars — share them
    return Volume(
        name=v.name,
        persistent_volume_claim=v.persistent_volume_claim,
        host_path=v.host_path,
        empty_dir=v.empty_dir,
        config_map=v.config_map,
        secret=v.secret,
        gce_persistent_disk=v.gce_persistent_disk,
        aws_elastic_block_store=v.aws_elastic_block_store,
        iscsi=v.iscsi,
        rbd=v.rbd,
        azure_disk=v.azure_disk,
        cinder=v.cinder,
    )


def _copy_pod_spec(s: PodSpec) -> PodSpec:
    return PodSpec(
        node_name=s.node_name,
        scheduler_name=s.scheduler_name,
        priority=s.priority,
        priority_class_name=s.priority_class_name,
        preemption_policy=s.preemption_policy,
        containers=[_copy_container(c) for c in s.containers],
        init_containers=[_copy_container(c) for c in s.init_containers],
        overhead=dict(s.overhead),
        node_selector=dict(s.node_selector),
        affinity=s.affinity,  # frozen
        tolerations=list(s.tolerations),  # items frozen
        topology_spread_constraints=list(s.topology_spread_constraints),
        host_network=s.host_network,
        restart_policy=s.restart_policy,
        termination_grace_period_seconds=s.termination_grace_period_seconds,
        volumes=[_copy_volume(v) for v in s.volumes],
        service_account_name=s.service_account_name,
        active_deadline_seconds=s.active_deadline_seconds,
        runtime_class_name=s.runtime_class_name,
    )


def _copy_pod_status(st: PodStatus) -> PodStatus:
    return PodStatus(
        phase=st.phase,
        conditions=[
            PodCondition(
                c.type, c.status, c.reason, c.message, c.last_transition_time
            )
            for c in st.conditions
        ],
        nominated_node_name=st.nominated_node_name,
        reason=st.reason,
        message=st.message,
        start_time=st.start_time,
        pod_ip=st.pod_ip,
        host_ip=st.host_ip,
        container_statuses=[
            ContainerStatus(cs.name, cs.ready, cs.restart_count, cs.state)
            for cs in st.container_statuses
        ],
    )


def compute_pod_resource_request(
    pod: Pod, non_zero: bool = False
) -> ResourceList:
    """Pod effective resource request.

    max(sum(containers), max(initContainers)) + overhead — the formula at
    reference pkg/scheduler/framework/plugins/noderesources/fit.go:99-116
    (computePodResourceRequest) and nodeinfo calculateResource
    (node_info.go:568). With non_zero=True, cpu/memory requests of 0 are
    replaced by the scoring defaults (100m / 200MB).
    """
    total = ResourceList()
    for c in pod.spec.containers:
        req = ResourceList.parse(c.requests)
        if non_zero:
            if req.get(CPU, 0) == 0:
                req[CPU] = DEFAULT_MILLI_CPU_REQUEST
            if req.get(MEMORY, 0) == 0:
                req[MEMORY] = DEFAULT_MEMORY_REQUEST
        total.add(req)
    init_max = ResourceList()
    for c in pod.spec.init_containers:
        req = ResourceList.parse(c.requests)
        init_max.set_max(req)
    total.set_max(init_max)
    if pod.spec.overhead:
        total.add(ResourceList.parse(pod.spec.overhead))
    return total


def pod_host_ports(pod: Pod) -> List[Tuple[str, str, int]]:
    """(hostIP, protocol, hostPort) triples a pod occupies (schedutil.GetContainerPorts)."""
    out = []
    for c in pod.spec.containers:
        for p in c.ports:
            if p.host_port > 0:
                out.append((p.host_ip or "0.0.0.0", p.protocol or "TCP", p.host_port))
    return out


# ---------------------------------------------------------------------------
# Nodes
# ---------------------------------------------------------------------------


@dataclass
class NodeSpec:
    unschedulable: bool = False
    taints: List[Taint] = field(default_factory=list)
    pod_cidr: str = ""
    provider_id: str = ""


@dataclass
class ContainerImage:
    names: List[str] = field(default_factory=list)
    size_bytes: int = 0


NODE_READY = "Ready"


@dataclass
class NodeCondition:
    type: str
    status: str
    reason: str = ""
    message: str = ""
    last_heartbeat_time: float = field(default_factory=time.time)
    last_transition_time: float = field(default_factory=time.time)


@dataclass
class NodeStatus:
    capacity: Dict[str, Quantity] = field(default_factory=dict)
    allocatable: Dict[str, Quantity] = field(default_factory=dict)
    conditions: List[NodeCondition] = field(default_factory=list)
    images: List[ContainerImage] = field(default_factory=list)
    addresses: List[Tuple[str, str]] = field(default_factory=list)
    node_info: Dict[str, str] = field(default_factory=dict)
    # kubelet volume manager reporting (reference VolumesInUse/
    # VolumesAttached): the safe-detach contract between node and the
    # attach-detach controller
    volumes_in_use: List[str] = field(default_factory=list)
    volumes_attached: List[str] = field(default_factory=list)


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)
    kind: str = "Node"

    def deep_copy(self) -> "Node":
        return Node(
            metadata=_copy_meta(self.metadata),
            spec=NodeSpec(
                unschedulable=self.spec.unschedulable,
                taints=list(self.spec.taints),  # items frozen
                pod_cidr=self.spec.pod_cidr,
                provider_id=self.spec.provider_id,
            ),
            status=NodeStatus(
                capacity=dict(self.status.capacity),
                allocatable=dict(self.status.allocatable),
                conditions=[
                    NodeCondition(
                        c.type,
                        c.status,
                        c.reason,
                        c.message,
                        c.last_heartbeat_time,
                        c.last_transition_time,
                    )
                    for c in self.status.conditions
                ],
                images=[
                    ContainerImage(list(im.names), im.size_bytes)
                    for im in self.status.images
                ],
                addresses=list(self.status.addresses),
                node_info=dict(self.status.node_info),
                volumes_in_use=list(self.status.volumes_in_use),
                volumes_attached=list(self.status.volumes_attached),
            ),
            kind=self.kind,
        )

    def __deepcopy__(self, memo) -> "Node":
        return self.deep_copy()

    def allocatable(self) -> ResourceList:
        src = self.status.allocatable or self.status.capacity
        rl = ResourceList.parse(src)
        rl.setdefault(PODS, 110)
        return rl


@dataclass
class Binding:
    """pods/{name}/binding subresource payload (DefaultBinder.Bind).

    Fields default empty so partial wire payloads decode; an empty pod_uid
    skips the uid check on bind."""

    pod_name: str = ""
    pod_namespace: str = ""
    pod_uid: str = ""
    target_node: str = ""
    kind: str = "Binding"


# ---------------------------------------------------------------------------
# Storage (subset needed for scheduling: volume binding / restrictions /
# zone / limits — reference staging/src/k8s.io/api/core/v1/types.go PV/PVC,
# storage/v1 StorageClass/CSINode)
# ---------------------------------------------------------------------------

CLAIM_PENDING = "Pending"
CLAIM_BOUND = "Bound"
CLAIM_LOST = "Lost"

BINDING_IMMEDIATE = "Immediate"
BINDING_WAIT_FOR_FIRST_CONSUMER = "WaitForFirstConsumer"


@dataclass
class PersistentVolumeClaimSpec:
    access_modes: List[str] = field(default_factory=list)
    resources: Dict[str, Quantity] = field(default_factory=dict)  # requests
    storage_class_name: Optional[str] = None
    volume_name: str = ""  # bound PV name


@dataclass
class PersistentVolumeClaimStatus:
    phase: str = CLAIM_PENDING
    # actual provisioned size; the expand controller reconciles
    # spec.resources["storage"] > status.capacity["storage"]
    capacity: Dict[str, Quantity] = field(default_factory=dict)


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeClaimSpec = field(
        default_factory=PersistentVolumeClaimSpec
    )
    status: PersistentVolumeClaimStatus = field(
        default_factory=PersistentVolumeClaimStatus
    )
    kind: str = "PersistentVolumeClaim"

    def deep_copy(self) -> "PersistentVolumeClaim":
        return copy.deepcopy(self)


@dataclass
class PersistentVolumeSpec:
    capacity: Dict[str, Quantity] = field(default_factory=dict)
    access_modes: List[str] = field(default_factory=list)
    storage_class_name: str = ""
    claim_ref: Optional[str] = None  # "namespace/name" of bound claim
    node_affinity: Optional[NodeSelector] = None  # volume node affinity
    gce_persistent_disk: Optional[GCEPersistentDiskVolumeSource] = None
    aws_elastic_block_store: Optional[AWSElasticBlockStoreVolumeSource] = None
    azure_disk: Optional[AzureDiskVolumeSource] = None
    cinder: Optional[CinderVolumeSource] = None
    iscsi: Optional[ISCSIVolumeSource] = None
    rbd: Optional[RBDVolumeSource] = None
    csi: Optional[CSIVolumeSource] = None


@dataclass
class PersistentVolumeStatus:
    phase: str = "Available"  # Available | Bound | Released | Failed


@dataclass
class PersistentVolume:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeSpec = field(default_factory=PersistentVolumeSpec)
    status: PersistentVolumeStatus = field(
        default_factory=PersistentVolumeStatus
    )
    kind: str = "PersistentVolume"

    def deep_copy(self) -> "PersistentVolume":
        return copy.deepcopy(self)


@dataclass
class StorageClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    provisioner: str = ""
    volume_binding_mode: str = BINDING_IMMEDIATE
    allow_volume_expansion: bool = False
    kind: str = "StorageClass"

    def deep_copy(self) -> "StorageClass":
        return copy.deepcopy(self)


@dataclass
class CSINodeDriver:
    name: str = ""
    node_id: str = ""
    allocatable_count: Optional[int] = None  # attachable volume limit


@dataclass
class CSINode:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    drivers: List[CSINodeDriver] = field(default_factory=list)
    kind: str = "CSINode"

    def deep_copy(self) -> "CSINode":
        return copy.deepcopy(self)


# ---------------------------------------------------------------------------
# Services & workload controllers (subset for SelectorSpread/ServiceAffinity)
# ---------------------------------------------------------------------------


@dataclass
class Namespace:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    phase: str = "Active"  # Active | Terminating
    kind: str = "Namespace"

    def deep_copy(self) -> "Namespace":
        return copy.deepcopy(self)


@dataclass
class PodTemplateSpec:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)


@dataclass
class ReplicaSetSpec:
    replicas: int = 1
    selector: Dict[str, str] = field(default_factory=dict)  # matchLabels
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)


@dataclass
class ReplicaSetStatus:
    replicas: int = 0
    ready_replicas: int = 0
    observed_generation: int = 0


@dataclass
class ReplicaSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ReplicaSetSpec = field(default_factory=ReplicaSetSpec)
    status: ReplicaSetStatus = field(default_factory=ReplicaSetStatus)
    kind: str = "ReplicaSet"

    def deep_copy(self) -> "ReplicaSet":
        return copy.deepcopy(self)


@dataclass
class ServiceSpec:
    selector: Dict[str, str] = field(default_factory=dict)
    cluster_ip: str = ""
    ports: List[Tuple[str, int]] = field(default_factory=list)
    type: str = "ClusterIP"  # ClusterIP | NodePort | LoadBalancer
    external_ips: List[str] = field(default_factory=list)  # LB-assigned


@dataclass
class LoadBalancerStatus:
    """v1.LoadBalancerStatus: provisioned LB ingress points (IPs)."""

    ingress: List[str] = field(default_factory=list)


@dataclass
class ServiceStatus:
    load_balancer: LoadBalancerStatus = field(
        default_factory=LoadBalancerStatus
    )


@dataclass
class Service:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ServiceSpec = field(default_factory=ServiceSpec)
    status: ServiceStatus = field(default_factory=ServiceStatus)
    kind: str = "Service"

    def deep_copy(self) -> "Service":
        return copy.deepcopy(self)


# ---------------------------------------------------------------------------
# Workload controllers: Deployment / Job / DaemonSet / StatefulSet
# (apps/v1 + batch/v1 subset; reference staging/src/k8s.io/api/apps/v1 and
# batch/v1 types.go — the fields the controllers in pkg/controller consume)
# ---------------------------------------------------------------------------

ROLLING_UPDATE = "RollingUpdate"
RECREATE = "Recreate"


@dataclass
class DeploymentStrategy:
    type: str = ROLLING_UPDATE
    max_surge: int = 1  # absolute counts (reference also allows percentages)
    max_unavailable: int = 0


@dataclass
class DeploymentSpec:
    replicas: int = 1
    selector: Dict[str, str] = field(default_factory=dict)  # matchLabels
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    strategy: DeploymentStrategy = field(default_factory=DeploymentStrategy)
    revision_history_limit: int = 10
    paused: bool = False


@dataclass
class DeploymentStatus:
    replicas: int = 0
    updated_replicas: int = 0
    ready_replicas: int = 0
    available_replicas: int = 0
    unavailable_replicas: int = 0
    observed_generation: int = 0


@dataclass
class Deployment:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: DeploymentSpec = field(default_factory=DeploymentSpec)
    status: DeploymentStatus = field(default_factory=DeploymentStatus)
    kind: str = "Deployment"

    def deep_copy(self) -> "Deployment":
        return copy.deepcopy(self)


@dataclass
class JobSpec:
    parallelism: int = 1
    completions: Optional[int] = None  # None => any single success completes
    backoff_limit: int = 6
    selector: Dict[str, str] = field(default_factory=dict)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    active_deadline_seconds: Optional[int] = None
    ttl_seconds_after_finished: Optional[int] = None  # ttlafterfinished GC


@dataclass
class JobStatus:
    active: int = 0
    succeeded: int = 0
    failed: int = 0
    start_time: Optional[float] = None
    completion_time: Optional[float] = None
    conditions: List[PodCondition] = field(default_factory=list)  # Complete/Failed


@dataclass
class Job:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: JobSpec = field(default_factory=JobSpec)
    status: JobStatus = field(default_factory=JobStatus)
    kind: str = "Job"

    def deep_copy(self) -> "Job":
        return copy.deepcopy(self)


@dataclass
class DaemonSetSpec:
    selector: Dict[str, str] = field(default_factory=dict)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)


@dataclass
class DaemonSetStatus:
    current_number_scheduled: int = 0
    desired_number_scheduled: int = 0
    number_ready: int = 0
    number_misscheduled: int = 0
    observed_generation: int = 0


@dataclass
class DaemonSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: DaemonSetSpec = field(default_factory=DaemonSetSpec)
    status: DaemonSetStatus = field(default_factory=DaemonSetStatus)
    kind: str = "DaemonSet"

    def deep_copy(self) -> "DaemonSet":
        return copy.deepcopy(self)


@dataclass
class StatefulSetSpec:
    replicas: int = 1
    selector: Dict[str, str] = field(default_factory=dict)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    service_name: str = ""
    pod_management_policy: str = "OrderedReady"  # or Parallel


@dataclass
class StatefulSetStatus:
    replicas: int = 0
    ready_replicas: int = 0
    current_replicas: int = 0
    observed_generation: int = 0


@dataclass
class StatefulSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: StatefulSetSpec = field(default_factory=StatefulSetSpec)
    status: StatefulSetStatus = field(default_factory=StatefulSetStatus)
    kind: str = "StatefulSet"

    def deep_copy(self) -> "StatefulSet":
        return copy.deepcopy(self)


# ---------------------------------------------------------------------------
# PodDisruptionBudget (policy/v1beta1) — consumed by the disruption
# controller and the scheduler's preemption PDB accounting
# (reference pkg/controller/disruption/disruption.go,
# pkg/scheduler/core/generic_scheduler.go:940 selectVictimsOnNode)
# ---------------------------------------------------------------------------


@dataclass
class PodDisruptionBudgetSpec:
    # exactly one of min_available / max_unavailable set (absolute counts;
    # the reference also allows percentages — intentional simplification)
    min_available: Optional[int] = None
    max_unavailable: Optional[int] = None
    selector: Dict[str, str] = field(default_factory=dict)  # matchLabels


@dataclass
class PodDisruptionBudgetStatus:
    disruptions_allowed: int = 0
    current_healthy: int = 0
    desired_healthy: int = 0
    expected_pods: int = 0
    observed_generation: int = 0


@dataclass
class PodDisruptionBudget:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodDisruptionBudgetSpec = field(default_factory=PodDisruptionBudgetSpec)
    status: PodDisruptionBudgetStatus = field(
        default_factory=PodDisruptionBudgetStatus
    )
    kind: str = "PodDisruptionBudget"

    def deep_copy(self) -> "PodDisruptionBudget":
        return copy.deepcopy(self)


# ---------------------------------------------------------------------------
# Endpoints (core/v1) — maintained by the endpoints controller, consumed by
# the proxy dataplane (reference pkg/controller/endpoint, pkg/proxy)
# ---------------------------------------------------------------------------


@dataclass
class EndpointAddress:
    ip: str = ""
    node_name: str = ""
    target_pod: str = ""  # namespace/name of backing pod


@dataclass
class EndpointSubset:
    addresses: List[EndpointAddress] = field(default_factory=list)
    not_ready_addresses: List[EndpointAddress] = field(default_factory=list)
    ports: List[Tuple[str, int]] = field(default_factory=list)


@dataclass
class Endpoints:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    subsets: List[EndpointSubset] = field(default_factory=list)
    kind: str = "Endpoints"

    def deep_copy(self) -> "Endpoints":
        return copy.deepcopy(self)


# ---------------------------------------------------------------------------
# PriorityClass (scheduling.k8s.io/v1) — admission resolves
# priority_class_name -> spec.priority (reference
# plugin/pkg/admission/priority/admission.go)
# ---------------------------------------------------------------------------


@dataclass
class PriorityClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0
    global_default: bool = False
    preemption_policy: str = "PreemptLowerPriority"  # or Never
    description: str = ""
    kind: str = "PriorityClass"

    def deep_copy(self) -> "PriorityClass":
        return copy.deepcopy(self)


# ---------------------------------------------------------------------------
# ConfigMap / Secret / ServiceAccount (core/v1) — reference
# staging/src/k8s.io/api/core/v1/types.go (ConfigMap, Secret, ServiceAccount)
# ---------------------------------------------------------------------------


@dataclass
class ConfigMap:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: Dict[str, str] = field(default_factory=dict)
    binary_data: Dict[str, bytes] = field(default_factory=dict)
    immutable: bool = False
    kind: str = "ConfigMap"

    def deep_copy(self) -> "ConfigMap":
        return copy.deepcopy(self)


@dataclass
class Secret:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: Dict[str, bytes] = field(default_factory=dict)
    string_data: Dict[str, str] = field(default_factory=dict)
    type: str = "Opaque"
    immutable: bool = False
    kind: str = "Secret"

    def deep_copy(self) -> "Secret":
        return copy.deepcopy(self)


@dataclass
class ServiceAccount:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    secrets: List[str] = field(default_factory=list)  # token secret names
    automount_service_account_token: bool = True
    kind: str = "ServiceAccount"

    def deep_copy(self) -> "ServiceAccount":
        return copy.deepcopy(self)


# ---------------------------------------------------------------------------
# HorizontalPodAutoscaler (autoscaling/v1) — reference
# staging/src/k8s.io/api/autoscaling/v1/types.go; controller semantics at
# pkg/controller/podautoscaler/horizontal.go
# ---------------------------------------------------------------------------


@dataclass
class CrossVersionObjectReference:
    kind: str = ""
    name: str = ""


@dataclass
class HorizontalPodAutoscalerSpec:
    scale_target_ref: CrossVersionObjectReference = field(
        default_factory=CrossVersionObjectReference
    )
    min_replicas: int = 1
    max_replicas: int = 1
    target_cpu_utilization_percentage: Optional[int] = None


@dataclass
class HorizontalPodAutoscalerStatus:
    current_replicas: int = 0
    desired_replicas: int = 0
    current_cpu_utilization_percentage: Optional[int] = None
    last_scale_time: Optional[float] = None
    observed_generation: int = 0


@dataclass
class HorizontalPodAutoscaler:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: HorizontalPodAutoscalerSpec = field(
        default_factory=HorizontalPodAutoscalerSpec
    )
    status: HorizontalPodAutoscalerStatus = field(
        default_factory=HorizontalPodAutoscalerStatus
    )
    kind: str = "HorizontalPodAutoscaler"

    def deep_copy(self) -> "HorizontalPodAutoscaler":
        return copy.deepcopy(self)


# ---------------------------------------------------------------------------
# CronJob (batch/v1beta1) — reference staging/src/k8s.io/api/batch/v1beta1;
# controller semantics at pkg/controller/cronjob/cronjob_controller.go
# ---------------------------------------------------------------------------


@dataclass
class JobTemplateSpec:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: "JobSpec" = field(default_factory=lambda: JobSpec())


@dataclass
class CronJobSpec:
    schedule: str = "* * * * *"  # 5-field cron
    suspend: bool = False
    concurrency_policy: str = "Allow"  # Allow | Forbid | Replace
    starting_deadline_seconds: Optional[int] = None
    job_template: JobTemplateSpec = field(default_factory=lambda: JobTemplateSpec())
    successful_jobs_history_limit: int = 3
    failed_jobs_history_limit: int = 1


@dataclass
class CronJobStatus:
    active: List[str] = field(default_factory=list)  # job keys
    last_schedule_time: Optional[float] = None


@dataclass
class CronJob:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: CronJobSpec = field(default_factory=CronJobSpec)
    status: CronJobStatus = field(default_factory=CronJobStatus)
    kind: str = "CronJob"

    def deep_copy(self) -> "CronJob":
        return copy.deepcopy(self)


# ---------------------------------------------------------------------------
# ResourceQuota (core/v1) — reference staging/src/k8s.io/api/core/v1 +
# pkg/controller/resourcequota/resource_quota_controller.go
# ---------------------------------------------------------------------------


@dataclass
class ResourceQuotaSpec:
    hard: Dict[str, Quantity] = field(default_factory=dict)
    # quota scopes (reference ResourceQuotaScope): BestEffort,
    # NotBestEffort, Terminating, NotTerminating — a quota with scopes
    # tracks/limits only pods matching ALL of them
    scopes: List[str] = field(default_factory=list)


@dataclass
class PodSecurityPolicySpec:
    """Subset of policy/v1beta1 PSPSpec the validation gate acts on
    (reference plugin/pkg/admission/security/podsecuritypolicy)."""

    privileged: bool = False  # allow privileged containers
    host_network: bool = False  # allow hostNetwork pods
    run_as_user_rule: str = "RunAsAny"  # RunAsAny | MustRunAsNonRoot


@dataclass
class PodSecurityPolicy:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSecurityPolicySpec = field(default_factory=PodSecurityPolicySpec)
    kind: str = "PodSecurityPolicy"

    def deep_copy(self) -> "PodSecurityPolicy":
        return copy.deepcopy(self)


@dataclass
class RuntimeClassScheduling:
    """node/v1 Scheduling: where pods of this class may run."""

    node_selector: Dict[str, str] = field(default_factory=dict)
    tolerations: List[Toleration] = field(default_factory=list)


@dataclass
class RuntimeClass:
    """node/v1 RuntimeClass (reference staging/src/k8s.io/api/node/v1):
    names a container runtime handler; overhead joins the pod's resource
    accounting and scheduling constrains placement — both merged into the
    pod by the RuntimeClass admission plugin."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    handler: str = ""
    overhead: Dict[str, Quantity] = field(default_factory=dict)
    scheduling: Optional[RuntimeClassScheduling] = None
    kind: str = "RuntimeClass"

    def deep_copy(self) -> "RuntimeClass":
        return copy.deepcopy(self)


@dataclass
class ResourceQuotaStatus:
    hard: Dict[str, Quantity] = field(default_factory=dict)
    used: Dict[str, Quantity] = field(default_factory=dict)


@dataclass
class ResourceQuota:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ResourceQuotaSpec = field(default_factory=ResourceQuotaSpec)
    status: ResourceQuotaStatus = field(default_factory=ResourceQuotaStatus)
    kind: str = "ResourceQuota"

    def deep_copy(self) -> "ResourceQuota":
        return copy.deepcopy(self)


# ---------------------------------------------------------------------------
# CustomResourceDefinition + Unstructured (apiextensions-apiserver
# equivalent) — reference staging/src/k8s.io/apiextensions-apiserver/pkg/
# apis/apiextensions/types.go; dynamic clients use unstructured objects
# (apimachinery/pkg/apis/meta/v1/unstructured).
# ---------------------------------------------------------------------------


@dataclass
class CustomResourceDefinitionNames:
    plural: str = ""
    singular: str = ""
    kind: str = ""
    list_kind: str = ""
    short_names: List[str] = field(default_factory=list)


@dataclass
class CustomResourceDefinitionSpec:
    group: str = ""
    names: CustomResourceDefinitionNames = field(
        default_factory=CustomResourceDefinitionNames
    )
    scope: str = "Namespaced"  # or Cluster
    versions: List[str] = field(default_factory=lambda: ["v1"])


@dataclass
class CustomResourceDefinitionStatus:
    accepted_names: CustomResourceDefinitionNames = field(
        default_factory=CustomResourceDefinitionNames
    )
    conditions: List[PodCondition] = field(default_factory=list)


@dataclass
class CustomResourceDefinition:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: CustomResourceDefinitionSpec = field(
        default_factory=CustomResourceDefinitionSpec
    )
    status: CustomResourceDefinitionStatus = field(
        default_factory=CustomResourceDefinitionStatus
    )
    kind: str = "CustomResourceDefinition"

    def deep_copy(self) -> "CustomResourceDefinition":
        return copy.deepcopy(self)


@dataclass
class Unstructured:
    """Schema-less object for custom resources: typed metadata (so the
    store/watch/WAL machinery works unchanged) + raw content for the rest."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    content: Dict[str, Any] = field(default_factory=dict)
    kind: str = ""
    api_version: str = "v1"

    def deep_copy(self) -> "Unstructured":
        return copy.deepcopy(self)


# ---------------------------------------------------------------------------
# APIService (kube-aggregator) — reference
# staging/src/k8s.io/kube-aggregator/pkg/apis/apiregistration/types.go:
# claims a (group, version) and names the backend serving it.
# ---------------------------------------------------------------------------


@dataclass
class APIServiceSpec:
    group: str = ""
    version: str = "v1"
    service_url: str = ""  # backend base URL ("" = served locally)
    priority: int = 100
    # TLS to the backend (kube-aggregator apiservice certs): base64 PEM
    # bundle the proxy verifies https backends against; skip flag mirrors
    # the reference's insecureSkipTLSVerify escape hatch
    ca_bundle: str = ""
    insecure_skip_tls_verify: bool = False


@dataclass
class APIService:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: APIServiceSpec = field(default_factory=APIServiceSpec)
    kind: str = "APIService"

    def deep_copy(self) -> "APIService":
        return copy.deepcopy(self)


# ---------------------------------------------------------------------------
# EndpointSlice (discovery.k8s.io/v1beta1) — reference
# staging/src/k8s.io/api/discovery/v1beta1/types.go; produced by
# pkg/controller/endpointslice with at most 100 endpoints per slice.
# ---------------------------------------------------------------------------


@dataclass
class Endpoint:
    addresses: List[str] = field(default_factory=list)
    ready: bool = True
    target_pod: str = ""  # namespace/name of backing pod
    node_name: str = ""


@dataclass
class EndpointSlice:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    address_type: str = "IPv4"
    endpoints: List[Endpoint] = field(default_factory=list)
    ports: List[Tuple[str, int]] = field(default_factory=list)
    kind: str = "EndpointSlice"

    def deep_copy(self) -> "EndpointSlice":
        return copy.deepcopy(self)


# ---------------------------------------------------------------------------
# VolumeAttachment (storage.k8s.io/v1) — reference
# staging/src/k8s.io/api/storage/v1/types.go; written by the attach-detach
# controller (pkg/controller/volume/attachdetach), consumed by CSI.
# ---------------------------------------------------------------------------


@dataclass
class VolumeAttachmentSpec:
    attacher: str = ""  # driver name
    node_name: str = ""
    pv_name: str = ""  # source.persistentVolumeName


@dataclass
class VolumeAttachmentStatus:
    attached: bool = False


@dataclass
class VolumeAttachment:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: VolumeAttachmentSpec = field(default_factory=VolumeAttachmentSpec)
    status: VolumeAttachmentStatus = field(
        default_factory=VolumeAttachmentStatus
    )
    kind: str = "VolumeAttachment"

    def deep_copy(self) -> "VolumeAttachment":
        return copy.deepcopy(self)


@dataclass
class Eviction:
    """pods/{name}/eviction subresource payload (policy/v1beta1 Eviction;
    reference registry/core/pod/rest/eviction.go): a PDB-respecting delete."""

    pod_name: str = ""
    pod_namespace: str = ""
    kind: str = "Eviction"


# ---------------------------------------------------------------------------
# ReplicationController (core/v1 — the pre-apps ancestor of ReplicaSet) and
# CertificateSigningRequest (certificates.k8s.io/v1beta1)
# ---------------------------------------------------------------------------


@dataclass
class ReplicationController:
    """Same reconcile contract as ReplicaSet (the reference implements both
    with one shared controller core, pkg/controller/replication)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: "ReplicaSetSpec" = None  # shared spec shape
    status: "ReplicaSetStatus" = None
    kind: str = "ReplicationController"

    def __post_init__(self):
        if self.spec is None:
            self.spec = ReplicaSetSpec()
        if self.status is None:
            self.status = ReplicaSetStatus()

    def deep_copy(self) -> "ReplicationController":
        return copy.deepcopy(self)


@dataclass
class CertificateSigningRequestSpec:
    request: str = ""  # CSR payload (opaque in this build; no x509)
    username: str = ""
    groups: List[str] = field(default_factory=list)
    usages: List[str] = field(default_factory=list)
    signer_name: str = "kubernetes.io/kube-apiserver-client-kubelet"


@dataclass
class CertificateSigningRequestStatus:
    conditions: List[PodCondition] = field(default_factory=list)  # Approved/Denied
    certificate: str = ""  # issued credential


@dataclass
class CertificateSigningRequest:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: CertificateSigningRequestSpec = field(
        default_factory=CertificateSigningRequestSpec
    )
    status: CertificateSigningRequestStatus = field(
        default_factory=CertificateSigningRequestStatus
    )
    kind: str = "CertificateSigningRequest"

    def deep_copy(self) -> "CertificateSigningRequest":
        return copy.deepcopy(self)


@dataclass
class LimitRangeItem:
    type: str = "Container"  # Container | Pod
    max: Dict[str, Quantity] = field(default_factory=dict)
    min: Dict[str, Quantity] = field(default_factory=dict)
    default: Dict[str, Quantity] = field(default_factory=dict)  # limits
    default_request: Dict[str, Quantity] = field(default_factory=dict)


@dataclass
class LimitRangeSpec:
    limits: List[LimitRangeItem] = field(default_factory=list)


@dataclass
class LimitRange:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: LimitRangeSpec = field(default_factory=LimitRangeSpec)
    kind: str = "LimitRange"

    def deep_copy(self) -> "LimitRange":
        return copy.deepcopy(self)


# ---------------------------------------------------------------------------
# RBAC (staging/src/k8s.io/api/rbac/v1/types.go): ClusterRole carries
# PolicyRules and optionally an AggregationRule; the aggregation controller
# (pkg/controller/clusterroleaggregation) unions rules of selected roles.


@dataclass
class PolicyRule:
    verbs: List[str] = field(default_factory=list)  # "*" = all
    resources: List[str] = field(default_factory=list)
    resource_names: List[str] = field(default_factory=list)
    api_groups: List[str] = field(default_factory=list)


@dataclass
class AggregationRule:
    cluster_role_selectors: List[LabelSelector] = field(default_factory=list)


@dataclass
class ClusterRole:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    rules: List[PolicyRule] = field(default_factory=list)
    aggregation_rule: Optional[AggregationRule] = None
    kind: str = "ClusterRole"

    def deep_copy(self) -> "ClusterRole":
        return copy.deepcopy(self)


@dataclass
class RoleRef:
    kind: str = "ClusterRole"
    name: str = ""


@dataclass
class Subject:
    kind: str = "User"  # User | Group | ServiceAccount
    name: str = ""
    namespace: str = ""


# ---------------------------------------------------------------------------
# Networking (staging/src/k8s.io/api/networking/v1): served types whose
# enforcement lives out of tree (ingress controllers, CNI plugins) — type
# parity so workloads can declare them and controllers/GC can own them.


@dataclass
class IngressBackend:
    service_name: str = ""
    service_port: int = 0


@dataclass
class IngressPath:
    path: str = "/"
    path_type: str = "Prefix"  # Prefix | Exact
    backend: IngressBackend = field(default_factory=IngressBackend)


@dataclass
class IngressRule:
    host: str = ""
    paths: List[IngressPath] = field(default_factory=list)


@dataclass
class IngressSpec:
    ingress_class_name: Optional[str] = None
    default_backend: Optional[IngressBackend] = None
    rules: List[IngressRule] = field(default_factory=list)


@dataclass
class Ingress:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: IngressSpec = field(default_factory=IngressSpec)
    kind: str = "Ingress"

    def deep_copy(self) -> "Ingress":
        return copy.deepcopy(self)


@dataclass
class IngressClassSpec:
    controller: str = ""  # e.g. "example.com/ingress-controller"


@dataclass
class IngressClass:
    """networking.k8s.io IngressClass (reference v1beta1, 1.18): names an
    ingress controller implementation; the cluster default is marked with
    the ingressclass.kubernetes.io/is-default-class annotation and
    stamped onto classless Ingresses by the DefaultIngressClass admission
    plugin."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: IngressClassSpec = field(default_factory=IngressClassSpec)
    kind: str = "IngressClass"

    def deep_copy(self) -> "IngressClass":
        return copy.deepcopy(self)


@dataclass
class NetworkPolicyPeer:
    pod_selector: Optional[LabelSelector] = None
    namespace_selector: Optional[LabelSelector] = None


@dataclass
class NetworkPolicyRule:
    ports: List[Tuple[str, int]] = field(default_factory=list)  # (proto, port)
    peers: List[NetworkPolicyPeer] = field(default_factory=list)


@dataclass
class NetworkPolicySpec:
    pod_selector: Optional[LabelSelector] = None  # None/empty = all pods
    policy_types: List[str] = field(default_factory=lambda: ["Ingress"])
    ingress: List[NetworkPolicyRule] = field(default_factory=list)
    egress: List[NetworkPolicyRule] = field(default_factory=list)


@dataclass
class NetworkPolicy:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NetworkPolicySpec = field(default_factory=NetworkPolicySpec)
    kind: str = "NetworkPolicy"

    def deep_copy(self) -> "NetworkPolicy":
        return copy.deepcopy(self)


# ---------------------------------------------------------------------------
# Dynamic admission (staging/src/k8s.io/api/admissionregistration/v1):
# webhook configurations consumed by the apiserver's webhook admission.


@dataclass
class WebhookClientConfig:
    url: str = ""  # http(s)://host:port/path — service refs are not modeled


@dataclass
class RuleWithOperations:
    operations: List[str] = field(default_factory=lambda: ["*"])  # CREATE/UPDATE/DELETE/*
    resources: List[str] = field(default_factory=lambda: ["*"])


@dataclass
class Webhook:
    name: str = ""
    client_config: WebhookClientConfig = field(default_factory=WebhookClientConfig)
    rules: List[RuleWithOperations] = field(default_factory=list)
    failure_policy: str = "Fail"  # Fail | Ignore
    timeout_seconds: float = 10.0


@dataclass
class MutatingWebhookConfiguration:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    webhooks: List[Webhook] = field(default_factory=list)
    kind: str = "MutatingWebhookConfiguration"

    def deep_copy(self) -> "MutatingWebhookConfiguration":
        return copy.deepcopy(self)


@dataclass
class ValidatingWebhookConfiguration:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    webhooks: List[Webhook] = field(default_factory=list)
    kind: str = "ValidatingWebhookConfiguration"

    def deep_copy(self) -> "ValidatingWebhookConfiguration":
        return copy.deepcopy(self)


@dataclass
class ClusterRoleBinding:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    role_ref: RoleRef = field(default_factory=RoleRef)
    subjects: List[Subject] = field(default_factory=list)
    kind: str = "ClusterRoleBinding"

    def deep_copy(self) -> "ClusterRoleBinding":
        return copy.deepcopy(self)
