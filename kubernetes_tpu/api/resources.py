"""Resource quantities and resource lists.

Replaces the reference's apimachinery resource.Quantity
(staging/src/k8s.io/apimachinery/pkg/api/resource) with a minimal parser that
covers the forms the scheduler consumes, and the scheduler's internal
Resource accounting (reference pkg/scheduler/nodeinfo/node_info.go:143-153:
MilliCPU, Memory, EphemeralStorage, AllowedPodNumber, ScalarResources).

Everything is normalised at parse time into the units the device kernels use:
  cpu               -> integer millicores   (column MILLI_CPU)
  memory/storage    -> integer bytes        (columns MEMORY / EPHEMERAL_STORAGE)
  pods              -> integer count        (column PODS)
  extended/scalar   -> raw integer value    (per-name extended columns)
"""

from __future__ import annotations

import re
from functools import lru_cache
from typing import Dict, Mapping, Union

# Canonical resource names (reference: v1.ResourceCPU etc.)
CPU = "cpu"
MEMORY = "memory"
EPHEMERAL_STORAGE = "ephemeral-storage"
PODS = "pods"

# Internal accounting column for cpu is millicores.
MILLI_CPU = "cpu"  # stored as millicores internally

# Reference defaults for the "non-zero" request used by scoring when a
# container specifies no request (pkg/scheduler/nodeinfo/node_info.go &
# priorities: DefaultMilliCPURequest=100, DefaultMemoryRequest=200MB).
DEFAULT_MILLI_CPU_REQUEST = 100
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024

_BINARY_SUFFIX = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}
_DECIMAL_SUFFIX = {
    "n": 10**-9,
    "u": 10**-6,
    "m": 10**-3,
    "": 1,
    "k": 10**3,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
    "P": 10**15,
    "E": 10**18,
}

_QTY_RE = re.compile(
    r"^(?P<num>[+-]?\d+(?:\.\d*)?|\.\d+)(?:[eE](?P<exp>[+-]?\d+))?"
    r"(?P<suffix>Ki|Mi|Gi|Ti|Pi|Ei|n|u|m|k|M|G|T|P|E)?$"
)

Quantity = Union[int, float, str]


def parse_quantity(q: Quantity) -> float:
    """Parse a Kubernetes quantity string into a plain float of base units.

    "100m" -> 0.1, "1Gi" -> 1073741824, "2" -> 2.0, 500 -> 500.0.
    """
    if isinstance(q, (int, float)):
        return float(q)
    return _parse_quantity_str(q)


@lru_cache(maxsize=4096)
def _parse_quantity_str(q: str) -> float:
    # quantity strings repeat endlessly ("500m", "1Gi", ...) across pod
    # events — memoized because this sits under every resource computation
    s = q.strip()
    m = _QTY_RE.match(s)
    if not m:
        raise ValueError(f"invalid quantity {q!r}")
    num = float(m.group("num"))
    if m.group("exp"):
        num *= 10 ** int(m.group("exp"))
    suffix = m.group("suffix") or ""
    if suffix in _BINARY_SUFFIX:
        return num * _BINARY_SUFFIX[suffix]
    return num * _DECIMAL_SUFFIX[suffix]


def cpu_to_millis(q: Quantity) -> int:
    """cpu quantity -> integer millicores (ceil, like resource.MilliValue)."""
    v = parse_quantity(q) * 1000.0
    iv = int(v)
    return iv if iv == v else iv + (1 if v > 0 else 0)


def to_int_value(q: Quantity) -> int:
    """Generic quantity -> integer base value (ceil)."""
    v = parse_quantity(q)
    iv = int(v)
    return iv if iv == v else iv + (1 if v > 0 else 0)


class ResourceList(dict):
    """A resource-name -> normalised-integer-amount mapping.

    cpu is stored in millicores; memory/ephemeral-storage in bytes; anything
    else in raw integer units. Mirrors the arithmetic the scheduler does on
    nodeinfo.Resource (Add/SetMaxResource, node_info.go:313,377).
    """

    @classmethod
    def parse(cls, raw: Mapping[str, Quantity] | None) -> "ResourceList":
        out = cls()
        if not raw:
            return out
        for name, q in raw.items():
            if name == CPU:
                out[CPU] = cpu_to_millis(q)
            else:
                out[name] = to_int_value(q)
        return out

    def add(self, other: Mapping[str, int]) -> "ResourceList":
        for k, v in other.items():
            self[k] = self.get(k, 0) + v
        return self

    def sub(self, other: Mapping[str, int]) -> "ResourceList":
        for k, v in other.items():
            self[k] = self.get(k, 0) - v
        return self

    def set_max(self, other: Mapping[str, int]) -> "ResourceList":
        """Element-wise max (init-container semantics, node_info.go:377)."""
        for k, v in other.items():
            self[k] = max(self.get(k, 0), v)
        return self

    def copy(self) -> "ResourceList":
        return ResourceList(self)


def is_extended_resource(name: str) -> bool:
    return name not in (CPU, MEMORY, EPHEMERAL_STORAGE, PODS)
