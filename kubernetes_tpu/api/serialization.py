"""Reflective JSON codec for the API object model.

The apimachinery serializer role (reference
staging/src/k8s.io/apimachinery/pkg/runtime/serializer/json): dataclasses
⇄ Kubernetes-style camelCase JSON. Field names convert snake_case →
lowerCamelCase; nested dataclasses, tuples, lists, dicts and Optionals
recurse; zero/empty values are omitted on output (omitempty).

The kind registry maps REST resource names ("pods") and JSON `kind`
strings ("Pod") to classes, standing in for runtime.Scheme's GVK mapping.
"""

from __future__ import annotations

import base64
import dataclasses
import typing
from typing import Any, Dict, Optional, Type, get_args, get_origin, get_type_hints

from . import objects as v1

# resource name -> (kind string, class)
RESOURCE_KINDS: Dict[str, Type] = {
    "pods": v1.Pod,
    "nodes": v1.Node,
    "services": v1.Service,
    "persistentvolumes": v1.PersistentVolume,
    "persistentvolumeclaims": v1.PersistentVolumeClaim,
    "storageclasses": v1.StorageClass,
    "csinodes": v1.CSINode,
    "bindings": v1.Binding,
    "namespaces": v1.Namespace,
    "replicasets": v1.ReplicaSet,
    "deployments": v1.Deployment,
    "jobs": v1.Job,
    "daemonsets": v1.DaemonSet,
    "statefulsets": v1.StatefulSet,
    "poddisruptionbudgets": v1.PodDisruptionBudget,
    "endpoints": v1.Endpoints,
    "priorityclasses": v1.PriorityClass,
    "configmaps": v1.ConfigMap,
    "secrets": v1.Secret,
    "serviceaccounts": v1.ServiceAccount,
    "horizontalpodautoscalers": v1.HorizontalPodAutoscaler,
    "cronjobs": v1.CronJob,
    "resourcequotas": v1.ResourceQuota,
    "customresourcedefinitions": v1.CustomResourceDefinition,
    "apiservices": v1.APIService,
    "endpointslices": v1.EndpointSlice,
    "volumeattachments": v1.VolumeAttachment,
    "replicationcontrollers": v1.ReplicationController,
    "certificatesigningrequests": v1.CertificateSigningRequest,
    "limitranges": v1.LimitRange,
    "clusterroles": v1.ClusterRole,
    "clusterrolebindings": v1.ClusterRoleBinding,
    "mutatingwebhookconfigurations": v1.MutatingWebhookConfiguration,
    "validatingwebhookconfigurations": v1.ValidatingWebhookConfiguration,
    "ingresses": v1.Ingress,
    "ingressclasses": v1.IngressClass,
    "networkpolicies": v1.NetworkPolicy,
    "podsecuritypolicies": v1.PodSecurityPolicy,
    "runtimeclasses": v1.RuntimeClass,
}

# Cluster-scoped resources: the store normalizes their namespace to ""
# ONCE at the write boundary (client/apiserver.py), so an object decoded
# from a plain manifest (ObjectMeta defaults namespace to "default") and
# one created namespace-less land under the SAME key — consumers never
# probe both spellings. kubectl shares this set for its path routing.
CLUSTER_SCOPED = frozenset(
    {
        "nodes",
        "persistentvolumes",
        "storageclasses",
        "csinodes",
        "namespaces",
        "priorityclasses",
        "customresourcedefinitions",
        "apiservices",
        "clusterroles",
        "clusterrolebindings",
        "mutatingwebhookconfigurations",
        "validatingwebhookconfigurations",
        "certificatesigningrequests",
        "runtimeclasses",
        "podsecuritypolicies",
        "ingressclasses",
        "scorepolicies",
    }
)

KIND_TO_RESOURCE = {
    cls.__name__: res for res, cls in RESOURCE_KINDS.items()
}


def register_kind(resource: str, cls: Type) -> None:
    RESOURCE_KINDS[resource] = cls
    KIND_TO_RESOURCE[cls.__name__] = resource


def _camel(name: str) -> str:
    parts = name.split("_")
    return parts[0] + "".join(p.capitalize() for p in parts[1:])


def _snake(name: str) -> str:
    out = []
    for ch in name:
        if ch.isupper():
            out.append("_")
            out.append(ch.lower())
        else:
            out.append(ch)
    return "".join(out)


def to_dict(obj: Any) -> Any:
    """Dataclass → JSON-ready dict (camelCase keys, omitempty)."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in dataclasses.fields(obj):
            val = getattr(obj, f.name)
            # omitempty: skip values equal to the field default (and empty
            # containers from default factories)
            if f.default is not dataclasses.MISSING and val == f.default:
                continue
            enc = to_dict(val)
            if enc is None or enc == {} or enc == []:
                continue
            if enc == "" and (
                f.default is dataclasses.MISSING or f.default == ""
            ):
                # an explicit empty string that differs from a non-empty
                # default is meaningful (e.g. cluster-scoped namespace="")
                continue
            out[_camel(f.name)] = enc
        return out
    if isinstance(obj, (list, tuple)):
        return [to_dict(x) for x in obj]
    if isinstance(obj, frozenset):
        return sorted(obj)
    if isinstance(obj, dict):
        return {k: to_dict(val) for k, val in obj.items()}
    if isinstance(obj, bytes):
        # Secret.data wire form is base64 (the k8s []byte convention)
        return base64.b64encode(obj).decode("ascii")
    return obj


def _resolve_optional(tp):
    if get_origin(tp) is typing.Union:
        args = [a for a in get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def from_dict(cls: Type, data: Any) -> Any:
    """JSON dict → dataclass instance (inverse of to_dict)."""
    if data is None:
        return None
    cls = _resolve_optional(cls)
    if isinstance(cls, str):  # unresolved forward ref — shouldn't happen
        raise TypeError(f"unresolved type {cls}")
    origin = get_origin(cls)
    if origin in (list, tuple):
        (item_tp, *_rest) = get_args(cls) or (Any,)
        seq = [from_dict(item_tp, x) for x in data]
        return tuple(seq) if origin is tuple else seq
    if origin is dict:
        _k, val_tp = get_args(cls) or (str, Any)
        return {k: from_dict(val_tp, val) for k, val in data.items()}
    if origin is typing.Union:
        resolved = _resolve_optional(cls)
        if get_origin(resolved) is typing.Union:
            # scalar union (e.g. Quantity = str|int|float): pass through
            return data
        return from_dict(resolved, data)
    if dataclasses.is_dataclass(cls):
        hints = get_type_hints(cls)
        kwargs = {}
        for f in dataclasses.fields(cls):
            camel = _camel(f.name)
            if camel in data:
                raw = data[camel]
            elif f.name in data:
                raw = data[f.name]
            else:
                continue
            kwargs[f.name] = from_dict(hints[f.name], raw)
        return cls(**kwargs)
    if cls in (Any, object):
        return data
    if cls is float and isinstance(data, int):
        return float(data)
    if cls is bytes and isinstance(data, str):
        return base64.b64decode(data)
    return data


def decode(resource: str, data: dict, allow_unstructured: bool = True) -> Any:
    """JSON body → typed object for a REST resource. Unknown resources
    decode as Unstructured (custom resources — the REST layer gates which
    unknown resources are actually served; the WAL replays them blindly)."""
    cls = RESOURCE_KINDS.get(resource)
    if cls is None:
        ensure_late_registration()  # import-order hole: see its docstring
        cls = RESOURCE_KINDS.get(resource)
    if cls is None:
        if allow_unstructured:
            return decode_unstructured(data)
        raise KeyError(f"unknown resource {resource!r}")
    return from_dict(cls, data)


def decode_any(data: dict) -> Any:
    """JSON body with a `kind` field → (resource, typed object). Documents
    at a registered NON-internal version (e.g. discovery.k8s.io/v1
    EndpointSlice) convert through the scheme's to-internal hop first
    (api/scheme.py)."""
    kind = data.get("kind", "")
    api_version = data.get("apiVersion", "")
    if api_version and "/" in api_version:
        from .scheme import scheme

        if scheme.recognizes(api_version, kind):
            return scheme.decode(data)
    resource = KIND_TO_RESOURCE.get(kind)
    if resource is None:
        ensure_late_registration()  # import-order hole: see its docstring
        resource = KIND_TO_RESOURCE.get(kind)
    if resource is None:
        raise KeyError(f"unknown kind {kind!r}")
    return resource, from_dict(RESOURCE_KINDS[resource], data)


def encode(obj: Any) -> dict:
    if isinstance(obj, v1.Unstructured):
        # custom resources round-trip their raw content; typed metadata is
        # re-attached under the standard key
        d = dict(obj.content)
        d["metadata"] = to_dict(obj.metadata)
        d["kind"] = obj.kind or "Unstructured"
        d["apiVersion"] = obj.api_version
        return d
    d = to_dict(obj)
    if isinstance(d, dict):
        d.setdefault("kind", type(obj).__name__)
        d.setdefault("apiVersion", "v1")
    return d


def decode_unstructured(data: dict) -> v1.Unstructured:
    """JSON body → Unstructured (dynamic-client path for CRD resources)."""
    meta = from_dict(v1.ObjectMeta, data.get("metadata", {}) or {})
    content = {
        k: val
        for k, val in data.items()
        if k not in ("metadata", "kind", "apiVersion")
    }
    return v1.Unstructured(
        metadata=meta,
        content=content,
        kind=data.get("kind", ""),
        api_version=data.get("apiVersion", "v1"),
    )


_late_registered = False


def ensure_late_registration() -> None:
    """Register the kinds that live in client/* (events, leases) —
    idempotent, safe to call from any lookup path. The import-time call
    below succeeds in most processes, but when THIS module is first
    imported via kubernetes_tpu.client's own import chain (e.g. a child
    process whose first touch is ``import kubernetes_tpu.client``), the
    client package is mid-import and the ImportError is swallowed — the
    lease kind would then silently decode as Unstructured forever (found
    by the netchaos multi-process suite: the REST elector's lease came
    back untyped and the renew thread died). Lookup paths (decode,
    decode_any, the REST serving gate) retry here on a miss."""
    global _late_registered
    if _late_registered:
        return
    try:
        from ..client.events import ClusterEvent
        from ..client.leaderelection import Lease
        from ..tuner.policy import ScorePolicy
    except ImportError:
        return
    RESOURCE_KINDS["events"] = ClusterEvent
    KIND_TO_RESOURCE["ClusterEvent"] = "events"
    KIND_TO_RESOURCE["Event"] = "events"
    RESOURCE_KINDS["leases"] = Lease
    KIND_TO_RESOURCE["Lease"] = "leases"
    RESOURCE_KINDS["scorepolicies"] = ScorePolicy
    KIND_TO_RESOURCE["ScorePolicy"] = "scorepolicies"
    _late_registered = True


ensure_late_registration()
