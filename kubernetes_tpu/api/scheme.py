"""Versioned scheme: GVK registry + hub-and-spoke conversion.

Reference: staging/src/k8s.io/apimachinery/pkg/runtime/scheme.go — types
register under (group, version, kind); conversion goes external-version ⇄
internal hub, so N versions need N converters, not N². This build keeps
ONE internal Python type per kind (the deliberate single-internal-version
choice, SURVEY §1 L2) and performs conversion at the WIRE-DICT level: an
external document is reshaped to the internal wire form before the codec's
from_dict, and an internal object reshapes on the way out when a target
version is requested.

The worked multi-version case is discovery.k8s.io EndpointSlice:
v1beta1 (the internal shape: endpoint.ready bool, topology map) and v1
(endpoint.conditions.ready, nodeName field, zone) — the same field moves
the reference's v1beta1→v1 graduation made.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

from . import serialization as codec

Converter = Callable[[dict], dict]  # wire dict -> wire dict


class Scheme:
    """GVK registry + converters (runtime.Scheme-lite)."""

    def __init__(self):
        # (group, version, kind) -> resource name
        self._gvk: Dict[Tuple[str, str, str], str] = {}
        # (group, version, kind) -> (to_internal, from_internal)
        self._convert: Dict[Tuple[str, str, str], Tuple[Converter, Converter]] = {}
        # group -> ordered versions, most preferred first
        self._versions: Dict[str, list] = {}

    def add_known_type(
        self,
        group: str,
        version: str,
        kind: str,
        resource: str,
        to_internal: Optional[Converter] = None,
        from_internal: Optional[Converter] = None,
    ) -> None:
        key = (group, version, kind)
        self._gvk[key] = resource
        ident = lambda d: d  # noqa: E731
        self._convert[key] = (to_internal or ident, from_internal or ident)
        self._versions.setdefault(group, [])
        if version not in self._versions[group]:
            self._versions[group].append(version)

    def prioritized_versions(self, group: str) -> list:
        return list(self._versions.get(group, []))

    @staticmethod
    def parse_api_version(api_version: str) -> Tuple[str, str]:
        """"discovery.k8s.io/v1" -> (group, version); "v1" -> ("", "v1")."""
        if "/" in api_version:
            g, _, v = api_version.partition("/")
            return g, v
        return "", api_version

    def recognizes(self, api_version: str, kind: str) -> bool:
        g, v = self.parse_api_version(api_version)
        return (g, v, kind) in self._gvk

    def decode(self, data: dict) -> Tuple[str, Any]:
        """External wire document -> (resource, internal typed object)."""
        api_version = data.get("apiVersion", "")
        kind = data.get("kind", "")
        g, v = self.parse_api_version(api_version)
        key = (g, v, kind)
        if key not in self._gvk:
            raise KeyError(f"no kind registered for {api_version}/{kind}")
        resource = self._gvk[key]
        to_internal, _ = self._convert[key]
        return resource, codec.decode(resource, to_internal(dict(data)))

    def encode(self, obj: Any, api_version: Optional[str] = None) -> dict:
        """Internal object -> wire document at `api_version` (default: the
        object's own/internal form)."""
        doc = codec.encode(obj)
        if api_version is None:
            return doc
        g, v = self.parse_api_version(api_version)
        kind = doc.get("kind", type(obj).__name__)
        key = (g, v, kind)
        if key not in self._convert:
            raise KeyError(f"no conversion to {api_version} for {kind}")
        _, from_internal = self._convert[key]
        out = from_internal(doc)
        out["apiVersion"] = api_version
        return out


# ---------------------------------------------------------------------------
# the default scheme: every served resource at its internal version, plus
# the EndpointSlice v1beta1/v1 pair as the worked conversion example
# ---------------------------------------------------------------------------


def _slice_v1_to_internal(doc: dict) -> dict:
    """discovery.k8s.io/v1 -> internal (v1beta1-shaped): conditions.ready
    flattens to ready, nodeName stays (internal carries it)."""
    out = dict(doc)
    eps = []
    for ep in doc.get("endpoints", []) or []:
        ep = dict(ep)
        conds = ep.pop("conditions", None)
        if conds is not None and "ready" not in ep:
            # nil-means-ready (v1 conditions.ready is *bool; nil endpoints
            # must be treated as serving for backward compatibility)
            r = conds.get("ready")
            ep["ready"] = True if r is None else bool(r)
        ep.pop("zone", None)  # internal has no zone field (topology-lite)
        eps.append(ep)
    out["endpoints"] = eps
    return out


def _slice_internal_to_v1(doc: dict) -> dict:
    """internal -> discovery.k8s.io/v1: ready nests under conditions."""
    out = dict(doc)
    eps = []
    for ep in doc.get("endpoints", []) or []:
        ep = dict(ep)
        ready = ep.pop("ready", True)
        ep["conditions"] = {"ready": bool(ready)}
        eps.append(ep)
    out["endpoints"] = eps
    return out


def _ingress_v1_backend_to_internal(b: Optional[dict]) -> Optional[dict]:
    """networking/v1 IngressBackend {service:{name,port:{number|name}}}
    -> internal flat {serviceName, servicePort} (the v1beta1 shape the
    internal type keeps; reference conversion in
    pkg/apis/networking/v1beta1 zz_generated.conversion)."""
    if not b:
        return b
    svc = b.get("service") or {}
    port = svc.get("port") or {}
    return {
        "serviceName": svc.get("name", ""),
        "servicePort": port.get("number") or port.get("name") or 0,
    }


def _ingress_internal_backend_to_v1(b: Optional[dict]) -> Optional[dict]:
    if not b:
        return b
    port = b.get("servicePort", 0)
    key = "number" if isinstance(port, int) else "name"
    return {"service": {"name": b.get("serviceName", ""), "port": {key: port}}}


def _ingress_v1_to_internal(doc: dict) -> dict:
    out = dict(doc)
    spec = dict(doc.get("spec", {}) or {})
    if "defaultBackend" in spec:
        spec["defaultBackend"] = _ingress_v1_backend_to_internal(
            spec["defaultBackend"]
        )
    rules = []
    for rule in spec.get("rules", []) or []:
        rule = dict(rule)
        # v1 nests paths under http.paths; internal keeps them flat
        http = rule.pop("http", None)
        paths = []
        for p in (http or {}).get("paths", []) or rule.get("paths", []) or []:
            p = dict(p)
            if "backend" in p:
                p["backend"] = _ingress_v1_backend_to_internal(p["backend"])
            paths.append(p)
        rule["paths"] = paths
        rules.append(rule)
    spec["rules"] = rules
    out["spec"] = spec
    return out


def _ingress_internal_to_v1(doc: dict) -> dict:
    out = dict(doc)
    spec = dict(doc.get("spec", {}) or {})
    if "defaultBackend" in spec:
        spec["defaultBackend"] = _ingress_internal_backend_to_v1(
            spec["defaultBackend"]
        )
    rules = []
    for rule in spec.get("rules", []) or []:
        rule = dict(rule)
        paths = []
        for p in rule.pop("paths", []) or []:
            p = dict(p)
            if "backend" in p:
                p["backend"] = _ingress_internal_backend_to_v1(p["backend"])
            paths.append(p)
        rule["http"] = {"paths": paths}
        rules.append(rule)
    spec["rules"] = rules
    out["spec"] = spec
    return out


def default_scheme() -> Scheme:
    s = Scheme()
    # core group: internal == v1 wire form (identity conversions)
    for resource, cls in codec.RESOURCE_KINDS.items():
        s.add_known_type("", "v1", cls.__name__, resource)
    # the multi-version pair (v1 preferred, v1beta1 served)
    s.add_known_type(
        "discovery.k8s.io",
        "v1",
        "EndpointSlice",
        "endpointslices",
        to_internal=_slice_v1_to_internal,
        from_internal=_slice_internal_to_v1,
    )
    s.add_known_type(
        "discovery.k8s.io", "v1beta1", "EndpointSlice", "endpointslices"
    )
    # Ingress: internal keeps the v1beta1 flat backend; networking/v1 is
    # the conversion spoke with the nested service backend + http.paths
    # (the real v1beta1->v1 graduation's field moves)
    s.add_known_type(
        "networking.k8s.io",
        "v1",
        "Ingress",
        "ingresses",
        to_internal=_ingress_v1_to_internal,
        from_internal=_ingress_internal_to_v1,
    )
    s.add_known_type("networking.k8s.io", "v1beta1", "Ingress", "ingresses")
    s.add_known_type("extensions", "v1beta1", "Ingress", "ingresses")
    # schema-identical graduations: both versions serve the internal shape
    s.add_known_type("batch", "v1", "CronJob", "cronjobs")
    s.add_known_type("batch", "v1beta1", "CronJob", "cronjobs")
    s.add_known_type("policy", "v1", "PodDisruptionBudget", "poddisruptionbudgets")
    s.add_known_type(
        "policy", "v1beta1", "PodDisruptionBudget", "poddisruptionbudgets"
    )
    return s


scheme = default_scheme()
