"""Label selectors and host-side matching.

The moral equivalent of apimachinery's labels.Selector / metav1.LabelSelector
(staging/src/k8s.io/apimachinery/pkg/labels, pkg/apis/meta/v1/types.go).
Selectors here are plain data with a canonical key so they can be interned
into the device-side selector vocabulary (see ops/encoding.py): per-node
match-count tensors are maintained per interned selector, which is how
InterPodAffinity / PodTopologySpread matching becomes integer gathers on TPU.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

# Operators (metav1.LabelSelectorOperator + node-selector extras)
OP_IN = "In"
OP_NOT_IN = "NotIn"
OP_EXISTS = "Exists"
OP_DOES_NOT_EXIST = "DoesNotExist"
OP_GT = "Gt"  # node-selector only
OP_LT = "Lt"  # node-selector only


@dataclass(frozen=True)
class Requirement:
    key: str
    operator: str
    values: Tuple[str, ...] = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        has = self.key in labels
        if self.operator == OP_IN:
            return has and labels[self.key] in self.values
        if self.operator == OP_NOT_IN:
            # metav1 semantics via LabelSelectorAsSelector: NotIn requires ...
            # labels.Selector semantics: NotIn matches if key absent OR value
            # not in set (apimachinery labels/selector.go Matches).
            return (not has) or labels[self.key] not in self.values
        if self.operator == OP_EXISTS:
            return has
        if self.operator == OP_DOES_NOT_EXIST:
            return not has
        if self.operator in (OP_GT, OP_LT):
            if not has:
                return False
            try:
                lv = int(labels[self.key])
                rv = int(self.values[0])
            except (ValueError, IndexError):
                return False
            return lv > rv if self.operator == OP_GT else lv < rv
        raise ValueError(f"unknown operator {self.operator!r}")


@dataclass(frozen=True)
class LabelSelector:
    """metav1.LabelSelector: AND of match_labels and match_expressions.

    An empty selector matches everything; None (no selector) matches nothing
    — callers encode that distinction themselves, mirroring
    LabelSelectorAsSelector.
    """

    match_labels: Tuple[Tuple[str, str], ...] = ()
    match_expressions: Tuple[Requirement, ...] = ()

    @classmethod
    def make(
        cls,
        match_labels: Optional[Mapping[str, str]] = None,
        match_expressions: Sequence[Requirement] = (),
    ) -> "LabelSelector":
        ml = tuple(sorted((match_labels or {}).items()))
        return cls(match_labels=ml, match_expressions=tuple(match_expressions))

    def matches(self, labels: Mapping[str, str]) -> bool:
        for k, v in self.match_labels:
            if labels.get(k) != v:
                return False
        for r in self.match_expressions:
            if not r.matches(labels):
                return False
        return True

    @property
    def is_empty(self) -> bool:
        return not self.match_labels and not self.match_expressions

    def canonical(self) -> Tuple:
        """Hashable canonical form used for selector interning."""
        return (
            self.match_labels,
            tuple(
                (r.key, r.operator, tuple(sorted(r.values)))
                for r in sorted(
                    self.match_expressions, key=lambda r: (r.key, r.operator)
                )
            ),
        )


def selector_from_match_labels(labels: Mapping[str, str]) -> LabelSelector:
    return LabelSelector.make(match_labels=dict(labels))


def labels_match_selector(
    labels: Mapping[str, str], selector: Optional[LabelSelector]
) -> bool:
    """None selector matches nothing (LabelSelectorAsSelector(nil))."""
    if selector is None:
        return False
    return selector.matches(labels)


def match_labels(selector: Mapping[str, str], labels: Mapping[str, str]) -> bool:
    """matchLabels subset semantics (labels.SelectorFromSet): EMPTY selector
    matches EVERYTHING — metav1.LabelSelector{} selects all pods, the
    convention PDBs and controllers rely on. Shared by the controllers and
    the preemptor so budget accounting and victim filtering can't diverge."""
    return all(labels.get(k) == val for k, val in selector.items())


# ---------------------------------------------------------------------------
# Selector-string parsing + field selectors (apimachinery pkg/labels
# Parse and pkg/fields): the ?labelSelector= / ?fieldSelector= list-option
# surface. Field selectors here are GENERIC dotted paths over the object
# (camelCase, as on the wire) — a superset of the reference's
# per-resource allowlists (spec.nodeName, status.phase, metadata.name...),
# so every reference-legal selector works.
# ---------------------------------------------------------------------------


def parse_label_selector(s: str) -> LabelSelector:
    """"a=b,c!=d,e,f in (x,y),!g" -> LabelSelector. ValueError on syntax
    errors (maps to 400 at the REST boundary)."""
    import re as _re

    labels = {}
    exprs = []
    s = (s or "").strip()
    # split on commas NOT inside parentheses
    terms = _re.split(r",(?![^(]*\))", s) if s else []
    for term in terms:
        term = term.strip()
        if not term:
            continue
        m = _re.match(r"^(\S+)\s+(in|notin)\s+\(([^)]*)\)$", term)
        if m:
            vals = tuple(v.strip() for v in m.group(3).split(",") if v.strip())
            op = OP_IN if m.group(2) == "in" else OP_NOT_IN
            exprs.append(Requirement(m.group(1), op, vals))
        elif "!=" in term:
            k, _, v = term.partition("!=")
            exprs.append(Requirement(k.strip(), OP_NOT_IN, (v.strip(),)))
        elif "==" in term or "=" in term:
            k, _, v = term.partition("==") if "==" in term else term.partition("=")
            k, v = k.strip(), v.strip()
            if not _re.match(r"^[\w.\-/]+$", k) or not _re.match(
                r"^[\w.\-]*$", v
            ):
                raise ValueError(f"bad label selector term {term!r}")
            labels[k] = v
        elif term.startswith("!"):
            exprs.append(Requirement(term[1:].strip(), OP_DOES_NOT_EXIST))
        elif _re.match(r"^[\w.\-/]+$", term):
            exprs.append(Requirement(term, OP_EXISTS))
        else:
            raise ValueError(f"bad label selector term {term!r}")
    return LabelSelector.make(match_labels=labels, match_expressions=exprs)


@dataclass(frozen=True)
class FieldSelector:
    """Parsed ?fieldSelector=: AND of (dotted path, op, value) terms with
    op '=' or '!='. Values compare as strings (fields.Set semantics)."""

    terms: Tuple[Tuple[str, str, str], ...] = ()

    @classmethod
    def parse(cls, s: str) -> "FieldSelector":
        terms = []
        for term in (s or "").split(","):
            term = term.strip()
            if not term:
                continue
            if "!=" in term:
                path, _, v = term.partition("!=")
                op = "!="
            elif "==" in term:
                path, _, v = term.partition("==")
                op = "="
            elif "=" in term:
                path, _, v = term.partition("=")
                op = "="
            else:
                raise ValueError(f"bad field selector term {term!r}")
            if not path.strip():
                raise ValueError(f"bad field selector term {term!r}")
            terms.append((path.strip(), op, v.strip()))
        return cls(terms=tuple(terms))

    @property
    def is_empty(self) -> bool:
        return not self.terms

    @staticmethod
    def _lookup(obj, path: str) -> str:
        from .serialization import _snake

        cur = obj
        for seg in path.split("."):
            if cur is None:
                return ""
            if isinstance(cur, Mapping):
                cur = cur.get(seg)
                continue
            cur = getattr(cur, _snake(seg), None)
        if cur is None or cur is False:
            return "" if cur is None else "false"
        if cur is True:
            return "true"
        return str(cur)

    def matches(self, obj) -> bool:
        for path, op, want in self.terms:
            got = self._lookup(obj, path)
            if op == "=" and got != want:
                return False
            if op == "!=" and got == want:
                return False
        return True
