"""Label selectors and host-side matching.

The moral equivalent of apimachinery's labels.Selector / metav1.LabelSelector
(staging/src/k8s.io/apimachinery/pkg/labels, pkg/apis/meta/v1/types.go).
Selectors here are plain data with a canonical key so they can be interned
into the device-side selector vocabulary (see ops/encoding.py): per-node
match-count tensors are maintained per interned selector, which is how
InterPodAffinity / PodTopologySpread matching becomes integer gathers on TPU.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

# Operators (metav1.LabelSelectorOperator + node-selector extras)
OP_IN = "In"
OP_NOT_IN = "NotIn"
OP_EXISTS = "Exists"
OP_DOES_NOT_EXIST = "DoesNotExist"
OP_GT = "Gt"  # node-selector only
OP_LT = "Lt"  # node-selector only


@dataclass(frozen=True)
class Requirement:
    key: str
    operator: str
    values: Tuple[str, ...] = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        has = self.key in labels
        if self.operator == OP_IN:
            return has and labels[self.key] in self.values
        if self.operator == OP_NOT_IN:
            # metav1 semantics via LabelSelectorAsSelector: NotIn requires ...
            # labels.Selector semantics: NotIn matches if key absent OR value
            # not in set (apimachinery labels/selector.go Matches).
            return (not has) or labels[self.key] not in self.values
        if self.operator == OP_EXISTS:
            return has
        if self.operator == OP_DOES_NOT_EXIST:
            return not has
        if self.operator in (OP_GT, OP_LT):
            if not has:
                return False
            try:
                lv = int(labels[self.key])
                rv = int(self.values[0])
            except (ValueError, IndexError):
                return False
            return lv > rv if self.operator == OP_GT else lv < rv
        raise ValueError(f"unknown operator {self.operator!r}")


@dataclass(frozen=True)
class LabelSelector:
    """metav1.LabelSelector: AND of match_labels and match_expressions.

    An empty selector matches everything; None (no selector) matches nothing
    — callers encode that distinction themselves, mirroring
    LabelSelectorAsSelector.
    """

    match_labels: Tuple[Tuple[str, str], ...] = ()
    match_expressions: Tuple[Requirement, ...] = ()

    @classmethod
    def make(
        cls,
        match_labels: Optional[Mapping[str, str]] = None,
        match_expressions: Sequence[Requirement] = (),
    ) -> "LabelSelector":
        ml = tuple(sorted((match_labels or {}).items()))
        return cls(match_labels=ml, match_expressions=tuple(match_expressions))

    def matches(self, labels: Mapping[str, str]) -> bool:
        for k, v in self.match_labels:
            if labels.get(k) != v:
                return False
        for r in self.match_expressions:
            if not r.matches(labels):
                return False
        return True

    @property
    def is_empty(self) -> bool:
        return not self.match_labels and not self.match_expressions

    def canonical(self) -> Tuple:
        """Hashable canonical form used for selector interning."""
        return (
            self.match_labels,
            tuple(
                (r.key, r.operator, tuple(sorted(r.values)))
                for r in sorted(
                    self.match_expressions, key=lambda r: (r.key, r.operator)
                )
            ),
        )


def selector_from_match_labels(labels: Mapping[str, str]) -> LabelSelector:
    return LabelSelector.make(match_labels=dict(labels))


def labels_match_selector(
    labels: Mapping[str, str], selector: Optional[LabelSelector]
) -> bool:
    """None selector matches nothing (LabelSelectorAsSelector(nil))."""
    if selector is None:
        return False
    return selector.matches(labels)


def match_labels(selector: Mapping[str, str], labels: Mapping[str, str]) -> bool:
    """matchLabels subset semantics (labels.SelectorFromSet): EMPTY selector
    matches EVERYTHING — metav1.LabelSelector{} selects all pods, the
    convention PDBs and controllers rely on. Shared by the controllers and
    the preemptor so budget accounting and victim filtering can't diverge."""
    return all(labels.get(k) == val for k, val in selector.items())
