"""Gym replay + outcome scoring: the measurement half of the tuner.

Replay rides the exact seam the autoscaler's WhatIfSimulator proved out
(autoscaler/planner.py): under the cache lock, encode the pod batch
FIRST (vocab interning settles capacities), then take a
``whatif_overlay`` copy of the live snapshot — alias-free, shares no
buffers with live state, never installed, never donated — and run the
PRODUCTION serial batch kernel (``make_schedule_batch``, the
non-donating variant) against it outside the lock. Weights are a kernel
INPUT: K candidates is K cheap re-launches of one compiled program over
one overlay, never a recompile.

Scoring is host-side arithmetic over one device readback per pass:
placed fraction (the time-to-bound proxy — an unplaced pod pays queue +
preemption latency), stranded-capacity fragmentation, preemption
pressure (unplaced count), and $-per-hour / energy from the PR-15
heterogeneity columns. Utility is a fixed bounded combination so a
noise floor is meaningful across windows.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

# utility mix: placed fraction dominates (a vector that fails to place
# traffic is worse than any amount of cost polish), then consolidation,
# then the $-and-watts opt-ins
FRAG_WEIGHT = 0.15
COST_WEIGHT = 0.20
ENERGY_WEIGHT = 0.10


def fragmentation_score(
    free_frac: np.ndarray, used_any: np.ndarray, valid: np.ndarray
) -> float:
    """Stranded-capacity fragmentation in [0, 1]: free capacity sitting
    on PARTIALLY used nodes / total free capacity. A consolidating
    placement leaves whole nodes empty (gang-sized holes survive); a
    smearing one strands its slack. ONE definition shared by the gym's
    outcome scoring (score_assignment), the live-fleet gauge
    (Scheduler.fragmentation_score), and the descheduler's planning
    signal — inputs come from SnapshotEncoder.utilization_stats or the
    gym's overlay columns, both derived from the same masters."""
    valid = np.asarray(valid, bool)
    stranded_mask = np.asarray(used_any, bool) & valid
    total_free = float(free_frac[valid].sum())
    stranded = float(free_frac[stranded_mask].sum())
    return stranded / total_free if total_free > 0 else 0.0


@dataclass
class WaveOutcome:
    """Scored outcome of one (replayed or production) wave placement."""

    placed: int
    total: int
    fragmentation: float  # stranded free capacity fraction, [0, 1]
    preempt_pressure: int  # unplaced pods (they go on to preempt/queue)
    cost_norm: float  # mean chosen-node cost / fleet max, [0, 1]
    energy_norm: float  # mean chosen-node energy / fleet max, [0, 1]
    utility: float

    @property
    def placed_frac(self) -> float:
        return self.placed / self.total if self.total else 1.0


@dataclass
class OverlaySnapshot:
    """One overlay + encoded batch, shared by every candidate launch of
    a gym pass, plus the host-side columns scoring needs (fetched
    once)."""

    snap: Any  # DeviceSnapshot overlay copy (never donated)
    batch: Any  # device PodBatch
    pod_valid: np.ndarray  # [P] bool — encoded and not fallback
    req: np.ndarray  # [P, R] host copy
    row_names: List[Optional[str]]
    v_cap: int
    node_valid: np.ndarray  # [N] bool
    free0: np.ndarray  # [N, R] allocatable - requested (pre-placement)
    alloc: np.ndarray  # [N, R]
    cost_milli: np.ndarray  # [N]
    energy_milli: np.ndarray  # [N]
    accel_class: np.ndarray  # [N] interned class id, -1 unlabeled


def pad_pow2(n: int) -> int:
    """The serial path's pad rule (Scheduler._pad): replay must encode
    with the same pad so differential replays share compiled shapes with
    production."""
    p = 1
    while p < max(1, n):
        p *= 2
    return p


def build_overlay(cache, pods: Sequence[Any]) -> Optional[OverlaySnapshot]:
    """Encode ``pods`` and take an isolated overlay of the live snapshot.
    Caller does NOT hold the cache lock. None when the encoder can't
    host the overlay (no free capacity — the gym skips the pass)."""
    import jax

    from ..ops.batch import encode_pod_batch

    with cache.lock:
        enc = cache.encoder
        eb = encode_pod_batch(enc, list(pods), pad_to=pad_pow2(len(pods)))
        ov = enc.whatif_overlay([])
        if ov is None:
            return None
        snap, _rows = ov
        row_names = list(enc.row_names)
        v_cap = enc.cfg.v_cap
    # ONE host fetch per pass, shared by every candidate's scoring
    requested, allocatable, node_valid, cost, energy, accel, req = (
        jax.device_get(
            (
                snap.requested,
                snap.allocatable,
                snap.valid,
                snap.cost_milli,
                snap.energy_milli,
                snap.accel_class,
                eb.batch.req,
            )
        )
    )
    req = np.asarray(req)
    pod_valid = np.zeros(req.shape[0], bool)
    pod_valid[: len(pods)] = True
    pod_valid[: len(pods)] &= ~np.asarray(eb.fallback[: len(pods)], bool)
    return OverlaySnapshot(
        snap=snap,
        batch=eb.batch,
        pod_valid=pod_valid,
        req=req,
        row_names=row_names,
        v_cap=v_cap,
        node_valid=np.asarray(node_valid, bool),
        free0=np.asarray(allocatable, np.int64)
        - np.asarray(requested, np.int64),
        alloc=np.asarray(allocatable, np.int64),
        cost_milli=np.asarray(cost, np.int64),
        energy_milli=np.asarray(energy, np.int64),
        accel_class=np.asarray(accel, np.int64),
    )


def replay_candidate(
    ov: OverlaySnapshot, weights: np.ndarray, rng_key, hard_weight: float
) -> np.ndarray:
    """One candidate launch over the shared overlay: returns host [P]
    chosen rows (-1 unplaced). The kernel is the cached production
    serial program — a new weight vector re-launches, never recompiles."""
    import jax

    from ..ops.lattice import make_schedule_batch

    kern = make_schedule_batch(ov.v_cap, hard_weight)
    res = kern(ov.snap, ov.batch, np.asarray(weights, np.float32), rng_key)
    return np.asarray(jax.device_get(res.chosen))


def rows_for_placements(
    ov: OverlaySnapshot, placements: Sequence[str]
) -> np.ndarray:
    """Production placements (node names, "" unplaced) → [P] rows on the
    overlay's row table, -1 where unplaced/unknown (a node that left the
    cluster since the wave scores as unplaced — honest, it no longer
    absorbs anything)."""
    index = {n: r for r, n in enumerate(ov.row_names) if n is not None}
    rows = np.full(ov.req.shape[0], -1, np.int64)
    for i, node in enumerate(placements[: ov.req.shape[0]]):
        if node:
            rows[i] = index.get(node, -1)
    return rows


def score_assignment(ov: OverlaySnapshot, chosen: np.ndarray) -> WaveOutcome:
    """Score an assignment (replayed or production) against the shared
    overlay columns. Pure host arithmetic — no device work."""
    chosen = np.asarray(chosen, np.int64)
    valid = ov.pod_valid.copy()
    total = int(valid.sum())
    n = ov.free0.shape[0]
    placed_mask = valid & (chosen >= 0) & (chosen < n)
    placed = int(placed_mask.sum())

    free = ov.free0.copy()
    if placed:
        np.subtract.at(
            free, chosen[placed_mask], ov.req[placed_mask].astype(np.int64)
        )
    # stranded-capacity fragmentation through the SHARED definition
    # (fragmentation_score above — the descheduler and the live gauge
    # consume the same arithmetic)
    nv = ov.node_valid
    alloc = np.maximum(ov.alloc, 1)
    used_any = (free < ov.alloc).any(axis=1) & nv
    free_frac = np.clip(free / alloc, 0.0, 1.0).mean(axis=1)
    fragmentation = fragmentation_score(free_frac, used_any, nv)

    cost_norm = energy_norm = 0.0
    if placed:
        max_cost = float(ov.cost_milli[nv].max(initial=0))
        max_energy = float(ov.energy_milli[nv].max(initial=0))
        rows = chosen[placed_mask]
        if max_cost > 0:
            cost_norm = float(ov.cost_milli[rows].mean()) / max_cost
        if max_energy > 0:
            energy_norm = float(ov.energy_milli[rows].mean()) / max_energy

    placed_frac = placed / total if total else 1.0
    utility = (
        placed_frac
        - FRAG_WEIGHT * fragmentation
        - COST_WEIGHT * cost_norm
        - ENERGY_WEIGHT * energy_norm
    )
    return WaveOutcome(
        placed=placed,
        total=total,
        fragmentation=fragmentation,
        preempt_pressure=total - placed,
        cost_norm=cost_norm,
        energy_norm=energy_norm,
        utility=float(utility),
    )


def divergence(
    ov: OverlaySnapshot, chosen: np.ndarray, prod_rows: np.ndarray
) -> float:
    """Fraction of (valid) pods the hypothetical assignment places on a
    DIFFERENT node than production did — the shadow-diff signal."""
    valid = ov.pod_valid
    total = int(valid.sum())
    if not total:
        return 0.0
    diff = (np.asarray(chosen, np.int64) != np.asarray(prod_rows, np.int64))
    return float((diff & valid).sum()) / total


def replay_wave(
    cache,
    pods: Sequence[Any],
    weights: np.ndarray,
    rng_key,
    hard_weight: float = 1.0,
) -> Optional[Tuple[List[str], WaveOutcome]]:
    """Single-wave replay convenience (the differential-corpus seam):
    encode + overlay + one candidate launch, returning pod-aligned node
    names ("" unplaced) and the scored outcome."""
    ov = build_overlay(cache, pods)
    if ov is None:
        return None
    chosen = replay_candidate(ov, weights, rng_key, hard_weight)
    names = []
    for i in range(len(pods)):
        row = int(chosen[i])
        name = ""
        if ov.pod_valid[i] and 0 <= row < len(ov.row_names):
            name = ov.row_names[row] or ""
        names.append(name)
    return names, score_assignment(ov, chosen)
