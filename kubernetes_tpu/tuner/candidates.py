"""Candidate weight-vector generators for the policy gym.

Three families, per the tentpole spec:

* **TOPSIS/entropy-derived** (arxiv 2506.04902): entropy weighting over
  a node-level decision matrix built from the snapshot columns. A
  criterion whose values DISPERSE across the fleet carries information
  (heterogeneous cost → the cost criterion can discriminate placements)
  and earns weight; a flat criterion earns none. Deterministic — same
  fleet, same candidate.
* **Gavel-style throughput-normalized heterogeneity weights** (arxiv
  2008.09213): the PR-15 ``accel_class``/``cost_milli``/``energy_milli``
  columns are exactly Gavel's inputs — cost and energy are normalized by
  the accelerator-class throughput proxy, so "cheapest" means cheapest
  per unit of delivered throughput, not per node-hour. Inert (returns
  nothing) on an unlabeled fleet.
* **Local perturbation of the incumbent**: seeded lognormal jitter —
  the hill-climbing arm that refines whatever already won.

Every generator returns finite float32 vectors; the promotion gate
re-validates through ``weights_for_policy`` anyway (defense in depth —
a poisoned injected candidate must die at the gate, not in a kernel).
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from ..ops.lattice import (
    DEFAULT_WEIGHTS,
    NUM_SCORE_COMPONENTS,
    SC_BALANCED,
    SC_COST,
    SC_ENERGY,
    SC_LEAST_ALLOC,
    SC_MOST_ALLOC,
    WEIGHT_PROFILES,
)

# perturbation jitter: multiplicative lognormal, sigma per component
PERTURB_SIGMA = 0.35


def perturbation_candidates(
    incumbent: np.ndarray, rng: np.random.Generator, k: int = 4
) -> List[np.ndarray]:
    """k seeded local perturbations of the incumbent (multiplicative, so
    zero components stay zero — a perturbation explores the incumbent's
    POLICY neighborhood, it doesn't resurrect opt-in components the
    incumbent disabled; the TOPSIS/Gavel arms own those jumps)."""
    base = np.asarray(incumbent, np.float32)
    out = []
    for _ in range(max(0, k)):
        jitter = rng.lognormal(0.0, PERTURB_SIGMA, NUM_SCORE_COMPONENTS)
        out.append((base * jitter).astype(np.float32))
    return out


def _entropy_weights(matrix: np.ndarray) -> np.ndarray:
    """Entropy-method criteria weights over an [m alternatives, n
    criteria] decision matrix (the TOPSIS pipeline's objective-weighting
    stage): w_j ∝ 1 - e_j where e_j is the normalized Shannon entropy of
    criterion j's value distribution across alternatives."""
    m = matrix.shape[0]
    if m < 2:
        return np.full(matrix.shape[1], 1.0 / matrix.shape[1], np.float64)
    col = matrix - matrix.min(axis=0, keepdims=True)
    col_sum = col.sum(axis=0, keepdims=True)
    p = np.where(col_sum > 0, col / np.maximum(col_sum, 1e-12), 0.0)
    with np.errstate(divide="ignore", invalid="ignore"):
        plogp = np.where(p > 0, p * np.log(p), 0.0)
    e = -plogp.sum(axis=0) / np.log(m)
    d = np.clip(1.0 - e, 0.0, None)
    # a criterion that never varies (col_sum 0) carries no information
    d = np.where(col_sum.ravel() > 0, d, 0.0)
    total = d.sum()
    if total <= 0:
        return np.full(matrix.shape[1], 1.0 / matrix.shape[1], np.float64)
    return d / total


def topsis_candidates(
    requested: np.ndarray,
    allocatable: np.ndarray,
    valid: np.ndarray,
    cost_milli: np.ndarray,
    energy_milli: np.ndarray,
) -> List[np.ndarray]:
    """One entropy-weighted candidate from the live fleet's dispersion
    structure. Criteria (node-level): free fraction (LeastAllocated's
    signal), used fraction (MostAllocated's), resource imbalance
    (Balanced's), cost, energy. The resulting criteria weights land on
    the matching score components over a default base — the rest of the
    vector keeps reference semantics (affinity/taints/spread are
    correctness-adjacent, not up for entropy deletion)."""
    mask = np.asarray(valid, bool)
    if mask.sum() < 2:
        return []
    alloc = np.maximum(np.asarray(allocatable, np.float64)[mask], 1.0)
    used = np.asarray(requested, np.float64)[mask] / alloc
    used = np.clip(used, 0.0, 1.0)
    free_frac = (1.0 - used).mean(axis=1)
    used_frac = used.mean(axis=1)
    imbalance = used.std(axis=1)
    cost = np.asarray(cost_milli, np.float64)[mask]
    energy = np.asarray(energy_milli, np.float64)[mask]
    matrix = np.stack([free_frac, used_frac, imbalance, cost, energy], axis=1)
    w = _entropy_weights(matrix)
    cand = DEFAULT_WEIGHTS.copy()
    # scale into the profile weight range (built-ins use O(1)-O(100))
    scale = 10.0 / max(w.max(), 1e-9)
    cand[SC_LEAST_ALLOC] = w[0] * scale
    cand[SC_MOST_ALLOC] = w[1] * scale
    cand[SC_BALANCED] = max(float(cand[SC_BALANCED]), w[2] * scale)
    cand[SC_COST] = w[3] * scale
    cand[SC_ENERGY] = w[4] * scale
    return [cand.astype(np.float32)]


def gavel_candidates(
    cost_milli: np.ndarray,
    energy_milli: np.ndarray,
    accel_class: np.ndarray,
    valid: np.ndarray,
) -> List[np.ndarray]:
    """Gavel-style heterogeneity-aware candidates: cost/energy pressure
    normalized by the accelerator-class throughput proxy. ``accel_class``
    is an interned class id (-1 = unlabeled); classes rank throughput in
    interning order, so class id + 1 is the throughput scale the $-term
    divides by. Empty on a fleet with no cost/energy labels — Gavel has
    nothing to normalize."""
    mask = np.asarray(valid, bool)
    if not mask.any():
        return []
    cost = np.asarray(cost_milli, np.float64)[mask]
    energy = np.asarray(energy_milli, np.float64)[mask]
    accel = np.asarray(accel_class, np.float64)[mask]
    if cost.max(initial=0.0) <= 0 and energy.max(initial=0.0) <= 0:
        return []
    throughput = np.maximum(accel + 1.0, 1.0)  # -1/0 → baseline class
    out = []
    if cost.max(initial=0.0) > 0:
        norm_cost = cost / throughput
        # dispersion of $/throughput decides how hard the vector leans:
        # a fleet where every node costs the same per unit of throughput
        # gains nothing from cost-aware placement
        spread = norm_cost.std() / max(norm_cost.mean(), 1e-9)
        cand = WEIGHT_PROFILES["pack"].copy()
        cand[SC_COST] = np.float32(100.0 * min(1.0, spread + 0.1))
        out.append(cand.astype(np.float32))
    if energy.max(initial=0.0) > 0:
        norm_energy = energy / throughput
        spread = norm_energy.std() / max(norm_energy.mean(), 1e-9)
        cand = WEIGHT_PROFILES["pack"].copy()
        cand[SC_ENERGY] = np.float32(100.0 * min(1.0, spread + 0.1))
        out.append(cand.astype(np.float32))
    return out


def profile_candidates() -> List[Tuple[str, np.ndarray]]:
    """The built-in named profiles: free candidates with stable names —
    the fast path for workload flips whose winner IS a known policy
    (cost pressure appears → "cheapest" wins shadow within windows,
    no gradient walk needed)."""
    return [
        (name, vec.copy())
        for name, vec in WEIGHT_PROFILES.items()
        if name != "spread"  # alias of default — no information
    ]
