"""PolicyTuner: the gym loop + the shadow A/B promotion gate.

The first closed feedback loop in the system: the scheduler records its
real waves (tuner/waves.py), a background tick replays K candidate
weight vectors against them over ONE shared overlay snapshot
(tuner/scoring.py — K cheap re-launches, zero recompiles), and the
winner has to EARN the live slot:

  1. a candidate that beats the incumbent beyond the noise floor enters
     SHADOW — scored on subsequent live waves without acting, its
     hypothetical placements diffed against production's;
  2. it promotes through ``Scheduler.set_score_policy`` only after
     beating the incumbent in N consecutive shadow windows; ONE lost
     window discards it (incumbent kept — a diverging shadow never
     ships);
  3. promotion persists the vector as the ScorePolicy API object FIRST
     (degraded store → counted skip, tuner pauses, retried) and applies
     second, so failover adopts the tuned vector instead of reverting;
  4. a post-promotion watch compares live production utility against the
     pre-promotion baseline and ROLLS BACK automatically on regression.

Candidate vectors are validated through ``weights_for_policy`` before
they may even be replayed — a poisoned (NaN/inf/mis-shaped) candidate
dies at the gate with a counted rejection, never inside a kernel.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional, Tuple

import numpy as np

from ..ops.lattice import (
    WEIGHT_PROFILES,
    register_weight_profile,
    weights_for_policy,
)
from ..testing.lockgraph import named_lock, track_attrs
from ..utils.metrics import metrics
from . import candidates as cand_gen
from .policy import (
    COUNTER_CANDIDATES_REJECTED,
    COUNTER_GYM_CANDIDATES,
    COUNTER_GYM_PASSES,
    COUNTER_POLICY_PROMOTIONS,
    COUNTER_ROLLBACKS,
    COUNTER_SHADOW_WINDOWS,
    COUNTER_TICK_ERRORS,
    GAUGE_ARM_UTILITY,
    GAUGE_SHADOW_DIVERGENCE,
    HIST_GYM_PASS_SECONDS,
    persist_active_policy,
)
from .scoring import (
    build_overlay,
    divergence,
    replay_candidate,
    rows_for_placements,
    score_assignment,
)
from .waves import WaveRingBuffer

logger = logging.getLogger("kubernetes_tpu.tuner")


class PolicyTuner:
    """Background self-tuning loop bound to one (leading) scheduler.

    Lifecycle follows leadership: cmd/scheduler.py starts it next to the
    autoscaler when scheduling starts and stops it when leadership (or
    the process) ends. ``start`` attaches the wave ring as the
    scheduler's recorder; ``stop`` detaches it."""

    def __init__(
        self,
        scheduler,
        server,
        *,
        period_s: float = 2.0,
        ring_capacity: int = 32,
        max_waves_per_pass: int = 8,
        max_pods_per_pass: int = 128,
        k_perturb: int = 3,
        shadow_windows: int = 3,
        noise_floor: float = 0.02,
        min_waves: int = 1,
        rollback_windows: int = 3,
        rollback_margin: float = 0.2,
        degraded_pause_ticks: int = 3,
        seed: int = 0,
    ):
        self.sched = scheduler
        self.server = server
        self.period_s = period_s
        self.max_waves_per_pass = max_waves_per_pass
        self.max_pods_per_pass = max_pods_per_pass
        self.k_perturb = k_perturb
        self.shadow_windows = shadow_windows
        self.noise_floor = noise_floor
        self.min_waves = min_waves
        self.rollback_windows = rollback_windows
        self.rollback_margin = rollback_margin
        self.degraded_pause_ticks = degraded_pause_ticks
        self.seed = seed
        self.ring = WaveRingBuffer(ring_capacity)
        self._lock = named_lock("tuner.state")
        self._rng = np.random.default_rng(seed)
        self._injected: List[Tuple[str, object]] = []
        self._shadow: Optional[dict] = None
        self._post: Optional[dict] = None  # post-promotion rollback watch
        self._pause_ticks = 0
        self._cand_seq = 0
        self._tick_count = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self.sched.wave_recorder = self.ring
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="policy-tuner"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if getattr(self.sched, "wave_recorder", None) is self.ring:
            self.sched.wave_recorder = None

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.tick()
            except Exception:
                metrics.inc(COUNTER_TICK_ERRORS)
                logger.exception("tuner tick failed (loop continues)")

    # -- chaos/test seam -----------------------------------------------------

    def inject_candidate(self, vec, name: str = "") -> None:
        """Queue an external candidate for the next gym pass (the chaos
        suites poison this with NaN vectors; the gate must reject them)."""
        with self._lock:
            self._injected.append((name or "injected", vec))

    # -- one gym pass --------------------------------------------------------

    def tick(self) -> None:
        with self._lock:
            if self._pause_ticks > 0:
                self._pause_ticks -= 1
                return
        waves = self.ring.snapshot(limit=self.max_waves_per_pass)
        if len(waves) < self.min_waves:
            return
        incumbent_vec = np.asarray(self.sched._weights, np.float32).copy()
        incumbent_name = getattr(
            self.sched, "_score_policy_name", "default"
        )
        # newest waves first, capped: one concatenated pseudo-wave — the
        # serial kernel's in-batch carry replays them in sequence against
        # the shared overlay
        pods: List = []
        placements: List[str] = []
        for rec in reversed(waves):
            if pods and len(pods) + len(rec.pods) > self.max_pods_per_pass:
                break
            pods.extend(rec.pods)
            placements.extend(rec.placements)
        if len(pods) > self.max_pods_per_pass:
            pods = pods[: self.max_pods_per_pass]
            placements = placements[: self.max_pods_per_pass]
        t0 = time.monotonic()
        ov = build_overlay(self.sched.cache, pods)
        if ov is None:
            return

        arms = self._assemble_candidates(incumbent_name, incumbent_vec, ov)
        import jax

        with self._lock:
            self._tick_count += 1
            tick = self._tick_count
        key = jax.random.PRNGKey(self.seed * 1_000_003 + tick)
        hard_w = self.sched.cfg.hard_pod_affinity_weight
        scored = []
        for source, name, vec in arms:
            chosen = replay_candidate(ov, vec, key, hard_w)
            scored.append(
                (source, name, vec, chosen, score_assignment(ov, chosen))
            )
        prod_rows = rows_for_placements(ov, placements)
        prod_outcome = score_assignment(ov, prod_rows)
        metrics.inc(COUNTER_GYM_PASSES)
        metrics.observe(HIST_GYM_PASS_SECONDS, time.monotonic() - t0)
        metrics.set_gauge(
            GAUGE_ARM_UTILITY, prod_outcome.utility, {"arm": "production"}
        )
        inc_outcome = scored[0][4]
        metrics.set_gauge(
            GAUGE_ARM_UTILITY, inc_outcome.utility, {"arm": "incumbent"}
        )
        self._decide(
            incumbent_name,
            incumbent_vec,
            scored,
            ov,
            prod_rows,
            prod_outcome,
        )

    def _assemble_candidates(self, incumbent_name, incumbent_vec, ov):
        """Gather + validate + dedupe the candidate arms. Index 0 is
        always the incumbent (the comparison baseline on the same
        overlay); a shadow challenger, if any, is always included."""
        with self._lock:
            shadow = self._shadow
            injected = list(self._injected)
            self._injected = []
            perturbs = cand_gen.perturbation_candidates(
                incumbent_vec, self._rng, self.k_perturb
            )
        raw: List[Tuple[str, str, object]] = [
            ("incumbent", incumbent_name, incumbent_vec)
        ]
        if shadow is not None:
            raw.append(("shadow", shadow["name"], shadow["vec"]))
        raw.extend(
            ("profile", name, vec)
            for name, vec in cand_gen.profile_candidates()
        )
        raw.extend(
            ("topsis", "", vec)
            for vec in cand_gen.topsis_candidates(
                ov.alloc - ov.free0,  # requested
                ov.alloc,
                ov.node_valid,
                ov.cost_milli,
                ov.energy_milli,
            )
        )
        raw.extend(
            ("gavel", "", vec)
            for vec in cand_gen.gavel_candidates(
                ov.cost_milli,
                ov.energy_milli,
                ov.accel_class,
                ov.node_valid,
            )
        )
        raw.extend(("perturb", "", vec) for vec in perturbs)
        raw.extend(("injected", name, vec) for name, vec in injected)
        out: List[Tuple[str, str, np.ndarray]] = []
        seen = set()
        for source, name, vec in raw:
            try:
                v = weights_for_policy(np.asarray(vec))
            except (ValueError, TypeError):
                # THE gate: a poisoned candidate is rejected before it
                # may touch a kernel, a shadow window, or the live slot
                metrics.inc(
                    COUNTER_CANDIDATES_REJECTED, {"reason": "invalid"}
                )
                if source == "shadow":
                    with self._lock:
                        self._shadow = None
                continue
            dedup = tuple(np.round(v, 4).tolist())
            if dedup in seen and source not in ("incumbent", "shadow"):
                continue
            seen.add(dedup)
            out.append((source, name, v))
            metrics.inc(COUNTER_GYM_CANDIDATES, {"source": source})
        return out

    # -- the gate ------------------------------------------------------------

    def _decide(
        self,
        incumbent_name,
        incumbent_vec,
        scored,
        ov,
        prod_rows,
        prod_outcome,
    ) -> None:
        inc_outcome = scored[0][4]
        # post-promotion rollback watch: live production utility vs the
        # pre-promotion baseline
        with self._lock:
            post = self._post
        if post is not None and self.ring.last_seq() > post["seq"]:
            if prod_outcome.utility < post["baseline"] - self.rollback_margin:
                post["bad"] += 1
                post["good"] = 0
            else:
                post["bad"] = 0
                post["good"] += 1
            if post["bad"] >= self.rollback_windows:
                self._rollback(post)
                return
            if post["good"] >= 2 * self.rollback_windows:
                with self._lock:
                    self._post = None  # promotion held up — watch ends

        by_shadow = next((s for s in scored if s[0] == "shadow"), None)
        if by_shadow is not None:
            _, name, vec, chosen, outcome = by_shadow
            div = divergence(ov, chosen, prod_rows)
            metrics.set_gauge(GAUGE_SHADOW_DIVERGENCE, div)
            metrics.set_gauge(
                GAUGE_ARM_UTILITY, outcome.utility, {"arm": "shadow"}
            )
            if outcome.utility - inc_outcome.utility > self.noise_floor:
                metrics.inc(COUNTER_SHADOW_WINDOWS, {"outcome": "win"})
                with self._lock:
                    if self._shadow is not None:
                        self._shadow["wins"] += 1
                        wins = self._shadow["wins"]
                    else:
                        wins = 0
                if wins >= self.shadow_windows:
                    self._promote(
                        name, vec, incumbent_name, incumbent_vec,
                        prod_outcome,
                    )
            else:
                # one lost window discards the challenger: a shadow that
                # diverges from "better" even once is not promoted
                metrics.inc(COUNTER_SHADOW_WINDOWS, {"outcome": "loss"})
                with self._lock:
                    self._shadow = None
            return

        # no shadow in flight: does any candidate beat the incumbent
        # beyond the noise floor on this window?
        challengers = [s for s in scored[1:] if s[0] != "shadow"]
        if not challengers:
            return
        best = max(challengers, key=lambda s: s[4].utility)
        source, name, vec, _chosen, outcome = best
        if outcome.utility - inc_outcome.utility <= self.noise_floor:
            return
        with self._lock:
            if not name:
                self._cand_seq += 1
                name = f"tuned-{self._cand_seq}"
            self._shadow = {
                "name": name,
                "vec": np.asarray(vec, np.float32).copy(),
                "wins": 1,
                "source": source,
            }
        logger.info(
            "tuner: candidate %s (%s) entered shadow (utility %.4f vs "
            "incumbent %.4f)",
            name, source, outcome.utility, inc_outcome.utility,
        )

    def _promote(
        self, name, vec, incumbent_name, incumbent_vec, prod_outcome
    ) -> None:
        try:
            vec = weights_for_policy(np.asarray(vec))
        except (ValueError, TypeError):
            metrics.inc(
                COUNTER_CANDIDATES_REJECTED, {"reason": "gate_invalid"}
            )
            with self._lock:
                self._shadow = None
            return
        identity = getattr(self.sched, "_ha_identity", "scheduler-0")
        # persist FIRST: a vector the store refused must not become the
        # only copy (failover would silently revert it) — degraded store
        # pauses the tuner; the shadow state survives for the retry
        if not persist_active_policy(self.server, name, vec, identity):
            with self._lock:
                self._pause_ticks = self.degraded_pause_ticks
            return
        if name not in WEIGHT_PROFILES or not np.array_equal(
            WEIGHT_PROFILES.get(name), vec
        ):
            register_weight_profile(name, vec, overwrite=True)
        self.sched.set_score_policy(name)
        metrics.inc(COUNTER_POLICY_PROMOTIONS)
        with self._lock:
            self._shadow = None
            self._post = {
                "prev_name": incumbent_name,
                "prev_vec": np.asarray(incumbent_vec, np.float32).copy(),
                "baseline": prod_outcome.utility,
                "bad": 0,
                "good": 0,
                "seq": self.ring.last_seq(),
            }
        logger.warning(
            "tuner: promoted score policy %r (was %r); rollback watch "
            "armed at baseline %.4f", name, incumbent_name,
            prod_outcome.utility,
        )

    def _rollback(self, post: dict) -> None:
        prev_name, prev_vec = post["prev_name"], post["prev_vec"]
        identity = getattr(self.sched, "_ha_identity", "scheduler-0")
        if not persist_active_policy(
            self.server, prev_name, prev_vec, identity
        ):
            with self._lock:
                self._pause_ticks = self.degraded_pause_ticks
            return
        if prev_name not in WEIGHT_PROFILES:
            register_weight_profile(prev_name, prev_vec, overwrite=True)
        try:
            self.sched.set_score_policy(prev_name)
        except ValueError:
            self.sched.set_score_policy(prev_vec)
        metrics.inc(COUNTER_ROLLBACKS)
        with self._lock:
            self._post = None
            self._shadow = None
        logger.error(
            "tuner: post-promotion regression — rolled back to %r",
            prev_name,
        )


track_attrs(PolicyTuner, "_shadow", "_post", "_injected")
