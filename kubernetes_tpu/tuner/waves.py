"""The wave replay ring: recent REAL waves, recorded for the gym.

The scheduler's device paths (wave pipeline + serial batch) call
``record_wave`` right after a batch commits: the pod specs, the weight
vector the kernel actually launched with, the rng key, the production
placements (row-aligned with ``pods``; ``""`` = unplaced) and the cache
generation the launch encoded against. The gym replays these pods
against a ``whatif_overlay`` copy of the CURRENT snapshot — deliberately
NOT a pinned launch-time generation: holding N reader pins would force
every subsequent wave launch through copy-on-pin, and the gym's question
("how would candidate W place this real traffic against this cluster")
is comparative — every candidate, incumbent included, replays the same
overlay, so drift between launch-time and replay-time state cancels out
of the ranking.

Records hold REFERENCES to the pod objects (replay only reads specs);
the ring is bounded, lock-leaf (nothing is acquired while holding it),
and Eraser-tracked like every shared structure in the tree.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional

import numpy as np

from ..testing.lockgraph import named_lock, track_attrs
from ..utils.metrics import metrics
from .policy import COUNTER_WAVES_RECORDED, GAUGE_WAVE_RING_DEPTH


@dataclass
class WaveRecord:
    """One real wave: the inputs a replay needs plus the outcome
    production actually committed (the shadow diff / rollback
    baseline)."""

    pods: List[Any]  # v1.Pod references, batch order
    weights: np.ndarray  # [NUM_SCORE_COMPONENTS] launch vector
    placements: List[str] = field(default_factory=list)  # "" = unplaced
    rng_key: Any = None  # the launch PRNG key (serial path: exact replay)
    launch_gen: int = 0
    path: str = "wave"  # "wave" | "serial"
    seq: int = 0  # ring-assigned monotonic sequence


class WaveRingBuffer:
    """Bounded ring of recent waves. The scheduler writes (hot path —
    one list append under a leaf lock), the tuner reads snapshots."""

    def __init__(self, capacity: int = 32):
        self.capacity = max(1, int(capacity))
        self._lock = named_lock("tuner.ring")
        self._ring: List[WaveRecord] = []
        self._seq = 0

    def record_wave(
        self,
        pods: List[Any],
        weights: np.ndarray,
        placements: List[str],
        rng_key: Any = None,
        launch_gen: int = 0,
        path: str = "wave",
    ) -> None:
        if not pods:
            return
        rec = WaveRecord(
            pods=list(pods),
            weights=np.asarray(weights, np.float32).copy(),
            placements=list(placements),
            rng_key=rng_key,
            launch_gen=int(launch_gen),
            path=path,
        )
        with self._lock:
            self._seq += 1
            rec.seq = self._seq
            self._ring.append(rec)
            if len(self._ring) > self.capacity:
                del self._ring[: len(self._ring) - self.capacity]
            depth = len(self._ring)
        metrics.inc(COUNTER_WAVES_RECORDED, {"path": path})
        metrics.set_gauge(GAUGE_WAVE_RING_DEPTH, float(depth))

    def snapshot(
        self, limit: Optional[int] = None, min_seq: int = 0
    ) -> List[WaveRecord]:
        """Newest-last copy of the ring (records themselves are shared,
        treated as immutable once recorded). ``min_seq`` filters to waves
        recorded after a known point (post-promotion rollback watch)."""
        with self._lock:
            out = [r for r in self._ring if r.seq > min_seq]
        if limit is not None and len(out) > limit:
            out = out[-limit:]
        return out

    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring = []
        metrics.set_gauge(GAUGE_WAVE_RING_DEPTH, 0.0)


track_attrs(WaveRingBuffer, "_ring", "_seq")
