"""The persisted score policy + the tuner metric surface.

The policy gym's promotion gate ends in TWO durable effects: the live
swap (``Scheduler.set_score_policy`` — a kernel-input change, zero
recompile) and this module's **ScorePolicy API object**. The object is
the one that survives the process: a leader failover or a restart reads
it back during ``Scheduler.promote()`` and adopts the tuned vector
instead of silently reverting to ``default`` (the failure the chaos-ha
regression pins). Promotion persists FIRST and applies second, so a
vector the store never accepted can never become the only copy.

Import discipline: this module is deliberately jax-free (stdlib + numpy
+ api objects) — ``api/serialization.ensure_late_registration`` imports
it from arbitrary processes (kubectl, REST frontends) that must decode
``scorepolicies`` without paying a jax import. Weight validation defers
to ``ops.lattice`` lazily, inside the scheduler-side helpers only.

Like scheduler/ha.py, this is also the one home for the ``tuner_*``
series names and the SIGUSR2 dump section, so the metrics contract
(graftlint pass 3) and the cache debugger read one surface.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

from ..api.objects import ObjectMeta
from ..client.apiserver import NotPrimary
from ..runtime.consensus import DegradedWrites
from ..utils.metrics import metrics

logger = logging.getLogger("kubernetes_tpu.tuner")

# the well-known singleton object name: there is ONE active policy per
# cluster, adopted by whoever leads
ACTIVE_POLICY_NAME = "active"

# -- the tuner_* metric surface (graftlint pass 3 reads these names) ---------

# waves the scheduler recorded into the replay ring, by producing path
COUNTER_WAVES_RECORDED = "tuner_waves_recorded_total"  # {path}
# current replay-ring depth
GAUGE_WAVE_RING_DEPTH = "tuner_wave_ring_depth"
# completed gym passes (one batched overlay replay per candidate set)
COUNTER_GYM_PASSES = "tuner_gym_passes_total"
# candidate vectors evaluated, by generator
COUNTER_GYM_CANDIDATES = "tuner_gym_candidates_total"  # {source}
# candidates refused before they could ever reach shadow/promotion
COUNTER_CANDIDATES_REJECTED = "tuner_candidates_rejected_total"  # {reason}
# shadow-window verdicts for the current challenger
COUNTER_SHADOW_WINDOWS = "tuner_shadow_windows_total"  # {outcome}
# fraction of pods the shadow vector would place DIFFERENTLY from
# production in the latest window (1.0 = fully divergent hypothetically)
GAUGE_SHADOW_DIVERGENCE = "tuner_shadow_divergence"
# promotions applied (persist landed + live swap done)
COUNTER_POLICY_PROMOTIONS = "tuner_promotions_total"
# post-promotion regressions that rolled the incumbent back
COUNTER_ROLLBACKS = "tuner_rollbacks_total"
# store writes refused while degraded — the tuner pauses (counted skip,
# promotion retried once the store heals)
COUNTER_DEGRADED_SKIPS = "tuner_degraded_write_skips_total"  # {write}
# persisted-policy adoption attempts at promote()/startup
COUNTER_POLICY_ADOPTIONS = "tuner_policy_adoptions_total"  # {outcome}
# background ticks that died (exception contained, loop keeps running)
COUNTER_TICK_ERRORS = "tuner_tick_errors_total"
# 1 for the active policy name, 0 for a policy this process retired
GAUGE_ACTIVE_POLICY = "tuner_active_policy_info"  # {policy}
# wall-clock of one full gym pass (encode + overlay + K kernel launches)
HIST_GYM_PASS_SECONDS = "tuner_gym_pass_duration_seconds"
# mean utility per arm over the latest scored window
GAUGE_ARM_UTILITY = "tuner_arm_utility"  # {arm}


@dataclass
class ScorePolicy:
    """The persisted active score policy (cluster-scoped, singleton
    ``active``). ``weights`` is the full raw vector — the authoritative
    copy; ``policy_name`` is the stable registered profile name metrics
    and dumps use; ``promotions`` counts gate passages over the object's
    lifetime (monotonic — a zombie's replayed promotion can't rewind
    it)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    weights: List[float] = field(default_factory=list)
    policy_name: str = "default"
    promoted_by: str = ""
    promotions: int = 0
    kind: str = "ScorePolicy"


def persist_active_policy(
    server, name: str, weights: np.ndarray, identity: str = ""
) -> bool:
    """Write the promoted vector as the singleton ScorePolicy object.
    Returns False on a degraded store (counted skip — the caller pauses
    and retries; promotion must NOT apply a vector the store refused,
    or failover would silently revert it)."""
    vec = [float(x) for x in np.asarray(weights, np.float32)]

    def mutate(cur: ScorePolicy) -> ScorePolicy:
        cur.weights = vec
        cur.policy_name = name
        cur.promoted_by = identity
        cur.promotions = int(cur.promotions) + 1
        return cur

    try:
        try:
            server.guaranteed_update(
                "scorepolicies", "", ACTIVE_POLICY_NAME, mutate
            )
            return True
        except KeyError:
            pass  # NotFound subclasses KeyError: first promotion creates
        server.create(
            "scorepolicies",
            ScorePolicy(
                metadata=ObjectMeta(name=ACTIVE_POLICY_NAME, namespace=""),
                weights=vec,
                policy_name=name,
                promoted_by=identity,
                promotions=1,
            ),
        )
        return True
    except (DegradedWrites, NotPrimary, OSError) as e:
        metrics.inc(COUNTER_DEGRADED_SKIPS, {"write": "policy_persist"})
        logger.warning(
            "score-policy persist refused (%s); tuner pauses promotion", e
        )
        return False


def read_persisted_policy(server) -> Optional[Tuple[str, np.ndarray]]:
    """Read + validate the persisted active policy. None when absent or
    unreadable (degraded-tolerant: a failed read is a counted skip, never
    a crash — the caller keeps its current weights, which for a fresh
    process means ``default``)."""
    from ..ops.lattice import weights_for_policy

    try:
        obj = server.get("scorepolicies", "", ACTIVE_POLICY_NAME)
    except KeyError:
        metrics.inc(COUNTER_POLICY_ADOPTIONS, {"outcome": "none"})
        return None
    except Exception as e:  # degraded / partitioned store: skip, don't die
        metrics.inc(COUNTER_POLICY_ADOPTIONS, {"outcome": "skipped"})
        logger.warning("persisted score policy unreadable (%s); skipped", e)
        return None
    weights = getattr(obj, "weights", None) or getattr(obj, "content", {}).get(
        "weights"
    )
    name = getattr(obj, "policy_name", "") or getattr(obj, "content", {}).get(
        "policyName", ""
    )
    if not weights or not name:
        metrics.inc(COUNTER_POLICY_ADOPTIONS, {"outcome": "invalid"})
        return None
    try:
        vec = weights_for_policy(np.asarray(weights, np.float32))
    except ValueError as e:
        metrics.inc(COUNTER_POLICY_ADOPTIONS, {"outcome": "invalid"})
        logger.error("persisted score policy invalid (%s); ignored", e)
        return None
    return str(name), vec


def adopt_persisted_policy(server) -> Optional[str]:
    """The promote()/startup adoption path: read the persisted policy,
    register its stable name (idempotent overwrite — re-adoption after a
    failover must not conflict with the dead leader's registration), and
    return the name for ``set_score_policy``. None = keep current
    weights."""
    from ..ops.lattice import WEIGHT_PROFILES, register_weight_profile

    got = read_persisted_policy(server)
    if got is None:
        return None
    name, vec = got
    if name not in WEIGHT_PROFILES or not np.array_equal(
        WEIGHT_PROFILES.get(name), vec
    ):
        try:
            register_weight_profile(name, vec, overwrite=True)
        except ValueError as e:
            # a persisted name colliding with a built-in profile: the
            # built-in identity wins, the persisted VECTOR still applies
            # if the built-in already equals it; otherwise refuse
            if not np.array_equal(WEIGHT_PROFILES.get(name), vec):
                metrics.inc(COUNTER_POLICY_ADOPTIONS, {"outcome": "invalid"})
                logger.error("persisted policy rejected (%s)", e)
                return None
    metrics.inc(COUNTER_POLICY_ADOPTIONS, {"outcome": "adopted"})
    return name


def set_active_policy_gauge(policy: str, previous: str = "") -> None:
    """Flip the active-policy info gauge: the new name reads 1, the
    retired name reads 0 (series linger by design — a dump shows the
    succession, not just the survivor)."""
    if previous and previous != policy:
        metrics.set_gauge(GAUGE_ACTIVE_POLICY, 0.0, {"policy": previous})
    metrics.set_gauge(GAUGE_ACTIVE_POLICY, 1.0, {"policy": policy})


def tuner_health_lines() -> List[str]:
    """Policy-gym state for the SIGUSR2 dump: ring depth, gym/shadow
    progress, promotion/rollback/adoption counters, degraded skips and
    the active-policy succession — whether (and why) the tuner is or
    isn't converging is diagnosable from one signal. Empty when no tuner
    has published state yet."""
    lines: List[str] = []
    for snap in (
        metrics.snapshot_gauges("tuner_"),
        metrics.snapshot_counters("tuner_"),
    ):
        for name, labels, value in snap:
            annotation = ""
            if name == GAUGE_ACTIVE_POLICY:
                annotation = "ACTIVE" if value else "retired"
            lines.append(
                metrics.format_series_line(name, labels, value, annotation)
            )
    return lines
