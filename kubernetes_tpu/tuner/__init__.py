"""Policy gym: self-tuning score weights behind a shadow A/B gate.

Eagerly exports only the jax-free persistence surface (policy.py) —
``api/serialization.ensure_late_registration`` imports this package from
decode-only processes that must not pay a jax import. The gym itself
(controller/waves/scoring/candidates) loads lazily via PEP 562.
"""

from .policy import (  # noqa: F401  (the import-light surface)
    ACTIVE_POLICY_NAME,
    ScorePolicy,
    adopt_persisted_policy,
    persist_active_policy,
    read_persisted_policy,
    set_active_policy_gauge,
    tuner_health_lines,
)

_LAZY = {
    "PolicyTuner": ("controller", "PolicyTuner"),
    "WaveRingBuffer": ("waves", "WaveRingBuffer"),
    "WaveRecord": ("waves", "WaveRecord"),
    "replay_wave": ("scoring", "replay_wave"),
    "build_overlay": ("scoring", "build_overlay"),
    "score_assignment": ("scoring", "score_assignment"),
}

__all__ = [
    "ACTIVE_POLICY_NAME",
    "ScorePolicy",
    "adopt_persisted_policy",
    "persist_active_policy",
    "read_persisted_policy",
    "set_active_policy_gauge",
    "tuner_health_lines",
    *_LAZY,
]


def __getattr__(name):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(name) from None
    import importlib

    mod = importlib.import_module(f".{mod_name}", __name__)
    return getattr(mod, attr)
