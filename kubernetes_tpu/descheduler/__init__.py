"""Descheduler: verified consolidation on the what-if overlay.

The first subsystem that deliberately destroys healthy work, so every
eviction is proven safe before (plan simulation through the production
lattice kernel), during (shared eviction budget, PDB re-checks, gang
quorum, leadership fence, degraded-store pause), and after (drift
re-simulation between waves with counted uncordon rollback) it happens.
See controller.py for the loop, planner.py for plan construction, and
executor.py for the wave machinery.
"""

from .controller import Descheduler, descheduler_health_lines
from .executor import PlanExecutor
from .planner import ConsolidationPlan, plan_consolidation

__all__ = [
    "ConsolidationPlan",
    "Descheduler",
    "PlanExecutor",
    "descheduler_health_lines",
    "plan_consolidation",
]
